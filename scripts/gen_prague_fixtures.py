"""Generate execution-spec-style Prague blockchain fixtures.

Same self-generated-oracle pattern as scripts/gen_cancun_fixtures.py
(shared helpers in scripts/fixturegen.py): blocks built with the python
EVM, real computed headers, every fixture re-verified through the
stateful AND stateless runners before being written.

Covers the Prague surface beyond the hand-written unit tests: a type-4
(EIP-7702) set-code tx inside a full fixture block, the EIP-7685
requests commitment (deposit log + 7002/7251 dequeues) end-to-end, an
invalid requests_hash block, an EIP-2537 BLS precompile call from
bytecode, and EIP-2935 ancestor-hash reads through the system contract.

Usage: python scripts/gen_prague_fixtures.py  (writes tests/fixtures/prague/)
"""

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fixturegen import (  # noqa: E402
    build_block,
    dump_state,
    fee_tx,
    fixture_entry,
    hex_,
    make_genesis,
    write_and_verify,
)

from phant_tpu.blockchain import requests as req  # noqa: E402
from phant_tpu.blockchain.fork import PragueFork  # noqa: E402
from phant_tpu.crypto import secp256k1 as secp  # noqa: E402
from phant_tpu.signer.signer import (  # noqa: E402
    TxSigner,
    address_from_pubkey,
    sign_authorization,
)
from phant_tpu.types.account import Account  # noqa: E402
from phant_tpu.types.transaction import SetCodeTx  # noqa: E402

CHAIN_ID = 1
SENDER_KEY = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = address_from_pubkey(secp.pubkey_of(SENDER_KEY))
AUTH_KEY = 0xB0B1CAFE
AUTHORITY = address_from_pubkey(secp.pubkey_of(AUTH_KEY))
GENESIS_TS = 0x11000000

_build = functools.partial(
    build_block, fork_cls=PragueFork, genesis_ts=GENESIS_TS,
    beacon_root=b"\x66" * 32,
)
_fixture = functools.partial(
    fixture_entry,
    network="Prague",
    genesis_ts=GENESIS_TS,
    generator="scripts/gen_prague_fixtures.py",
)
_fee_tx = functools.partial(fee_tx, SENDER_KEY)


def _addr(n: int) -> bytes:
    return n.to_bytes(20, "big")


EMPTY_QUEUE = bytes.fromhex("5f5ff3")  # PUSH0 PUSH0 RETURN


def _base_pre(*contracts) -> dict:
    pre = {
        SENDER: Account(balance=10**20),
        req.WITHDRAWAL_REQUEST_ADDRESS: Account(nonce=1, code=EMPTY_QUEUE),
        req.CONSOLIDATION_REQUEST_ADDRESS: Account(nonce=1, code=EMPTY_QUEUE),
    }
    for addr, code in contracts:
        pre[addr] = Account(nonce=1, code=code)
    return pre


# --- scenario contracts -----------------------------------------------------

DELEGATE = _addr(0xDE1E)
# delegate runtime: SSTORE(0, 0x77) in the executing account's context
DELEGATE_CODE = bytes.fromhex("60775f5500")

BLS_CALLER = _addr(0xB15)


def _bls_caller_code() -> bytes:
    """CALLDATACOPY the input, CALL 0x0B (G1ADD) with it, store success at
    slot 0 and the first 32 bytes of the returned point at slot 1."""
    return (
        bytes.fromhex("6101005f5f37")
        + bytes.fromhex("60806101006101005f5f600b620fffff")
        + bytes.fromhex("f15f55")
        + bytes.fromhex("61010051600155")
        + b"\x00"
    )


HISTORY_READER = _addr(0x2935)


def _history_reader_code() -> bytes:
    """Read ancestor hash 0 via the EIP-2935 system contract: MSTORE(0, 0);
    CALL(HISTORY, input=32B block number) -> store returned hash."""
    from phant_tpu.blockchain.fork import HISTORY_STORAGE_ADDRESS

    return (
        bytes.fromhex("5f5f52")
        + bytes.fromhex("6020602060205f5f73") + HISTORY_STORAGE_ADDRESS
        + bytes.fromhex("620fffff")
        + bytes.fromhex("f1600155")
        + bytes.fromhex("602051600055")
        + b"\x00"
    )


def gen_setcode_fixture() -> dict:
    pre = _base_pre((DELEGATE, DELEGATE_CODE))
    pre[AUTHORITY] = Account(balance=10**18)
    auth = sign_authorization(CHAIN_ID, DELEGATE, 0, AUTH_KEY)
    tx = TxSigner(CHAIN_ID).sign(
        SetCodeTx(
            chain_id_val=CHAIN_ID, nonce=0, max_priority_fee_per_gas=1,
            max_fee_per_gas=1000, gas_limit=400_000, to=AUTHORITY, value=0,
            data=b"", access_list=(), authorization_list=(auth,),
            y_parity=0, r=0, s=0,
        ),
        SENDER_KEY,
    )
    genesis, block, state = _build(pre, [tx])
    post = dump_state(state)
    from phant_tpu.evm import gas as G

    assert post[AUTHORITY].code == G.DELEGATION_PREFIX + DELEGATE
    assert post[AUTHORITY].nonce == 1
    assert post[AUTHORITY].storage[0] == 0x77  # delegate ran in its context
    out = _fixture(
        "setcode_tx_delegated_execution", pre,
        [{"rlp": hex_(block.encode())}], block, post, genesis=genesis,
    )
    # the same block with a corrupted requests_hash must be rejected
    genesis2, bad, _ = _build(pre, [tx], requests_hash_override=b"\x13" * 32)
    out.update(
        _fixture(
            "requests_hash_mismatch", pre,
            [{"rlp": hex_(bad.encode()),
              "expectException": "requests hash mismatch"}],
            make_genesis(pre, GENESIS_TS), pre,
        )
    )
    return out


def gen_deposit_fixture() -> dict:
    # deposit contract that re-emits calldata as a DepositEvent (same mock
    # as tests/test_requests.py)
    logger = (
        bytes.fromhex("6102406000600037")
        + b"\x7f" + req.DEPOSIT_EVENT_SIGNATURE_HASH
        + bytes.fromhex("6102406000a100")
    )
    pre = _base_pre((req.DEPOSIT_CONTRACT_ADDRESS, logger))

    def word(n):
        return n.to_bytes(32, "big")

    def tail(payload):
        return word(len(payload)) + payload + bytes(-len(payload) % 32)

    event = (
        word(160) + word(256) + word(320) + word(384) + word(512)
        + tail(b"\x0a" * 48) + tail(b"\x0b" * 32) + tail(b"\x0c" * 8)
        + tail(b"\x0d" * 96) + tail(b"\x0e" * 8)
    )
    genesis, block, state = _build(
        pre, [_fee_tx(req.DEPOSIT_CONTRACT_ADDRESS, data=event)]
    )
    post = dump_state(state)
    expect = req.compute_requests_hash(
        [req.DEPOSIT_REQUEST_TYPE
         + (b"\x0a" * 48 + b"\x0b" * 32 + b"\x0c" * 8 + b"\x0d" * 96 + b"\x0e" * 8)]
    )
    assert block.header.requests_hash == expect
    return _fixture(
        "deposit_log_to_requests_hash", pre,
        [{"rlp": hex_(block.encode())}], block, post, genesis=genesis,
    )


def gen_bls_precompile_fixture() -> dict:
    from phant_tpu.crypto import bls12_381 as bls
    from phant_tpu.evm import precompiles_bls as pb

    pre = _base_pre((BLS_CALLER, _bls_caller_code()))
    g = bls.G1_GEN
    g2 = bls.g1_mul(g, 2)
    data = pb._write_g1(g) + pb._write_g1(g2)  # G1ADD(G, 2G) = 3G
    genesis, block, state = _build(pre, [_fee_tx(BLS_CALLER, data=data)])
    post = dump_state(state)
    g3 = bls.g1_mul(g, 3)
    assert post[BLS_CALLER].storage[0] == 1
    assert post[BLS_CALLER].storage[1] == int.from_bytes(
        pb._write_fp(g3[0])[:32], "big"
    )
    return _fixture(
        "bls12_g1add_precompile", pre,
        [{"rlp": hex_(block.encode())}], block, post, genesis=genesis,
    )


def gen_history_fixture() -> dict:
    pre = _base_pre((HISTORY_READER, _history_reader_code()))
    genesis, block, state = _build(pre, [_fee_tx(HISTORY_READER)])
    post = dump_state(state)
    assert post[HISTORY_READER].storage[1] == 1
    assert post[HISTORY_READER].storage[0] == int.from_bytes(
        genesis.header.hash(), "big"
    )
    return _fixture(
        "eip2935_history_contract_read", pre,
        [{"rlp": hex_(block.encode())}], block, post, genesis=genesis,
    )


def main():
    write_and_verify(
        os.path.join("tests", "fixtures", "prague"),
        {
            "setcode_txs.json": gen_setcode_fixture(),
            "deposit_requests.json": gen_deposit_fixture(),
            "bls_precompiles.json": gen_bls_precompile_fixture(),
            "history_contract.json": gen_history_fixture(),
        },
    )


if __name__ == "__main__":
    main()
