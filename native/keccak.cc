// Keccak-256 (Ethereum variant: 0x01 domain padding) — native CPU hot path.
//
// The reference client gets its native keccak from the ethash submodule's
// C implementation (reference: build.zig:94, ethash/lib/keccak/keccak.c) and
// Zig std's Keccak256 on the client side (reference: src/crypto/hasher.zig:1).
// This is a from-scratch C++ implementation exposing a C ABI consumed via
// ctypes (phant_tpu/utils/native.py) — it is the CPU baseline the TPU Pallas
// kernel (phant_tpu/ops/keccak_jax.py) is benchmarked against.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <utility>
#include <vector>

namespace {

constexpr uint64_t kRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

// Rotation offsets for lane A[x + 5y].
constexpr int kRot[25] = {
    0,  1,  62, 28, 27,   // y = 0
    36, 44, 6,  55, 20,   // y = 1
    3,  10, 43, 25, 39,   // y = 2
    41, 45, 15, 21, 8,    // y = 3
    18, 2,  61, 56, 14,   // y = 4
};

inline uint64_t rotl(uint64_t v, int s) {
  return s == 0 ? v : (v << s) | (v >> (64 - s));
}

void keccak_f1600(uint64_t a[25]) {
  uint64_t b[25];
  uint64_t c[5], d[5];
  for (int rnd = 0; rnd < 24; ++rnd) {
    // theta
    for (int x = 0; x < 5; ++x)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; ++x)
      d[x] = c[(x + 4) % 5] ^ rotl(c[(x + 1) % 5], 1);
    for (int i = 0; i < 25; ++i) a[i] ^= d[i % 5];
    // rho + pi: B[y + 5*((2x+3y)%5)] = rotl(A[x + 5y])
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl(a[x + 5 * y], kRot[x + 5 * y]);
    // chi
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        a[x + 5 * y] =
            b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y]);
    // iota
    a[0] ^= kRC[rnd];
  }
}

constexpr size_t kRate = 136;

void keccak256_one(const uint8_t* in, size_t len, uint8_t* out) {
  uint64_t state[25];
  std::memset(state, 0, sizeof(state));
  // absorb full blocks
  while (len >= kRate) {
    for (size_t i = 0; i < kRate / 8; ++i) {
      uint64_t lane;
      std::memcpy(&lane, in + 8 * i, 8);  // little-endian hosts only
      state[i] ^= lane;
    }
    keccak_f1600(state);
    in += kRate;
    len -= kRate;
  }
  // final (padded) block
  uint8_t block[kRate];
  std::memset(block, 0, sizeof(block));
  if (len != 0) {  // memcpy from a null `in` is UB even at length 0
    std::memcpy(block, in, len);
  }
  block[len] ^= 0x01;
  block[kRate - 1] ^= 0x80;
  for (size_t i = 0; i < kRate / 8; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    state[i] ^= lane;
  }
  keccak_f1600(state);
  std::memcpy(out, state, 32);
}

}  // namespace

// --- 8-way multi-buffer keccak (AVX-512) -----------------------------------
// Eight independent messages permute in lock-step: zmm register j holds
// lane j of all eight states (64-bit element m = message m). Rotations are
// single vprolq instructions and the chi step is one vpternlogq
// (a ^ (~b & c) = imm 0xD2) — the permutation itself vectorizes perfectly;
// the only scalar work left is staging each message's padded rate block.
// Messages with fewer chunks retire early (their digest is extracted at
// their own final permute); the batch dispatcher sorts by chunk count so
// grouped lanes waste almost no permutes.

#if defined(__x86_64__)
#include <immintrin.h>

namespace {

// The rho rotation counts must be 8-bit immediates in vprolq, so the
// rho+pi step is unrolled at compile time (an -O1 sanitizer build does
// not constant-fold a runtime loop index into an immediate).
template <int I>
__attribute__((target("avx512f"))) inline void rho_pi_one(__m512i* b,
                                                          const __m512i* a) {
  constexpr int x = I % 5, y = I / 5;
  b[y + 5 * ((2 * x + 3 * y) % 5)] = _mm512_rol_epi64(a[I], kRot[I]);
}

template <int... Is>
__attribute__((target("avx512f"))) inline void rho_pi_all(
    __m512i* b, const __m512i* a, std::integer_sequence<int, Is...>) {
  (rho_pi_one<Is>(b, a), ...);
}

__attribute__((target("avx512f"))) void keccak_f1600_x8(__m512i a[25]) {
  __m512i b[25], c[5], d[5];
  for (int rnd = 0; rnd < 24; ++rnd) {
    for (int x = 0; x < 5; ++x)
      c[x] = _mm512_xor_si512(
          _mm512_xor_si512(_mm512_xor_si512(a[x], a[x + 5]),
                           _mm512_xor_si512(a[x + 10], a[x + 15])),
          a[x + 20]);
    for (int x = 0; x < 5; ++x)
      d[x] = _mm512_xor_si512(c[(x + 4) % 5], _mm512_rol_epi64(c[(x + 1) % 5], 1));
    for (int i = 0; i < 25; ++i) a[i] = _mm512_xor_si512(a[i], d[i % 5]);
    rho_pi_all(b, a, std::make_integer_sequence<int, 25>{});
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        a[x + 5 * y] = _mm512_ternarylogic_epi64(
            b[x + 5 * y], b[(x + 1) % 5 + 5 * y], b[(x + 2) % 5 + 5 * y],
            0xD2);  // x ^ (~y & z)
    a[0] = _mm512_xor_si512(a[0], _mm512_set1_epi64((long long)kRC[rnd]));
  }
}

// Transpose an 8x8 block of u64: in[m] = 8 consecutive words of lane m,
// out[w] = word w across the 8 lanes. Three permute stages, 24 ops.
__attribute__((target("avx512f"))) inline void transpose8x8(
    const __m512i in[8], __m512i out[8]) {
  const __m512i idxA = _mm512_setr_epi64(0, 1, 8, 9, 4, 5, 12, 13);
  const __m512i idxB = _mm512_setr_epi64(2, 3, 10, 11, 6, 7, 14, 15);
  const __m512i idxLo = _mm512_setr_epi64(0, 1, 2, 3, 8, 9, 10, 11);
  const __m512i idxHi = _mm512_setr_epi64(4, 5, 6, 7, 12, 13, 14, 15);
  // pairs: t0 = [r0w0,r1w0,r0w2,r1w2,r0w4,r1w4,r0w6,r1w6] etc.
  __m512i t0 = _mm512_unpacklo_epi64(in[0], in[1]);
  __m512i t1 = _mm512_unpackhi_epi64(in[0], in[1]);
  __m512i t2 = _mm512_unpacklo_epi64(in[2], in[3]);
  __m512i t3 = _mm512_unpackhi_epi64(in[2], in[3]);
  __m512i t4 = _mm512_unpacklo_epi64(in[4], in[5]);
  __m512i t5 = _mm512_unpackhi_epi64(in[4], in[5]);
  __m512i t6 = _mm512_unpacklo_epi64(in[6], in[7]);
  __m512i t7 = _mm512_unpackhi_epi64(in[6], in[7]);
  // quads: qA0 = [r0w0,r1w0,r2w0,r3w0, r0w4,r1w4,r2w4,r3w4]
  __m512i qA0 = _mm512_permutex2var_epi64(t0, idxA, t2);
  __m512i qB0 = _mm512_permutex2var_epi64(t0, idxB, t2);
  __m512i qA1 = _mm512_permutex2var_epi64(t4, idxA, t6);
  __m512i qB1 = _mm512_permutex2var_epi64(t4, idxB, t6);
  __m512i qA2 = _mm512_permutex2var_epi64(t1, idxA, t3);
  __m512i qB2 = _mm512_permutex2var_epi64(t1, idxB, t3);
  __m512i qA3 = _mm512_permutex2var_epi64(t5, idxA, t7);
  __m512i qB3 = _mm512_permutex2var_epi64(t5, idxB, t7);
  out[0] = _mm512_permutex2var_epi64(qA0, idxLo, qA1);
  out[4] = _mm512_permutex2var_epi64(qA0, idxHi, qA1);
  out[2] = _mm512_permutex2var_epi64(qB0, idxLo, qB1);
  out[6] = _mm512_permutex2var_epi64(qB0, idxHi, qB1);
  out[1] = _mm512_permutex2var_epi64(qA2, idxLo, qA3);
  out[5] = _mm512_permutex2var_epi64(qA2, idxHi, qA3);
  out[3] = _mm512_permutex2var_epi64(qB2, idxLo, qB3);
  out[7] = _mm512_permutex2var_epi64(qB2, idxHi, qB3);
}

// Hash 8 messages; digests written to outs[m] as each lane retires.
__attribute__((target("avx512f"))) void keccak256_x8(
    const uint8_t* const ptrs[8], const size_t lens[8], uint8_t* const outs[8]) {
  __m512i S[25];
  for (int i = 0; i < 25; ++i) S[i] = _mm512_setzero_si512();
  size_t nch[8];
  size_t max_ch = 0;
  for (int m = 0; m < 8; ++m) {
    nch[m] = lens[m] / kRate + 1;
    if (nch[m] > max_ch) max_ch = nch[m];
  }
  alignas(64) static const uint8_t kZeros[kRate] = {0};
  alignas(64) uint8_t padbuf[8][kRate];
  alignas(64) uint64_t head[4][8];
  for (size_t c = 0; c < max_ch; ++c) {
    // each lane's 136B rate block for this chunk: the message bytes for
    // full blocks, a padded copy for the final block, zeros once retired
    const uint8_t* blk[8];
    for (int m = 0; m < 8; ++m) {
      if (c >= nch[m]) {  // retired lane: absorb zeros (state unused)
        blk[m] = kZeros;
      } else if (c + 1 < nch[m]) {  // full block: read in place
        blk[m] = ptrs[m] + c * kRate;
      } else {  // final padded block
        const size_t rem = lens[m] - c * kRate;
        std::memset(padbuf[m], 0, kRate);
        if (rem) std::memcpy(padbuf[m], ptrs[m] + c * kRate, rem);
        padbuf[m][rem] ^= 0x01;
        padbuf[m][kRate - 1] ^= 0x80;
        blk[m] = padbuf[m];
      }
    }
    // words 0..15 via two 8x8 transposes straight from the block bytes
    __m512i rows[8], lanes[8];
    for (int half = 0; half < 2; ++half) {
      for (int m = 0; m < 8; ++m)
        rows[m] = _mm512_loadu_si512(blk[m] + 64 * half);
      transpose8x8(rows, lanes);
      for (int w = 0; w < 8; ++w) {
        S[8 * half + w] = _mm512_xor_si512(S[8 * half + w], lanes[w]);
      }
    }
    // straggler word 16 (bytes 128..135)
    alignas(64) uint64_t w16[8];
    for (int m = 0; m < 8; ++m) std::memcpy(&w16[m], blk[m] + 128, 8);
    S[16] = _mm512_xor_si512(S[16], _mm512_load_si512(w16));
    keccak_f1600_x8(S);
    for (int m = 0; m < 8; ++m) {
      if (nch[m] != c + 1) continue;  // not this lane's final permute
      _mm512_store_si512(&head[0][0], S[0]);
      _mm512_store_si512(&head[1][0], S[1]);
      _mm512_store_si512(&head[2][0], S[2]);
      _mm512_store_si512(&head[3][0], S[3]);
      for (int w = 0; w < 4; ++w) std::memcpy(outs[m] + 8 * w, &head[w][m], 8);
    }
  }
}

bool have_avx512() {
  static const bool ok = __builtin_cpu_supports("avx512f");
  return ok;
}

}  // namespace
#endif  // __x86_64__

extern "C" {

void phant_keccak256(const uint8_t* in, size_t len, uint8_t* out) {
  keccak256_one(in, len, out);
}

// Batched: payload i is in[offsets[i] .. offsets[i] + lens[i]); out is n*32B.
// Strictly scalar — this is the reference-equivalent baseline (the
// reference hashes one node at a time through Zig std / ethash's C,
// src/crypto/hasher.zig:4-17) that bench.py's cpu_baseline measures.
void phant_keccak256_batch(const uint8_t* in, const uint64_t* offsets,
                           const uint32_t* lens, size_t n, uint8_t* out) {
  for (size_t i = 0; i < n; ++i) {
    keccak256_one(in + offsets[i], lens[i], out + 32 * i);
  }
}

// Batched, fast, scattered inputs (payload i at ptrs[i]): 8-way AVX-512
// multi-buffer when the CPU has it (runtime dispatch; scalar otherwise/
// elsewhere). Bit-identical output, ~4-6x the scalar batch on avx512
// hosts. This is the framework's own hashing path (witness-engine novel
// nodes, state-root plans, tx hashing).
void phant_keccak256_ptrs_fast(const uint8_t* const* ptrs,
                               const uint32_t* lens, size_t n,
                               uint8_t* out) {
#if defined(__x86_64__)
  if (have_avx512() && n >= 8) {
    // order by chunk count so grouped lanes retire together (stable:
    // counting sort over the small chunk range, overflow bucket for
    // oversized payloads)
    constexpr size_t kMaxBucket = 32;
    static thread_local std::vector<uint32_t> order;
    order.resize(n);
    size_t counts[kMaxBucket + 1] = {0};
    for (size_t i = 0; i < n; ++i) {
      size_t ch = lens[i] / kRate + 1;
      ++counts[ch < kMaxBucket ? ch : kMaxBucket];
    }
    size_t start[kMaxBucket + 1], acc = 0;
    for (size_t b = 0; b <= kMaxBucket; ++b) {
      start[b] = acc;
      acc += counts[b];
    }
    for (size_t i = 0; i < n; ++i) {
      size_t ch = lens[i] / kRate + 1;
      order[start[ch < kMaxBucket ? ch : kMaxBucket]++] = (uint32_t)i;
    }
    size_t g = 0;
    for (; g + 8 <= n; g += 8) {
      const uint8_t* p8[8];
      size_t lens8[8];
      uint8_t* outs[8];
      for (int m = 0; m < 8; ++m) {
        const uint32_t i = order[g + m];
        p8[m] = ptrs[i];
        lens8[m] = lens[i];
        outs[m] = out + 32 * i;
      }
      keccak256_x8(p8, lens8, outs);
    }
    for (; g < n; ++g) {
      const uint32_t i = order[g];
      keccak256_one(ptrs[i], lens[i], out + 32 * i);
    }
    return;
  }
#endif
  for (size_t i = 0; i < n; ++i) keccak256_one(ptrs[i], lens[i], out + 32 * i);
}

// Contiguous-blob adapter over the ptrs variant (the ctypes interface).
void phant_keccak256_batch_fast(const uint8_t* in, const uint64_t* offsets,
                                const uint32_t* lens, size_t n,
                                uint8_t* out) {
  static thread_local std::vector<const uint8_t*> ptrs;
  ptrs.resize(n);
  for (size_t i = 0; i < n; ++i) ptrs[i] = in + offsets[i];
  phant_keccak256_ptrs_fast(ptrs.data(), lens, n, out);
}

}  // extern "C"
