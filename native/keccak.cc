// Keccak-256 (Ethereum variant: 0x01 domain padding) — native CPU hot path.
//
// The reference client gets its native keccak from the ethash submodule's
// C implementation (reference: build.zig:94, ethash/lib/keccak/keccak.c) and
// Zig std's Keccak256 on the client side (reference: src/crypto/hasher.zig:1).
// This is a from-scratch C++ implementation exposing a C ABI consumed via
// ctypes (phant_tpu/utils/native.py) — it is the CPU baseline the TPU Pallas
// kernel (phant_tpu/ops/keccak_jax.py) is benchmarked against.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr uint64_t kRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

// Rotation offsets for lane A[x + 5y].
constexpr int kRot[25] = {
    0,  1,  62, 28, 27,   // y = 0
    36, 44, 6,  55, 20,   // y = 1
    3,  10, 43, 25, 39,   // y = 2
    41, 45, 15, 21, 8,    // y = 3
    18, 2,  61, 56, 14,   // y = 4
};

inline uint64_t rotl(uint64_t v, int s) {
  return s == 0 ? v : (v << s) | (v >> (64 - s));
}

void keccak_f1600(uint64_t a[25]) {
  uint64_t b[25];
  uint64_t c[5], d[5];
  for (int rnd = 0; rnd < 24; ++rnd) {
    // theta
    for (int x = 0; x < 5; ++x)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; ++x)
      d[x] = c[(x + 4) % 5] ^ rotl(c[(x + 1) % 5], 1);
    for (int i = 0; i < 25; ++i) a[i] ^= d[i % 5];
    // rho + pi: B[y + 5*((2x+3y)%5)] = rotl(A[x + 5y])
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl(a[x + 5 * y], kRot[x + 5 * y]);
    // chi
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        a[x + 5 * y] =
            b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y]);
    // iota
    a[0] ^= kRC[rnd];
  }
}

constexpr size_t kRate = 136;

void keccak256_one(const uint8_t* in, size_t len, uint8_t* out) {
  uint64_t state[25];
  std::memset(state, 0, sizeof(state));
  // absorb full blocks
  while (len >= kRate) {
    for (size_t i = 0; i < kRate / 8; ++i) {
      uint64_t lane;
      std::memcpy(&lane, in + 8 * i, 8);  // little-endian hosts only
      state[i] ^= lane;
    }
    keccak_f1600(state);
    in += kRate;
    len -= kRate;
  }
  // final (padded) block
  uint8_t block[kRate];
  std::memset(block, 0, sizeof(block));
  if (len != 0) {  // memcpy from a null `in` is UB even at length 0
    std::memcpy(block, in, len);
  }
  block[len] ^= 0x01;
  block[kRate - 1] ^= 0x80;
  for (size_t i = 0; i < kRate / 8; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    state[i] ^= lane;
  }
  keccak_f1600(state);
  std::memcpy(out, state, 32);
}

}  // namespace

extern "C" {

void phant_keccak256(const uint8_t* in, size_t len, uint8_t* out) {
  keccak256_one(in, len, out);
}

// Batched: payload i is in[offsets[i] .. offsets[i] + lens[i]); out is n*32B.
void phant_keccak256_batch(const uint8_t* in, const uint64_t* offsets,
                           const uint32_t* lens, size_t n, uint8_t* out) {
  for (size_t i = 0; i < n; ++i) {
    keccak256_one(in + offsets[i], lens[i], out + 32 * i);
  }
}

}  // extern "C"
