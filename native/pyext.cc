// CPython extension driver for the native witness-engine core
// (native/engine.cc). The ctypes interface hands the core contiguous
// numpy buffers, which costs a b"".join + fromiter per batch (~30us/block
// at mainnet witness shapes — half the steady-state budget). This module
// walks the witness list structure directly with the CPython API and
// feeds the core scattered PyBytes pointers, so the Python side of
// verify_batch is two calls and zero copies.
//
// Two protocols share the walk/commit machinery:
//
// Classic (one batch at a time, mirrors WitnessEngine._verify_native):
//   scan(witnesses)  -> (novel: list[bytes], miss: int, total: int)
//                       witnesses = sequence of (root32, sequence[bytes]);
//                       batch state (node ptrs, rows, block bounds, roots)
//                       is retained on the engine object, and the
//                       witnesses object is INCREF'd so the pointers stay
//                       alive until finish()/the next scan().
//   [caller hashes the novel nodes on its routed backend]
//   finish(digests)  -> bytes verdicts (1 byte per block, 0/1);
//                       digests = b"".join of 32B digests for scan's
//                       novel list, or None when nothing was novel.
//   flush()          -> drop the interned generation (eviction).
//   nodes/digests()  -> interned counts (eviction policy + stats RPC).
//
// Pipelined (WitnessEngine.begin_batch/resolve_batch, PR 5): batch state
// lives in a standalone Batch object so several scanned batches can be
// outstanding at once — batch N+1 scans (executor thread, pack stage)
// while batch N hashes/commits (resolve worker). A node novel in two
// outstanding batches is interned twice (a benign duplicate row: both
// rows carry the same digest refid, so verdicts are unaffected); flushes
// are the caller's responsibility to order around outstanding batches
// (WitnessEngine defers eviction while handles are in flight).
//   scan_begin(witnesses)        -> (Batch, novel, miss, total)
//   finish_batch(Batch, digests) -> verdict bytes
//   finish_batch_native(Batch)   -> verdict bytes (in-C keccak)
//
// The pure-C stages (scan loop, commit, verdict, in-C hashing) release
// the GIL: the whole point of the pipelined protocol is that the resolve
// worker's C time runs concurrently with the executor's Python time.
// Engine-level exclusion of table mutation is WitnessEngine._lock.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

extern "C" {
void* phant_engine_new();
void phant_engine_free(void*);
void phant_engine_flush(void*);
uint64_t phant_engine_nodes(void*);
uint64_t phant_engine_digests(void*);
int phant_engine_scan_ptrs(void*, const uint8_t* const*, const uint32_t*,
                           uint64_t, int64_t*, uint32_t*, uint64_t*);
int64_t phant_engine_commit_ptrs(void*, const uint8_t* const*,
                                 const uint32_t*, uint64_t, int64_t*,
                                 const uint32_t*, uint64_t, const uint8_t*);
int64_t phant_engine_commit_hash_ptrs(void*, const uint8_t* const*,
                                      const uint32_t*, uint64_t, int64_t*,
                                      const uint32_t*, uint64_t);
int phant_engine_verdict(void*, const int64_t*, const uint64_t*, uint64_t,
                         const uint8_t*, uint8_t*);
void phant_keccak256_ptrs_fast(const uint8_t* const*, const uint32_t*,
                               uint64_t, uint8_t*);
}

namespace {

// One scanned batch: node pointers (pinned via `keep`), scan rows, block
// bounds, roots. Owned inline by the engine (classic protocol) or by a
// Batch object (pipelined protocol).
struct BatchState {
  std::vector<PyObject*> node_objs;  // borrowed (owned via `keep`)
  std::vector<const uint8_t*> ptrs;
  std::vector<uint32_t> lens;
  std::vector<int64_t> rows;
  std::vector<uint32_t> novel_idx;
  std::vector<uint64_t> block_offs;
  std::vector<uint8_t> roots;
  std::vector<uint8_t> digests;  // 32B/novel, filled by hash_batch()
  uint64_t n_novel = 0;
  PyObject* keep = nullptr;  // the witnesses object (pins node bytes)
};

void batch_clear(BatchState* bs) {
  bs->n_novel = 0;
  Py_CLEAR(bs->keep);
}

struct EngineObject {
  PyObject_HEAD
  void* eng;
  BatchState* batch;  // classic-protocol slot, valid between scan/finish
  int have_batch;
};

void Engine_dealloc(EngineObject* self) {
  if (self->eng) phant_engine_free(self->eng);
  if (self->batch) {
    batch_clear(self->batch);
    delete self->batch;
  }
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* Engine_new(PyTypeObject* type, PyObject*, PyObject*) {
  EngineObject* self =
      reinterpret_cast<EngineObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->eng = phant_engine_new();
  self->batch = new BatchState();
  self->have_batch = 0;
  return reinterpret_cast<PyObject*>(self);
}

void clear_batch(EngineObject* self) {
  self->have_batch = 0;
  batch_clear(self->batch);
}

// Walk `witnesses` into `bs` (ptrs/lens/block_offs/roots + keep), run the
// C hit-scan, and build the novel list. Returns the (novel, miss, total)
// tuple, or nullptr with an exception set (bs left cleared).
PyObject* scan_into(EngineObject* self, PyObject* witnesses, BatchState* bs) {
  batch_clear(bs);
  // `keep` pins every container whose items back a stored pointer: the
  // materialized outer sequence plus each block's materialized node
  // sequence (PySequence_Fast returns the list/tuple itself, or a fresh
  // list for lazy inputs — either way it owns the bytes objects).
  PyObject* keep = PyList_New(0);
  if (!keep) return nullptr;
  PyObject* wseq = PySequence_Fast(witnesses, "witnesses must be a sequence");
  if (!wseq || PyList_Append(keep, wseq) < 0) {
    Py_XDECREF(wseq);
    Py_DECREF(keep);
    return nullptr;
  }
  Py_DECREF(wseq);  // owned by `keep` now
  const Py_ssize_t n_blocks = PySequence_Fast_GET_SIZE(wseq);
  auto& ptrs = bs->ptrs;
  auto& node_objs = bs->node_objs;
  auto& lens = bs->lens;
  auto& boffs = bs->block_offs;
  auto& roots = bs->roots;
  ptrs.clear();
  node_objs.clear();
  lens.clear();
  boffs.clear();
  roots.clear();
  boffs.push_back(0);
  roots.reserve(32 * n_blocks);
  for (Py_ssize_t b = 0; b < n_blocks; ++b) {
    PyObject* pair = PySequence_Fast_GET_ITEM(wseq, b);  // borrowed
    PyObject* root_obj;
    PyObject* nodes_obj;
    PyObject* p2 = nullptr;
    if (PyTuple_Check(pair) && PyTuple_GET_SIZE(pair) == 2) {
      root_obj = PyTuple_GET_ITEM(pair, 0);
      nodes_obj = PyTuple_GET_ITEM(pair, 1);
    } else {
      p2 = PySequence_Fast(pair, "witness must be (root, nodes)");
      if (!p2 || PySequence_Fast_GET_SIZE(p2) != 2 ||
          PyList_Append(keep, p2) < 0) {
        Py_XDECREF(p2);
        Py_DECREF(keep);
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_ValueError, "witness must be (root, nodes)");
        return nullptr;
      }
      root_obj = PySequence_Fast_GET_ITEM(p2, 0);
      nodes_obj = PySequence_Fast_GET_ITEM(p2, 1);
      Py_DECREF(p2);  // owned by `keep`
    }
    char* rbuf;
    Py_ssize_t rlen;
    if (PyBytes_AsStringAndSize(root_obj, &rbuf, &rlen) < 0 || rlen != 32) {
      Py_DECREF(keep);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "root must be 32 bytes");
      return nullptr;
    }
    roots.insert(roots.end(), rbuf, rbuf + 32);
    PyObject* nseq = PySequence_Fast(nodes_obj, "nodes must be a sequence");
    if (!nseq || PyList_Append(keep, nseq) < 0) {
      Py_XDECREF(nseq);
      Py_DECREF(keep);
      return nullptr;
    }
    Py_DECREF(nseq);  // owned by `keep`
    const Py_ssize_t n_nodes = PySequence_Fast_GET_SIZE(nseq);
    for (Py_ssize_t i = 0; i < n_nodes; ++i) {
      PyObject* node = PySequence_Fast_GET_ITEM(nseq, i);  // borrowed
      char* buf;
      Py_ssize_t blen;
      if (PyBytes_AsStringAndSize(node, &buf, &blen) < 0) {
        Py_DECREF(keep);
        return nullptr;
      }
      ptrs.push_back(reinterpret_cast<const uint8_t*>(buf));
      node_objs.push_back(node);  // borrowed; pinned via `keep`
      lens.push_back(static_cast<uint32_t>(blen));
    }
    boffs.push_back(ptrs.size());
  }
  // roots vector backs the verdict call; node ptrs live until finish
  bs->keep = keep;

  const uint64_t n = ptrs.size();
  bs->rows.resize(n);
  bs->novel_idx.resize(n ? n : 1);
  uint64_t counts[2] = {0, 0};
  // pure C from here: the scan loop touches only the pinned buffers
  Py_BEGIN_ALLOW_THREADS
  phant_engine_scan_ptrs(self->eng, ptrs.data(), lens.data(), n,
                         bs->rows.data(), bs->novel_idx.data(), counts);
  Py_END_ALLOW_THREADS
  bs->n_novel = counts[1];

  // the novel list shares the existing bytes objects (no copies) — they
  // are alive via `keep` and the INCREF here
  PyObject* novel = PyList_New(static_cast<Py_ssize_t>(counts[1]));
  if (!novel) {
    batch_clear(bs);  // don't leave a half-built batch retained on OOM
    return nullptr;
  }
  for (uint64_t k = 0; k < counts[1]; ++k) {
    PyObject* nb = node_objs[bs->novel_idx[k]];
    Py_INCREF(nb);
    PyList_SET_ITEM(novel, static_cast<Py_ssize_t>(k), nb);
  }
  PyObject* ret = Py_BuildValue("(NKK)", novel, (unsigned long long)counts[0],
                                (unsigned long long)n);
  if (!ret) {
    // "N" args are consumed by Py_BuildValue even on failure (CPython
    // modsupport.c releases them so they don't leak) — only the batch
    // state needs unwinding here, a DECREF would double-release `novel`
    batch_clear(bs);
  }
  return ret;
}

// scan(witnesses) -> (novel list, miss, total) — classic protocol
PyObject* Engine_scan(EngineObject* self, PyObject* witnesses) {
  clear_batch(self);
  PyObject* ret = scan_into(self, witnesses, self->batch);
  if (ret) self->have_batch = 1;
  return ret;
}

// Per-block verdicts over a batch state (GIL released around the C join).
PyObject* batch_verdict(EngineObject* self, BatchState* bs) {
  const uint64_t n_blocks = bs->block_offs.size() - 1;
  PyObject* out = PyBytes_FromStringAndSize(nullptr,
                                            static_cast<Py_ssize_t>(n_blocks));
  if (!out) return nullptr;
  uint8_t* obuf = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  Py_BEGIN_ALLOW_THREADS
  phant_engine_verdict(self->eng, bs->rows.data(), bs->block_offs.data(),
                       n_blocks, bs->roots.data(), obuf);
  Py_END_ALLOW_THREADS
  return out;
}

// Shared tail of both classic finish paths: verdicts + batch reset.
PyObject* verdict_and_clear(EngineObject* self) {
  PyObject* out = batch_verdict(self, self->batch);
  clear_batch(self);
  return out;
}

// Commit a batch's novel nodes with caller digests (GIL released).
// Returns 0, or -1 with an exception set.
int batch_commit(EngineObject* self, BatchState* bs, PyObject* digests_obj) {
  if (!bs->n_novel) return 0;
  char* dbuf;
  Py_ssize_t dlen;
  if (digests_obj == Py_None ||
      PyBytes_AsStringAndSize(digests_obj, &dbuf, &dlen) < 0) {
    if (!PyErr_Occurred())
      PyErr_SetString(PyExc_ValueError, "novel nodes need digests");
    return -1;
  }
  if (static_cast<uint64_t>(dlen) != 32 * bs->n_novel) {
    PyErr_SetString(PyExc_ValueError, "digests must be 32B per novel node");
    return -1;
  }
  Py_BEGIN_ALLOW_THREADS
  phant_engine_commit_ptrs(self->eng, bs->ptrs.data(), bs->lens.data(),
                           bs->ptrs.size(), bs->rows.data(),
                           bs->novel_idx.data(), bs->n_novel,
                           reinterpret_cast<const uint8_t*>(dbuf));
  Py_END_ALLOW_THREADS
  return 0;
}

// Commit with in-C keccak of the novel nodes (GIL released: the commit
// touches only raw pointers pinned by `keep` — a big novel batch, tens of
// MB of keccak at startup/post-eviction, must not stall the Engine API's
// other serving threads).
void batch_commit_native(EngineObject* self, BatchState* bs) {
  if (!bs->n_novel) return;
  Py_BEGIN_ALLOW_THREADS
  phant_engine_commit_hash_ptrs(self->eng, bs->ptrs.data(), bs->lens.data(),
                                bs->ptrs.size(), bs->rows.data(),
                                bs->novel_idx.data(), bs->n_novel);
  Py_END_ALLOW_THREADS
}

// finish_native() -> verdict bytes; novel nodes are hashed IN C through
// the fast keccak batch — the zero-Python-round-trip path the engine
// takes when the routed hashing backend is the host.
PyObject* Engine_finish_native(EngineObject* self, PyObject*) {
  if (!self->have_batch) {
    PyErr_SetString(PyExc_RuntimeError, "finish_native() without a batch");
    return nullptr;
  }
  batch_commit_native(self, self->batch);
  return verdict_and_clear(self);
}

// finish(digests_or_None) -> verdict bytes (one 0/1 byte per block)
PyObject* Engine_finish(EngineObject* self, PyObject* digests_obj) {
  if (!self->have_batch) {
    PyErr_SetString(PyExc_RuntimeError, "finish() without a scanned batch");
    return nullptr;
  }
  if (batch_commit(self, self->batch, digests_obj) < 0) return nullptr;
  return verdict_and_clear(self);
}

// --- pipelined protocol ----------------------------------------------------

extern PyTypeObject BatchType;

struct BatchObject {
  PyObject_HEAD
  EngineObject* owner;  // strong ref: a live batch pins its engine
  BatchState* bs;
  int finished;
};

void Batch_dealloc(BatchObject* self) {
  if (self->bs) {
    batch_clear(self->bs);
    delete self->bs;
  }
  Py_CLEAR(self->owner);
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* Batch_n_novel(BatchObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(self->bs ? self->bs->n_novel : 0);
}

PyMethodDef Batch_methods[] = {
    {"n_novel", reinterpret_cast<PyCFunction>(Batch_n_novel), METH_NOARGS,
     "novel first occurrences in this batch"},
    {nullptr, nullptr, 0, nullptr},
};

// scan_begin(witnesses) -> (Batch, novel, miss, total). Unlike scan(),
// the batch state lives in the returned Batch object, so any number of
// scanned batches can be outstanding (pipelining). Batches may finish in
// ANY order — the tables are append-only, rows encode their own novel
// indices, and a node novel in two outstanding batches commits a benign
// duplicate row whichever lands first.
PyObject* Engine_scan_begin(EngineObject* self, PyObject* witnesses) {
  BatchObject* batch = PyObject_New(BatchObject, &BatchType);
  if (!batch) return nullptr;
  Py_INCREF(self);
  batch->owner = self;
  batch->bs = new BatchState();
  batch->finished = 0;
  PyObject* scanned = scan_into(self, witnesses, batch->bs);
  if (!scanned) {
    Py_DECREF(batch);
    return nullptr;
  }
  // (novel, miss, total) -> (Batch, novel, miss, total)
  PyObject* ret = PyTuple_New(4);
  if (!ret) {
    Py_DECREF(batch);
    Py_DECREF(scanned);
    return nullptr;
  }
  PyTuple_SET_ITEM(ret, 0, reinterpret_cast<PyObject*>(batch));
  for (int i = 0; i < 3; ++i) {
    PyObject* item = PyTuple_GET_ITEM(scanned, i);
    Py_INCREF(item);
    PyTuple_SET_ITEM(ret, i + 1, item);
  }
  Py_DECREF(scanned);
  return ret;
}

BatchObject* checked_batch(EngineObject* self, PyObject* arg) {
  if (!PyObject_TypeCheck(arg, &BatchType)) {
    PyErr_SetString(PyExc_TypeError, "expected a Batch from scan_begin()");
    return nullptr;
  }
  BatchObject* batch = reinterpret_cast<BatchObject*>(arg);
  if (batch->owner != self) {
    PyErr_SetString(PyExc_ValueError, "batch belongs to a different engine");
    return nullptr;
  }
  if (batch->finished) {
    PyErr_SetString(PyExc_RuntimeError, "batch already finished");
    return nullptr;
  }
  return batch;
}

PyObject* batch_finish_tail(BatchObject* batch, PyObject* out) {
  batch->finished = 1;
  batch_clear(batch->bs);  // release the pinned witnesses promptly
  return out;
}

// hash_batch(batch): keccak the batch's novel nodes into batch-local
// digest storage — touches NO engine table, so callers run it WITHOUT
// the engine lock (GIL released too): the resolve worker hashes batch N
// here while the executor's scan_begin(N+1) probes the tables under the
// lock. finish_batch(batch, None) then commits with the stored digests.
PyObject* Engine_hash_batch(EngineObject* self, PyObject* arg) {
  BatchObject* batch = checked_batch(self, arg);
  if (!batch) return nullptr;
  BatchState* bs = batch->bs;
  if (bs->n_novel) {
    bs->digests.resize(32 * bs->n_novel);
    // batch-local ptr/len scratch (the Engine's scratch vectors belong
    // to lock-holding calls; this one deliberately runs outside it)
    std::vector<const uint8_t*> nptrs(bs->n_novel);
    std::vector<uint32_t> nlens(bs->n_novel);
    for (uint64_t k = 0; k < bs->n_novel; ++k) {
      nptrs[k] = bs->ptrs[bs->novel_idx[k]];
      nlens[k] = bs->lens[bs->novel_idx[k]];
    }
    Py_BEGIN_ALLOW_THREADS
    phant_keccak256_ptrs_fast(nptrs.data(), nlens.data(), bs->n_novel,
                              bs->digests.data());
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

// finish_batch(batch, digests_or_None) -> verdict bytes. None is valid
// when the batch had no novel nodes OR hash_batch() already filled the
// batch-local digests.
PyObject* Engine_finish_batch(EngineObject* self, PyObject* args) {
  PyObject* batch_obj;
  PyObject* digests_obj;
  if (!PyArg_ParseTuple(args, "OO", &batch_obj, &digests_obj)) return nullptr;
  BatchObject* batch = checked_batch(self, batch_obj);
  if (!batch) return nullptr;
  BatchState* bs = batch->bs;
  if (digests_obj == Py_None && bs->n_novel &&
      bs->digests.size() == 32 * bs->n_novel) {
    Py_BEGIN_ALLOW_THREADS
    phant_engine_commit_ptrs(self->eng, bs->ptrs.data(), bs->lens.data(),
                             bs->ptrs.size(), bs->rows.data(),
                             bs->novel_idx.data(), bs->n_novel,
                             bs->digests.data());
    Py_END_ALLOW_THREADS
  } else if (batch_commit(self, bs, digests_obj) < 0) {
    return nullptr;
  }
  return batch_finish_tail(batch, batch_verdict(self, batch->bs));
}

// finish_batch_native(batch) -> verdict bytes (in-C keccak of the novels)
PyObject* Engine_finish_batch_native(EngineObject* self, PyObject* arg) {
  BatchObject* batch = checked_batch(self, arg);
  if (!batch) return nullptr;
  batch_commit_native(self, batch->bs);
  return batch_finish_tail(batch, batch_verdict(self, batch->bs));
}

PyObject* Engine_flush(EngineObject* self, PyObject*) {
  clear_batch(self);
  phant_engine_flush(self->eng);
  Py_RETURN_NONE;
}

PyObject* Engine_nodes(EngineObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(phant_engine_nodes(self->eng));
}

PyObject* Engine_digests(EngineObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(phant_engine_digests(self->eng));
}

PyMethodDef Engine_methods[] = {
    {"scan", reinterpret_cast<PyCFunction>(Engine_scan), METH_O,
     "scan(witnesses) -> (novel, miss, total)"},
    {"finish", reinterpret_cast<PyCFunction>(Engine_finish), METH_O,
     "finish(digests|None) -> verdict bytes"},
    {"finish_native", reinterpret_cast<PyCFunction>(Engine_finish_native),
     METH_NOARGS, "finish with in-C keccak of the novel nodes"},
    {"scan_begin", reinterpret_cast<PyCFunction>(Engine_scan_begin), METH_O,
     "scan_begin(witnesses) -> (Batch, novel, miss, total)"},
    {"hash_batch", reinterpret_cast<PyCFunction>(Engine_hash_batch), METH_O,
     "keccak the batch's novel nodes into batch-local digests (no "
     "engine-table access: safe without the engine lock)"},
    {"finish_batch", reinterpret_cast<PyCFunction>(Engine_finish_batch),
     METH_VARARGS, "finish_batch(batch, digests|None) -> verdict bytes"},
    {"finish_batch_native",
     reinterpret_cast<PyCFunction>(Engine_finish_batch_native), METH_O,
     "finish_batch(batch) with in-C keccak of the novel nodes"},
    {"flush", reinterpret_cast<PyCFunction>(Engine_flush), METH_NOARGS,
     "drop the interned generation"},
    {"nodes", reinterpret_cast<PyCFunction>(Engine_nodes), METH_NOARGS,
     "interned node count"},
    {"digests", reinterpret_cast<PyCFunction>(Engine_digests), METH_NOARGS,
     "interned digest count"},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject EngineType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "phant_engine_ext.Engine",           /* tp_name */
    sizeof(EngineObject),                /* tp_basicsize */
};

PyTypeObject BatchType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "phant_engine_ext.Batch",            /* tp_name */
    sizeof(BatchObject),                 /* tp_basicsize */
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT,
    "phant_engine_ext",
    "CPython driver for the native witness-engine core",
    -1,
};

}  // namespace

extern "C" PyObject* PyInit_phant_engine_ext() {
  EngineType.tp_dealloc = reinterpret_cast<destructor>(Engine_dealloc);
  EngineType.tp_flags = Py_TPFLAGS_DEFAULT;
  EngineType.tp_methods = Engine_methods;
  EngineType.tp_new = Engine_new;
  if (PyType_Ready(&EngineType) < 0) return nullptr;
  BatchType.tp_dealloc = reinterpret_cast<destructor>(Batch_dealloc);
  BatchType.tp_flags = Py_TPFLAGS_DEFAULT;
  BatchType.tp_methods = Batch_methods;
  // Batch objects are created only by scan_begin(); no tp_new exposed
  if (PyType_Ready(&BatchType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&moduledef);
  if (!m) return nullptr;
  Py_INCREF(&EngineType);
  if (PyModule_AddObject(m, "Engine",
                         reinterpret_cast<PyObject*>(&EngineType)) < 0) {
    Py_DECREF(&EngineType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
