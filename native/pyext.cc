// CPython extension driver for the native witness-engine core
// (native/engine.cc). The ctypes interface hands the core contiguous
// numpy buffers, which costs a b"".join + fromiter per batch (~30us/block
// at mainnet witness shapes — half the steady-state budget). This module
// walks the witness list structure directly with the CPython API and
// feeds the core scattered PyBytes pointers, so the Python side of
// verify_batch is two calls and zero copies.
//
// Protocol (mirrors ops/witness_engine.WitnessEngine._verify_native):
//   scan(witnesses)  -> (novel: list[bytes], miss: int, total: int)
//                       witnesses = sequence of (root32, sequence[bytes]);
//                       batch state (node ptrs, rows, block bounds, roots)
//                       is retained on the engine object, and the
//                       witnesses object is INCREF'd so the pointers stay
//                       alive until finish()/the next scan().
//   [caller hashes the novel nodes on its routed backend]
//   finish(digests)  -> bytes verdicts (1 byte per block, 0/1);
//                       digests = b"".join of 32B digests for scan's
//                       novel list, or None when nothing was novel.
//   flush()          -> drop the interned generation (eviction).
//   nodes/digests()  -> interned counts (eviction policy + stats RPC).
//
// Everything runs under the GIL — the engine is driven under
// WitnessEngine's lock anyway, and each call is microseconds-scale.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

extern "C" {
void* phant_engine_new();
void phant_engine_free(void*);
void phant_engine_flush(void*);
uint64_t phant_engine_nodes(void*);
uint64_t phant_engine_digests(void*);
int phant_engine_scan_ptrs(void*, const uint8_t* const*, const uint32_t*,
                           uint64_t, int64_t*, uint32_t*, uint64_t*);
int64_t phant_engine_commit_ptrs(void*, const uint8_t* const*,
                                 const uint32_t*, uint64_t, int64_t*,
                                 const uint32_t*, uint64_t, const uint8_t*);
int64_t phant_engine_commit_hash_ptrs(void*, const uint8_t* const*,
                                      const uint32_t*, uint64_t, int64_t*,
                                      const uint32_t*, uint64_t);
int phant_engine_verdict(void*, const int64_t*, const uint64_t*, uint64_t,
                         const uint8_t*, uint8_t*);
}

namespace {

struct EngineObject {
  PyObject_HEAD
  void* eng;
  // batch state, valid between scan() and finish()
  std::vector<PyObject*>* node_objs;  // borrowed (owned via `keep`)
  std::vector<const uint8_t*>* ptrs;
  std::vector<uint32_t>* lens;
  std::vector<int64_t>* rows;
  std::vector<uint32_t>* novel_idx;
  std::vector<uint64_t>* block_offs;
  std::vector<uint8_t>* roots;
  uint64_t n_novel;
  int have_batch;
  PyObject* keep;  // the witnesses object (pins every node's bytes)
};

void Engine_dealloc(EngineObject* self) {
  if (self->eng) phant_engine_free(self->eng);
  delete self->node_objs;
  delete self->ptrs;
  delete self->lens;
  delete self->rows;
  delete self->novel_idx;
  delete self->block_offs;
  delete self->roots;
  Py_CLEAR(self->keep);
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

PyObject* Engine_new(PyTypeObject* type, PyObject*, PyObject*) {
  EngineObject* self =
      reinterpret_cast<EngineObject*>(type->tp_alloc(type, 0));
  if (!self) return nullptr;
  self->eng = phant_engine_new();
  self->node_objs = new std::vector<PyObject*>();
  self->ptrs = new std::vector<const uint8_t*>();
  self->lens = new std::vector<uint32_t>();
  self->rows = new std::vector<int64_t>();
  self->novel_idx = new std::vector<uint32_t>();
  self->block_offs = new std::vector<uint64_t>();
  self->roots = new std::vector<uint8_t>();
  self->n_novel = 0;
  self->have_batch = 0;
  self->keep = nullptr;
  return reinterpret_cast<PyObject*>(self);
}

void clear_batch(EngineObject* self) {
  self->have_batch = 0;
  self->n_novel = 0;
  Py_CLEAR(self->keep);
}

// scan(witnesses) -> (novel list, miss, total)
PyObject* Engine_scan(EngineObject* self, PyObject* witnesses) {
  clear_batch(self);
  // `keep` pins every container whose items back a stored pointer: the
  // materialized outer sequence plus each block's materialized node
  // sequence (PySequence_Fast returns the list/tuple itself, or a fresh
  // list for lazy inputs — either way it owns the bytes objects).
  PyObject* keep = PyList_New(0);
  if (!keep) return nullptr;
  PyObject* wseq = PySequence_Fast(witnesses, "witnesses must be a sequence");
  if (!wseq || PyList_Append(keep, wseq) < 0) {
    Py_XDECREF(wseq);
    Py_DECREF(keep);
    return nullptr;
  }
  Py_DECREF(wseq);  // owned by `keep` now
  const Py_ssize_t n_blocks = PySequence_Fast_GET_SIZE(wseq);
  auto& ptrs = *self->ptrs;
  auto& node_objs = *self->node_objs;
  auto& lens = *self->lens;
  auto& boffs = *self->block_offs;
  auto& roots = *self->roots;
  ptrs.clear();
  node_objs.clear();
  lens.clear();
  boffs.clear();
  roots.clear();
  boffs.push_back(0);
  roots.reserve(32 * n_blocks);
  for (Py_ssize_t b = 0; b < n_blocks; ++b) {
    PyObject* pair = PySequence_Fast_GET_ITEM(wseq, b);  // borrowed
    PyObject* root_obj;
    PyObject* nodes_obj;
    PyObject* p2 = nullptr;
    if (PyTuple_Check(pair) && PyTuple_GET_SIZE(pair) == 2) {
      root_obj = PyTuple_GET_ITEM(pair, 0);
      nodes_obj = PyTuple_GET_ITEM(pair, 1);
    } else {
      p2 = PySequence_Fast(pair, "witness must be (root, nodes)");
      if (!p2 || PySequence_Fast_GET_SIZE(p2) != 2 ||
          PyList_Append(keep, p2) < 0) {
        Py_XDECREF(p2);
        Py_DECREF(keep);
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_ValueError, "witness must be (root, nodes)");
        return nullptr;
      }
      root_obj = PySequence_Fast_GET_ITEM(p2, 0);
      nodes_obj = PySequence_Fast_GET_ITEM(p2, 1);
      Py_DECREF(p2);  // owned by `keep`
    }
    char* rbuf;
    Py_ssize_t rlen;
    if (PyBytes_AsStringAndSize(root_obj, &rbuf, &rlen) < 0 || rlen != 32) {
      Py_DECREF(keep);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "root must be 32 bytes");
      return nullptr;
    }
    roots.insert(roots.end(), rbuf, rbuf + 32);
    PyObject* nseq = PySequence_Fast(nodes_obj, "nodes must be a sequence");
    if (!nseq || PyList_Append(keep, nseq) < 0) {
      Py_XDECREF(nseq);
      Py_DECREF(keep);
      return nullptr;
    }
    Py_DECREF(nseq);  // owned by `keep`
    const Py_ssize_t n_nodes = PySequence_Fast_GET_SIZE(nseq);
    for (Py_ssize_t i = 0; i < n_nodes; ++i) {
      PyObject* node = PySequence_Fast_GET_ITEM(nseq, i);  // borrowed
      char* buf;
      Py_ssize_t blen;
      if (PyBytes_AsStringAndSize(node, &buf, &blen) < 0) {
        Py_DECREF(keep);
        return nullptr;
      }
      ptrs.push_back(reinterpret_cast<const uint8_t*>(buf));
      node_objs.push_back(node);  // borrowed; pinned via `keep`
      lens.push_back(static_cast<uint32_t>(blen));
    }
    boffs.push_back(ptrs.size());
  }
  // roots vector backs the verdict call; node ptrs live until finish()
  self->keep = keep;

  const uint64_t n = ptrs.size();
  self->rows->resize(n);
  self->novel_idx->resize(n ? n : 1);
  uint64_t counts[2] = {0, 0};
  phant_engine_scan_ptrs(self->eng, ptrs.data(), lens.data(), n,
                         self->rows->data(), self->novel_idx->data(), counts);
  self->n_novel = counts[1];
  self->have_batch = 1;

  // the novel list shares the existing bytes objects (no copies) — they
  // are alive via `keep` and the INCREF here
  PyObject* novel = PyList_New(static_cast<Py_ssize_t>(counts[1]));
  if (!novel) {
    clear_batch(self);  // don't leave a half-built batch retained on OOM
    return nullptr;
  }
  for (uint64_t k = 0; k < counts[1]; ++k) {
    PyObject* nb = node_objs[(*self->novel_idx)[k]];
    Py_INCREF(nb);
    PyList_SET_ITEM(novel, static_cast<Py_ssize_t>(k), nb);
  }
  PyObject* ret = Py_BuildValue("(NKK)", novel, (unsigned long long)counts[0],
                                (unsigned long long)n);
  if (!ret) {
    // "N" args are consumed by Py_BuildValue even on failure (CPython
    // modsupport.c releases them so they don't leak) — only the batch
    // state needs unwinding here, a DECREF would double-release `novel`
    clear_batch(self);
  }
  return ret;
}

// Shared tail of both finish paths: per-block verdicts + batch reset.
PyObject* verdict_and_clear(EngineObject* self) {
  const uint64_t n_blocks = self->block_offs->size() - 1;
  PyObject* out = PyBytes_FromStringAndSize(nullptr,
                                            static_cast<Py_ssize_t>(n_blocks));
  if (!out) return nullptr;
  phant_engine_verdict(self->eng, self->rows->data(),
                       self->block_offs->data(), n_blocks,
                       self->roots->data(),
                       reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out)));
  clear_batch(self);
  return out;
}

// finish_native() -> verdict bytes; novel nodes are hashed IN C through
// the fast keccak batch — the zero-Python-round-trip path the engine
// takes when the routed hashing backend is the host.
PyObject* Engine_finish_native(EngineObject* self, PyObject*) {
  if (!self->have_batch) {
    PyErr_SetString(PyExc_RuntimeError, "finish_native() without a batch");
    return nullptr;
  }
  if (self->n_novel) {
    // the commit touches only raw pointers pinned by `keep` — release
    // the GIL so a big novel batch (startup / post-eviction: tens of MB
    // of keccak) does not stall the Engine API's other serving threads
    // (engine-level exclusion is WitnessEngine._lock, already held)
    Py_BEGIN_ALLOW_THREADS
    phant_engine_commit_hash_ptrs(self->eng, self->ptrs->data(),
                                  self->lens->data(), self->ptrs->size(),
                                  self->rows->data(),
                                  self->novel_idx->data(), self->n_novel);
    Py_END_ALLOW_THREADS
  }
  return verdict_and_clear(self);
}

// finish(digests_or_None) -> verdict bytes (one 0/1 byte per block)
PyObject* Engine_finish(EngineObject* self, PyObject* digests_obj) {
  if (!self->have_batch) {
    PyErr_SetString(PyExc_RuntimeError, "finish() without a scanned batch");
    return nullptr;
  }
  if (self->n_novel) {
    char* dbuf;
    Py_ssize_t dlen;
    if (digests_obj == Py_None ||
        PyBytes_AsStringAndSize(digests_obj, &dbuf, &dlen) < 0) {
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "novel nodes need digests");
      return nullptr;
    }
    if (static_cast<uint64_t>(dlen) != 32 * self->n_novel) {
      PyErr_SetString(PyExc_ValueError, "digests must be 32B per novel node");
      return nullptr;
    }
    phant_engine_commit_ptrs(self->eng, self->ptrs->data(),
                             self->lens->data(), self->ptrs->size(),
                             self->rows->data(), self->novel_idx->data(),
                             self->n_novel,
                             reinterpret_cast<const uint8_t*>(dbuf));
  }
  return verdict_and_clear(self);
}

PyObject* Engine_flush(EngineObject* self, PyObject*) {
  clear_batch(self);
  phant_engine_flush(self->eng);
  Py_RETURN_NONE;
}

PyObject* Engine_nodes(EngineObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(phant_engine_nodes(self->eng));
}

PyObject* Engine_digests(EngineObject* self, PyObject*) {
  return PyLong_FromUnsignedLongLong(phant_engine_digests(self->eng));
}

PyMethodDef Engine_methods[] = {
    {"scan", reinterpret_cast<PyCFunction>(Engine_scan), METH_O,
     "scan(witnesses) -> (novel, miss, total)"},
    {"finish", reinterpret_cast<PyCFunction>(Engine_finish), METH_O,
     "finish(digests|None) -> verdict bytes"},
    {"finish_native", reinterpret_cast<PyCFunction>(Engine_finish_native),
     METH_NOARGS, "finish with in-C keccak of the novel nodes"},
    {"flush", reinterpret_cast<PyCFunction>(Engine_flush), METH_NOARGS,
     "drop the interned generation"},
    {"nodes", reinterpret_cast<PyCFunction>(Engine_nodes), METH_NOARGS,
     "interned node count"},
    {"digests", reinterpret_cast<PyCFunction>(Engine_digests), METH_NOARGS,
     "interned digest count"},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject EngineType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "phant_engine_ext.Engine",           /* tp_name */
    sizeof(EngineObject),                /* tp_basicsize */
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT,
    "phant_engine_ext",
    "CPython driver for the native witness-engine core",
    -1,
};

}  // namespace

extern "C" PyObject* PyInit_phant_engine_ext() {
  EngineType.tp_dealloc = reinterpret_cast<destructor>(Engine_dealloc);
  EngineType.tp_flags = Py_TPFLAGS_DEFAULT;
  EngineType.tp_methods = Engine_methods;
  EngineType.tp_new = Engine_new;
  if (PyType_Ready(&EngineType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&moduledef);
  if (!m) return nullptr;
  Py_INCREF(&EngineType);
  if (PyModule_AddObject(m, "Engine",
                         reinterpret_cast<PyObject*>(&EngineType)) < 0) {
    Py_DECREF(&EngineType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
