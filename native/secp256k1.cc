// secp256k1 ECDSA public-key recovery — native CPU hot path.
//
// The reference links bitcoin-core libsecp256k1 through a Zig wrapper
// (reference: build.zig.zon:9-12, src/crypto/ecdsa.zig:10-26). This is a
// from-scratch C++ implementation of exactly the subset the client needs —
// ecrecover (and the point/scalar arithmetic under it) — exposed over a C
// ABI for ctypes. It is the CPU baseline the batched TPU kernel
// (phant_tpu/ops/secp256k1_jax.py) is benchmarked against; both are
// differential-tested against the pure-Python oracle.
//
// Field arithmetic: 5x52-bit limbs would be faster, but 4x64 with __int128
// and fold-based reduction (2^256 ≡ 0x1000003D1 mod p) is simple, branch-
// light, and already ~100x the pure-Python path. Not constant-time —
// consensus verification only ever sees public data.

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" void phant_keccak256(const uint8_t* in, size_t len, uint8_t* out);

namespace {

using u128 = unsigned __int128;

struct U256 {
  uint64_t w[4];  // little-endian limbs
};

constexpr U256 kP = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                      0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
constexpr U256 kN = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                      0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};
constexpr uint64_t kPFold = 0x1000003D1ULL;  // 2^256 - p

constexpr U256 kGx = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                       0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
constexpr U256 kGy = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                       0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

inline bool is_zero(const U256& a) {
  return (a.w[0] | a.w[1] | a.w[2] | a.w[3]) == 0;
}

inline int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

inline U256 sub_raw(const U256& a, const U256& b) {
  U256 r;
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.w[i] - b.w[i] - (uint64_t)borrow;
    r.w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return r;
}

// add with carry-out
inline uint64_t add_raw(const U256& a, const U256& b, U256* r) {
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (u128)a.w[i] + b.w[i];
    r->w[i] = (uint64_t)c;
    c >>= 64;
  }
  return (uint64_t)c;
}

// ---------------------------------------------------------------------------
// F_p arithmetic (fold reduction: 2^256 ≡ kPFold)
// ---------------------------------------------------------------------------

inline U256 p_norm(const U256& a) {  // one conditional subtract
  return cmp(a, kP) >= 0 ? sub_raw(a, kP) : a;
}

inline U256 p_add(const U256& a, const U256& b) {
  U256 r;
  uint64_t c = add_raw(a, b, &r);
  if (c) {  // wrapped past 2^256: add the fold constant
    u128 t = (u128)r.w[0] + kPFold;
    r.w[0] = (uint64_t)t;
    for (int i = 1; i < 4 && (t >>= 64); ++i) {
      t += r.w[i];
      r.w[i] = (uint64_t)t;
    }
  }
  return p_norm(r);
}

inline U256 p_sub(const U256& a, const U256& b) {
  if (cmp(a, b) >= 0) return sub_raw(a, b);
  // a + p - b: the add's carry and the sub's borrow cancel, and the true
  // value fits 256 bits (a < b < p so a + p - b < p), so wrapping is exact
  U256 t;
  add_raw(a, kP, &t);
  return sub_raw(t, b);
}

// schoolbook 512-bit product (shared by the p- and n- multiplies)
inline void mul_wide(const U256& a, const U256& b, uint64_t lo[8]) {
  std::memset(lo, 0, 8 * sizeof(uint64_t));
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      carry += (u128)a.w[i] * b.w[j] + lo[i + j];
      lo[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    lo[i + 4] = (uint64_t)carry;
  }
}

// full 512-bit product then two folds
U256 p_mul(const U256& a, const U256& b) {
  uint64_t lo[8];
  mul_wide(a, b, lo);
  // fold: result = L + H * kPFold  (H < 2^256, kPFold < 2^33 -> < 2^290)
  uint64_t acc[5] = {lo[0], lo[1], lo[2], lo[3], 0};
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (u128)lo[4 + i] * kPFold + acc[i];
    acc[i] = (uint64_t)c;
    c >>= 64;
  }
  acc[4] = (uint64_t)c;
  // second fold of the small overflow limb; the propagation itself can
  // wrap past 2^256 once more (when L + H*kPold lands within
  // acc[4]*kPFold of 2^256), costing one further fold
  U256 r = {{acc[0], acc[1], acc[2], acc[3]}};
  if (acc[4]) {
    u128 t = (u128)r.w[0] + (u128)acc[4] * kPFold;
    r.w[0] = (uint64_t)t;
    t >>= 64;
    for (int i = 1; i < 4; ++i) {
      t += r.w[i];
      r.w[i] = (uint64_t)t;
      t >>= 64;
    }
    if (t) {  // third fold; the value is now tiny, no further wrap possible
      u128 u = (u128)r.w[0] + kPFold;
      r.w[0] = (uint64_t)u;
      for (int i = 1; i < 4 && (u >>= 64); ++i) {
        u += r.w[i];
        r.w[i] = (uint64_t)u;
      }
    }
  }
  return p_norm(r);
}

inline U256 p_sqr(const U256& a) { return p_mul(a, a); }

U256 p_pow(const U256& a, const U256& e) {
  U256 acc = {{1, 0, 0, 0}};
  for (int i = 255; i >= 0; --i) {
    acc = p_sqr(acc);
    if ((e.w[i >> 6] >> (i & 63)) & 1) acc = p_mul(acc, a);
  }
  return acc;
}

inline U256 p_inv(const U256& a) {
  U256 e = kP;
  e.w[0] -= 2;
  return p_pow(a, e);
}

// ---------------------------------------------------------------------------
// scalar (mod n) arithmetic — generic bit-serial reduction (cold path)
// ---------------------------------------------------------------------------

U256 n_mod_words(const uint64_t* words, int nwords) {
  U256 r = {{0, 0, 0, 0}};
  for (int i = 64 * nwords - 1; i >= 0; --i) {
    uint64_t top = r.w[3] >> 63;
    r.w[3] = (r.w[3] << 1) | (r.w[2] >> 63);
    r.w[2] = (r.w[2] << 1) | (r.w[1] >> 63);
    r.w[1] = (r.w[1] << 1) | (r.w[0] >> 63);
    r.w[0] = (r.w[0] << 1) | ((words[i >> 6] >> (i & 63)) & 1);
    if (top || cmp(r, kN) >= 0) r = sub_raw(r, kN);
  }
  return r;
}

U256 n_mul(const U256& a, const U256& b) {
  uint64_t lo[8];
  mul_wide(a, b, lo);
  return n_mod_words(lo, 8);
}

U256 n_pow(const U256& a, const U256& e) {
  U256 acc = {{1, 0, 0, 0}};
  for (int i = 255; i >= 0; --i) {
    acc = n_mul(acc, acc);
    if ((e.w[i >> 6] >> (i & 63)) & 1) acc = n_mul(acc, a);
  }
  return acc;
}

inline U256 n_inv(const U256& a) {
  U256 e = kN;
  e.w[0] -= 2;
  return n_pow(a, e);
}

// ---------------------------------------------------------------------------
// point arithmetic (Jacobian; infinity is Z == 0)
// ---------------------------------------------------------------------------

struct Jac {
  U256 x, y, z;
};

inline bool jac_inf(const Jac& p) { return is_zero(p.z); }

Jac jac_dbl(const Jac& p) {
  if (jac_inf(p) || is_zero(p.y)) return Jac{{{1, 0, 0, 0}}, {{1, 0, 0, 0}}, {{0, 0, 0, 0}}};
  U256 a = p_sqr(p.x);
  U256 b = p_sqr(p.y);
  U256 c = p_sqr(b);
  U256 xb = p_add(p.x, b);
  U256 d = p_sub(p_sub(p_sqr(xb), a), c);
  d = p_add(d, d);
  U256 e = p_add(p_add(a, a), a);
  U256 f = p_sqr(e);
  Jac r;
  r.x = p_sub(p_sub(f, d), d);
  U256 c8 = p_add(c, c);
  c8 = p_add(c8, c8);
  c8 = p_add(c8, c8);
  r.y = p_sub(p_mul(e, p_sub(d, r.x)), c8);
  U256 yz = p_mul(p.y, p.z);
  r.z = p_add(yz, yz);
  return r;
}

Jac jac_add(const Jac& p, const Jac& q) {
  if (jac_inf(p)) return q;
  if (jac_inf(q)) return p;
  U256 z1z1 = p_sqr(p.z);
  U256 z2z2 = p_sqr(q.z);
  U256 u1 = p_mul(p.x, z2z2);
  U256 u2 = p_mul(q.x, z1z1);
  U256 s1 = p_mul(p.y, p_mul(q.z, z2z2));
  U256 s2 = p_mul(q.y, p_mul(p.z, z1z1));
  U256 h = p_sub(u2, u1);
  U256 rr = p_sub(s2, s1);
  if (is_zero(h)) {
    if (is_zero(rr)) return jac_dbl(p);
    return Jac{{{1, 0, 0, 0}}, {{1, 0, 0, 0}}, {{0, 0, 0, 0}}};  // inverse pts
  }
  U256 hh = p_sqr(h);
  U256 hhh = p_mul(h, hh);
  U256 v = p_mul(u1, hh);
  Jac r;
  r.x = p_sub(p_sub(p_sqr(rr), hhh), p_add(v, v));
  r.y = p_sub(p_mul(rr, p_sub(v, r.x)), p_mul(s1, hhh));
  r.z = p_mul(h, p_mul(p.z, q.z));
  return r;
}

// Shamir double-scalar multiply: k1*A + k2*B
Jac jac_shamir(const U256& k1, const Jac& a, const U256& k2, const Jac& b) {
  Jac ab = jac_add(a, b);
  Jac acc{{{1, 0, 0, 0}}, {{1, 0, 0, 0}}, {{0, 0, 0, 0}}};
  for (int i = 255; i >= 0; --i) {
    acc = jac_dbl(acc);
    int b1 = (k1.w[i >> 6] >> (i & 63)) & 1;
    int b2 = (k2.w[i >> 6] >> (i & 63)) & 1;
    if (b1 && b2)
      acc = jac_add(acc, ab);
    else if (b1)
      acc = jac_add(acc, a);
    else if (b2)
      acc = jac_add(acc, b);
  }
  return acc;
}

inline U256 be_to_u(const uint8_t in[32]) {
  U256 r;
  for (int i = 0; i < 4; ++i) {
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | in[8 * i + j];
    r.w[3 - i] = v;
  }
  return r;
}

inline void u_to_be(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; ++i) {
    uint64_t v = a.w[3 - i];
    for (int j = 0; j < 8; ++j) out[8 * i + j] = (uint8_t)(v >> (56 - 8 * j));
  }
}

}  // namespace

extern "C" {

// ecrecover: 32B message hash, 32B r, 32B s (big-endian), recovery id 0..3.
// On success writes the 64-byte uncompressed pubkey (X||Y) and returns 0;
// returns nonzero on any invalid input (range, off-curve, infinity).
// (reference scope: Signer.erecover, src/crypto/ecdsa.zig:19-26)
int32_t phant_ecrecover(const uint8_t msg_hash[32], const uint8_t r_be[32],
                        const uint8_t s_be[32], int32_t recid,
                        uint8_t pubkey_out[64]) {
  if (recid < 0 || recid > 3) return 1;
  U256 r = be_to_u(r_be), s = be_to_u(s_be);
  if (is_zero(r) || cmp(r, kN) >= 0) return 2;
  if (is_zero(s) || cmp(s, kN) >= 0) return 3;

  // x = r + jN must stay below p
  U256 x = r;
  if (recid >= 2) {
    U256 t;
    if (add_raw(r, kN, &t) || cmp(t, kP) >= 0) return 4;
    x = t;
  }
  // lift x: y = (x^3 + 7)^((p+1)/4)
  U256 ysq = p_add(p_mul(p_sqr(x), x), U256{{7, 0, 0, 0}});
  U256 e = kP;  // (p+1)/4: p ≡ 3 (mod 4) so this is exact
  // e = (p+1)/4 — compute via shift of p+1
  {
    U256 p1 = kP;
    u128 t = (u128)p1.w[0] + 1;
    p1.w[0] = (uint64_t)t;
    for (int i = 1; i < 4 && (t >>= 64); ++i) {
      t += p1.w[i];
      p1.w[i] = (uint64_t)t;
    }
    for (int i = 0; i < 4; ++i) {
      uint64_t hi = i < 3 ? p1.w[i + 1] : 0;
      e.w[i] = (p1.w[i] >> 2) | (hi << 62);
    }
  }
  U256 y = p_pow(ysq, e);
  if (cmp(p_sqr(y), ysq) != 0) return 5;  // x not on curve
  if ((y.w[0] & 1) != (uint64_t)(recid & 1)) y = p_sub(kP, y);

  // scalars: u1 = -z/r, u2 = s/r (mod n)
  uint64_t zw[4];
  U256 z_raw = be_to_u(msg_hash);
  std::memcpy(zw, z_raw.w, sizeof(zw));
  U256 z = n_mod_words(zw, 4);
  U256 rinv = n_inv(r);
  U256 u1 = n_mul(z, rinv);
  if (!is_zero(u1)) u1 = sub_raw(kN, u1);
  U256 u2 = n_mul(s, rinv);

  Jac G{kGx, kGy, {{1, 0, 0, 0}}};
  Jac R{x, y, {{1, 0, 0, 0}}};
  Jac Q = jac_shamir(u1, G, u2, R);
  if (jac_inf(Q)) return 6;

  U256 zi = p_inv(Q.z);
  U256 zi2 = p_sqr(zi);
  U256 qx = p_mul(Q.x, zi2);
  U256 qy = p_mul(Q.y, p_mul(zi, zi2));
  u_to_be(qx, pubkey_out);
  u_to_be(qy, pubkey_out + 32);
  return 0;
}

// Batched sender recovery: recover + keccak + take bytes 12..31 per
// signature; ok[i]=1 and addrs[i*20..] on success, ok[i]=0 otherwise.
void phant_ecrecover_batch(const uint8_t* msg_hashes, const uint8_t* rs,
                           const uint8_t* ss, const int32_t* recids, size_t n,
                           uint8_t* addrs_out, uint8_t* ok_out) {
  for (size_t i = 0; i < n; ++i) {
    uint8_t pubkey[64];
    if (phant_ecrecover(msg_hashes + 32 * i, rs + 32 * i, ss + 32 * i,
                        recids[i], pubkey) == 0) {
      uint8_t digest[32];
      phant_keccak256(pubkey, 64, digest);
      std::memcpy(addrs_out + 20 * i, digest + 12, 20);
      ok_out[i] = 1;
    } else {
      ok_out[i] = 0;
    }
  }
}

}  // extern "C"
