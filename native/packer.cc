// Host-side device-buffer packer — the C-ABI shim of the north star
// (BASELINE.json: "The Zig side packs variable-length trie paths and node
// RLP into padded device buffers"; the reference's analogous native glue is
// src/glue.c). Pads variable-length payloads with keccak multi-rate padding
// and lays them out as the fixed-shape (B, C, 136-byte) chunk buffer the
// device keccak kernel (phant_tpu/ops/keccak_jax.py) consumes.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {
constexpr size_t kRate = 136;
}

extern "C" {

// Pack payload i = in[offsets[i] .. offsets[i]+lens[i]) into
// out[i * max_chunks * kRate ...], keccak-padded into nchunks[i] rate blocks.
// out must be zero-initialised to B * max_chunks * kRate bytes by the caller
// (numpy allocates it zeroed). Returns 0 on success, -1 if any payload
// overflows the bucket bound.
int phant_pack_keccak(const uint8_t* in, const uint64_t* offsets,
                      const uint32_t* lens, size_t n, size_t max_chunks,
                      uint8_t* out, int32_t* nchunks) {
  const size_t row = max_chunks * kRate;
  for (size_t i = 0; i < n; ++i) {
    const size_t len = lens[i];
    const size_t k = len / kRate + 1;  // padding always adds >= 1 bit
    if (k > max_chunks) return -1;
    nchunks[i] = static_cast<int32_t>(k);
    uint8_t* dst = out + i * row;
    std::memcpy(dst, in + offsets[i], len);
    dst[len] ^= 0x01;
    dst[k * kRate - 1] ^= 0x80;
  }
  return 0;
}

// --- witness child-ref scanner ---------------------------------------------
// Finds the byte offsets (into the witness blob) of every child hash
// reference inside each RLP trie node: the 32-byte string children of a
// branch node (items 0..15), the child of an extension node (2-item node
// whose hex-prefix flag has the leaf bit 0x20 clear), recursing into
// embedded (<32B) child structures. Leaf values and branch values are NOT
// references. Host-side complement of the device linkage verdict
// (phant_tpu/ops/witness_jax.py witness_verify_linked); the reference's
// analogous node walk is src/mpt/mpt.zig:47-119 (it computes roots only).

namespace {

// One RLP item at *pos (absolute into d, item must end by `end`).
// kind: 0 = string, 1 = list; [*ps, *pe) = payload span. Returns false on
// malformed input.
bool rlp_item(const uint8_t* d, size_t end, size_t* pos, int* kind,
              size_t* ps, size_t* pe) {
  if (*pos >= end) return false;
  const uint8_t b = d[*pos];
  size_t l, s;
  if (b < 0x80) {
    *kind = 0;
    *ps = *pos;
    *pe = *pos + 1;
    *pos += 1;
    return true;
  }
  if (b < 0xb8) {
    l = b - 0x80;
    s = *pos + 1;
    *kind = 0;
  } else if (b < 0xc0) {
    const size_t ll = b - 0xb7;
    if (*pos + 1 + ll > end) return false;
    l = 0;
    for (size_t i = 0; i < ll; ++i) l = (l << 8) | d[*pos + 1 + i];
    s = *pos + 1 + ll;
    *kind = 0;
  } else if (b < 0xf8) {
    l = b - 0xc0;
    s = *pos + 1;
    *kind = 1;
  } else {
    const size_t ll = b - 0xf7;
    if (*pos + 1 + ll > end) return false;
    l = 0;
    for (size_t i = 0; i < ll; ++i) l = (l << 8) | d[*pos + 1 + i];
    s = *pos + 1 + ll;
    *kind = 1;
  }
  if (l > end || s + l > end) return false;
  *ps = s;
  *pe = s + l;
  *pos = s + l;
  return true;
}

// If a leaf's value payload [s, e) is account-shaped RLP — a list of
// exactly four strings whose 3rd and 4th are 32 bytes (nonce, balance,
// storage_root, code_hash) — return the absolute offset of the storage
// root, else -1. The storage root is a commitment the leaf carries, so a
// witness's storage-trie nodes link through it. Malformed input is simply
// "not an account" (no error): leaf values are opaque in general.
long account_storage_root_off(const uint8_t* d, size_t s, size_t e) {
  size_t pos = s;
  int kind;
  size_t ps, pe;
  if (!rlp_item(d, e, &pos, &kind, &ps, &pe) || kind != 1 || pos != e)
    return -1;
  size_t ips[4], ipe[4];
  int n = 0;
  size_t p = ps;
  while (p < pe) {
    if (n >= 4) return -1;
    int k;
    if (!rlp_item(d, pe, &p, &k, &ips[n], &ipe[n]) || k != 0) return -1;
    ++n;
  }
  if (n != 4 || ipe[2] - ips[2] != 32 || ipe[3] - ips[3] != 32) return -1;
  return static_cast<long>(ips[2]);
}

// Scan a node's list payload [s, e) for child refs; returns the updated ref
// count, or -1 on malformed input / capacity overflow.
long scan_node_list(const uint8_t* d, size_t s, size_t e, int64_t* out_off,
                    int32_t* out_node, long cap, long cnt, int32_t node,
                    int depth) {
  if (depth > 64) return -1;
  int kinds[17];
  size_t pss[17], pes[17];
  int nitems = 0;
  size_t pos = s;
  while (pos < e) {
    if (nitems >= 17) return -1;
    if (!rlp_item(d, e, &pos, &kinds[nitems], &pss[nitems], &pes[nitems]))
      return -1;
    ++nitems;
  }
  if (nitems == 17) {
    for (int i = 0; i < 16; ++i) {
      if (kinds[i] == 0 && pes[i] - pss[i] == 32) {
        if (cnt >= cap) return -1;
        out_off[cnt] = static_cast<int64_t>(pss[i]);
        out_node[cnt] = node;
        ++cnt;
      } else if (kinds[i] == 1 && pes[i] > pss[i]) {
        cnt = scan_node_list(d, pss[i], pes[i], out_off, out_node, cap, cnt,
                             node, depth + 1);
        if (cnt < 0) return -1;
      }
    }
  } else if (nitems == 2) {
    if (pes[0] == pss[0]) return -1;  // hex-prefix path is never empty
    const bool is_leaf = (d[pss[0]] & 0x20) != 0;
    if (!is_leaf) {
      if (kinds[1] == 0 && pes[1] - pss[1] == 32) {
        if (cnt >= cap) return -1;
        out_off[cnt] = static_cast<int64_t>(pss[1]);
        out_node[cnt] = node;
        ++cnt;
      } else if (kinds[1] == 1) {
        cnt = scan_node_list(d, pss[1], pes[1], out_off, out_node, cap, cnt,
                             node, depth + 1);
        if (cnt < 0) return -1;
      }
    } else if (kinds[1] == 0) {
      const long sr = account_storage_root_off(d, pss[1], pes[1]);
      if (sr >= 0) {
        if (cnt >= cap) return -1;
        out_off[cnt] = sr;
        out_node[cnt] = node;
        ++cnt;
      }
    }
  }
  // other item counts: not a trie node shape — contributes no refs
  return cnt;
}

}  // namespace

// Scan n nodes (node i = blob[offsets[i] .. +lens[i])) for child hash refs.
// Writes each ref's absolute blob offset and owning node index; returns the
// ref count, or -1 on malformed RLP / capacity overflow.
long phant_scan_refs(const uint8_t* blob, const uint64_t* offsets,
                     const uint32_t* lens, size_t n, int64_t* out_off,
                     int32_t* out_node, size_t cap) {
  long cnt = 0;
  for (size_t i = 0; i < n; ++i) {
    const size_t s = offsets[i];
    const size_t e = s + lens[i];
    size_t pos = s;
    int kind;
    size_t ps, pe;
    if (!rlp_item(blob, e, &pos, &kind, &ps, &pe) || kind != 1 || pos != e)
      return -1;
    cnt = scan_node_list(blob, ps, pe, out_off, out_node,
                         static_cast<long>(cap), cnt, static_cast<int32_t>(i),
                         0);
    if (cnt < 0) return -1;
  }
  return cnt;
}

}  // extern "C"
