// Host-side device-buffer packer — the C-ABI shim of the north star
// (BASELINE.json: "The Zig side packs variable-length trie paths and node
// RLP into padded device buffers"; the reference's analogous native glue is
// src/glue.c). Pads variable-length payloads with keccak multi-rate padding
// and lays them out as the fixed-shape (B, C, 136-byte) chunk buffer the
// device keccak kernel (phant_tpu/ops/keccak_jax.py) consumes.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {
constexpr size_t kRate = 136;
}

extern "C" {

// Pack payload i = in[offsets[i] .. offsets[i]+lens[i]) into
// out[i * max_chunks * kRate ...], keccak-padded into nchunks[i] rate blocks.
// out must be zero-initialised to B * max_chunks * kRate bytes by the caller
// (numpy allocates it zeroed). Returns 0 on success, -1 if any payload
// overflows the bucket bound.
int phant_pack_keccak(const uint8_t* in, const uint64_t* offsets,
                      const uint32_t* lens, size_t n, size_t max_chunks,
                      uint8_t* out, int32_t* nchunks) {
  const size_t row = max_chunks * kRate;
  for (size_t i = 0; i < n; ++i) {
    const size_t len = lens[i];
    const size_t k = len / kRate + 1;  // padding always adds >= 1 bit
    if (k > max_chunks) return -1;
    nchunks[i] = static_cast<int32_t>(k);
    uint8_t* dst = out + i * row;
    std::memcpy(dst, in + offsets[i], len);
    dst[len] ^= 0x01;
    dst[k * kRate - 1] ^= 0x80;
  }
  return 0;
}

}  // extern "C"
