// Native EVM bytecode interpreter core (Shanghai revision).
//
// The reference executes bytecode in C++ too: the evmone interpreter behind
// the EVMC C ABI, with the client providing a host-interface vtable over its
// StateDB (reference: src/blockchain/vm.zig:33-558, build.zig:116-127). This
// file is the equivalent native core for this framework, written from
// scratch: a C ABI (`phant_evm_execute`) takes a host vtable of function
// pointers (state access, logs, nested call/create) that the Python side
// implements over its StateDB via ctypes — mirroring how the reference's
// Zig host backs evmone's 14 callbacks. Semantics are differential-tested
// opcode-for-opcode against the Python interpreter
// (phant_tpu/evm/interpreter.py) on the execution-spec-test fixtures.
//
// Notes:
// - u256 is 4x64-bit limbs (little-endian limb order) with __uint128
//   products; div/mod are bit-serial (exactness over speed; DIV is cold).
// - Exceptional halts consume all frame gas (status kFail, gas_left 0);
//   REVERT preserves remaining gas (status kRevert).
// - The EVM stack lives on the heap (1024 u256 = 32 KiB) so depth-1024
//   call chains do not overflow the C stack.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <vector>

extern "C" void phant_keccak256(const uint8_t* in, size_t len, uint8_t* out);

namespace {

// ---------------------------------------------------------------------------
// u256
// ---------------------------------------------------------------------------

struct U256 {
  uint64_t w[4];  // w[0] = least significant
};

inline U256 u_zero() { return U256{{0, 0, 0, 0}}; }

inline U256 u_from64(uint64_t v) { return U256{{v, 0, 0, 0}}; }

inline bool u_is_zero(const U256& a) {
  return (a.w[0] | a.w[1] | a.w[2] | a.w[3]) == 0;
}

inline int u_cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] < b.w[i]) return -1;
    if (a.w[i] > b.w[i]) return 1;
  }
  return 0;
}

inline U256 u_add(const U256& a, const U256& b) {
  U256 r;
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (unsigned __int128)a.w[i] + b.w[i];
    r.w[i] = (uint64_t)c;
    c >>= 64;
  }
  return r;
}

inline U256 u_sub(const U256& a, const U256& b) {
  U256 r;
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d =
        (unsigned __int128)a.w[i] - b.w[i] - (uint64_t)borrow;
    r.w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return r;
}

inline U256 u_mul(const U256& a, const U256& b) {  // low 256 bits
  uint64_t r[4] = {0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 carry = 0;
    for (int j = 0; i + j < 4; ++j) {
      carry += (unsigned __int128)a.w[i] * b.w[j] + r[i + j];
      r[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
  }
  return U256{{r[0], r[1], r[2], r[3]}};
}

inline void u_mul_full(const U256& a, const U256& b, uint64_t out[8]) {
  std::memset(out, 0, 8 * sizeof(uint64_t));
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      carry += (unsigned __int128)a.w[i] * b.w[j] + out[i + j];
      out[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    out[i + 4] = (uint64_t)carry;
  }
}

inline int u_bit(const uint64_t* words, int i) {
  return (words[i >> 6] >> (i & 63)) & 1;
}

inline int u_bitlen(const U256& a) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i]) return 64 * i + 64 - __builtin_clzll(a.w[i]);
  }
  return 0;
}

// words[nwords] mod m (bit-serial); m != 0.
U256 u_mod_words(const uint64_t* words, int nwords, const U256& m) {
  U256 r = u_zero();
  for (int i = 64 * nwords - 1; i >= 0; --i) {
    uint64_t top = r.w[3] >> 63;
    r.w[3] = (r.w[3] << 1) | (r.w[2] >> 63);
    r.w[2] = (r.w[2] << 1) | (r.w[1] >> 63);
    r.w[1] = (r.w[1] << 1) | (r.w[0] >> 63);
    r.w[0] = (r.w[0] << 1) | (uint64_t)u_bit(words, i);
    if (top || u_cmp(r, m) >= 0) r = u_sub(r, m);
  }
  return r;
}

// a / b and a % b; b != 0. The remainder shift can carry past bit 255 when
// b >= 2^255, so the shifted-out top bit forces a subtraction (the wrapped
// subtraction is still exact: 2r+bit <= 2b-1 < 2^257).
void u_divmod(const U256& a, const U256& b, U256* q, U256* r) {
  *q = u_zero();
  *r = u_zero();
  for (int i = 255; i >= 0; --i) {
    uint64_t top = r->w[3] >> 63;
    r->w[3] = (r->w[3] << 1) | (r->w[2] >> 63);
    r->w[2] = (r->w[2] << 1) | (r->w[1] >> 63);
    r->w[1] = (r->w[1] << 1) | (r->w[0] >> 63);
    r->w[0] = (r->w[0] << 1) | (uint64_t)u_bit(a.w, i);
    if (top || u_cmp(*r, b) >= 0) {
      *r = u_sub(*r, b);
      q->w[i >> 6] |= 1ULL << (i & 63);
    }
  }
}

inline bool u_sign(const U256& a) { return a.w[3] >> 63; }

inline U256 u_neg(const U256& a) { return u_sub(u_zero(), a); }

inline U256 u_abs(const U256& a) { return u_sign(a) ? u_neg(a) : a; }

// true if the value fits in uint64 (all high limbs zero)
inline bool u_fits64(const U256& a, uint64_t* out) {
  if (a.w[1] | a.w[2] | a.w[3]) return false;
  *out = a.w[0];
  return true;
}

inline void u_to_be(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; ++i) {
    uint64_t v = a.w[3 - i];
    for (int j = 0; j < 8; ++j) out[8 * i + j] = (uint8_t)(v >> (56 - 8 * j));
  }
}

inline U256 u_from_be(const uint8_t in[32]) {
  U256 r;
  for (int i = 0; i < 4; ++i) {
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | in[8 * i + j];
    r.w[3 - i] = v;
  }
  return r;
}

inline U256 u_from_addr(const uint8_t addr[20]) {
  uint8_t be[32];
  std::memset(be, 0, 12);
  std::memcpy(be + 12, addr, 20);
  return u_from_be(be);
}

inline void u_to_addr(const U256& a, uint8_t out[20]) {
  uint8_t be[32];
  u_to_be(a, be);
  std::memcpy(out, be + 12, 20);
}

// ---------------------------------------------------------------------------
// C ABI structs (shared with phant_tpu/evm/native_vm.py)
// ---------------------------------------------------------------------------

}  // namespace

extern "C" {

struct PhantTxContext {
  uint8_t origin[20];
  uint8_t coinbase[20];
  uint64_t block_number;
  uint64_t timestamp;
  uint64_t gas_limit;
  uint64_t chain_id;
  uint8_t gas_price[32];
  uint8_t prev_randao[32];
  uint8_t base_fee[32];
  // EVM revision: 0 = Shanghai, 1 = Cancun, 2 = Prague — Cancun opcode
  // gates check `revision >= 1` so Prague inherits them; EIP-7702
  // delegation resolves host-side in the shared _call_inner, so this core
  // needs no Prague-specific opcodes. (The reference hardcodes
  // EVMC_SHANGHAI, src/blockchain/vm.zig:472; this core fork-dispatches)
  uint64_t revision;
  uint8_t blob_base_fee[32];          // EIP-7516
  const uint8_t* blob_hashes;         // EIP-4844: n x 32 bytes, may be null
  uint64_t n_blob_hashes;
};

// kinds for PhantMsg / the host `call` callback
enum PhantCallKind : int32_t {
  PHANT_CALL = 0,
  PHANT_CALLCODE = 1,
  PHANT_DELEGATECALL = 2,
  PHANT_STATICCALL = 3,
  PHANT_CREATE = 4,
  PHANT_CREATE2 = 5,
};

struct PhantMsg {
  int32_t kind;
  int32_t is_static;
  int32_t depth;
  int64_t gas;
  uint8_t caller[20];    // msg.sender inside the child
  uint8_t target[20];    // storage/balance context of the child
  uint8_t code_address[20];  // where the code comes from (CALLCODE/DELEGATE)
  uint8_t value[32];
  const uint8_t* data;
  uint64_t data_len;
  uint8_t salt[32];  // CREATE2
};

struct PhantResult {
  int32_t status;  // 0 success, 1 revert, 2 failure
  int64_t gas_left;
  const uint8_t* output;  // owned by the host (callback) or by phant (entry)
  uint64_t output_len;
  uint8_t create_address[20];
};

// Host vtable: the Python StateDB side of the interface (the reference's
// equivalent is the 14-entry EVMC host_interface at vm.zig:40-55).
struct PhantHost {
  void* ctx;
  int32_t (*access_account)(void*, const uint8_t addr[20]);  // 1 if was warm
  int32_t (*access_storage)(void*, const uint8_t addr[20], const uint8_t key[32]);
  void (*get_storage)(void*, const uint8_t addr[20], const uint8_t key[32], uint8_t out[32]);
  void (*get_original_storage)(void*, const uint8_t addr[20], const uint8_t key[32], uint8_t out[32]);
  void (*set_storage)(void*, const uint8_t addr[20], const uint8_t key[32], const uint8_t val[32]);
  void (*get_balance)(void*, const uint8_t addr[20], uint8_t out[32]);
  uint64_t (*get_code_size)(void*, const uint8_t addr[20]);
  void (*copy_code)(void*, const uint8_t addr[20], uint64_t offset, uint8_t* out, uint64_t size);
  void (*get_code_hash)(void*, const uint8_t addr[20], uint8_t out[32]);
  int32_t (*is_empty)(void*, const uint8_t addr[20]);
  void (*get_block_hash)(void*, uint64_t number, uint8_t out[32]);
  void (*emit_log)(void*, const uint8_t addr[20], const uint8_t* data, uint64_t len,
                   const uint8_t* topics, int32_t ntopics);
  void (*add_refund)(void*, int64_t delta);
  void (*selfdestruct)(void*, const uint8_t addr[20], const uint8_t beneficiary[20]);
  void (*call)(void*, const PhantMsg* msg, PhantResult* result);
  // EIP-1153 transient storage (Cancun); appended so pre-Cancun embedders'
  // vtable layout is a strict prefix
  void (*get_transient)(void*, const uint8_t addr[20], const uint8_t key[32], uint8_t out[32]);
  void (*set_transient)(void*, const uint8_t addr[20], const uint8_t key[32], const uint8_t val[32]);
  // optional per-instruction tracer (NULL = tracing off, zero overhead
  // beyond one branch). The reference compiles evmone's tracing.cpp into
  // its binary but never installs a tracer (build.zig:118, SURVEY §5);
  // this is the equivalent debugging surface, actually wired up.
  void (*trace)(void*, uint64_t pc, int32_t op, int64_t gas, int32_t depth,
                int32_t stack_size);
  // EIP-7702 (Prague): extra CALL-family charge when the code target is a
  // delegated account — warms the delegate host-side and returns its
  // warm/cold access cost (0 when not delegated / pre-Prague). Appended
  // last so older vtable layouts stay a strict prefix.
  int64_t (*delegate_access_cost)(void*, const uint8_t addr[20]);
};

}  // extern "C"

namespace {

// ---------------------------------------------------------------------------
// gas schedule (Shanghai; mirrors phant_tpu/evm/gas.py and, transitively,
// reference src/blockchain/params.zig:5-39)
// ---------------------------------------------------------------------------

constexpr int64_t kColdAccount = 2600, kWarmAccount = 100;
constexpr int64_t kColdSload = 2100, kWarmSload = 100;
constexpr int64_t kSstoreSet = 20000, kSstoreReset = 2900, kSstoreSentry = 2300;
constexpr int64_t kSstoreClearsRefund = 4800;
constexpr int64_t kCreateGas = 32000, kCodeDepositPerByte = 200;
constexpr int64_t kMaxCodeSize = 0x6000, kMaxInitcodeSize = 2 * kMaxCodeSize;
constexpr int64_t kInitcodeWordCost = 2;
constexpr int64_t kCallValueGas = 9000, kCallStipend = 2300, kNewAccountGas = 25000;
constexpr int64_t kKeccakGas = 30, kKeccakWordGas = 6, kCopyWordGas = 3;
constexpr int64_t kLogGas = 375, kLogTopicGas = 375, kLogDataGas = 8;
constexpr int64_t kExpGas = 10, kExpByteGas = 50;
constexpr int64_t kSelfdestructGas = 5000;
constexpr int64_t kMemoryGas = 3, kQuadDiv = 512;

inline int64_t mem_cost(uint64_t size_bytes) {
  uint64_t words = (size_bytes + 31) / 32;
  return (int64_t)(kMemoryGas * words + (words * words) / kQuadDiv);
}

inline int64_t copy_cost_words(uint64_t len) {
  return kCopyWordGas * (int64_t)((len + 31) / 32);
}

enum class Halt { kNone, kStop, kReturn, kRevert, kFail };

struct Interp {
  const PhantHost* host;
  const PhantTxContext* txc;
  const PhantMsg* msg;
  const uint8_t* code;
  uint64_t code_len;
  uint8_t self_addr[20];  // frame.address = storage context

  std::vector<U256> stack;
  std::vector<uint8_t> mem;
  std::vector<uint8_t> retdata;  // child return data buffer
  std::vector<uint8_t> out;      // RETURN / REVERT payload
  std::vector<uint8_t> jumpdests;  // bitmap
  uint64_t pc = 0;
  int64_t gas = 0;

  Interp(const PhantHost* h, const PhantTxContext* t, const PhantMsg* m,
         const uint8_t* c, uint64_t clen)
      : host(h), txc(t), msg(m), code(c), code_len(clen) {
    stack.reserve(64);
    std::memcpy(self_addr, m->target, 20);
    gas = m->gas;
    jumpdests.assign((clen + 7) / 8, 0);
    for (uint64_t i = 0; i < clen; ++i) {
      uint8_t op = code[i];
      if (op == 0x5B) jumpdests[i >> 3] |= (uint8_t)(1 << (i & 7));
      if (op >= 0x60 && op <= 0x7F) i += op - 0x5F;
    }
  }

  bool is_jumpdest(uint64_t i) const {
    return i < code_len && (jumpdests[i >> 3] >> (i & 7)) & 1;
  }

  bool use_gas(int64_t amount) {
    if (amount < 0 || gas < amount) return false;
    gas -= amount;
    return true;
  }

  bool push(const U256& v) {
    if (stack.size() >= 1024) return false;
    stack.push_back(v);
    return true;
  }

  bool pop(U256* v) {
    if (stack.empty()) return false;
    *v = stack.back();
    stack.pop_back();
    return true;
  }

  // charge + grow memory to cover [off, off+size); size==0 is free
  bool expand(const U256& off_u, const U256& size_u) {
    if (u_is_zero(size_u)) return true;
    uint64_t off, size;
    if (!u_fits64(off_u, &off) || !u_fits64(size_u, &size)) return false;
    if (off > (1ULL << 32) || size > (1ULL << 32)) return false;
    uint64_t new_size = off + size;
    if (new_size <= mem.size()) return true;
    uint64_t new_words = (new_size + 31) / 32;
    if (!use_gas(mem_cost(new_words * 32) - mem_cost(mem.size()))) return false;
    mem.resize(new_words * 32, 0);
    return true;
  }

  void mread(uint64_t off, uint64_t size, std::vector<uint8_t>* dst) {
    dst->assign(size, 0);
    if (size && off + size <= mem.size())
      std::memcpy(dst->data(), mem.data() + off, size);
  }

  Halt run();
};

// saturating word-count cost for possibly-huge u256 sizes: any non-u64 size
// exceeds all gas, which reads as "out of gas" exactly like the Python side
inline bool size_cost(const U256& size_u, int64_t per_word, int64_t* out) {
  uint64_t size;
  if (!u_fits64(size_u, &size) || size > (1ULL << 40)) return false;
  *out = per_word * (int64_t)((size + 31) / 32);
  return true;
}

#define POP1(a) \
  U256 a;       \
  if (!pop(&a)) return Halt::kFail;
#define POP2(a, b) POP1(a) POP1(b)
#define POP3(a, b, c) POP2(a, b) POP1(c)
#define GAS(n) \
  if (!use_gas(n)) return Halt::kFail;
#define PUSH(v) \
  if (!push(v)) return Halt::kFail;

Halt Interp::run() {
  while (pc < code_len) {
    uint8_t op = code[pc];
    if (host->trace)
      host->trace(host->ctx, pc, (int32_t)op, gas, msg->depth,
                  (int32_t)stack.size());
    ++pc;

    // PUSH1..PUSH32
    if (op >= 0x60 && op <= 0x7F) {
      GAS(3);
      int width = op - 0x5F;
      uint8_t be[32];
      std::memset(be, 0, 32);
      uint64_t avail = pc < code_len ? code_len - pc : 0;
      uint64_t take = (uint64_t)width < avail ? (uint64_t)width : avail;
      // value is the immediate left-aligned to `width`, zero-extended past
      // the end of code, interpreted big-endian
      std::memcpy(be + 32 - width, code + pc, take);
      PUSH(u_from_be(be));
      pc += width;
      continue;
    }
    // DUP1..DUP16
    if (op >= 0x80 && op <= 0x8F) {
      GAS(3);
      size_t i = op - 0x7F;
      if (stack.size() < i) return Halt::kFail;
      PUSH(stack[stack.size() - i]);
      continue;
    }
    // SWAP1..SWAP16
    if (op >= 0x90 && op <= 0x9F) {
      GAS(3);
      size_t i = op - 0x8F;
      if (stack.size() < i + 1) return Halt::kFail;
      std::swap(stack[stack.size() - 1], stack[stack.size() - 1 - i]);
      continue;
    }

    switch (op) {
      case 0x00:  // STOP
        return Halt::kStop;

      case 0x01: {  // ADD
        GAS(3);
        POP2(a, b);
        PUSH(u_add(a, b));
        break;
      }
      case 0x02: {  // MUL
        GAS(5);
        POP2(a, b);
        PUSH(u_mul(a, b));
        break;
      }
      case 0x03: {  // SUB
        GAS(3);
        POP2(a, b);
        PUSH(u_sub(a, b));
        break;
      }
      case 0x04: {  // DIV
        GAS(5);
        POP2(a, b);
        if (u_is_zero(b)) {
          PUSH(u_zero());
        } else {
          U256 q, r;
          u_divmod(a, b, &q, &r);
          PUSH(q);
        }
        break;
      }
      case 0x05: {  // SDIV
        GAS(5);
        POP2(a, b);
        if (u_is_zero(b)) {
          PUSH(u_zero());
        } else {
          U256 q, r;
          u_divmod(u_abs(a), u_abs(b), &q, &r);
          PUSH(u_sign(a) != u_sign(b) ? u_neg(q) : q);
        }
        break;
      }
      case 0x06: {  // MOD
        GAS(5);
        POP2(a, b);
        if (u_is_zero(b)) {
          PUSH(u_zero());
        } else {
          U256 q, r;
          u_divmod(a, b, &q, &r);
          PUSH(r);
        }
        break;
      }
      case 0x07: {  // SMOD
        GAS(5);
        POP2(a, b);
        if (u_is_zero(b)) {
          PUSH(u_zero());
        } else {
          U256 q, r;
          u_divmod(u_abs(a), u_abs(b), &q, &r);
          PUSH(u_sign(a) ? u_neg(r) : r);
        }
        break;
      }
      case 0x08: {  // ADDMOD
        GAS(8);
        POP3(a, b, m);
        if (u_is_zero(m)) {
          PUSH(u_zero());
        } else {
          uint64_t wide[5];
          unsigned __int128 c = 0;
          for (int i = 0; i < 4; ++i) {
            c += (unsigned __int128)a.w[i] + b.w[i];
            wide[i] = (uint64_t)c;
            c >>= 64;
          }
          wide[4] = (uint64_t)c;
          PUSH(u_mod_words(wide, 5, m));
        }
        break;
      }
      case 0x09: {  // MULMOD
        GAS(8);
        POP3(a, b, m);
        if (u_is_zero(m)) {
          PUSH(u_zero());
        } else {
          uint64_t wide[8];
          u_mul_full(a, b, wide);
          PUSH(u_mod_words(wide, 8, m));
        }
        break;
      }
      case 0x0A: {  // EXP
        POP2(base, exp);
        int byte_len = (u_bitlen(exp) + 7) / 8;
        GAS(kExpGas + kExpByteGas * byte_len);
        U256 acc = u_from64(1);
        for (int i = u_bitlen(exp) - 1; i >= 0; --i) {
          acc = u_mul(acc, acc);
          if (u_bit(exp.w, i)) acc = u_mul(acc, base);
        }
        PUSH(acc);
        break;
      }
      case 0x0B: {  // SIGNEXTEND
        GAS(5);
        POP2(k, v);
        uint64_t kk;
        if (u_fits64(k, &kk) && kk < 31) {
          int bit = 8 * (int)(kk + 1) - 1;
          bool set = u_bit(v.w, bit);
          for (int i = bit + 1; i < 256; ++i) {
            if (set)
              v.w[i >> 6] |= 1ULL << (i & 63);
            else
              v.w[i >> 6] &= ~(1ULL << (i & 63));
          }
        }
        PUSH(v);
        break;
      }

      case 0x10: {  // LT
        GAS(3);
        POP2(a, b);
        PUSH(u_from64(u_cmp(a, b) < 0));
        break;
      }
      case 0x11: {  // GT
        GAS(3);
        POP2(a, b);
        PUSH(u_from64(u_cmp(a, b) > 0));
        break;
      }
      case 0x12: {  // SLT
        GAS(3);
        POP2(a, b);
        bool sa = u_sign(a), sb = u_sign(b);
        int c = u_cmp(a, b);
        PUSH(u_from64(sa != sb ? sa : c < 0));
        break;
      }
      case 0x13: {  // SGT
        GAS(3);
        POP2(a, b);
        bool sa = u_sign(a), sb = u_sign(b);
        int c = u_cmp(a, b);
        PUSH(u_from64(sa != sb ? sb : c > 0));
        break;
      }
      case 0x14: {  // EQ
        GAS(3);
        POP2(a, b);
        PUSH(u_from64(u_cmp(a, b) == 0));
        break;
      }
      case 0x15: {  // ISZERO
        GAS(3);
        POP1(a);
        PUSH(u_from64(u_is_zero(a)));
        break;
      }
      case 0x16: {  // AND
        GAS(3);
        POP2(a, b);
        for (int i = 0; i < 4; ++i) a.w[i] &= b.w[i];
        PUSH(a);
        break;
      }
      case 0x17: {  // OR
        GAS(3);
        POP2(a, b);
        for (int i = 0; i < 4; ++i) a.w[i] |= b.w[i];
        PUSH(a);
        break;
      }
      case 0x18: {  // XOR
        GAS(3);
        POP2(a, b);
        for (int i = 0; i < 4; ++i) a.w[i] ^= b.w[i];
        PUSH(a);
        break;
      }
      case 0x19: {  // NOT
        GAS(3);
        POP1(a);
        for (int i = 0; i < 4; ++i) a.w[i] = ~a.w[i];
        PUSH(a);
        break;
      }
      case 0x1A: {  // BYTE
        GAS(3);
        POP2(i_u, v);
        uint64_t i;
        if (u_fits64(i_u, &i) && i < 32) {
          uint8_t be[32];
          u_to_be(v, be);
          PUSH(u_from64(be[i]));
        } else {
          PUSH(u_zero());
        }
        break;
      }
      case 0x1B: {  // SHL
        GAS(3);
        POP2(sh_u, v);
        uint64_t sh;
        if (!u_fits64(sh_u, &sh) || sh >= 256) {
          PUSH(u_zero());
        } else {
          U256 r = u_zero();
          int limb = (int)(sh / 64), bits = (int)(sh % 64);
          for (int i = 3; i >= 0; --i) {
            uint64_t lo = (i - limb) >= 0 ? v.w[i - limb] : 0;
            uint64_t lo2 = (i - limb - 1) >= 0 ? v.w[i - limb - 1] : 0;
            r.w[i] = bits ? (lo << bits) | (lo2 >> (64 - bits)) : lo;
          }
          PUSH(r);
        }
        break;
      }
      case 0x1C: {  // SHR
        GAS(3);
        POP2(sh_u, v);
        uint64_t sh;
        if (!u_fits64(sh_u, &sh) || sh >= 256) {
          PUSH(u_zero());
        } else {
          U256 r = u_zero();
          int limb = (int)(sh / 64), bits = (int)(sh % 64);
          for (int i = 0; i < 4; ++i) {
            uint64_t hi = (i + limb) < 4 ? v.w[i + limb] : 0;
            uint64_t hi2 = (i + limb + 1) < 4 ? v.w[i + limb + 1] : 0;
            r.w[i] = bits ? (hi >> bits) | (hi2 << (64 - bits)) : hi;
          }
          PUSH(r);
        }
        break;
      }
      case 0x1D: {  // SAR
        GAS(3);
        POP2(sh_u, v);
        bool neg = u_sign(v);
        uint64_t sh;
        if (!u_fits64(sh_u, &sh) || sh >= 256) {
          U256 ones{{~0ULL, ~0ULL, ~0ULL, ~0ULL}};
          PUSH(neg ? ones : u_zero());
        } else {
          U256 r;
          int limb = (int)(sh / 64), bits = (int)(sh % 64);
          for (int i = 0; i < 4; ++i) {
            uint64_t hi = (i + limb) < 4 ? v.w[i + limb] : (neg ? ~0ULL : 0);
            uint64_t hi2 =
                (i + limb + 1) < 4 ? v.w[i + limb + 1] : (neg ? ~0ULL : 0);
            r.w[i] = bits ? (hi >> bits) | (hi2 << (64 - bits)) : hi;
          }
          PUSH(r);
        }
        break;
      }

      case 0x20: {  // KECCAK256
        POP2(off_u, size_u);
        int64_t words;
        if (!size_cost(size_u, kKeccakWordGas, &words)) return Halt::kFail;
        GAS(kKeccakGas + words);
        if (!expand(off_u, size_u)) return Halt::kFail;
        uint64_t off = 0, size = 0;
        u_fits64(off_u, &off);
        u_fits64(size_u, &size);
        uint8_t digest[32];
        phant_keccak256(size ? mem.data() + off : digest, size, digest);
        PUSH(u_from_be(digest));
        break;
      }

      case 0x30:  // ADDRESS
        GAS(2);
        PUSH(u_from_addr(self_addr));
        break;
      case 0x31: {  // BALANCE
        POP1(a_u);
        uint8_t addr[20];
        u_to_addr(a_u, addr);
        int warm = host->access_account(host->ctx, addr);
        GAS(warm ? kWarmAccount : kColdAccount);
        uint8_t bal[32];
        host->get_balance(host->ctx, addr, bal);
        PUSH(u_from_be(bal));
        break;
      }
      case 0x32:  // ORIGIN
        GAS(2);
        PUSH(u_from_addr(txc->origin));
        break;
      case 0x33:  // CALLER
        GAS(2);
        PUSH(u_from_addr(msg->caller));
        break;
      case 0x34:  // CALLVALUE
        GAS(2);
        PUSH(u_from_be(msg->value));
        break;
      case 0x35: {  // CALLDATALOAD
        GAS(3);
        POP1(i_u);
        uint64_t i;
        if (!u_fits64(i_u, &i) || i >= msg->data_len) {
          PUSH(u_zero());
        } else {
          uint8_t be[32];
          std::memset(be, 0, 32);
          uint64_t take = msg->data_len - i < 32 ? msg->data_len - i : 32;
          std::memcpy(be, msg->data + i, take);
          PUSH(u_from_be(be));
        }
        break;
      }
      case 0x36:  // CALLDATASIZE
        GAS(2);
        PUSH(u_from64(msg->data_len));
        break;
      case 0x37: {  // CALLDATACOPY
        POP3(dst_u, src_u, size_u);
        int64_t cost;
        if (!size_cost(size_u, kCopyWordGas, &cost)) return Halt::kFail;
        GAS(3 + cost);
        if (!expand(dst_u, size_u)) return Halt::kFail;
        uint64_t dst = 0, src = 0, size = 0;
        u_fits64(dst_u, &dst);
        u_fits64(size_u, &size);
        bool src_ok = u_fits64(src_u, &src);
        if (size) {
          // in-range prefix copied, remainder zero-filled (no src+i wrap)
          uint64_t avail =
              (src_ok && src < msg->data_len) ? msg->data_len - src : 0;
          uint64_t take = avail < size ? avail : size;
          if (take) std::memcpy(mem.data() + dst, msg->data + src, take);
          std::memset(mem.data() + dst + take, 0, size - take);
        }
        break;
      }
      case 0x38:  // CODESIZE
        GAS(2);
        PUSH(u_from64(code_len));
        break;
      case 0x39: {  // CODECOPY
        POP3(dst_u, src_u, size_u);
        int64_t cost;
        if (!size_cost(size_u, kCopyWordGas, &cost)) return Halt::kFail;
        GAS(3 + cost);
        if (!expand(dst_u, size_u)) return Halt::kFail;
        uint64_t dst = 0, src = 0, size = 0;
        u_fits64(dst_u, &dst);
        u_fits64(size_u, &size);
        bool src_ok = u_fits64(src_u, &src);
        if (size) {
          uint64_t avail = (src_ok && src < code_len) ? code_len - src : 0;
          uint64_t take = avail < size ? avail : size;
          if (take) std::memcpy(mem.data() + dst, code + src, take);
          std::memset(mem.data() + dst + take, 0, size - take);
        }
        break;
      }
      case 0x3A:  // GASPRICE
        GAS(2);
        PUSH(u_from_be(txc->gas_price));
        break;
      case 0x3B: {  // EXTCODESIZE
        POP1(a_u);
        uint8_t addr[20];
        u_to_addr(a_u, addr);
        int warm = host->access_account(host->ctx, addr);
        GAS(warm ? kWarmAccount : kColdAccount);
        PUSH(u_from64(host->get_code_size(host->ctx, addr)));
        break;
      }
      case 0x3C: {  // EXTCODECOPY
        POP1(a_u);
        POP3(dst_u, src_u, size_u);
        uint8_t addr[20];
        u_to_addr(a_u, addr);
        int warm = host->access_account(host->ctx, addr);
        int64_t cost;
        if (!size_cost(size_u, kCopyWordGas, &cost)) return Halt::kFail;
        GAS((warm ? kWarmAccount : kColdAccount) + cost);
        if (!expand(dst_u, size_u)) return Halt::kFail;
        uint64_t dst = 0, src = 0, size = 0;
        u_fits64(dst_u, &dst);
        u_fits64(size_u, &size);
        uint64_t ext_len = host->get_code_size(host->ctx, addr);
        bool src_ok = u_fits64(src_u, &src);
        if (size) {
          // zero-fill then copy the in-range slice (Python pads with zeros)
          std::memset(mem.data() + dst, 0, size);
          if (src_ok && src < ext_len) {
            uint64_t take = ext_len - src < size ? ext_len - src : size;
            host->copy_code(host->ctx, addr, src, mem.data() + dst, take);
          }
        }
        break;
      }
      case 0x3D:  // RETURNDATASIZE
        GAS(2);
        PUSH(u_from64(retdata.size()));
        break;
      case 0x3E: {  // RETURNDATACOPY
        POP3(dst_u, src_u, size_u);
        int64_t cost;
        if (!size_cost(size_u, kCopyWordGas, &cost)) return Halt::kFail;
        GAS(3 + cost);
        uint64_t src = 0, size = 0;
        u_fits64(size_u, &size);
        // overflow-safe bounds check: out-of-bounds is an exceptional halt
        if (!u_fits64(src_u, &src) || size > retdata.size() ||
            src > retdata.size() - size)
          return Halt::kFail;
        if (!expand(dst_u, size_u)) return Halt::kFail;
        uint64_t dst = 0;
        u_fits64(dst_u, &dst);
        if (size) std::memcpy(mem.data() + dst, retdata.data() + src, size);
        break;
      }
      case 0x3F: {  // EXTCODEHASH
        POP1(a_u);
        uint8_t addr[20];
        u_to_addr(a_u, addr);
        int warm = host->access_account(host->ctx, addr);
        GAS(warm ? kWarmAccount : kColdAccount);
        if (host->is_empty(host->ctx, addr)) {
          PUSH(u_zero());
        } else {
          uint8_t h[32];
          host->get_code_hash(host->ctx, addr, h);
          PUSH(u_from_be(h));
        }
        break;
      }

      case 0x40: {  // BLOCKHASH
        GAS(20);
        POP1(n_u);
        uint64_t n;
        uint64_t cur = txc->block_number;
        if (!u_fits64(n_u, &n) || n >= cur || cur - n > 256) {
          PUSH(u_zero());
        } else {
          uint8_t h[32];
          host->get_block_hash(host->ctx, n, h);
          PUSH(u_from_be(h));
        }
        break;
      }
      case 0x41:  // COINBASE
        GAS(2);
        PUSH(u_from_addr(txc->coinbase));
        break;
      case 0x42:  // TIMESTAMP
        GAS(2);
        PUSH(u_from64(txc->timestamp));
        break;
      case 0x43:  // NUMBER
        GAS(2);
        PUSH(u_from64(txc->block_number));
        break;
      case 0x44:  // PREVRANDAO
        GAS(2);
        PUSH(u_from_be(txc->prev_randao));
        break;
      case 0x45:  // GASLIMIT
        GAS(2);
        PUSH(u_from64(txc->gas_limit));
        break;
      case 0x46:  // CHAINID
        GAS(2);
        PUSH(u_from64(txc->chain_id));
        break;
      case 0x47: {  // SELFBALANCE
        GAS(5);
        uint8_t bal[32];
        host->get_balance(host->ctx, self_addr, bal);
        PUSH(u_from_be(bal));
        break;
      }
      case 0x48:  // BASEFEE
        GAS(2);
        PUSH(u_from_be(txc->base_fee));
        break;
      case 0x49: {  // BLOBHASH (EIP-4844, Cancun)
        if (txc->revision < 1) return Halt::kFail;
        GAS(3);
        POP1(idx_u);
        uint64_t idx;
        if (u_fits64(idx_u, &idx) && idx < txc->n_blob_hashes &&
            txc->blob_hashes != nullptr) {
          PUSH(u_from_be(txc->blob_hashes + 32 * idx));
        } else {
          PUSH(u_zero());
        }
        break;
      }
      case 0x4A:  // BLOBBASEFEE (EIP-7516, Cancun)
        if (txc->revision < 1) return Halt::kFail;
        GAS(2);
        PUSH(u_from_be(txc->blob_base_fee));
        break;

      case 0x50: {  // POP
        GAS(2);
        POP1(v);
        (void)v;
        break;
      }
      case 0x51: {  // MLOAD
        POP1(off_u);
        GAS(3);
        if (!expand(off_u, u_from64(32))) return Halt::kFail;
        uint64_t off = 0;
        u_fits64(off_u, &off);
        uint8_t be[32];
        std::memcpy(be, mem.data() + off, 32);
        PUSH(u_from_be(be));
        break;
      }
      case 0x52: {  // MSTORE
        POP2(off_u, val);
        GAS(3);
        if (!expand(off_u, u_from64(32))) return Halt::kFail;
        uint64_t off = 0;
        u_fits64(off_u, &off);
        u_to_be(val, mem.data() + off);
        break;
      }
      case 0x53: {  // MSTORE8
        POP2(off_u, val);
        GAS(3);
        if (!expand(off_u, u_from64(1))) return Halt::kFail;
        uint64_t off = 0;
        u_fits64(off_u, &off);
        mem[off] = (uint8_t)(val.w[0] & 0xFF);
        break;
      }
      case 0x54: {  // SLOAD
        POP1(slot);
        uint8_t key[32];
        u_to_be(slot, key);
        int warm = host->access_storage(host->ctx, self_addr, key);
        GAS(warm ? kWarmSload : kColdSload);
        uint8_t val[32];
        host->get_storage(host->ctx, self_addr, key, val);
        PUSH(u_from_be(val));
        break;
      }
      case 0x55: {  // SSTORE (EIP-2200 + 2929 + 3529 lattice)
        if (msg->is_static) return Halt::kFail;
        if (gas <= kSstoreSentry) return Halt::kFail;
        POP2(slot, new_v);
        uint8_t key[32];
        u_to_be(slot, key);
        int64_t cost = 0;
        if (!host->access_storage(host->ctx, self_addr, key)) cost += kColdSload;
        uint8_t cur_b[32], orig_b[32];
        host->get_storage(host->ctx, self_addr, key, cur_b);
        host->get_original_storage(host->ctx, self_addr, key, orig_b);
        U256 cur = u_from_be(cur_b), orig = u_from_be(orig_b);
        bool cur_eq_new = u_cmp(cur, new_v) == 0;
        bool cur_eq_orig = u_cmp(cur, orig) == 0;
        if (cur_eq_new) {
          cost += kWarmSload;
        } else if (cur_eq_orig) {
          cost += u_is_zero(orig) ? kSstoreSet : kSstoreReset;
        } else {
          cost += kWarmSload;
        }
        GAS(cost);
        if (!cur_eq_new) {
          if (cur_eq_orig) {
            if (!u_is_zero(orig) && u_is_zero(new_v))
              host->add_refund(host->ctx, kSstoreClearsRefund);
          } else {
            if (!u_is_zero(orig)) {
              if (u_is_zero(cur))
                host->add_refund(host->ctx, -kSstoreClearsRefund);
              else if (u_is_zero(new_v))
                host->add_refund(host->ctx, kSstoreClearsRefund);
            }
            if (u_cmp(new_v, orig) == 0) {
              host->add_refund(host->ctx, u_is_zero(orig)
                                              ? kSstoreSet - kWarmSload
                                              : kSstoreReset - kWarmSload);
            }
          }
          uint8_t nv[32];
          u_to_be(new_v, nv);
          host->set_storage(host->ctx, self_addr, key, nv);
        }
        break;
      }
      case 0x56: {  // JUMP
        GAS(8);
        POP1(dst_u);
        uint64_t dst;
        if (!u_fits64(dst_u, &dst) || !is_jumpdest(dst)) return Halt::kFail;
        pc = dst;
        break;
      }
      case 0x57: {  // JUMPI
        GAS(10);
        POP2(dst_u, cond);
        if (!u_is_zero(cond)) {
          uint64_t dst;
          if (!u_fits64(dst_u, &dst) || !is_jumpdest(dst)) return Halt::kFail;
          pc = dst;
        }
        break;
      }
      case 0x58:  // PC
        GAS(2);
        PUSH(u_from64(pc - 1));
        break;
      case 0x59:  // MSIZE
        GAS(2);
        PUSH(u_from64(mem.size()));
        break;
      case 0x5A:  // GAS
        GAS(2);
        PUSH(u_from64((uint64_t)gas));
        break;
      case 0x5B:  // JUMPDEST
        GAS(1);
        break;
      case 0x5C: {  // TLOAD (EIP-1153, Cancun)
        if (txc->revision < 1) return Halt::kFail;
        GAS(kWarmSload);
        POP1(slot);
        uint8_t key[32], val[32];
        u_to_be(slot, key);
        host->get_transient(host->ctx, self_addr, key, val);
        PUSH(u_from_be(val));
        break;
      }
      case 0x5D: {  // TSTORE (EIP-1153, Cancun)
        if (txc->revision < 1) return Halt::kFail;
        if (msg->is_static) return Halt::kFail;
        GAS(kWarmSload);
        POP2(slot, val_u);
        uint8_t key[32], val[32];
        u_to_be(slot, key);
        u_to_be(val_u, val);
        host->set_transient(host->ctx, self_addr, key, val);
        break;
      }
      case 0x5E: {  // MCOPY (EIP-5656, Cancun)
        if (txc->revision < 1) return Halt::kFail;
        POP3(dst_u, src_u, size_u);
        int64_t words_cost;
        if (!size_cost(size_u, kCopyWordGas, &words_cost)) return Halt::kFail;
        GAS(3 + words_cost);
        if (!u_is_zero(size_u)) {
          // one expansion covering both ranges (charge on the larger end)
          const U256& far = u_cmp(dst_u, src_u) >= 0 ? dst_u : src_u;
          if (!expand(far, size_u)) return Halt::kFail;
          uint64_t dst = 0, src = 0, size = 0;
          u_fits64(dst_u, &dst);
          u_fits64(src_u, &src);
          u_fits64(size_u, &size);
          std::memmove(mem.data() + dst, mem.data() + src, size);
        }
        break;
      }
      case 0x5F:  // PUSH0 (EIP-3855, Shanghai)
        GAS(2);
        PUSH(u_zero());
        break;

      case 0xA0:
      case 0xA1:
      case 0xA2:
      case 0xA3:
      case 0xA4: {  // LOG0..LOG4
        if (msg->is_static) return Halt::kFail;
        int ntopics = op - 0xA0;
        POP2(off_u, size_u);
        uint8_t topics[4 * 32];
        for (int i = 0; i < ntopics; ++i) {
          POP1(t);
          u_to_be(t, topics + 32 * i);
        }
        uint64_t size = 0;
        int64_t data_gas;
        if (!u_fits64(size_u, &size) || size > (1ULL << 40)) return Halt::kFail;
        data_gas = kLogDataGas * (int64_t)size;
        GAS(kLogGas + kLogTopicGas * ntopics + data_gas);
        if (!expand(off_u, size_u)) return Halt::kFail;
        uint64_t off = 0;
        u_fits64(off_u, &off);
        host->emit_log(host->ctx, self_addr, size ? mem.data() + off : nullptr,
                       size, topics, ntopics);
        break;
      }

      case 0xF0:    // CREATE
      case 0xF5: {  // CREATE2
        bool is_c2 = op == 0xF5;
        if (msg->is_static) return Halt::kFail;
        POP3(value, off_u, size_u);
        U256 salt = u_zero();
        if (is_c2) {
          POP1(s);
          salt = s;
        }
        uint64_t size = 0;
        if (!u_fits64(size_u, &size) || (int64_t)size > kMaxInitcodeSize)
          return Halt::kFail;  // EIP-3860
        int64_t words = (int64_t)((size + 31) / 32);
        GAS(kCreateGas +
            (kInitcodeWordCost + (is_c2 ? kKeccakWordGas : 0)) * words);
        if (!expand(off_u, size_u)) return Halt::kFail;
        uint64_t off = 0;
        u_fits64(off_u, &off);
        std::vector<uint8_t> init;
        mread(off, size, &init);
        retdata.clear();
        uint8_t bal[32];
        host->get_balance(host->ctx, self_addr, bal);
        if (u_cmp(value, u_from_be(bal)) > 0) {
          PUSH(u_zero());
          break;
        }
        int64_t child_gas = gas - gas / 64;  // EIP-150
        gas -= child_gas;
        PhantMsg cmsg;
        std::memset(&cmsg, 0, sizeof(cmsg));
        cmsg.kind = is_c2 ? PHANT_CREATE2 : PHANT_CREATE;
        cmsg.is_static = 0;
        cmsg.depth = msg->depth + 1;
        cmsg.gas = child_gas;
        std::memcpy(cmsg.caller, self_addr, 20);
        u_to_be(value, cmsg.value);
        cmsg.data = init.data();
        cmsg.data_len = init.size();
        u_to_be(salt, cmsg.salt);
        PhantResult cres;
        std::memset(&cres, 0, sizeof(cres));
        host->call(host->ctx, &cmsg, &cres);
        gas += cres.gas_left;
        if (cres.status == 0) {
          PUSH(u_from_addr(cres.create_address));
        } else {
          if (cres.status == 1 && cres.output_len)
            retdata.assign(cres.output, cres.output + cres.output_len);
          PUSH(u_zero());
        }
        break;
      }

      case 0xF1:    // CALL
      case 0xF2:    // CALLCODE
      case 0xF4:    // DELEGATECALL
      case 0xFA: {  // STATICCALL
        POP2(gas_req, addr_u);
        U256 value = u_zero();
        if (op == 0xF1 || op == 0xF2) {
          POP1(v);
          value = v;
        }
        POP2(in_off, in_size);
        POP2(ret_off, ret_size);
        uint8_t addr[20];
        u_to_addr(addr_u, addr);
        if (op == 0xF1 && !u_is_zero(value) && msg->is_static)
          return Halt::kFail;
        int warm = host->access_account(host->ctx, addr);
        GAS(warm ? kWarmAccount : kColdAccount);
        // EIP-7702: a delegated code target charges the delegate's
        // warm/cold access to THIS instruction, before the 63/64 split
        GAS(host->delegate_access_cost(host->ctx, addr));
        if (!expand(in_off, in_size)) return Halt::kFail;
        if (!expand(ret_off, ret_size)) return Halt::kFail;
        int64_t extra = 0;
        if (!u_is_zero(value)) {
          extra += kCallValueGas;
          if (op == 0xF1 && host->is_empty(host->ctx, addr))
            extra += kNewAccountGas;
        }
        GAS(extra);
        int64_t cap = gas - gas / 64;  // EIP-150
        uint64_t req64;
        int64_t child_gas =
            (u_fits64(gas_req, &req64) && (int64_t)req64 >= 0 &&
             (int64_t)req64 < cap)
                ? (int64_t)req64
                : cap;
        GAS(child_gas);
        if (!u_is_zero(value)) child_gas += kCallStipend;

        uint64_t ioff = 0, isize = 0, roff = 0, rsize = 0;
        u_fits64(in_off, &ioff);
        u_fits64(in_size, &isize);
        u_fits64(ret_off, &roff);
        u_fits64(ret_size, &rsize);
        std::vector<uint8_t> args;
        mread(ioff, isize, &args);
        retdata.clear();

        if (!u_is_zero(value) && (op == 0xF1 || op == 0xF2)) {
          uint8_t bal[32];
          host->get_balance(host->ctx, self_addr, bal);
          if (u_cmp(u_from_be(bal), value) < 0) {
            gas += child_gas;
            PUSH(u_zero());
            break;
          }
        }

        PhantMsg cmsg;
        std::memset(&cmsg, 0, sizeof(cmsg));
        cmsg.depth = msg->depth + 1;
        cmsg.gas = child_gas;
        cmsg.data = args.data();
        cmsg.data_len = args.size();
        if (op == 0xF1) {  // CALL
          cmsg.kind = PHANT_CALL;
          cmsg.is_static = msg->is_static;
          std::memcpy(cmsg.caller, self_addr, 20);
          std::memcpy(cmsg.target, addr, 20);
          std::memcpy(cmsg.code_address, addr, 20);
          u_to_be(value, cmsg.value);
        } else if (op == 0xF2) {  // CALLCODE: run addr's code in our context
          cmsg.kind = PHANT_CALLCODE;
          cmsg.is_static = msg->is_static;
          std::memcpy(cmsg.caller, self_addr, 20);
          std::memcpy(cmsg.target, self_addr, 20);
          std::memcpy(cmsg.code_address, addr, 20);
          u_to_be(value, cmsg.value);
        } else if (op == 0xF4) {  // DELEGATECALL: keep caller + value
          cmsg.kind = PHANT_DELEGATECALL;
          cmsg.is_static = msg->is_static;
          std::memcpy(cmsg.caller, msg->caller, 20);
          std::memcpy(cmsg.target, self_addr, 20);
          std::memcpy(cmsg.code_address, addr, 20);
          std::memcpy(cmsg.value, msg->value, 32);
        } else {  // STATICCALL
          cmsg.kind = PHANT_STATICCALL;
          cmsg.is_static = 1;
          std::memcpy(cmsg.caller, self_addr, 20);
          std::memcpy(cmsg.target, addr, 20);
          std::memcpy(cmsg.code_address, addr, 20);
        }
        PhantResult cres;
        std::memset(&cres, 0, sizeof(cres));
        host->call(host->ctx, &cmsg, &cres);
        if (cres.output_len)
          retdata.assign(cres.output, cres.output + cres.output_len);
        gas += cres.gas_left;
        if (rsize && cres.output_len) {
          uint64_t take = cres.output_len < rsize ? cres.output_len : rsize;
          std::memcpy(mem.data() + roff, cres.output, take);
        }
        PUSH(u_from64(cres.status == 0));
        break;
      }

      case 0xF3: {  // RETURN
        POP2(off_u, size_u);
        if (!expand(off_u, size_u)) return Halt::kFail;
        uint64_t off = 0, size = 0;
        u_fits64(off_u, &off);
        u_fits64(size_u, &size);
        mread(off, size, &out);
        return Halt::kReturn;
      }
      case 0xFD: {  // REVERT
        POP2(off_u, size_u);
        if (!expand(off_u, size_u)) return Halt::kFail;
        uint64_t off = 0, size = 0;
        u_fits64(off_u, &off);
        u_fits64(size_u, &size);
        mread(off, size, &out);
        return Halt::kRevert;
      }
      case 0xFE:  // INVALID
        return Halt::kFail;
      case 0xFF: {  // SELFDESTRUCT
        if (msg->is_static) return Halt::kFail;
        POP1(b_u);
        uint8_t beneficiary[20];
        u_to_addr(b_u, beneficiary);
        GAS(kSelfdestructGas);
        if (!host->access_account(host->ctx, beneficiary)) {
          GAS(kColdAccount);
        }
        uint8_t bal[32];
        host->get_balance(host->ctx, self_addr, bal);
        if (!u_is_zero(u_from_be(bal)) &&
            host->is_empty(host->ctx, beneficiary)) {
          GAS(kNewAccountGas);
        }
        host->selfdestruct(host->ctx, self_addr, beneficiary);
        return Halt::kStop;
      }

      default:
        return Halt::kFail;  // unknown opcode
    }
  }
  return Halt::kStop;  // ran off the end of code
}

}  // namespace

extern "C" {

// Execute one frame of bytecode. The host has already done snapshotting,
// value transfer, and precompile dispatch (exactly the split the reference
// has between its Zig host and evmone). Returns result->status.
// result->output is heap-allocated when non-null; free with phant_evm_free.
int32_t phant_evm_execute(const PhantHost* host, const PhantTxContext* txc,
                          const PhantMsg* msg, const uint8_t* code,
                          uint64_t code_len, PhantResult* result) {
  Interp in(host, txc, msg, code, code_len);
  Halt halt = in.run();
  result->output = nullptr;
  result->output_len = 0;
  std::memset(result->create_address, 0, 20);
  switch (halt) {
    case Halt::kStop:
      result->status = 0;
      result->gas_left = in.gas;
      break;
    case Halt::kReturn:
    case Halt::kRevert: {
      result->status = halt == Halt::kReturn ? 0 : 1;
      result->gas_left = in.gas;
      if (!in.out.empty()) {
        uint8_t* buf = new uint8_t[in.out.size()];
        std::memcpy(buf, in.out.data(), in.out.size());
        result->output = buf;
        result->output_len = in.out.size();
      }
      break;
    }
    default:
      result->status = 2;  // exceptional halt: all gas consumed
      result->gas_left = 0;
      break;
  }
  return result->status;
}

void phant_evm_free(const uint8_t* ptr) { delete[] ptr; }

}  // extern "C"
