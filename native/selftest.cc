// Sanitizer self-test harness for the native runtime (SURVEY §5 race
// detection / sanitizers: the reference relies on Zig's release-safe
// bounds/UB checks; the C++ runtime here gets an explicit
// ASan+UBSan-instrumented known-answer + adversarial-input run instead).
//
// Build + run: `make sanitize` (g++ -fsanitize=address,undefined over all
// native sources + this file; no Python involved, so the sanitizer runtime
// preloads cleanly).
//
// Coverage: keccak256 known-answer vectors + batch layout, the keccak
// bucket packer (incl. overflow rejection), the RLP child-ref scanner on
// real trie-node shapes AND byte-level fuzz (every parse must stay in
// bounds for arbitrary input), and ecrecover round-trips incl. invalid
// signatures. Failures abort with a message; sanitizer findings abort the
// process by themselves.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void phant_keccak256(const uint8_t* in, size_t len, uint8_t* out);
void phant_keccak256_batch(const uint8_t* in, const uint64_t* offsets,
                           const uint32_t* lens, size_t n, uint8_t* out);
int phant_pack_keccak(const uint8_t* in, const uint64_t* offsets,
                      const uint32_t* lens, size_t n, size_t max_chunks,
                      uint8_t* out, int32_t* nchunks);
long phant_scan_refs(const uint8_t* blob, const uint64_t* offsets,
                     const uint32_t* lens, size_t n, int64_t* out_off,
                     int32_t* out_node, size_t cap);
int32_t phant_ecrecover(const uint8_t* msg_hash, const uint8_t* r,
                        const uint8_t* s, int32_t recid, uint8_t* pubkey_out);
void phant_ecrecover_batch(const uint8_t* msg_hashes, const uint8_t* rs,
                           const uint8_t* ss, const int32_t* recids, size_t n,
                           uint8_t* addrs_out, uint8_t* ok_out);
}

static void expect(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "selftest FAILED: %s\n", what);
    std::abort();
  }
}

static std::string hex(const uint8_t* p, size_t n) {
  static const char* d = "0123456789abcdef";
  std::string out;
  for (size_t i = 0; i < n; ++i) {
    out += d[p[i] >> 4];
    out += d[p[i] & 15];
  }
  return out;
}

// xorshift PRNG: deterministic fuzz corpus, no libc rand UB debates
static uint64_t rng_state = 0x9E3779B97F4A7C15ull;
static uint64_t rnd() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

static void test_keccak() {
  uint8_t out[32];
  phant_keccak256(nullptr, 0, out);
  expect(hex(out, 32) ==
             "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470",
         "keccak(empty)");
  phant_keccak256(reinterpret_cast<const uint8_t*>("abc"), 3, out);
  expect(hex(out, 32) ==
             "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45",
         "keccak(abc)");
  // batch layout: 3 payloads incl. one empty and one spanning a rate block
  std::vector<uint8_t> blob(300);
  for (size_t i = 0; i < blob.size(); ++i) blob[i] = uint8_t(rnd());
  uint64_t offsets[3] = {0, 0, 100};
  uint32_t lens[3] = {0, 100, 200};
  uint8_t digests[96];
  phant_keccak256_batch(blob.data(), offsets, lens, 3, digests);
  for (int i = 0; i < 3; ++i) {
    phant_keccak256(blob.data() + offsets[i], lens[i], out);
    expect(std::memcmp(out, digests + 32 * i, 32) == 0, "keccak batch row");
  }
  std::puts("keccak OK");
}

static void test_packer() {
  const size_t kRate = 136;
  std::vector<uint8_t> payloads(500);
  for (auto& b : payloads) b = uint8_t(rnd());
  uint64_t offsets[3] = {0, 10, 200};
  uint32_t lens[3] = {10, 190, 300};
  const size_t max_chunks = 5;
  std::vector<uint8_t> out(3 * max_chunks * kRate, 0);
  int32_t nchunks[3];
  expect(phant_pack_keccak(payloads.data(), offsets, lens, 3, max_chunks,
                           out.data(), nchunks) == 0,
         "pack ok");
  for (int i = 0; i < 3; ++i)
    expect(nchunks[i] == int32_t(lens[i] / kRate + 1), "chunk count");
  // payload over the bucket bound must be rejected, not overrun
  uint32_t big[1] = {uint32_t(max_chunks * kRate)};
  uint64_t off0[1] = {0};
  std::vector<uint8_t> huge(max_chunks * kRate, 7);
  expect(phant_pack_keccak(huge.data(), off0, big, 1, max_chunks, out.data(),
                           nchunks) != 0,
         "oversize payload rejected");
  std::puts("packer OK");
}

static void test_scan_refs() {
  // a hand-built branch node: 17 items, two 32-byte child refs
  std::vector<uint8_t> node;
  std::vector<uint8_t> payload;
  for (int slot = 0; slot < 16; ++slot) {
    if (slot == 3 || slot == 9) {
      payload.push_back(0xA0);
      for (int k = 0; k < 32; ++k) payload.push_back(uint8_t(slot));
    } else {
      payload.push_back(0x80);
    }
  }
  payload.push_back(0x80);  // empty value
  node.push_back(0xF8);
  node.push_back(uint8_t(payload.size()));
  node.insert(node.end(), payload.begin(), payload.end());

  uint64_t offsets[1] = {0};
  uint32_t lens[1] = {uint32_t(node.size())};
  int64_t ref_off[64];
  int32_t ref_node[64];
  long n = phant_scan_refs(node.data(), offsets, lens, 1, ref_off, ref_node, 64);
  expect(n == 2, "branch ref count");
  expect(node[size_t(ref_off[0])] == 3 && node[size_t(ref_off[1])] == 9,
         "branch ref offsets");

  // adversarial fuzz: arbitrary bytes must parse or fail IN BOUNDS — the
  // sanitizers catch any overread; a negative return (malformed) is fine
  for (int iter = 0; iter < 20000; ++iter) {
    size_t len = 1 + rnd() % 120;
    std::vector<uint8_t> junk(len);
    for (auto& b : junk) b = uint8_t(rnd());
    uint64_t o[1] = {0};
    uint32_t l[1] = {uint32_t(len)};
    (void)phant_scan_refs(junk.data(), o, l, 1, ref_off, ref_node, 64);
  }
  // truncation fuzz on the real node: every prefix must stay in bounds
  for (size_t cut = 0; cut < node.size(); ++cut) {
    uint32_t l[1] = {uint32_t(cut)};
    uint64_t o[1] = {0};
    (void)phant_scan_refs(node.data(), o, l, 1, ref_off, ref_node, 64);
  }
  std::puts("scan_refs OK");
}

static void test_ecrecover() {
  // a known mainnet-style signature round-trip is covered by the Python
  // diff tests; here exercise memory safety: valid-range and garbage inputs
  uint8_t msg[32], r[32], s[32], pubkey[64];
  for (int iter = 0; iter < 200; ++iter) {
    for (int i = 0; i < 32; ++i) {
      msg[i] = uint8_t(rnd());
      r[i] = uint8_t(rnd());
      s[i] = uint8_t(rnd());
    }
    (void)phant_ecrecover(msg, r, s, int(rnd() % 4), pubkey);
  }
  // all-zero r/s must be rejected
  std::memset(r, 0, 32);
  std::memset(s, 0, 32);
  expect(phant_ecrecover(msg, r, s, 0, pubkey) != 0, "zero sig rejected");
  // batch path incl. the ok/addr outputs
  uint8_t msgs[2 * 32], rs[2 * 32], ss[2 * 32], addrs[2 * 20], ok[2];
  int32_t recids[2] = {0, 1};
  for (int i = 0; i < 64; ++i) {
    msgs[i] = uint8_t(rnd());
    rs[i] = uint8_t(rnd() % 200);
    ss[i] = uint8_t(rnd() % 200);
  }
  phant_ecrecover_batch(msgs, rs, ss, recids, 2, addrs, ok);
  std::puts("ecrecover OK");
}

int main() {
  test_keccak();
  test_packer();
  test_scan_refs();
  test_ecrecover();
  std::puts("native selftest: ALL OK");
  return 0;
}
