// Sanitizer self-test harness for the native runtime (SURVEY §5 race
// detection / sanitizers: the reference relies on Zig's release-safe
// bounds/UB checks; the C++ runtime here gets an explicit
// ASan+UBSan-instrumented known-answer + adversarial-input run instead).
//
// Build + run: `make sanitize` (g++ -fsanitize=address,undefined over all
// native sources + this file; no Python involved, so the sanitizer runtime
// preloads cleanly).
//
// Coverage: keccak256 known-answer vectors + batch layout, the keccak
// bucket packer (incl. overflow rejection), the RLP child-ref scanner on
// real trie-node shapes AND byte-level fuzz (every parse must stay in
// bounds for arbitrary input), and ecrecover round-trips incl. invalid
// signatures. Failures abort with a message; sanitizer findings abort the
// process by themselves.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void phant_keccak256(const uint8_t* in, size_t len, uint8_t* out);
void phant_keccak256_batch(const uint8_t* in, const uint64_t* offsets,
                           const uint32_t* lens, size_t n, uint8_t* out);
void phant_keccak256_batch_fast(const uint8_t* in, const uint64_t* offsets,
                                const uint32_t* lens, size_t n, uint8_t* out);
int phant_pack_keccak(const uint8_t* in, const uint64_t* offsets,
                      const uint32_t* lens, size_t n, size_t max_chunks,
                      uint8_t* out, int32_t* nchunks);
long phant_scan_refs(const uint8_t* blob, const uint64_t* offsets,
                     const uint32_t* lens, size_t n, int64_t* out_off,
                     int32_t* out_node, size_t cap);
int32_t phant_ecrecover(const uint8_t* msg_hash, const uint8_t* r,
                        const uint8_t* s, int32_t recid, uint8_t* pubkey_out);
void phant_ecrecover_batch(const uint8_t* msg_hashes, const uint8_t* rs,
                           const uint8_t* ss, const int32_t* recids, size_t n,
                           uint8_t* addrs_out, uint8_t* ok_out);
}

static void expect(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "selftest FAILED: %s\n", what);
    std::abort();
  }
}

static std::string hex(const uint8_t* p, size_t n) {
  static const char* d = "0123456789abcdef";
  std::string out;
  for (size_t i = 0; i < n; ++i) {
    out += d[p[i] >> 4];
    out += d[p[i] & 15];
  }
  return out;
}

// xorshift PRNG: deterministic fuzz corpus, no libc rand UB debates
static uint64_t rng_state = 0x9E3779B97F4A7C15ull;
static uint64_t rnd() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

static void test_keccak() {
  uint8_t out[32];
  phant_keccak256(nullptr, 0, out);
  expect(hex(out, 32) ==
             "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470",
         "keccak(empty)");
  phant_keccak256(reinterpret_cast<const uint8_t*>("abc"), 3, out);
  expect(hex(out, 32) ==
             "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45",
         "keccak(abc)");
  // batch layout: 3 payloads incl. one empty and one spanning a rate block
  std::vector<uint8_t> blob(300);
  for (size_t i = 0; i < blob.size(); ++i) blob[i] = uint8_t(rnd());
  uint64_t offsets[3] = {0, 0, 100};
  uint32_t lens[3] = {0, 100, 200};
  uint8_t digests[96];
  phant_keccak256_batch(blob.data(), offsets, lens, 3, digests);
  for (int i = 0; i < 3; ++i) {
    phant_keccak256(blob.data() + offsets[i], lens[i], out);
    expect(std::memcmp(out, digests + 32 * i, 32) == 0, "keccak batch row");
  }
  // the 8-way AVX-512 multi-buffer batch must be bit-identical to scalar
  // (and memory-clean under ASan): randomized sizes across chunk
  // boundaries, incl. empty payloads and the <8 scalar tail
  constexpr size_t kN = 61;
  std::vector<uint8_t> big;
  uint64_t foffs[kN];
  uint32_t flens[kN];
  for (size_t i = 0; i < kN; ++i) {
    const uint32_t len = i == 7 ? 0 : uint32_t(rnd() % 700);
    foffs[i] = big.size();
    flens[i] = len;
    for (uint32_t k = 0; k < len; ++k) big.push_back(uint8_t(rnd()));
  }
  std::vector<uint8_t> dig_s(32 * kN), dig_f(32 * kN);
  phant_keccak256_batch(big.data(), foffs, flens, kN, dig_s.data());
  phant_keccak256_batch_fast(big.data(), foffs, flens, kN, dig_f.data());
  expect(dig_s == dig_f, "fast batch == scalar batch");
  std::puts("keccak OK");
}

static void test_packer() {
  const size_t kRate = 136;
  std::vector<uint8_t> payloads(500);
  for (auto& b : payloads) b = uint8_t(rnd());
  uint64_t offsets[3] = {0, 10, 200};
  uint32_t lens[3] = {10, 190, 300};
  const size_t max_chunks = 5;
  std::vector<uint8_t> out(3 * max_chunks * kRate, 0);
  int32_t nchunks[3];
  expect(phant_pack_keccak(payloads.data(), offsets, lens, 3, max_chunks,
                           out.data(), nchunks) == 0,
         "pack ok");
  for (int i = 0; i < 3; ++i)
    expect(nchunks[i] == int32_t(lens[i] / kRate + 1), "chunk count");
  // payload over the bucket bound must be rejected, not overrun
  uint32_t big[1] = {uint32_t(max_chunks * kRate)};
  uint64_t off0[1] = {0};
  std::vector<uint8_t> huge(max_chunks * kRate, 7);
  expect(phant_pack_keccak(huge.data(), off0, big, 1, max_chunks, out.data(),
                           nchunks) != 0,
         "oversize payload rejected");
  std::puts("packer OK");
}

static void test_scan_refs() {
  // a hand-built branch node: 17 items, two 32-byte child refs
  std::vector<uint8_t> node;
  std::vector<uint8_t> payload;
  for (int slot = 0; slot < 16; ++slot) {
    if (slot == 3 || slot == 9) {
      payload.push_back(0xA0);
      for (int k = 0; k < 32; ++k) payload.push_back(uint8_t(slot));
    } else {
      payload.push_back(0x80);
    }
  }
  payload.push_back(0x80);  // empty value
  node.push_back(0xF8);
  node.push_back(uint8_t(payload.size()));
  node.insert(node.end(), payload.begin(), payload.end());

  uint64_t offsets[1] = {0};
  uint32_t lens[1] = {uint32_t(node.size())};
  int64_t ref_off[64];
  int32_t ref_node[64];
  long n = phant_scan_refs(node.data(), offsets, lens, 1, ref_off, ref_node, 64);
  expect(n == 2, "branch ref count");
  expect(node[size_t(ref_off[0])] == 3 && node[size_t(ref_off[1])] == 9,
         "branch ref offsets");

  // adversarial fuzz: arbitrary bytes must parse or fail IN BOUNDS — the
  // sanitizers catch any overread; a negative return (malformed) is fine
  for (int iter = 0; iter < 20000; ++iter) {
    size_t len = 1 + rnd() % 120;
    std::vector<uint8_t> junk(len);
    for (auto& b : junk) b = uint8_t(rnd());
    uint64_t o[1] = {0};
    uint32_t l[1] = {uint32_t(len)};
    (void)phant_scan_refs(junk.data(), o, l, 1, ref_off, ref_node, 64);
  }
  // truncation fuzz on the real node: every prefix must stay in bounds
  for (size_t cut = 0; cut < node.size(); ++cut) {
    uint32_t l[1] = {uint32_t(cut)};
    uint64_t o[1] = {0};
    (void)phant_scan_refs(node.data(), o, l, 1, ref_off, ref_node, 64);
  }
  std::puts("scan_refs OK");
}


// Known-answer edge vectors, cross-generated from the independent
// pure-Python implementation (phant_tpu/crypto/secp256k1.py) — the two
// from-scratch implementations must agree bit-for-bit on the corners
// libsecp256k1's test corpus stresses: recid 2/3 with r just above p-n,
// s at the low-s maximum (n-1)/2, high-s (precompile semantics accept it),
// s = 1, and a fixed-key sign/recover roundtrip.
struct EdgeVector {
  uint8_t e[32];
  uint8_t r[32];
  uint8_t s[32];
  int32_t recid;
  uint8_t pub[64];
};

static const EdgeVector kEdgeVectors[] = {
    // boundary_r_recid23
    {{0x94,0x58,0x27,0x43,0x30,0x17,0xc1,0xaf,0x30,0xa8,0x32,0xbd,0xcb,0xd1,0x6b,0x5a,0x76,0x73,0x1a,0x8c,0x9d,0xc9,0x2d,0x67,0x83,0x1b,0xe3,0x7b,0x95,0x11,0x4c,0x8d},
     {0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x02},
     {0x7f,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0x5d,0x57,0x6e,0x73,0x57,0xa4,0x50,0x1d,0xdf,0xe9,0x2f,0x46,0x68,0x1b,0x20,0xa0}, 3,
     {0x9e,0x64,0xc6,0xcd,0x99,0xa8,0x42,0x6a,0x4e,0xc7,0xe0,0x8e,0x6c,0xea,0x1c,0x3b,0x08,0x11,0x8d,0xba,0x0b,0x27,0xa4,0x06,0x4b,0xe6,0xb5,0xde,0x3c,0x8f,0x3a,0x2e,0xf7,0xda,0x44,0xae,0x2f,0x09,0x28,0xd9,0xda,0x5e,0x1e,0x6b,0x15,0x9b,0x36,0x98,0x9d,0x88,0xb7,0x17,0x4d,0xeb,0x29,0x3e,0x50,0xdf,0xf3,0xf9,0x08,0x79,0x19,0x2f}},
    // high_s
    {{0x44,0x8c,0xf7,0x73,0xae,0x2d,0xd3,0xa9,0xc8,0x42,0xae,0xb1,0xb9,0xe5,0x43,0x8b,0x54,0x2d,0x3f,0xcd,0x57,0x8c,0xac,0xf8,0x76,0x56,0x4c,0x9e,0xc3,0x9f,0x4e,0xab},
     {0xd4,0x76,0x44,0x53,0x9a,0xce,0xc3,0xda,0x5e,0x3e,0xcf,0x5f,0xe8,0x86,0x3c,0x62,0x8a,0x9c,0x97,0xe8,0xb7,0x1e,0x9e,0xa9,0x16,0x7a,0x6f,0x4f,0x83,0xc0,0x3c,0x32},
     {0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xfe,0xba,0xae,0xdc,0xe6,0xaf,0x48,0xa0,0x3b,0xbf,0xd2,0x5e,0x8c,0xd0,0x36,0x41,0x3c}, 0,
     {0x5e,0x85,0x3a,0xa1,0x36,0x24,0xbe,0xac,0x17,0xfe,0xfe,0xd7,0x8c,0xa6,0x05,0x28,0x9a,0xc4,0xcc,0x3d,0xed,0x3c,0xde,0x92,0x75,0xeb,0x0f,0x9b,0xf9,0x28,0x06,0x4d,0x47,0x9a,0x18,0x57,0x8e,0xc0,0x63,0x2e,0xa7,0xaf,0xe3,0x00,0xc3,0x14,0x95,0x55,0xa9,0xe0,0x07,0xbc,0xc0,0x99,0xe1,0x79,0x69,0x0a,0xd9,0xd0,0x59,0x11,0x48,0x04}},
    // s_one
    {{0x44,0x8c,0xf7,0x73,0xae,0x2d,0xd3,0xa9,0xc8,0x42,0xae,0xb1,0xb9,0xe5,0x43,0x8b,0x54,0x2d,0x3f,0xcd,0x57,0x8c,0xac,0xf8,0x76,0x56,0x4c,0x9e,0xc3,0x9f,0x4e,0xab},
     {0xd4,0x76,0x44,0x53,0x9a,0xce,0xc3,0xda,0x5e,0x3e,0xcf,0x5f,0xe8,0x86,0x3c,0x62,0x8a,0x9c,0x97,0xe8,0xb7,0x1e,0x9e,0xa9,0x16,0x7a,0x6f,0x4f,0x83,0xc0,0x3c,0x32},
     {0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x00,0x01}, 0,
     {0x02,0xc3,0x0c,0x7a,0x08,0x27,0xc7,0x54,0x56,0x20,0xcd,0xb0,0x17,0x58,0xc6,0x35,0xdd,0x9f,0xb6,0xcb,0x2c,0x42,0xcb,0x8f,0x06,0x21,0x40,0x4f,0x84,0x57,0xbc,0x32,0x7a,0x0a,0x7d,0x9e,0x3c,0x50,0x9e,0x1f,0x09,0xc2,0x36,0xb2,0x29,0xaa,0xad,0x40,0xdb,0xdf,0x0a,0x02,0x48,0x08,0x63,0x70,0x12,0x07,0x9f,0x0b,0x8f,0x0d,0xe2,0x5e}},
    // roundtrip
    {{0x11,0x20,0xdd,0xfc,0x5d,0x8e,0x04,0xe3,0xae,0x61,0x96,0x6c,0xa0,0xeb,0x28,0xd5,0x1a,0xee,0x7c,0x34,0x9a,0xd9,0xe5,0x0d,0xbf,0xfd,0x19,0x75,0xd2,0x56,0x8f,0x47},
     {0xf3,0x2e,0x7c,0x74,0xaa,0xd0,0xc3,0x00,0x42,0x4a,0x09,0xd6,0x75,0x81,0x7b,0x83,0xde,0x1d,0x43,0xf0,0xd1,0xbc,0x39,0xa2,0xd6,0xf4,0xcf,0x5a,0xd1,0x83,0xf0,0xcd},
     {0x57,0x97,0x44,0x1a,0x2f,0x51,0xc5,0x12,0x51,0xf2,0x70,0x96,0x23,0xeb,0x61,0x06,0x4f,0x85,0xa9,0xf4,0xac,0xcf,0x77,0xd2,0xa7,0xc0,0x5b,0x07,0xce,0x1b,0x55,0x71}, 1,
     {0x2a,0x5b,0xbc,0xb0,0xee,0xde,0x52,0x8e,0x6a,0xbe,0x5f,0x2e,0xc5,0x0a,0xd7,0x88,0x7e,0xb5,0x67,0x7a,0xf3,0x83,0xa4,0x60,0xb0,0x5e,0xe2,0x3b,0xf8,0x92,0xdf,0xe5,0x52,0xc9,0x37,0x47,0x55,0x0e,0xda,0x84,0x04,0xc8,0xb4,0x73,0x78,0x6c,0x00,0xdf,0xd8,0xfd,0x1e,0xf4,0xbc,0x03,0x3f,0x35,0x9c,0xcf,0x5b,0x77,0xbd,0x65,0x6d,0x21}},
};

static void test_ecrecover_edge_vectors() {
  uint8_t pubkey[64];
  for (const auto& v : kEdgeVectors) {
    expect(phant_ecrecover(v.e, v.r, v.s, v.recid, pubkey) == 0,
           "edge vector must recover");
    expect(std::memcmp(pubkey, v.pub, 64) == 0, "edge vector pubkey match");
  }
  // rejections: r = n, s = n, and recid 2 with x = r + n >= p
  uint8_t e[32], r[32], s[32];
  std::memcpy(e, kEdgeVectors[0].e, 32);
  // n (big-endian)
  static const uint8_t kN[32] = {0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xff,
                                 0xff,0xff,0xff,0xff,0xff,0xff,0xff,0xfe,
                                 0xba,0xae,0xdc,0xe6,0xaf,0x48,0xa0,0x3b,
                                 0xbf,0xd2,0x5e,0x8c,0xd0,0x36,0x41,0x41};
  // p - n = 0x14551231950b75fc4402da1722fc9baee (x = p overflows the field)
  static const uint8_t kPminusN[32] = {0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,
                                       0x01,0x45,0x51,0x23,0x19,0x50,0xb7,
                                       0x5f,0xc4,0x40,0x2d,0xa1,0x72,0x2f,
                                       0xc9,0xba,0xee};
  std::memcpy(s, kEdgeVectors[0].s, 32);
  std::memcpy(r, kN, 32);
  expect(phant_ecrecover(e, r, s, 0, pubkey) != 0, "r = n rejected");
  std::memcpy(r, kEdgeVectors[0].r, 32);
  std::memcpy(s, kN, 32);
  expect(phant_ecrecover(e, r, s, 0, pubkey) != 0, "s = n rejected");
  std::memcpy(r, kPminusN, 32);
  std::memcpy(s, kEdgeVectors[0].s, 32);
  expect(phant_ecrecover(e, r, s, 2, pubkey) != 0,
         "recid 2 with r + n >= p rejected");
  std::puts("ecrecover edge vectors OK");
}

static void test_ecrecover() {
  // a known mainnet-style signature round-trip is covered by the Python
  // diff tests; here exercise memory safety: valid-range and garbage inputs
  uint8_t msg[32], r[32], s[32], pubkey[64];
  for (int iter = 0; iter < 200; ++iter) {
    for (int i = 0; i < 32; ++i) {
      msg[i] = uint8_t(rnd());
      r[i] = uint8_t(rnd());
      s[i] = uint8_t(rnd());
    }
    (void)phant_ecrecover(msg, r, s, int(rnd() % 4), pubkey);
  }
  // all-zero r/s must be rejected
  std::memset(r, 0, 32);
  std::memset(s, 0, 32);
  expect(phant_ecrecover(msg, r, s, 0, pubkey) != 0, "zero sig rejected");
  // batch path incl. the ok/addr outputs
  uint8_t msgs[2 * 32], rs[2 * 32], ss[2 * 32], addrs[2 * 20], ok[2];
  int32_t recids[2] = {0, 1};
  for (int i = 0; i < 64; ++i) {
    msgs[i] = uint8_t(rnd());
    rs[i] = uint8_t(rnd() % 200);
    ss[i] = uint8_t(rnd() % 200);
  }
  phant_ecrecover_batch(msgs, rs, ss, recids, 2, addrs, ok);
  std::puts("ecrecover OK");
}

// --- witness-engine core (native/engine.cc) under the sanitizers ----------
// The engine parses untrusted witness bytes (RLP ref scan, open-addressing
// tables, arena copies); feed it garbage and adversarial shapes.

extern "C" {
void* phant_engine_new();
void phant_engine_free(void*);
void phant_engine_flush(void*);
uint64_t phant_engine_nodes(void*);
uint64_t phant_engine_digests(void*);
int phant_engine_scan(void*, const uint8_t*, const uint64_t*, const uint32_t*,
                      uint64_t, int64_t*, uint32_t*, uint64_t*);
int64_t phant_engine_commit(void*, const uint8_t*, const uint64_t*,
                            const uint32_t*, uint64_t, int64_t*,
                            const uint32_t*, uint64_t, const uint8_t*);
int phant_engine_verdict(void*, const int64_t*, const uint64_t*, uint64_t,
                         const uint8_t*, uint8_t*);
}

static void test_engine_fuzz() {
  void* eng = phant_engine_new();
  std::vector<uint8_t> blob;
  std::vector<uint64_t> offs;
  std::vector<uint32_t> lens;
  // 4096 garbage nodes (0..200B, random bytes incl. zero-length), some
  // repeated verbatim to exercise batch-dup and cross-batch hit paths
  std::vector<std::vector<uint8_t>> nodes;
  for (int i = 0; i < 4096; ++i) {
    if (i % 7 == 3 && !nodes.empty()) {
      nodes.push_back(nodes[rnd() % nodes.size()]);
      continue;
    }
    std::vector<uint8_t> n(rnd() % 201);
    for (auto& b : n) b = static_cast<uint8_t>(rnd());
    if (!n.empty() && i % 3 == 0) n[0] = 0xc0 + (rnd() % 56);  // RLP-ish list
    nodes.push_back(std::move(n));
  }
  for (int round = 0; round < 3; ++round) {  // round 2+: all-hit rescans
    blob.clear();
    offs.clear();
    lens.clear();
    for (const auto& n : nodes) {
      offs.push_back(blob.size());
      lens.push_back(static_cast<uint32_t>(n.size()));
      blob.insert(blob.end(), n.begin(), n.end());
    }
    const uint64_t N = nodes.size();
    std::vector<int64_t> rows(N);
    std::vector<uint32_t> novel(N);
    uint64_t counts[2];
    expect(phant_engine_scan(eng, blob.data(), offs.data(), lens.data(), N,
                             rows.data(), novel.data(), counts) == 0,
           "engine scan");
    if (counts[1]) {
      // digests are garbage too (the engine trusts the caller's hasher)
      std::vector<uint8_t> digs(32 * counts[1]);
      for (auto& b : digs) b = static_cast<uint8_t>(rnd());
      phant_engine_commit(eng, blob.data(), offs.data(), lens.data(), N,
                          rows.data(), novel.data(), counts[1], digs.data());
    } else {
      expect(round > 0, "first round must find novel nodes");
    }
    // verdicts over ragged fake blocks + garbage roots
    std::vector<uint64_t> boffs{0};
    while (boffs.back() < N)
      boffs.push_back(
          std::min<uint64_t>(N, boffs.back() + 1 + rnd() % 33));
    const uint64_t nb = boffs.size() - 1;
    std::vector<uint8_t> roots(32 * nb);
    for (auto& b : roots) b = static_cast<uint8_t>(rnd());
    std::vector<uint8_t> ok(nb);
    expect(phant_engine_verdict(eng, rows.data(), boffs.data(), nb,
                                roots.data(), ok.data()) == 0,
           "engine verdict");
  }
  expect(phant_engine_nodes(eng) > 0 && phant_engine_digests(eng) > 0,
         "engine interned");
  phant_engine_flush(eng);
  expect(phant_engine_nodes(eng) == 0, "engine flush");
  phant_engine_free(eng);
  std::puts("engine fuzz OK");
}

int main() {
  test_keccak();
  test_packer();
  test_scan_refs();
  test_ecrecover();
  test_ecrecover_edge_vectors();
  test_engine_fuzz();
  std::puts("native selftest: ALL OK");
  return 0;
}
