// Native witness-engine core: the memoized linked-multiproof verifier's
// interning tables and verdict join, in C++ (the framework keeps the
// runtime native where the reference's is — the reference's hot loop is
// src/mpt/mpt.zig:38-119 + src/crypto/hasher.zig:4-17, recomputing every
// node hash per block; this core is the redesigned cross-block engine
// behind phant_tpu/ops/witness_engine.py).
//
// Division of labor: Python (witness_engine.WitnessEngine) keeps the
// policy — batch assembly, the device/native hashing route for novel
// nodes, eviction decisions, stats — and this core keeps the mechanism:
//   * node-bytes -> row interning (open-addressing table keyed by a
//     64-bit multiply-mix hash, exact bytes compare on probe, node bytes
//     copied into an arena);
//   * digest -> refid interning (every 32-byte digest that appears as a
//     node's hash OR inside a node as a child reference gets one id, so
//     parent->child linkage resolves at insert time);
//   * per-row own_refid + 17 child-refid slots (branch(16) + account
//     storage root), child references extracted by the same per-node RLP
//     scan as native/packer.cc but per-node tolerant: a malformed node
//     contributes no refs (it can still BE referenced), matching
//     witness_engine._extract_ref_digests;
//   * the batched verdict: block b verifies iff some node's digest equals
//     root_b AND every node is that root or is hash-referenced by another
//     node of block b — an epoch-stamped refid scan, zero cryptography.
//
// Protocol per verify_batch (driven from Python under the engine lock):
//   scan(blob,offs,lens)  -> rows (row id, or -2-k for novel index k),
//                            novel first-occurrence indices, miss count
//   [Python hashes the novel nodes on the routed backend]
//   commit(..., digests)  -> inserts novel rows, interns digests + refs,
//                            patches the negative rows in place
//   verdict(rows, block_offsets, roots) -> per-block 0/1
//
// Soundness notes: memoization keys are the FULL node bytes (hash match
// is confirmed with memcmp), digest interning compares all 32 bytes, and
// digests are only ever computed from full node bytes by the
// differential-tested keccak backends — linking a foreign node would need
// a keccak collision. The 64-bit table hashes are a perf detail only.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <chrono>
#include <vector>

extern "C" void phant_keccak256_ptrs_fast(const uint8_t* const*,
                                          const uint32_t*, size_t, uint8_t*);

namespace {

constexpr int kChildSlots = 17;

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

inline uint64_t load_tail(const uint8_t* p, size_t len) {
  uint64_t v = 0;
  std::memcpy(&v, p, len);
  return v;
}

inline uint64_t mix(uint64_t a, uint64_t b) {
  __uint128_t r = static_cast<__uint128_t>(a) * b;
  return static_cast<uint64_t>(r) ^ static_cast<uint64_t>(r >> 64);
}

// Multiply-mix string hash (wyhash-family construction): 16B/iteration of
// 128-bit multiply folding. Node bytes are untrusted but a collision only
// costs a memcmp; the secrets below are fixed odd constants.
uint64_t hash_bytes(const uint8_t* p, size_t len, uint64_t seed) {
  constexpr uint64_t k0 = 0x9e3779b97f4a7c15ULL;
  constexpr uint64_t k1 = 0xd1b54a32d192ed03ULL;
  constexpr uint64_t k2 = 0x8bb84b93962eacc9ULL;
  constexpr uint64_t k3 = 0x589965cc75374cc3ULL;
  uint64_t h = mix(static_cast<uint64_t>(len) ^ k0 ^ seed, k2);
  uint64_t g = h ^ k3;
  // two independent 16B multiply chains per iteration (ILP)
  while (len >= 32) {
    h = mix(load64(p) ^ k1, load64(p + 8) ^ h);
    g = mix(load64(p + 16) ^ k2, load64(p + 24) ^ g);
    p += 32;
    len -= 32;
  }
  while (len >= 16) {
    h = mix(load64(p) ^ k1, load64(p + 8) ^ h);
    p += 16;
    len -= 16;
  }
  uint64_t a = 0, b = 0;
  if (len >= 8) {
    a = load64(p);
    b = load_tail(p + 8, len - 8);
  } else if (len) {
    b = load_tail(p, len);
  }
  return mix(a ^ k2, (b ^ h) + g);
}

inline uint64_t hash_digest(const uint8_t* d, uint64_t seed) {
  // 32 uniform (or attacker-chosen) bytes; one 128-bit multiply over the
  // first two words, keyed by the per-engine secret seed. Without the
  // seed any public mix is invertible and crafted child refs could grow
  // one probe chain quadratically (perf DoS only — the full-digest
  // memcmp keeps correctness either way). This runs ~17x per novel node,
  // the hot half of commit, so it stays two loads + one multiply.
  return mix(load64(d) ^ seed, load64(d + 8) ^ 0x9e3779b97f4a7c15ULL);
}

// --- RLP child-ref scan (per-node tolerant twin of packer.cc) --------------

bool rlp_item(const uint8_t* d, size_t end, size_t* pos, int* kind,
              size_t* ps, size_t* pe) {
  if (*pos >= end) return false;
  const uint8_t b = d[*pos];
  size_t l, s;
  if (b < 0x80) {
    *kind = 0;
    *ps = *pos;
    *pe = *pos + 1;
    *pos += 1;
    return true;
  }
  if (b < 0xb8) {
    l = b - 0x80;
    s = *pos + 1;
    *kind = 0;
  } else if (b < 0xc0) {
    const size_t ll = b - 0xb7;
    if (*pos + 1 + ll > end) return false;
    l = 0;
    for (size_t i = 0; i < ll; ++i) l = (l << 8) | d[*pos + 1 + i];
    s = *pos + 1 + ll;
    *kind = 0;
  } else if (b < 0xf8) {
    l = b - 0xc0;
    s = *pos + 1;
    *kind = 1;
  } else {
    const size_t ll = b - 0xf7;
    if (*pos + 1 + ll > end) return false;
    l = 0;
    for (size_t i = 0; i < ll; ++i) l = (l << 8) | d[*pos + 1 + i];
    s = *pos + 1 + ll;
    *kind = 1;
  }
  if (l > end || s + l > end) return false;
  *ps = s;
  *pe = s + l;
  *pos = s + l;
  return true;
}

long account_storage_root_off(const uint8_t* d, size_t s, size_t e) {
  size_t pos = s;
  int kind;
  size_t ps, pe;
  if (!rlp_item(d, e, &pos, &kind, &ps, &pe) || kind != 1 || pos != e)
    return -1;
  size_t ips[4], ipe[4];
  int n = 0;
  size_t p = ps;
  while (p < pe) {
    if (n >= 4) return -1;
    int k;
    if (!rlp_item(d, pe, &p, &k, &ips[n], &ipe[n]) || k != 0) return -1;
    ++n;
  }
  if (n != 4 || ipe[2] - ips[2] != 32 || ipe[3] - ips[3] != 32) return -1;
  return static_cast<long>(ips[2]);
}

// Collect child-ref offsets of one node's list payload into out[0..cap).
// Returns the count, or -1 on malformed input (caller discards ALL of the
// node's refs — the Python twin's catch-ValueError-return-[] contract).
long scan_node_list(const uint8_t* d, size_t s, size_t e, size_t* out,
                    long cap, long cnt, int depth) {
  if (depth > 64) return -1;
  int kinds[kChildSlots];
  size_t pss[kChildSlots], pes[kChildSlots];
  int nitems = 0;
  size_t pos = s;
  while (pos < e) {
    if (nitems >= kChildSlots) return -1;
    if (!rlp_item(d, e, &pos, &kinds[nitems], &pss[nitems], &pes[nitems]))
      return -1;
    ++nitems;
  }
  if (nitems == 17) {
    for (int i = 0; i < 16; ++i) {
      if (kinds[i] == 0 && pes[i] - pss[i] == 32) {
        if (cnt < cap) out[cnt] = pss[i];
        ++cnt;  // past-cap refs still count (they are DROPPED, not an error)
      } else if (kinds[i] == 1 && pes[i] > pss[i]) {
        cnt = scan_node_list(d, pss[i], pes[i], out, cap, cnt, depth + 1);
        if (cnt < 0) return -1;
      }
    }
  } else if (nitems == 2) {
    if (pes[0] == pss[0]) return -1;  // hex-prefix path is never empty
    const bool is_leaf = (d[pss[0]] & 0x20) != 0;
    if (!is_leaf) {
      if (kinds[1] == 0 && pes[1] - pss[1] == 32) {
        if (cnt < cap) out[cnt] = pss[1];
        ++cnt;
      } else if (kinds[1] == 1) {
        cnt = scan_node_list(d, pss[1], pes[1], out, cap, cnt, depth + 1);
        if (cnt < 0) return -1;
      }
    } else if (kinds[1] == 0) {
      const long sr = account_storage_root_off(d, pss[1], pes[1]);
      if (sr >= 0) {
        if (cnt < cap) out[cnt] = static_cast<size_t>(sr);
        ++cnt;
      }
    }
  }
  return cnt;
}

// Refs of node [s, e): up to kChildSlots offsets (first in scan order, the
// Python twin drops slots >= 17 before interning). 0 refs on malformed.
int node_refs(const uint8_t* d, size_t s, size_t e, size_t* out) {
  size_t pos = s;
  int kind;
  size_t ps, pe;
  if (!rlp_item(d, e, &pos, &kind, &ps, &pe) || kind != 1 || pos != e)
    return 0;
  long cnt = scan_node_list(d, ps, pe, out, kChildSlots, 0, 0);
  if (cnt < 0) return 0;
  return static_cast<int>(cnt < kChildSlots ? cnt : kChildSlots);
}

// --- open-addressing tables -------------------------------------------------

struct NodeEntry {
  uint64_t hash;
  uint64_t arena_off;
  uint32_t len;
  int32_t row;  // -1 = empty slot
};

// Probe entry is 16B (4 per cache line); digest bytes live in a separate
// refid-indexed arena written sequentially — commit's ~17 intern_digest
// calls per novel node are memory-bound, so the probe path touches as few
// random lines as possible.
struct DigestEntry {
  uint64_t hash;
  int32_t refid;  // -1 = empty slot
  uint32_t pre4;  // first 4 digest bytes: probe filter (exact memcmp still
                  // decides equality — this only prunes false slot hits
                  // and lets commit's pass A run compare-free)
};

struct Engine {
  // node interning
  std::vector<NodeEntry> ntab;
  std::vector<uint8_t> arena;
  uint64_t n_nodes = 0;
  // digest interning
  std::vector<DigestEntry> dtab;
  std::vector<uint8_t> digest_arena;  // 32B per refid, refid-indexed
  uint64_t n_digests = 0;
  // per-row linkage
  std::vector<int32_t> own_refid;
  std::vector<int32_t> child_refids;  // n_rows * kChildSlots, -1 sentinel
  // verdict scratch: stamp[refid] = tag of the last block referencing it
  std::vector<uint64_t> stamp;
  uint64_t stamp_serial = 0;
  // secret table seed: keys both hashes so untrusted witness bytes cannot
  // engineer probe-chain collisions (address + clock entropy, mixed)
  uint64_t seed;
  // batch scratch (scan -> commit)
  std::vector<uint32_t> novel_dup;  // open table over this batch's novel set
  std::vector<const uint8_t*> ptr_scratch;  // blob-adapter node pointers
  std::vector<const uint8_t*> novel_ptrs;  // commit_hash scratch
  std::vector<uint32_t> novel_lens;
  std::vector<uint8_t> digest_scratch;
  // commit's flattened digest-ref stream (pass A/B pipeline scratch)
  std::vector<const uint8_t*> flat_d;
  std::vector<uint64_t> flat_h;
  std::vector<int32_t*> flat_out;
  std::vector<int32_t> flat_refid;

  Engine() {
    seed = mix(reinterpret_cast<uint64_t>(this) ^ 0xa0761d6478bd642fULL,
               static_cast<uint64_t>(
                   std::chrono::steady_clock::now().time_since_epoch().count()) |
                   1ULL);
    // sized for a mainnet-shaped working set out of the gate (64k nodes,
    // ~1M digests): early growth rehashes the whole table mid-batch
    ntab.resize(1 << 14);
    for (auto& e : ntab) e.row = -1;
    dtab.resize(1 << 17);
    for (auto& e : dtab) e.refid = -1;
  }

  void flush() {
    for (auto& e : ntab) e.row = -1;
    for (auto& e : dtab) e.refid = -1;
    digest_arena.clear();
    arena.clear();
    own_refid.clear();
    child_refids.clear();
    stamp.clear();
    stamp_serial = 0;
    n_nodes = 0;
    n_digests = 0;
  }

  void grow_ntab() {
    std::vector<NodeEntry> old;
    old.swap(ntab);
    ntab.resize(old.size() * 2);
    for (auto& e : ntab) e.row = -1;
    const uint64_t mask = ntab.size() - 1;
    for (const auto& e : old) {
      if (e.row < 0) continue;
      uint64_t i = e.hash & mask;
      while (ntab[i].row >= 0) i = (i + 1) & mask;
      ntab[i] = e;
    }
  }

  void grow_dtab() {
    std::vector<DigestEntry> old;
    old.swap(dtab);
    dtab.resize(old.size() * 2);
    for (auto& e : dtab) e.refid = -1;
    const uint64_t mask = dtab.size() - 1;
    for (const auto& e : old) {
      if (e.refid < 0) continue;
      uint64_t i = e.hash & mask;
      while (dtab[i].refid >= 0) i = (i + 1) & mask;
      dtab[i] = e;
    }
  }

  // row of node bytes, or -1
  int32_t find_node(const uint8_t* p, uint32_t len, uint64_t h) const {
    const uint64_t mask = ntab.size() - 1;
    uint64_t i = h & mask;
    while (true) {
      const NodeEntry& e = ntab[i];
      if (e.row < 0) return -1;
      if (e.hash == h && e.len == len &&
          std::memcmp(arena.data() + e.arena_off, p, len) == 0)
        return e.row;
      i = (i + 1) & mask;
    }
  }

  void insert_node(const uint8_t* p, uint32_t len, uint64_t h, int32_t row) {
    if ((n_nodes + 1) * 10 >= ntab.size() * 7) grow_ntab();
    const uint64_t off = arena.size();
    arena.insert(arena.end(), p, p + len);
    const uint64_t mask = ntab.size() - 1;
    uint64_t i = h & mask;
    while (ntab[i].row >= 0) i = (i + 1) & mask;
    ntab[i] = NodeEntry{h, off, len, row};
    ++n_nodes;
  }

  int32_t find_refid(const uint8_t* d) const {
    const uint64_t h = hash_digest(d, seed);
    const uint64_t mask = dtab.size() - 1;
    uint32_t p4;
    std::memcpy(&p4, d, 4);
    uint64_t i = h & mask;
    while (true) {
      const DigestEntry& e = dtab[i];
      if (e.refid < 0) return -1;
      if (e.hash == h && e.pre4 == p4 &&
          std::memcmp(digest_arena.data() + 32 * e.refid, d, 32) == 0)
        return e.refid;
      i = (i + 1) & mask;
    }
  }

  int32_t intern_digest(const uint8_t* d) {
    return intern_digest_h(d, hash_digest(d, seed));
  }

  int32_t intern_digest_h(const uint8_t* d, uint64_t h) {
    if ((n_digests + 1) * 10 >= dtab.size() * 7) grow_dtab();
    const uint64_t mask = dtab.size() - 1;
    uint32_t p4;
    std::memcpy(&p4, d, 4);
    uint64_t i = h & mask;
    while (true) {
      DigestEntry& e = dtab[i];
      if (e.refid < 0) {
        e.hash = h;
        e.refid = static_cast<int32_t>(n_digests++);
        e.pre4 = p4;
        digest_arena.insert(digest_arena.end(), d, d + 32);
        return e.refid;
      }
      if (e.hash == h && e.pre4 == p4 &&
          std::memcmp(digest_arena.data() + 32 * e.refid, d, 32) == 0)
        return e.refid;
      i = (i + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

void* phant_engine_new() { return new Engine(); }

void phant_engine_free(void* h) { delete static_cast<Engine*>(h); }

void phant_engine_flush(void* h) { static_cast<Engine*>(h)->flush(); }

uint64_t phant_engine_nodes(void* h) {
  return static_cast<Engine*>(h)->n_nodes;
}

uint64_t phant_engine_digests(void* h) {
  return static_cast<Engine*>(h)->n_digests;
}

// Hit-scan the batch (node i at ptrs[i], lens[i] bytes). rows[i] = row id
// for known nodes, or -2 - k where k indexes this batch's novel
// first-occurrence list (duplicates of one novel byte-string share k).
// novel_idx (caller-sized >= n) receives the batch index of each novel
// first occurrence. counts[0] = miss occurrences (novel duplicates
// included — the "hits" complement), counts[1] = number of novel first
// occurrences. Returns 0.
int phant_engine_scan_ptrs(void* h, const uint8_t* const* ptrs,
                           const uint32_t* lens, uint64_t n, int64_t* rows,
                           uint32_t* novel_idx, uint64_t* counts) {
  Engine& E = *static_cast<Engine*>(h);
  uint64_t miss = 0, novel = 0;
  // per-batch dup table: open addressing over novel first occurrences
  uint64_t dcap = 64;
  while (dcap < n * 2) dcap <<= 1;
  E.novel_dup.assign(dcap, UINT32_MAX);
  const uint64_t dmask = dcap - 1;
  for (uint64_t i = 0; i < n; ++i) {
    const uint8_t* p = ptrs[i];
    const uint32_t len = lens[i];
    const uint64_t hsh = hash_bytes(p, len, E.seed);
    const int32_t row = E.find_node(p, len, hsh);
    if (row >= 0) {
      rows[i] = row;
      continue;
    }
    ++miss;
    // dup among this batch's novels?
    uint64_t j = hsh & dmask;
    int64_t found = -1;
    while (E.novel_dup[j] != UINT32_MAX) {
      // the table stores novel-list indices; novel_idx[cand] = batch index
      const uint32_t cand = E.novel_dup[j];
      const uint8_t* cp = ptrs[novel_idx[cand]];
      const uint32_t cl = lens[novel_idx[cand]];
      if (cl == len && std::memcmp(cp, p, len) == 0) {
        found = cand;
        break;
      }
      j = (j + 1) & dmask;
    }
    if (found >= 0) {
      rows[i] = -2 - found;
      continue;
    }
    novel_idx[novel] = static_cast<uint32_t>(i);
    E.novel_dup[j] = static_cast<uint32_t>(novel);
    rows[i] = -2 - static_cast<int64_t>(novel);
    ++novel;
  }
  counts[0] = miss;
  counts[1] = novel;
  return 0;
}

// Insert the scanned batch's novel nodes (digests[32*k] = keccak of novel
// k, computed by the caller on the routed backend), intern their digests
// and child references, fill the per-row link slots, and patch every
// negative row in rows[0..n) to its real row id. Returns the base row.
int64_t phant_engine_commit_ptrs(void* h, const uint8_t* const* ptrs,
                                 const uint32_t* lens, uint64_t n,
                                 int64_t* rows, const uint32_t* novel_idx,
                                 uint64_t n_novel, const uint8_t* digests) {
  Engine& E = *static_cast<Engine*>(h);
  const int64_t base_row = static_cast<int64_t>(E.own_refid.size());
  E.own_refid.resize(base_row + n_novel);
  E.child_refids.resize((base_row + n_novel) * kChildSlots, -1);

  // The ~17 digest interns per novel node are random-access bound; a
  // per-node prefetch can only hide ~1 node of latency. Instead the whole
  // batch's digest refs are FLATTENED into one stream and processed as a
  // two-pass pipeline:
  //   pass A: probe-only (seeded hash + 4-byte prefix, no memcmp, no
  //           insertion) with the dtab line prefetched D entries ahead
  //           and the hit's arena line prefetched for pass B;
  //   pass B: exact memcmp on hits (line already in flight), full
  //           intern (with insertion, in stream order) for misses and
  //           the ~never filter false-positives — refid assignment order
  //           is identical to the serial loop.
  size_t ref_off[kChildSlots];
  E.flat_d.clear();
  E.flat_h.clear();
  E.flat_out.clear();
  for (uint64_t k = 0; k < n_novel; ++k) {
    const uint64_t i = novel_idx[k];
    const uint8_t* p = ptrs[i];
    const uint32_t len = lens[i];
    E.insert_node(p, len, hash_bytes(p, len, E.seed),
                  static_cast<int32_t>(base_row + k));
    const uint8_t* dg = digests + 32 * k;
    E.flat_d.push_back(dg);
    E.flat_h.push_back(hash_digest(dg, E.seed));
    E.flat_out.push_back(&E.own_refid[base_row + k]);
    const int nref = node_refs(p, 0, len, ref_off);
    int32_t* slots = E.child_refids.data() + (base_row + k) * kChildSlots;
    for (int r = 0; r < nref; ++r) {
      const uint8_t* rd = p + ref_off[r];
      E.flat_d.push_back(rd);
      E.flat_h.push_back(hash_digest(rd, E.seed));
      E.flat_out.push_back(&slots[r]);
    }
  }
  const size_t F = E.flat_d.size();
  // pre-grow so pass B insertions never rehash mid-stream
  while ((E.n_digests + F + 1) * 10 >= E.dtab.size() * 7) E.grow_dtab();
  const uint64_t mask = E.dtab.size() - 1;
  E.flat_refid.assign(F, -2);
  constexpr size_t D = 16;  // prefetch depth
  for (size_t j = 0; j < F; ++j) {
    if (j + D < F) __builtin_prefetch(&E.dtab[E.flat_h[j + D] & mask]);
    const uint8_t* d = E.flat_d[j];
    const uint64_t hh = E.flat_h[j];
    uint32_t p4;
    std::memcpy(&p4, d, 4);
    uint64_t i = hh & mask;
    int32_t found = -2;
    while (true) {
      const DigestEntry& e = E.dtab[i];
      if (e.refid < 0) break;  // empty: slow path inserts in pass B
      if (e.hash == hh && e.pre4 == p4) {
        found = e.refid;
        break;
      }
      i = (i + 1) & mask;
    }
    if (found >= 0) __builtin_prefetch(E.digest_arena.data() + 32 * found);
    E.flat_refid[j] = found;
  }
  for (size_t j = 0; j < F; ++j) {
    const int32_t f = E.flat_refid[j];
    if (f >= 0 &&
        std::memcmp(E.digest_arena.data() + 32 * f, E.flat_d[j], 32) == 0) {
      *E.flat_out[j] = f;
    } else {
      *E.flat_out[j] = E.intern_digest_h(E.flat_d[j], E.flat_h[j]);
    }
  }
  for (uint64_t i = 0; i < n; ++i)
    if (rows[i] < -1) rows[i] = base_row + (-2 - rows[i]);
  return base_row;
}

// Commit with NATIVE hashing: digests of the novel nodes are computed
// in-process through the fast keccak batch (no Python round trip). This
// is the hot path when the routed backend is the host — the device route
// still flows through phant_engine_commit_ptrs with caller digests.
int64_t phant_engine_commit_hash_ptrs(void* h, const uint8_t* const* ptrs,
                                      const uint32_t* lens, uint64_t n,
                                      int64_t* rows,
                                      const uint32_t* novel_idx,
                                      uint64_t n_novel) {
  Engine& E = *static_cast<Engine*>(h);
  E.novel_ptrs.resize(n_novel);
  E.novel_lens.resize(n_novel);
  for (uint64_t k = 0; k < n_novel; ++k) {
    E.novel_ptrs[k] = ptrs[novel_idx[k]];
    E.novel_lens[k] = lens[novel_idx[k]];
  }
  E.digest_scratch.resize(32 * n_novel);
  phant_keccak256_ptrs_fast(E.novel_ptrs.data(), E.novel_lens.data(),
                            n_novel, E.digest_scratch.data());
  return phant_engine_commit_ptrs(h, ptrs, lens, n, rows, novel_idx, n_novel,
                                  E.digest_scratch.data());
}

// Contiguous-blob adapters (the ctypes/numpy interface): build the ptr
// array and delegate.
int phant_engine_scan(void* h, const uint8_t* blob, const uint64_t* offs,
                      const uint32_t* lens, uint64_t n, int64_t* rows,
                      uint32_t* novel_idx, uint64_t* counts) {
  Engine& E = *static_cast<Engine*>(h);
  E.ptr_scratch.resize(n);
  for (uint64_t i = 0; i < n; ++i) E.ptr_scratch[i] = blob + offs[i];
  return phant_engine_scan_ptrs(h, E.ptr_scratch.data(), lens, n, rows,
                                novel_idx, counts);
}

int64_t phant_engine_commit(void* h, const uint8_t* blob,
                            const uint64_t* offs, const uint32_t* lens,
                            uint64_t n, int64_t* rows,
                            const uint32_t* novel_idx, uint64_t n_novel,
                            const uint8_t* digests) {
  Engine& E = *static_cast<Engine*>(h);
  E.ptr_scratch.resize(n);
  for (uint64_t i = 0; i < n; ++i) E.ptr_scratch[i] = blob + offs[i];
  return phant_engine_commit_ptrs(h, E.ptr_scratch.data(), lens, n, rows,
                                  novel_idx, n_novel, digests);
}

// Per-block linked-multiproof verdicts. block b = rows[block_offs[b] ..
// block_offs[b+1]); roots = 32B per block; ok[b] = 1 iff some node's
// digest equals root_b and every node is that root or is referenced by a
// same-block node. Exactly witness_engine._verify_interned's semantics.
int phant_engine_verdict(void* h, const int64_t* rows,
                         const uint64_t* block_offs, uint64_t n_blocks,
                         const uint8_t* roots, uint8_t* ok) {
  Engine& E = *static_cast<Engine*>(h);
  if (E.stamp.size() < E.n_digests) E.stamp.resize(E.n_digests, 0);
  for (uint64_t b = 0; b < n_blocks; ++b) {
    const uint64_t s = block_offs[b], e = block_offs[b + 1];
    if (e <= s) {
      ok[b] = 0;
      continue;
    }
    const int32_t root_refid = E.find_refid(roots + 32 * b);
    const uint64_t tag = ++E.stamp_serial;
    // pass 1: stamp every child reference of the block's nodes
    for (uint64_t i = s; i < e; ++i) {
      const int32_t* slots = E.child_refids.data() + rows[i] * kChildSlots;
      for (int r = 0; r < kChildSlots; ++r) {
        const int32_t c = slots[r];
        if (c < 0) break;  // slots fill left-to-right
        E.stamp[c] = tag;
      }
    }
    // pass 2: every node must be referenced or be the root; the root must
    // be PRESENT as some node's own digest
    uint8_t all_ok = 1, root_present = 0;
    for (uint64_t i = s; i < e; ++i) {
      const int32_t own = E.own_refid[rows[i]];
      const uint8_t is_root = own == root_refid;
      root_present |= is_root;
      if (!is_root && E.stamp[own] != tag) {
        all_ok = 0;
        break;
      }
    }
    ok[b] = all_ok & root_present;
  }
  return 0;
}

}  // extern "C"
