"""Release version + git revision.

Equivalent to the reference's generated version module (reference:
build.zig:40-58 writes src/version.zig from build.zig.zon + `git rev-parse`,
gitRevision at build.zig:23). Here the revision is resolved lazily at
runtime instead of at build time.
"""

from __future__ import annotations

import functools
import subprocess
from pathlib import Path

RELEASE = "0.0.1-beta-0"


@functools.lru_cache(maxsize=1)
def revision() -> str:
    """Short git revision of the framework's own checkout, or "unknown".
    Guards against reporting the hash of an unrelated repo that happens to
    enclose an installed copy (e.g. site-packages under a monorepo)."""
    pkg_dir = Path(__file__).resolve().parent
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=pkg_dir,
            capture_output=True,
            text=True,
            timeout=5,
        )
        if top.returncode != 0 or not (Path(top.stdout.strip()) / "phant_tpu").is_dir():
            return "unknown"
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=pkg_dir,
            capture_output=True,
            text=True,
            timeout=5,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return "unknown"
