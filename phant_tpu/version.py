"""Release version + git revision.

Equivalent to the reference's generated version module (reference:
build.zig:40-58 writes src/version.zig from build.zig.zon + `git rev-parse`,
gitRevision at build.zig:23). Here the revision is resolved lazily at
runtime instead of at build time.
"""

from __future__ import annotations

import functools
import subprocess
from pathlib import Path

RELEASE = "0.0.1-beta-0"


@functools.lru_cache(maxsize=1)
def revision() -> str:
    """Short git revision of the working tree, or "unknown" outside git."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=Path(__file__).parent,
            capture_output=True,
            text=True,
            timeout=5,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return "unknown"
