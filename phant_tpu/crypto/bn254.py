"""alt_bn128 (bn254) curve operations for precompiles 0x06/0x07/0x08.

Pure-Python field towers (Fp, Fp2, Fp12) and the optimal-ate pairing.
The reference delegates these to evmone's precompile set; this framework
owns them. Structure follows the standard construction (as in the public
py_ecc implementation of EIP-196/197): Fp12 = Fp[w]/(w^12 - 18 w^6 + 82),
G2 points twisted into Fp12 by (x, y) -> (x w^2, y w^3).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
N = 21888242871839275222246405745257275088548364400416034343698204186575808495617

ATE_LOOP_COUNT = 29793968203157093288
LOG_ATE = 63


class BN254Error(ValueError):
    pass


# --- generic polynomial extension field over Fp ---------------------------
# An FQP element is a tuple of ints (coefficients, low degree first).

FQ12_MOD = [82, 0, 0, 0, 0, 0, -18, 0, 0, 0, 0, 0]  # w^12 = 18 w^6 - 82
FQ2_MOD = [1, 0]  # i^2 = -1


def _poly_add(a, b):
    return tuple((x + y) % P for x, y in zip(a, b))


def _poly_sub(a, b):
    return tuple((x - y) % P for x, y in zip(a, b))


def _poly_mul(a, b, mod_coeffs):
    deg = len(a)
    buf = [0] * (2 * deg - 1)
    for i, x in enumerate(a):
        if x:
            for j, y in enumerate(b):
                buf[i + j] += x * y
    for i in range(2 * deg - 2, deg - 1, -1):
        top = buf[i]
        if top:
            base = i - deg
            for j, c in enumerate(mod_coeffs):
                buf[base + j] -= top * c
        buf[i] = 0
    return tuple(c % P for c in buf[:deg])


def _pdeg(p) -> int:
    for i in range(len(p) - 1, -1, -1):
        if p[i] % P:
            return i
    return -1  # zero polynomial


def _pdivmod(num, den):
    """Quotient and remainder in Fp[x]."""
    num = [c % P for c in num]
    den = [c % P for c in den]
    dn, dd = _pdeg(num), _pdeg(den)
    if dd < 0:
        raise BN254Error("division by zero polynomial")
    q = [0] * max(dn - dd + 1, 1)
    inv_lead = pow(den[dd], P - 2, P)
    while dn >= dd:
        coef = num[dn] * inv_lead % P
        q[dn - dd] = coef
        for j in range(dd + 1):
            num[dn - dd + j] = (num[dn - dd + j] - coef * den[j]) % P
        dn = _pdeg(num)
    return q, num


def _poly_inv(a, mod_coeffs):
    """Inverse in Fp[x]/(m) via extended Euclid; invariant s_i·a ≡ r_i (mod m)."""
    d = len(a)
    m = [c % P for c in mod_coeffs] + [1]  # full modulus polynomial, degree d
    r0, r1 = m, list(a) + [0]
    width = 2 * d + 2
    s0 = [0] * width
    s1 = [1] + [0] * (width - 1)
    while _pdeg(r1) > 0:
        q, r = _pdivmod(r0, r1)
        s = s0[:]
        for i, qc in enumerate(q):
            if qc:
                for j in range(width - i):
                    if s1[j]:
                        s[i + j] = (s[i + j] - qc * s1[j]) % P
        r0, r1 = r1, r
        s0, s1 = s1, s
    lead = _pdeg(r1)
    if lead < 0:
        raise BN254Error("element not invertible")
    inv_c = pow(r1[lead], P - 2, P)
    return tuple(c * inv_c % P for c in s1[:d])


def _poly_one(deg):
    return tuple([1] + [0] * (deg - 1))


def _poly_zero(deg):
    return tuple([0] * deg)


def _poly_pow(a, exp, mod_coeffs):
    result = _poly_one(len(a))
    base = a
    while exp:
        if exp & 1:
            result = _poly_mul(result, base, mod_coeffs)
        base = _poly_mul(base, base, mod_coeffs)
        exp >>= 1
    return result


def _poly_neg(a):
    return tuple((-x) % P for x in a)


# --- elliptic curve over a generic field ----------------------------------
# Points are (x, y) tuples of field elements (or None = infinity). The field
# is parameterized by (one, zero, add, sub, mul, inv) closures.


class _Field:
    def __init__(self, deg, mod_coeffs):
        self.deg = deg
        self.mod = mod_coeffs

    def one(self):
        return _poly_one(self.deg)

    def zero(self):
        return _poly_zero(self.deg)

    def add(self, a, b):
        return _poly_add(a, b)

    def sub(self, a, b):
        return _poly_sub(a, b)

    def mul(self, a, b):
        return _poly_mul(a, b, self.mod)

    def inv(self, a):
        return _poly_inv(a, self.mod)

    def neg(self, a):
        return _poly_neg(a)

    def scalar(self, k):
        return tuple([k % P] + [0] * (self.deg - 1))

    def is_zero(self, a):
        return all(c == 0 for c in a)

    def eq(self, a, b):
        return a == b


FQ2 = _Field(2, FQ2_MOD)
FQ12 = _Field(12, FQ12_MOD)


def _ec_double(field: _Field, pt):
    if pt is None:
        return None
    x, y = pt
    if field.is_zero(y):
        return None
    # lam = 3x^2 / 2y
    num = field.mul(field.scalar(3), field.mul(x, x))
    lam = field.mul(num, field.inv(field.mul(field.scalar(2), y)))
    x3 = field.sub(field.mul(lam, lam), field.mul(field.scalar(2), x))
    y3 = field.sub(field.mul(lam, field.sub(x, x3)), y)
    return (x3, y3)


def _ec_add(field: _Field, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if field.eq(x1, x2):
        if field.eq(y1, y2):
            return _ec_double(field, p1)
        return None
    lam = field.mul(field.sub(y2, y1), field.inv(field.sub(x2, x1)))
    x3 = field.sub(field.sub(field.mul(lam, lam), x1), x2)
    y3 = field.sub(field.mul(lam, field.sub(x1, x3)), y1)
    return (x3, y3)


def _ec_mul(field: _Field, pt, k: int):
    result = None
    addend = pt
    while k:
        if k & 1:
            result = _ec_add(field, result, addend)
        addend = _ec_double(field, addend)
        k >>= 1
    return result


# --- G1 (over Fp, plain ints) ---------------------------------------------


def _g1_on_curve(pt: Optional[Tuple[int, int]]) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - 3) % P == 0


def _g1_add(p1, p2):
    f = _Field(1, [0])
    a = None if p1 is None else ((p1[0],), (p1[1],))
    b = None if p2 is None else ((p2[0],), (p2[1],))
    r = _ec_add(f, a, b)
    return None if r is None else (r[0][0], r[1][0])


def _g1_mul(pt, k):
    f = _Field(1, [0])
    a = None if pt is None else ((pt[0],), (pt[1],))
    r = _ec_mul(f, a, k)
    return None if r is None else (r[0][0], r[1][0])


# --- precompile byte interfaces -------------------------------------------


def _read_g1(data: bytes, off: int) -> Optional[Tuple[int, int]]:
    x = int.from_bytes(data[off : off + 32].ljust(32, b"\x00"), "big")
    y = int.from_bytes(data[off + 32 : off + 64].ljust(32, b"\x00"), "big")
    if x >= P or y >= P:
        raise BN254Error("coordinate >= field modulus")
    if x == 0 and y == 0:
        return None
    pt = (x, y)
    if not _g1_on_curve(pt):
        raise BN254Error("point not on curve")
    return pt


def _write_g1(pt: Optional[Tuple[int, int]]) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def ec_add_bytes(data: bytes) -> bytes:
    data = data[:128].ljust(128, b"\x00")
    return _write_g1(_g1_add(_read_g1(data, 0), _read_g1(data, 64)))


def ec_mul_bytes(data: bytes) -> bytes:
    data = data[:96].ljust(96, b"\x00")
    pt = _read_g1(data, 0)
    k = int.from_bytes(data[64:96], "big")
    return _write_g1(_g1_mul(pt, k))


# --- pairing ---------------------------------------------------------------

# G2 generator twist: x -> x*w^2, y -> y*w^3 where FQ2 (a + b i) embeds into
# FQ12 with a at degree 0 and b at degree 6 (standard py_ecc layout).


def _fq2_to_fq12(el) -> tuple:
    a, b = el
    out = [0] * 12
    out[0] = a
    out[6] = b
    return tuple(out)


_W2 = tuple([0, 0, 1] + [0] * 9)  # w^2
_W3 = tuple([0, 0, 0, 1] + [0] * 8)  # w^3


def _twist(pt_fq2):
    if pt_fq2 is None:
        return None
    x, y = pt_fq2
    # untwist-twist trick: multiply x by 9+i shifted coefficients
    # standard: represent x = x' - 9*x_i adjustments... use py_ecc formulation:
    xc = ((x[0] - 9 * x[1]) % P, x[1])
    yc = ((y[0] - 9 * y[1]) % P, y[1])
    nx = FQ12.mul(_fq2_to_fq12(xc), _W2)
    ny = FQ12.mul(_fq2_to_fq12(yc), _W3)
    return (nx, ny)


def _g1_to_fq12(pt):
    if pt is None:
        return None
    return (FQ12.scalar(pt[0]), FQ12.scalar(pt[1]))


def _linefunc(f: _Field, p1, p2, t):
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if not f.eq(x1, x2):
        m = f.mul(f.sub(y2, y1), f.inv(f.sub(x2, x1)))
        return f.sub(f.mul(m, f.sub(xt, x1)), f.sub(yt, y1))
    if f.eq(y1, y2):
        m = f.mul(f.mul(f.scalar(3), f.mul(x1, x1)), f.inv(f.mul(f.scalar(2), y1)))
        return f.sub(f.mul(m, f.sub(xt, x1)), f.sub(yt, y1))
    return f.sub(xt, x1)


def _miller_loop(Q, Pt):
    if Q is None or Pt is None:
        return FQ12.one()
    f = FQ12
    R = Q
    acc = f.one()
    for i in range(LOG_ATE, -1, -1):
        acc = f.mul(f.mul(acc, acc), _linefunc(f, R, R, Pt))
        R = _ec_double(f, R)
        if ATE_LOOP_COUNT & (1 << i):
            acc = f.mul(acc, _linefunc(f, R, Q, Pt))
            R = _ec_add(f, R, Q)
    # Frobenius endomorphism applications
    Q1 = (_poly_pow(Q[0], P, FQ12_MOD), _poly_pow(Q[1], P, FQ12_MOD))
    nQ2 = (_poly_pow(Q1[0], P, FQ12_MOD), f.neg(_poly_pow(Q1[1], P, FQ12_MOD)))
    acc = f.mul(acc, _linefunc(f, R, Q1, Pt))
    R = _ec_add(f, R, Q1)
    acc = f.mul(acc, _linefunc(f, R, nQ2, Pt))
    return _poly_pow(acc, (P**12 - 1) // N, FQ12_MOD)


_B2 = None  # lazily computed twist curve b-coefficient checks


def _g2_on_curve(pt) -> bool:
    if pt is None:
        return True
    # b2 = 3 / (9 + i) in FQ2
    nine_i = (9, 1)
    b2 = FQ2.mul(FQ2.scalar(3), FQ2.inv(nine_i))
    x, y = pt
    lhs = FQ2.mul(y, y)
    rhs = FQ2.add(FQ2.mul(FQ2.mul(x, x), x), b2)
    return lhs == rhs


def _read_g2(data: bytes, off: int):
    # EVM encoding: x_imag, x_real, y_imag, y_real (each 32 bytes)
    xi = int.from_bytes(data[off : off + 32], "big")
    xr = int.from_bytes(data[off + 32 : off + 64], "big")
    yi = int.from_bytes(data[off + 64 : off + 96], "big")
    yr = int.from_bytes(data[off + 96 : off + 128], "big")
    if max(xi, xr, yi, yr) >= P:
        raise BN254Error("G2 coordinate >= modulus")
    if xi == xr == yi == yr == 0:
        return None
    pt = ((xr, xi), (yr, yi))
    if not _g2_on_curve(pt):
        raise BN254Error("G2 point not on curve")
    # subgroup check: n * Q == infinity
    if _ec_mul(FQ2, pt, N) is not None:
        raise BN254Error("G2 point not in subgroup")
    return pt


def pairing_check_bytes(data: bytes) -> bool:
    """EIP-197: product of pairings == 1."""
    k = len(data) // 192
    acc = FQ12.one()
    for i in range(k):
        off = i * 192
        p1 = _read_g1(data, off)
        q2 = _read_g2(data, off + 64)
        if p1 is None or q2 is None:
            continue  # pairing with infinity contributes 1
        acc = FQ12.mul(acc, _miller_loop(_twist(q2), _g1_to_fq12(p1)))
    return acc == FQ12.one()
