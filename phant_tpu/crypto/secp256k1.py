"""secp256k1 ECDSA: recover / sign / verify, pure Python CPU backend.

The reference binds bitcoin-core libsecp256k1 through a Zig wrapper
(reference: build.zig.zon:9-12, src/crypto/ecdsa.zig:10-36). Here the CPU
backend is a from-scratch implementation (correctness oracle + test signer);
the batched TPU backend lives in phant_tpu/ops/ecrecover_jax.py and is
differential-tested against this module. Not constant-time — consensus
verification only ever handles public data.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass
from typing import Optional, Tuple

# Curve: y^2 = x^3 + 7 over F_p
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

HALF_N = N // 2


class SignatureError(ValueError):
    """Invalid signature field or unrecoverable point."""


Point = Optional[Tuple[int, int]]  # None = point at infinity (affine)


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def _point_add(p1: Point, p2: Point) -> Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        # doubling
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def _point_mul(k: int, point: Point) -> Point:
    result: Point = None
    addend = point
    while k:
        if k & 1:
            result = _point_add(result, addend)
        addend = _point_add(addend, addend)
        k >>= 1
    return result


def _lift_x(x: int, y_odd: bool) -> Tuple[int, int]:
    """Recover (x, y) on the curve from x and y-parity; p ≡ 3 (mod 4) so the
    square root is a single exponentiation."""
    y_sq = (pow(x, 3, P) + 7) % P
    y = pow(y_sq, (P + 1) // 4, P)
    if (y * y) % P != y_sq:
        raise SignatureError("x is not on the curve")
    if bool(y & 1) != y_odd:
        y = P - y
    return (x, y)


def validate_signature_fields(r: int, s: int, *, require_low_s: bool = True) -> None:
    """r/s range checks + EIP-2 low-s malleability rule
    (reference: src/crypto/ecdsa.zig:28-36)."""
    if not (1 <= r < N):
        raise SignatureError("r out of range")
    if not (1 <= s < N):
        raise SignatureError("s out of range")
    if require_low_s and s > HALF_N:
        raise SignatureError("s too high (EIP-2)")


def recover_pubkey_python(msg_hash: bytes, r: int, s: int, recovery_id: int) -> bytes:
    """Pure-Python ecrecover (the readable oracle for the native and TPU
    backends) -> 65-byte uncompressed pubkey (0x04 || X || Y)."""
    if recovery_id not in (0, 1, 2, 3):
        raise SignatureError(f"bad recovery id {recovery_id}")
    validate_signature_fields(r, s, require_low_s=False)
    x = r + (N if recovery_id >= 2 else 0)
    if x >= P:
        raise SignatureError("r + jN exceeds field")
    R = _lift_x(x, bool(recovery_id & 1))
    z = int.from_bytes(msg_hash, "big") % N
    r_inv = _inv(r, N)
    # Q = r^-1 (s*R - z*G)
    sR = _point_mul(s, R)
    zG = _point_mul(z, (GX, GY))
    neg_zG = None if zG is None else (zG[0], (P - zG[1]) % P)
    Q = _point_mul(r_inv, _point_add(sR, neg_zG))
    if Q is None:
        raise SignatureError("recovered point at infinity")
    return b"\x04" + Q[0].to_bytes(32, "big") + Q[1].to_bytes(32, "big")


def recover_pubkey(msg_hash: bytes, r: int, s: int, recovery_id: int) -> bytes:
    """ecrecover -> 65-byte uncompressed pubkey (0x04 || X || Y); native C++
    fast path when the toolchain is available (reference links C
    libsecp256k1 the same way, src/crypto/ecdsa.zig:19-26)."""
    from phant_tpu.utils.native import load_native

    native = load_native()
    # the C side reads exactly 32 bytes; odd-length hashes (legal for the
    # Python path, which treats them as big-endian ints) stay in Python
    if native is not None and len(msg_hash) == 32:
        if recovery_id not in (0, 1, 2, 3):
            raise SignatureError(f"bad recovery id {recovery_id}")
        if not (0 <= r < 2**256 and 0 <= s < 2**256):
            raise SignatureError("r/s out of u256 range")
        pub = native.ecrecover(msg_hash, r, s, recovery_id)
        if pub is None:
            raise SignatureError("unrecoverable signature")
        return b"\x04" + pub
    return recover_pubkey_python(msg_hash, r, s, recovery_id)


def _rfc6979_k(msg_hash: bytes, private_key: int) -> int:
    """Deterministic nonce (RFC 6979, HMAC-SHA256)."""
    x = private_key.to_bytes(32, "big")
    h1 = msg_hash
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        candidate = int.from_bytes(v, "big")
        if 1 <= candidate < N:
            return candidate
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(msg_hash: bytes, private_key: int) -> Tuple[int, int, int]:
    """Returns (r, s, y_parity) with low-s normalization
    (reference: src/crypto/ecdsa.zig:23-26)."""
    if not (1 <= private_key < N):
        raise SignatureError("private key out of range")
    z = int.from_bytes(msg_hash, "big") % N
    while True:
        k = _rfc6979_k(msg_hash, private_key)
        R = _point_mul(k, (GX, GY))
        assert R is not None
        r = R[0] % N
        if r == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        s = _inv(k, N) * (z + r * private_key) % N
        if s == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        y_parity = R[1] & 1
        if s > HALF_N:
            s = N - s
            y_parity ^= 1
        return (r, s, y_parity)


def pubkey_of(private_key: int) -> bytes:
    Q = _point_mul(private_key, (GX, GY))
    assert Q is not None
    return b"\x04" + Q[0].to_bytes(32, "big") + Q[1].to_bytes(32, "big")
