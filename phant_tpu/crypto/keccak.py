"""Keccak-256 (the pre-NIST Keccak with 0x01 domain padding, as used by Ethereum).

Three backends, selected transparently:

1. ``native``  — C++ implementation in native/keccak.cc, loaded via ctypes.
                 This is the CPU fast path (the reference links ethash's C keccak
                 for evmone and uses Zig std's Keccak256 for the client side,
                 reference: build.zig:94, src/crypto/hasher.zig:1-17).
2. ``python``  — pure-Python fallback, also the readable spec used to
                 differential-test the native and TPU paths.
3. the TPU path lives in phant_tpu/ops/keccak_jax.py and is batched; this
   module is the scalar/host-side API mirroring hasher.zig's
   `keccak256` / `keccak256WithPrefix` (reference: src/crypto/hasher.zig:4-17).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from phant_tpu.utils.native import load_native

RATE = 136  # bytes; keccak-256 rate (1600 - 2*256 bits)

_KECCAK_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# Rotation offsets r[x][y] for lane A[x, y].
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1


def _rotl(value: int, shift: int) -> int:
    if shift == 0:
        return value
    return ((value << shift) | (value >> (64 - shift))) & _MASK


def keccak_f1600(lanes: List[int]) -> List[int]:
    """Keccak-f[1600] permutation over 25 lanes indexed A[x + 5*y]."""
    a = lanes
    for rnd in range(24):
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[i] ^ d[i % 5] for i in range(25)]
        # rho + pi: B[y, 2x+3y] = rot(A[x, y])
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _ROT[x][y])
        # chi
        a = [
            b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y] & _MASK) & b[(x + 2) % 5 + 5 * y])
            for y in range(5)
            for x in range(5)
        ]
        # note: list comprehension above iterates x fastest -> index x + 5*y
        a[0] ^= _KECCAK_RC[rnd]
    return a


def pad_keccak(data: bytes, rate: int = RATE) -> bytes:
    """Multi-rate padding with the Keccak (0x01 ... 0x80) domain byte."""
    pad_len = rate - (len(data) % rate)
    if pad_len == 1:
        return data + b"\x81"
    return data + b"\x01" + b"\x00" * (pad_len - 2) + b"\x80"


def _keccak256_python(data: bytes) -> bytes:
    padded = pad_keccak(data)
    lanes = [0] * 25
    for chunk_start in range(0, len(padded), RATE):
        chunk = padded[chunk_start : chunk_start + RATE]
        for i in range(RATE // 8):
            lanes[i] ^= int.from_bytes(chunk[8 * i : 8 * i + 8], "little")
        lanes = keccak_f1600(lanes)
    out = b"".join(lane.to_bytes(8, "little") for lane in lanes[:4])
    return out


_native = load_native()


def keccak256(data: bytes) -> bytes:
    """keccak256 over bytes (reference: src/crypto/hasher.zig:4-8)."""
    if _native is not None:
        return _native.keccak256(data)
    return _keccak256_python(data)


def keccak256_python(data: bytes) -> bytes:
    """Always the pure-Python path (for differential tests)."""
    return _keccak256_python(data)


def keccak256_with_prefix(prefix: int, data: bytes) -> bytes:
    """keccak256 of a one-byte prefix || data, for EIP-2718 typed-tx hashing
    (reference: src/crypto/hasher.zig:10-17)."""
    return keccak256(bytes([prefix]) + data)


def keccak256_batch(payloads: Sequence[bytes]) -> List[bytes]:
    """Hash many payloads on the selected backend: the TPU kernel when
    `--crypto_backend=tpu` (phant_tpu/ops/keccak_jax.py), else the CPU
    fast path (native loop if available)."""
    from phant_tpu.backend import crypto_backend

    if crypto_backend() == "tpu":
        from phant_tpu.ops.keccak_jax import keccak256_batch_jax

        return keccak256_batch_jax(payloads)
    return keccak256_batch_cpu(payloads)


def keccak256_batch_cpu(payloads: Sequence[bytes]) -> List[bytes]:
    """Always the CPU path (native loop if available) — the baseline side
    of CPU-vs-TPU differential tests."""
    if _native is not None:
        return _native.keccak256_batch_fast(payloads)
    return [_keccak256_python(p) for p in payloads]


EMPTY_KECCAK = bytes.fromhex(
    "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
)
