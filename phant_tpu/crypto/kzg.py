"""EIP-4844 KZG point-evaluation verification (precompile 0x0A backend).

Verifies that a KZG commitment C to a blob polynomial p satisfies
p(z) == y, given a proof [q(tau)]_1 with q = (p - y)/(X - z):

    e(C - [y]_1, [1]_2) == e(proof, [tau - z]_2)

checked as a single product via bls12_381.pairing_check.

Trusted setup: the only ceremony datum this equation needs is [tau]_2
(the commitments themselves arrive from the network).  The mainnet
ceremony bytes are public constants but are NOT embedded here (this tree
is built in a zero-egress environment and a misremembered constant would
be silent consensus divergence — worse than a loud gap).  Supply them via
PHANT_KZG_SETUP_G2=<hex of the 96-byte compressed [tau]_2> or a chainspec
"kzgSetupG2" field; without either, an explicitly INSECURE dev setup with
a known tau serves tests and self-generated chains, and `setup_source()`
says which one is active so callers/operators can refuse to validate
mainnet with the dev setup.

Reference scope anchor: src/blockchain/params.zig:30-39 (the precompile
set the VM must serve; the reference predates 4844 and stops at 0x09).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Optional, Tuple

from phant_tpu.crypto import bls12_381 as bls

BLS_MODULUS = bls.R
FIELD_ELEMENTS_PER_BLOB = 4096
VERSIONED_HASH_VERSION_KZG = 0x01

# tau for the INSECURE dev setup — a fixed, public constant, so anyone can
# forge proofs against it.  Never use for a chain whose blobs you did not
# produce yourself.
_DEV_TAU = (
    int.from_bytes(hashlib.sha256(b"phant-tpu insecure dev kzg setup").digest(), "big")
    % BLS_MODULUS
)

_SETUP: Optional[Tuple[bls.G2Point, str]] = None
_setup_lock = threading.Lock()

# Name of the public network the process is serving, or None for fixture /
# self-generated chains. Set by Blockchain.__init__ when its chain config
# names a PUBLIC_CHAIN_IDS member; 0x0A refuses the insecure-dev setup
# while this is set (precompiles_bls.point_evaluation) — a forgeable tau
# on a chain whose blobs arrive from strangers is consensus theater.
_PUBLIC_NETWORK: Optional[str] = None


def set_public_network(name: Optional[str]) -> None:
    """Declare (or clear, with None) the public network being validated."""
    global _PUBLIC_NETWORK
    _PUBLIC_NETWORK = name


def public_network() -> Optional[str]:
    return _PUBLIC_NETWORK


def configured_source() -> str:
    """What `setup_source()` WOULD report, without paying for the dev
    setup's g2_mul: the cached answer when the setup is already loaded,
    otherwise a peek at the operator env knob. Lets the 0x0A public-network
    gate refuse the dev setup before ever computing it."""
    if _SETUP is not None:
        return _SETUP[1]
    return "operator" if os.environ.get("PHANT_KZG_SETUP_G2", "") else "insecure-dev"


def dev_tau() -> int:
    """The dev setup's tau (public by construction — tests use it to build
    commitments/proofs by direct scalar arithmetic)."""
    return _DEV_TAU


def _load_setup() -> Tuple[bls.G2Point, str]:
    hexstr = os.environ.get("PHANT_KZG_SETUP_G2", "")
    if hexstr:
        raw = bytes.fromhex(hexstr.removeprefix("0x"))
        return bls.g2_decompress(raw), "operator"
    return bls.g2_mul(bls.G2_GEN, _DEV_TAU), "insecure-dev"


def _setup() -> Tuple[bls.G2Point, str]:
    """Lazy [tau]G2 memo, lock-serialized (phantlint LOCK): blob-carrying
    payloads verify from Engine API handler threads, and the dev-mode
    g2_mul fallback is expensive enough that a race means seconds of
    duplicated pairing work."""
    global _SETUP
    if _SETUP is None:
        with _setup_lock:
            if _SETUP is None:
                _SETUP = _load_setup()
    return _SETUP


def setup_g2_tau() -> bls.G2Point:
    return _setup()[0]


def setup_source() -> str:
    """"operator" (real ceremony bytes supplied) or "insecure-dev"."""
    return _setup()[1]


def reset_setup_cache() -> None:
    global _SETUP
    _SETUP = None


def kzg_to_versioned_hash(commitment: bytes) -> bytes:
    return bytes([VERSIONED_HASH_VERSION_KZG]) + hashlib.sha256(commitment).digest()[1:]


class KZGProofError(ValueError):
    pass


def verify_kzg_proof(
    commitment: bytes, z: bytes, y: bytes, proof: bytes
) -> bool:
    """The EIP-4844 verify_kzg_proof: True iff the proof checks out.

    Raises KZGProofError for malformed inputs (non-canonical field
    elements, invalid/off-subgroup points) — the precompile maps any
    raise to a precompile failure.
    """
    z_int = int.from_bytes(z, "big")
    y_int = int.from_bytes(y, "big")
    if z_int >= BLS_MODULUS or y_int >= BLS_MODULUS:
        raise KZGProofError("field element not canonical")
    try:
        c_pt = bls.g1_decompress(commitment)
        proof_pt = bls.g1_decompress(proof)
    except bls.PointDecodeError as e:
        raise KZGProofError(str(e)) from e
    # e(C - [y]_1, [1]_2) == e(proof, [tau]_2 - [z]_2)
    # <=> e(C - [y]_1, [1]_2) * e(-proof, [tau - z]_2) == 1
    p_minus_y = bls.g1_add(c_pt, bls.g1_mul(bls.G1_GEN, -y_int))
    x_minus_z = bls.g2_add(setup_g2_tau(), bls.g2_mul(bls.G2_GEN, -z_int))
    return bls.pairing_check(
        [(p_minus_y, bls.G2_GEN), (bls.g1_neg(proof_pt), x_minus_z)]
    )
