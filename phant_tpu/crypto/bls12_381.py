"""BLS12-381: field tower, G1/G2 groups, optimal-ate pairing check.

Serves the EIP-4844 point-evaluation precompile (0x0A) and the EIP-2537
Prague precompiles (G1/G2 add, MSM, pairing).  The reference has neither
(its Cancun/Prague support predates both EIPs; scope anchor:
src/blockchain/params.zig:30-39 enumerates its precompile set) — this is
framework-beyond-reference surface required by the advertised forks.

Pure Python by design: these precompiles are cold control-plane work (a
handful of calls per block at most) while the hot loop (keccak/ecrecover/
trie) runs on the device kernels.  Clarity and auditability beat speed
here.

Implementation notes:
- Tower: Fq2 = Fq[u]/(u^2+1); Fq6 = Fq2[v]/(v^3 - (u+1));
  Fq12 = Fq6[w]/(w^2 - v).
- G2 points live on the twist E'(Fq2): y^2 = x^3 + 4(u+1); the Miller
  loop untwists into E(Fq12) via x -> x*w^-2 (w^-2 = v^-1 w^0... computed
  as a true Fq12 inverse), y -> y*w^-3.
- pairing_check evaluates prod_i e(P_i, Q_i) == 1 with one shared final
  exponentiation; all consumers (KZG verify, 2537 PAIRING) only ever need
  that boolean, which is invariant under the exact pairing normalization
  (any fixed power of the canonical pairing gives identical verdicts), so
  the loop sign convention for the negative BLS parameter need not match
  other libraries element-for-element — bilinearity and non-degeneracy
  are what the tests pin.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

# field modulus
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# subgroup order (= the BLS_MODULUS of EIP-4844)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative); |x| drives the Miller loop
X_ABS = 0xD201000000010000

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# ---------------------------------------------------------------------------
# Fq2 as (c0, c1) tuples: c0 + c1*u with u^2 = -1
# ---------------------------------------------------------------------------

Fq2 = Tuple[int, int]
FQ2_ZERO: Fq2 = (0, 0)
FQ2_ONE: Fq2 = (1, 0)
XI: Fq2 = (1, 1)  # the sextic non-residue u + 1


def fq2_add(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a: Fq2) -> Fq2:
    return (-a[0] % P, -a[1] % P)


def fq2_mul(a: Fq2, b: Fq2) -> Fq2:
    # (a0 + a1 u)(b0 + b1 u) = (a0b0 - a1b1) + (a0b1 + a1b0) u
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fq2_mul_int(a: Fq2, k: int) -> Fq2:
    return (a[0] * k % P, a[1] * k % P)


def fq2_sq(a: Fq2) -> Fq2:
    return fq2_mul(a, a)


def fq2_inv(a: Fq2) -> Fq2:
    # 1/(a0 + a1 u) = (a0 - a1 u)/(a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    inv = pow(norm, P - 2, P)
    return (a[0] * inv % P, -a[1] * inv % P)


def fq2_is_zero(a: Fq2) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


# ---------------------------------------------------------------------------
# Fq6 as (c0, c1, c2): c0 + c1 v + c2 v^2 with v^3 = XI
# ---------------------------------------------------------------------------

Fq6 = Tuple[Fq2, Fq2, Fq2]
FQ6_ZERO: Fq6 = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE: Fq6 = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def fq6_add(a: Fq6, b: Fq6) -> Fq6:
    return (fq2_add(a[0], b[0]), fq2_add(a[1], b[1]), fq2_add(a[2], b[2]))


def fq6_sub(a: Fq6, b: Fq6) -> Fq6:
    return (fq2_sub(a[0], b[0]), fq2_sub(a[1], b[1]), fq2_sub(a[2], b[2]))


def fq6_neg(a: Fq6) -> Fq6:
    return (fq2_neg(a[0]), fq2_neg(a[1]), fq2_neg(a[2]))


def fq6_mul(a: Fq6, b: Fq6) -> Fq6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    # c0 = t0 + XI*((a1+a2)(b1+b2) - t1 - t2)
    c0 = fq2_add(
        t0,
        fq2_mul(
            XI,
            fq2_sub(
                fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2
            ),
        ),
    )
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + XI*t2
    c1 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1),
        fq2_mul(XI, t2),
    )
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fq6_mul_by_v(a: Fq6) -> Fq6:
    # v * (c0 + c1 v + c2 v^2) = XI*c2 + c0 v + c1 v^2
    return (fq2_mul(XI, a[2]), a[0], a[1])


def fq6_inv(a: Fq6) -> Fq6:
    a0, a1, a2 = a
    t0 = fq2_sub(fq2_sq(a0), fq2_mul(XI, fq2_mul(a1, a2)))
    t1 = fq2_sub(fq2_mul(XI, fq2_sq(a2)), fq2_mul(a0, a1))
    t2 = fq2_sub(fq2_sq(a1), fq2_mul(a0, a2))
    denom = fq2_add(
        fq2_mul(a0, t0),
        fq2_mul(XI, fq2_add(fq2_mul(a2, t1), fq2_mul(a1, t2))),
    )
    dinv = fq2_inv(denom)
    return (fq2_mul(t0, dinv), fq2_mul(t1, dinv), fq2_mul(t2, dinv))


# ---------------------------------------------------------------------------
# Fq12 as (c0, c1): c0 + c1 w with w^2 = v
# ---------------------------------------------------------------------------

Fq12 = Tuple[Fq6, Fq6]
FQ12_ONE: Fq12 = (FQ6_ONE, FQ6_ZERO)


def fq12_add(a: Fq12, b: Fq12) -> Fq12:
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_sub(a: Fq12, b: Fq12) -> Fq12:
    return (fq6_sub(a[0], b[0]), fq6_sub(a[1], b[1]))


def fq12_mul(a: Fq12, b: Fq12) -> Fq12:
    t0 = fq6_mul(a[0], b[0])
    t1 = fq6_mul(a[1], b[1])
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(
        fq6_sub(fq6_mul(fq6_add(a[0], a[1]), fq6_add(b[0], b[1])), t0), t1
    )
    return (c0, c1)


def fq12_sq(a: Fq12) -> Fq12:
    return fq12_mul(a, a)


def fq12_inv(a: Fq12) -> Fq12:
    # (a0 + a1 w)^-1 = (a0 - a1 w) / (a0^2 - v a1^2)
    denom = fq6_sub(fq6_sq_(a[0]), fq6_mul_by_v(fq6_sq_(a[1])))
    dinv = fq6_inv(denom)
    return (fq6_mul(a[0], dinv), fq6_neg(fq6_mul(a[1], dinv)))


def fq6_sq_(a: Fq6) -> Fq6:
    return fq6_mul(a, a)


def fq12_is_one(a: Fq12) -> bool:
    c0, c1 = a
    return (
        c0[0] == FQ2_ONE
        and fq2_is_zero(c0[1])
        and fq2_is_zero(c0[2])
        and all(fq2_is_zero(x) for x in c1)
    )


def fq12_pow(a: Fq12, e: int) -> Fq12:
    result = FQ12_ONE
    base = a
    while e:
        if e & 1:
            result = fq12_mul(result, base)
        base = fq12_sq(base)
        e >>= 1
    return result


def fq12_scalar_fq2(c: Fq2) -> Fq12:
    """Embed an Fq2 scalar into Fq12."""
    return ((c, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


# w and its inverse powers, used by the untwist map
_W: Fq12 = (FQ6_ZERO, (FQ2_ONE, FQ2_ZERO, FQ2_ZERO))
_W2: Fq12 = ((FQ2_ZERO, FQ2_ONE, FQ2_ZERO), FQ6_ZERO)  # w^2 = v
_W3: Fq12 = (FQ6_ZERO, (FQ2_ZERO, FQ2_ONE, FQ2_ZERO))  # w^3 = v w
_W2_INV = fq12_inv(_W2)
_W3_INV = fq12_inv(_W3)


# ---------------------------------------------------------------------------
# G1: E(Fq): y^2 = x^3 + 4.  Points are (x, y) ints or None for infinity.
# ---------------------------------------------------------------------------

G1Point = Optional[Tuple[int, int]]
G1_GEN: G1Point = (G1_X, G1_Y)
B1 = 4


def g1_is_on_curve(pt: G1Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + B1)) % P == 0


def g1_neg(pt: G1Point) -> G1Point:
    if pt is None:
        return None
    return (pt[0], -pt[1] % P)


def g1_add(a: G1Point, b: G1Point) -> G1Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_mul(pt: G1Point, k: int) -> G1Point:
    # no implicit mod-R here: g1_in_subgroup relies on multiplying by R
    if k < 0:
        return g1_mul(g1_neg(pt), -k)
    result: G1Point = None
    addend = pt
    while k:
        if k & 1:
            result = g1_add(result, addend)
        addend = g1_add(addend, addend)
        k >>= 1
    return result


def g1_in_subgroup(pt: G1Point) -> bool:
    """Full check: on curve and r*pt == infinity."""
    return g1_is_on_curve(pt) and g1_mul(pt, R) is None


# ---------------------------------------------------------------------------
# G2: E'(Fq2): y^2 = x^3 + 4(u+1)
# ---------------------------------------------------------------------------

G2Point = Optional[Tuple[Fq2, Fq2]]
G2_GEN: G2Point = (G2_X, G2_Y)
B2: Fq2 = (4, 4)


def g2_is_on_curve(pt: G2Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    lhs = fq2_sq(y)
    rhs = fq2_add(fq2_mul(fq2_sq(x), x), B2)
    return fq2_is_zero(fq2_sub(lhs, rhs))


def g2_neg(pt: G2Point) -> G2Point:
    if pt is None:
        return None
    return (pt[0], fq2_neg(pt[1]))


def g2_add(a: G2Point, b: G2Point) -> G2Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if fq2_is_zero(fq2_sub(x1, x2)):
        if fq2_is_zero(fq2_add(y1, y2)):
            return None
        lam = fq2_mul(fq2_mul_int(fq2_sq(x1), 3), fq2_inv(fq2_mul_int(y1, 2)))
    else:
        lam = fq2_mul(fq2_sub(y2, y1), fq2_inv(fq2_sub(x2, x1)))
    x3 = fq2_sub(fq2_sub(fq2_sq(lam), x1), x2)
    y3 = fq2_sub(fq2_mul(lam, fq2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_mul(pt: G2Point, k: int) -> G2Point:
    if k < 0:
        return g2_mul(g2_neg(pt), -k)
    result: G2Point = None
    addend = pt
    while k:
        if k & 1:
            result = g2_add(result, addend)
        addend = g2_add(addend, addend)
        k >>= 1
    return result


def g2_in_subgroup(pt: G2Point) -> bool:
    return g2_is_on_curve(pt) and g2_mul(pt, R) is None


# ---------------------------------------------------------------------------
# pairing
# ---------------------------------------------------------------------------

E12Point = Optional[Tuple[Fq12, Fq12]]


def _untwist(pt: G2Point) -> E12Point:
    """E'(Fq2) -> E(Fq12): (x, y) -> (x w^-2, y w^-3)."""
    if pt is None:
        return None
    x = fq12_mul(fq12_scalar_fq2(pt[0]), _W2_INV)
    y = fq12_mul(fq12_scalar_fq2(pt[1]), _W3_INV)
    return (x, y)


def _e12_embed_g1(pt: Tuple[int, int]) -> Tuple[Fq12, Fq12]:
    return (
        fq12_scalar_fq2((pt[0], 0)),
        fq12_scalar_fq2((pt[1], 0)),
    )


def _e12_double(a: Tuple[Fq12, Fq12]) -> Tuple[Fq12, Fq12]:
    x, y = a
    lam = fq12_mul(
        fq12_mul(fq12_sq(x), fq12_scalar_fq2((3, 0))),
        fq12_inv(fq12_mul(y, fq12_scalar_fq2((2, 0)))),
    )
    x3 = fq12_sub(fq12_sq(lam), fq12_add(x, x))
    y3 = fq12_sub(fq12_mul(lam, fq12_sub(x, x3)), y)
    return (x3, y3)


def _e12_add(
    a: Tuple[Fq12, Fq12], b: Tuple[Fq12, Fq12]
) -> Tuple[Fq12, Fq12]:
    x1, y1 = a
    x2, y2 = b
    lam = fq12_mul(fq12_sub(y2, y1), fq12_inv(fq12_sub(x2, x1)))
    x3 = fq12_sub(fq12_sub(fq12_sq(lam), x1), x2)
    y3 = fq12_sub(fq12_mul(lam, fq12_sub(x1, x3)), y1)
    return (x3, y3)


def _line(
    r: Tuple[Fq12, Fq12],
    q: Tuple[Fq12, Fq12],
    p: Tuple[Fq12, Fq12],
) -> Fq12:
    """Evaluate the line through r and q (tangent if r == q) at p."""
    xr, yr = r
    xq, yq = q
    xp, yp = p
    if fq12_is_eq(xr, xq) and fq12_is_eq(yr, yq):
        lam = fq12_mul(
            fq12_mul(fq12_sq(xr), fq12_scalar_fq2((3, 0))),
            fq12_inv(fq12_mul(yr, fq12_scalar_fq2((2, 0)))),
        )
        return fq12_sub(fq12_sub(yp, yr), fq12_mul(lam, fq12_sub(xp, xr)))
    if fq12_is_eq(xr, xq):
        # vertical line
        return fq12_sub(xp, xr)
    lam = fq12_mul(fq12_sub(yq, yr), fq12_inv(fq12_sub(xq, xr)))
    return fq12_sub(fq12_sub(yp, yr), fq12_mul(lam, fq12_sub(xp, xr)))


def fq12_is_eq(a: Fq12, b: Fq12) -> bool:
    d = fq12_sub(a, b)
    return all(fq2_is_zero(c) for c in d[0]) and all(
        fq2_is_zero(c) for c in d[1]
    )


_X_BITS = bin(X_ABS)[3:]  # msb-first, leading 1 dropped

FINAL_EXP = (P**12 - 1) // R


def _miller_loop(q: Tuple[Fq12, Fq12], p: Tuple[Fq12, Fq12]) -> Fq12:
    """f_{|x|, Q}(P), no final exponentiation."""
    r = q
    f = FQ12_ONE
    for bit in _X_BITS:
        f = fq12_mul(fq12_sq(f), _line(r, r, p))
        r = _e12_double(r)
        if bit == "1":
            f = fq12_mul(f, _line(r, q, p))
            r = _e12_add(r, q)
    return f


def pairing_check(pairs: Sequence[Tuple[G1Point, G2Point]]) -> bool:
    """prod_i e(P_i, Q_i) == 1, with one shared final exponentiation.

    Infinity entries contribute the neutral element and are skipped
    (matches EIP-2537 PAIRING and the KZG verify equation).  Callers are
    responsible for curve/subgroup membership checks.
    """
    f = FQ12_ONE
    for g1, g2 in pairs:
        if g1 is None or g2 is None:
            continue
        q = _untwist(g2)
        p = _e12_embed_g1(g1)
        f = fq12_mul(f, _miller_loop(q, p))
    return fq12_is_one(fq12_pow(f, FINAL_EXP))


# ---------------------------------------------------------------------------
# serialization (zcash/EIP-4844 compressed format)
# ---------------------------------------------------------------------------


class PointDecodeError(ValueError):
    pass


def g1_decompress(data: bytes) -> G1Point:
    """48-byte compressed G1 point -> point, with curve + subgroup check."""
    if len(data) != 48:
        raise PointDecodeError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise PointDecodeError("compression bit not set")
    infinity = bool(flags & 0x40)
    sort = bool(flags & 0x20)
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if infinity:
        if sort or x != 0:
            raise PointDecodeError("malformed infinity encoding")
        return None
    if x >= P:
        raise PointDecodeError("x not a canonical field element")
    y2 = (x * x * x + B1) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise PointDecodeError("x not on curve")
    if (y > P - y) != sort:
        y = P - y
    pt = (x, y)
    if not g1_in_subgroup(pt):
        raise PointDecodeError("point not in G1 subgroup")
    return pt


def g1_compress(pt: G1Point) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(47)
    x, y = pt
    flags = 0x80 | (0x20 if y > P - y else 0)
    raw = x.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g2_decompress(data: bytes) -> G2Point:
    """96-byte compressed G2 point (c1 || c0 big-endian) with checks."""
    if len(data) != 96:
        raise PointDecodeError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise PointDecodeError("compression bit not set")
    infinity = bool(flags & 0x40)
    sort = bool(flags & 0x20)
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:96], "big")
    if infinity:
        if sort or x1 != 0 or x0 != 0:
            raise PointDecodeError("malformed infinity encoding")
        return None
    if x0 >= P or x1 >= P:
        raise PointDecodeError("x not canonical")
    x: Fq2 = (x0, x1)
    y2 = fq2_add(fq2_mul(fq2_sq(x), x), B2)
    y = fq2_sqrt(y2)
    if y is None:
        raise PointDecodeError("x not on curve")
    if _fq2_lex_larger(y) != sort:
        y = fq2_neg(y)
    pt = (x, y)
    if not g2_in_subgroup(pt):
        raise PointDecodeError("point not in G2 subgroup")
    return pt


def g2_compress(pt: G2Point) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(95)
    x, y = pt
    flags = 0x80 | (0x20 if _fq2_lex_larger(y) else 0)
    raw1 = x[1].to_bytes(48, "big")
    raw0 = x[0].to_bytes(48, "big")
    return bytes([raw1[0] | flags]) + raw1[1:] + raw0


def _fq2_lex_larger(y: Fq2) -> bool:
    """Is y lexicographically larger than -y (c1 compared first)?"""
    ny = fq2_neg(y)
    return (y[1], y[0]) > (ny[1], ny[0])


def fq2_sqrt(a: Fq2) -> Optional[Fq2]:
    """Square root in Fq2 (p^2 ≡ 9 mod 16; use the p ≡ 3 mod 4 trick on
    the tower): candidate = a^((p^2+7)/16) style algorithms are fussy —
    use the simple complex method: sqrt(a0 + a1 u) via Fq square roots."""
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        # sqrt of a base-field element: either sqrt(a0) or sqrt(-a0)*u
        s = pow(a0, (P + 1) // 4, P)
        if s * s % P == a0:
            return (s, 0)
        s = pow(-a0 % P, (P + 1) // 4, P)
        if s * s % P == (-a0) % P:
            return (0, s)
        return None
    # norm = a0^2 + a1^2; alpha = sqrt(norm) in Fq (if it exists)
    norm = (a0 * a0 + a1 * a1) % P
    alpha = pow(norm, (P + 1) // 4, P)
    if alpha * alpha % P != norm:
        return None
    # x0^2 = (a0 + alpha)/2 or (a0 - alpha)/2
    inv2 = pow(2, P - 2, P)
    for sign in (1, -1):
        delta = (a0 + sign * alpha) * inv2 % P
        x0 = pow(delta, (P + 1) // 4, P)
        if x0 * x0 % P != delta:
            continue
        if x0 == 0:
            continue
        x1 = a1 * inv2 % P * pow(x0, P - 2, P) % P
        cand = (x0, x1)
        if fq2_is_zero(fq2_sub(fq2_sq(cand), a)):
            return cand
    return None


# ---------------------------------------------------------------------------
# multi-scalar helpers (EIP-2537 MSM)
# ---------------------------------------------------------------------------


def g1_msm(pairs: Sequence[Tuple[G1Point, int]]) -> G1Point:
    acc: G1Point = None
    for pt, k in pairs:
        acc = g1_add(acc, g1_mul(pt, k % R))
    return acc


def g2_msm(pairs: Sequence[Tuple[G2Point, int]]) -> G2Point:
    acc: G2Point = None
    for pt, k in pairs:
        acc = g2_add(acc, g2_mul(pt, k % R))
    return acc
