"""QoS policies for the verification scheduler: tenancy, priority, fairness.

Serving "millions of users" means the scheduler cannot treat every client
as one FIFO stream: a backfill indexer replaying a year of history and a
consensus client pushing the chain head offer wildly different traffic
(PAPERS.md's Patricia-trie reuse analysis makes the per-tenant engine cost
skew concrete — witness node reuse is heavy and tenant-mix dependent), and
under a burst naive FIFO admission lets the cheap-to-submit tenant starve
the latency-critical one. This module holds the three policy pieces the
scheduler composes, each deliberately free of scheduler state so it can be
unit-tested in isolation (tests/test_qos.py):

* **Tenant identity** — `tenant_context`/`current_tenant`: a per-thread
  lane tag, bound by the Engine API server from the `X-Phant-Tenant`
  request header (engine_api/server.py) exactly the way `trace_context`
  binds the trace id. Scheduler submissions made inside the context
  inherit it; everything else lands in `DEFAULT_TENANT` — which is why
  offline callers (verify_many, the spec runner, bench) see byte-identical
  single-tenant behavior.
* **Priority classes** — `PRIORITY_HEAD` (head-of-chain work: the serial
  mutation lane's `engine_newPayload*`/`engine_forkchoiceUpdated`, or a
  witness verification explicitly marked `X-Phant-Priority: head`) and
  `PRIORITY_BACKFILL` (default for `engine_executeStatelessPayloadV1`).
  Head work preempts backfill at dequeue time and, when the global queue
  is full, may EVICT the newest backfill job (never another head job,
  never the serial lane) — the documented shed order.
* **`WeightedFairPicker`** — smooth weighted round-robin over tenant
  lanes (the nginx/LVS SWRR shape): every pick adds each candidate's
  weight to its credit, the highest credit wins and pays back the total.
  Over any window the pick ratio converges to the weight ratio, and a
  tenant that was absent does not bank unbounded credit (credits are
  clamped when a tenant leaves the candidate set), so a returning lane
  cannot monopolize the executor.
* **`AdaptiveWait`** — the batching-wait policy (the inference-serving
  shape PR 3 copied, now closed-loop): an under-full batch waits for
  followers only while the queue is SHALLOW. As queue depth approaches
  one full batch the wait decays linearly to `min_wait_ms` — the backlog
  IS the batch, waiting longer only adds latency — and an idle scheduler
  widens back to `max_wait_ms` so a lone request still gets coalescing
  headroom. Pure function of depth: `wait_ms(depth)`.

Nothing here takes locks; the scheduler calls these under its own `_lock`
(tenant-context reads are thread-local, lock-free by construction).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Mapping, Optional, Sequence

#: priority classes (lower = more urgent). Head-of-chain work preempts
#: backfill at dequeue and may evict backfill at admission; the reverse
#: never happens.
PRIORITY_HEAD = 0
PRIORITY_BACKFILL = 1

#: the lane every untagged submission lands in — offline callers
#: (verify_many, spec runner, bench) never leave it, which is what keeps
#: single-tenant behavior identical to the pre-QoS scheduler.
DEFAULT_TENANT = "default"

#: the fold-over lane once the scheduler has seen its max distinct
#: tenants: an attacker spraying random X-Phant-Tenant values must not be
#: able to grow per-tenant state (or metric cardinality) without bound.
OVERFLOW_TENANT = "other"

_TENANT_MAXLEN = 64

_tls = threading.local()


def sanitize_tenant(raw: Optional[str]) -> str:
    """Clamp an untrusted tenant tag to a metrics-safe label: charset
    `[A-Za-z0-9_.-]`, bounded length, empty -> DEFAULT_TENANT. Applied at
    the HTTP boundary (the header is attacker-controlled) so everything
    downstream — lane keys, metric labels, flight records — is clean."""
    if not raw:
        return DEFAULT_TENANT
    out = []
    for ch in raw[:_TENANT_MAXLEN]:
        out.append(ch if (ch.isalnum() or ch in "_.-") else "_")
    return "".join(out) or DEFAULT_TENANT


@contextlib.contextmanager
def tenant_context(
    tenant: str, priority: int = PRIORITY_BACKFILL
) -> Iterator[None]:
    """Bind a (tenant, priority) pair to the current thread: scheduler
    submissions made inside inherit it (serving/scheduler.py reads it at
    `_witness_job` build time, same pattern as `trace_context`). Nests;
    the innermost binding wins."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((tenant, priority))
    try:
        yield
    finally:
        stack.pop()


def current_tenant() -> str:
    """The innermost bound tenant, or DEFAULT_TENANT."""
    stack = getattr(_tls, "stack", None)
    return stack[-1][0] if stack else DEFAULT_TENANT


def current_priority() -> int:
    """The innermost bound priority class, or PRIORITY_BACKFILL."""
    stack = getattr(_tls, "stack", None)
    return stack[-1][1] if stack else PRIORITY_BACKFILL


def parse_weights(spec: Optional[str]) -> Dict[str, float]:
    """`"cl:4,indexer:1"` -> {"cl": 4.0, "indexer": 1.0} (the
    `--sched-tenant-weights` / PHANT_SCHED_TENANT_WEIGHTS format).
    Unlisted tenants get weight 1. Malformed entries raise ValueError —
    a typo'd weight flag must fail loudly at startup, not silently
    deweight a tenant."""
    out: Dict[str, float] = {}
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        if not name or not w:
            raise ValueError(f"bad tenant weight entry {part!r} (want name:weight)")
        weight = float(w)
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0: {part!r}")
        out[sanitize_tenant(name)] = weight
    return out


class WeightedFairPicker:
    """Smooth weighted round-robin over a changing candidate set.

    Classic SWRR: each `pick` adds every candidate's weight to its
    credit, the largest credit wins and pays back the candidate total —
    over N picks tenant t is chosen ~ N * w_t / sum(w). Two departures
    from the textbook version, both for a LIVE queue where lanes appear
    and drain:

    * unknown tenants get `default_weight` lazily (a new API key must
      not need a config push to be served);
    * a tenant absent from the candidate set has its banked credit
      clamped to one round's worth, so a lane that idled for an hour
      cannot return and monopolize the executor while it burns saved
      credit (fairness is over offered load, not over wall time).
    """

    def __init__(
        self,
        weights: Optional[Mapping[str, float]] = None,
        default_weight: float = 1.0,
    ):
        self._weights: Dict[str, float] = dict(weights or {})
        self._default = float(default_weight)
        self._credit: Dict[str, float] = {}

    def weight_of(self, tenant: str) -> float:
        return self._weights.get(tenant, self._default)

    def pick(self, candidates: Sequence[str]) -> str:
        """Choose the next tenant among `candidates` (non-empty; order
        does not matter — ties break deterministically by name)."""
        if not candidates:
            raise ValueError("pick() needs at least one candidate")
        if len(candidates) == 1:
            # fast path: the common single-tenant scheduler never pays
            # for credit bookkeeping (and its credit stays clamped below)
            self._credit.pop(candidates[0], None)
            return candidates[0]
        total = 0.0
        for t in candidates:
            w = self.weight_of(t)
            total += w
            self._credit[t] = self._credit.get(t, 0.0) + w
        # absent tenants must not bank credit across rounds
        cand = set(candidates)
        for t in list(self._credit):
            if t not in cand:
                self._credit[t] = min(self._credit[t], self.weight_of(t))
        best = max(sorted(candidates), key=lambda t: self._credit[t])
        self._credit[best] -= total
        return best


class AdaptiveWait:
    """Queue-depth-adaptive batching wait.

    `wait_ms(depth)` is the time an under-full batch should wait for
    followers when `depth` requests are queued BEHIND its head:

        depth 0           -> max_wait_ms   (idle: full coalescing window)
        0 < d < full      -> linear decay  (backlog forming: shrink)
        depth >= full     -> min_wait_ms   (the backlog IS the batch)

    `full_depth` defaults to `max_batch`: once a whole batch is already
    waiting, assembly should grab it and go — extra wait is pure added
    latency, the queue-depth signal every production inference server
    keys its batching timeout on. Monotone non-increasing in depth and
    pure (no internal state), so the scheduler can re-evaluate it every
    assembly pass and the policy stays trivially unit-testable."""

    def __init__(
        self, max_wait_ms: float, min_wait_ms: float = 0.0, full_depth: int = 1
    ):
        if min_wait_ms > max_wait_ms:
            min_wait_ms = max_wait_ms
        self.max_wait_ms = float(max_wait_ms)
        self.min_wait_ms = float(min_wait_ms)
        self.full_depth = max(1, int(full_depth))

    def wait_ms(self, depth: int) -> float:
        if depth <= 0:
            return self.max_wait_ms
        if depth >= self.full_depth:
            return self.min_wait_ms
        frac = 1.0 - depth / self.full_depth
        return self.min_wait_ms + (self.max_wait_ms - self.min_wait_ms) * frac
