"""Serving subsystem: the continuous-batching verification scheduler.

`scheduler.py` holds the machinery (admission queue, shape-bucketed batch
assembler, single executor thread, `verify_many()`); this package root
holds the process-global *active scheduler* slot:

* the Engine API server installs its scheduler here on construction and
  uninstalls it on shutdown;
* `stateless.verify_witness_nodes` routes witness verification through
  the active scheduler when one is installed (so concurrent
  `engine_executeStatelessPayloadV1` handler threads coalesce their
  linked-multiproof checks into one engine/device dispatch) and falls
  back to the direct shared-engine path otherwise — offline callers,
  tests, and bench sections that never installed a scheduler are
  untouched;
* `/healthz` (engine_api/server.py) reads the active scheduler's state
  and turns an executor crash into a 503.
"""

from __future__ import annotations

import threading
from typing import Optional

from phant_tpu.serving.qos import (
    DEFAULT_TENANT,
    PRIORITY_BACKFILL,
    PRIORITY_HEAD,
    current_priority,
    current_tenant,
    parse_weights,
    sanitize_tenant,
    tenant_context,
)
from phant_tpu.serving.mesh_exec import MeshExecutorPool, affinity_device
from phant_tpu.serving.scheduler import (
    DeadlineExpired,
    QueueFull,
    SchedulerConfig,
    SchedulerDown,
    SchedulerError,
    VerificationScheduler,
)

__all__ = [
    "DEFAULT_TENANT",
    "PRIORITY_BACKFILL",
    "PRIORITY_HEAD",
    "DeadlineExpired",
    "MeshExecutorPool",
    "QueueFull",
    "SchedulerConfig",
    "SchedulerDown",
    "SchedulerError",
    "VerificationScheduler",
    "active_scheduler",
    "affinity_device",
    "current_priority",
    "current_tenant",
    "install",
    "parse_weights",
    "sanitize_tenant",
    "tenant_context",
    "uninstall",
]

_active: Optional[VerificationScheduler] = None
_active_lock = threading.Lock()


def install(scheduler: VerificationScheduler) -> Optional[VerificationScheduler]:
    """Make `scheduler` the process's active scheduler; returns the one it
    displaced (None normally — two live servers would fight over the slot,
    and the last one in wins, same as binding a port twice would)."""
    global _active
    with _active_lock:
        prev, _active = _active, scheduler
    return prev


def uninstall(scheduler: VerificationScheduler) -> None:
    """Clear the slot IF `scheduler` still owns it (a later install wins)."""
    global _active
    with _active_lock:
        if _active is scheduler:
            _active = None


def active_scheduler() -> Optional[VerificationScheduler]:
    """The installed scheduler, or None (read is lock-free: a stale read
    just takes the direct-engine path for one call)."""
    return _active
