"""Continuous-batching verification scheduler.

The Engine API server used to execute one request at a time behind a
global lock: concurrent CL requests queued on a mutex and each one paid a
batch-of-1 engine dispatch — the exact opposite of the framework's win
condition (vmapping witness verification across hundreds of blocks per
device dispatch). This module gives the serving path the inference-server
shape instead:

    admission queue  ->  batch assembler  ->  single executor thread

* **Admission queue** — bounded (`queue_depth`); a full queue REJECTS the
  request with `QueueFull` (JSON-RPC `-32050`, counted in
  `sched.rejected{reason=queue_full}`) instead of building unbounded
  latency. Every request carries a deadline; a request whose deadline
  passes while queued fails with `DeadlineExpired` (`-32051`) without
  ever touching the engine.
* **Batch assembler** — coalesces concurrent *witness-verification*
  requests into shape buckets (bucket key = total witness bytes rounded
  up to a power of two, the same rounding the device keccak path pads
  its blob buffer to, ops/witness_jax._pow2ceil), so the padded device
  buffers of one batch stay dense; `sched.padding_waste` reports the
  unused fraction of the padded buffer the last batch would occupy.
  Assembly runs under a `max_batch` / `max_wait_ms` policy: a batch
  executes as soon as it is full, and an under-full batch waits at most
  `max_wait_ms` from its head request's admission. Under load the
  executor's busy period makes that wait moot (the backlog that formed
  while the previous batch executed IS the next batch); the wait only
  costs anything for a request arriving at an idle executor, which is
  why it bounds — and is the whole of — the serial-client latency tax.
* **Executor** — ONE thread drains buckets into the engine and resolves
  per-request futures. The same thread runs *serial* jobs
  (state-mutating `engine_newPayload*` execution) one at a time, in
  admission order — which is what replaces the server's global execution
  lock: mutation is serialized by the executor, not by a mutex held
  across the whole request.
* **Pipeline** (`pipeline_depth`, default 2 via
  PHANT_SCHED_PIPELINE_DEPTH / `--sched-pipeline-depth`) — with depth
  >= 2 the executor splits witness execution through the engine's
  two-phase API (ops/witness_engine.py `begin_batch`/`resolve_batch`):
  it PACKS batch N+1 (bucket assembly + lock-held intern scan) and
  DISPATCHES its novel-node keccak with no host sync while a dedicated
  *resolve worker* thread RESOLVES batch N (digest readback / GIL-free C
  hashing outside the engine lock, then commit + linkage join). JAX's
  async dispatch means the device was idle during host packing and the
  host idle during device compute — this is the overlap that closes it,
  the same double-buffered-prefetch shape inference servers use. At
  most `pipeline_depth` batches are in flight; the executor blocks on a
  full pipeline (`sched.pipeline_stall` names resolve as the
  bottleneck). Depth 1 — or an engine without `begin_batch` — is the
  pre-pipeline behavior, byte-identical inline verify_batch execution.
  The serial lane drains the WHOLE pipeline first, so mutation stays
  exclusive against in-flight witness work; futures still complete in
  admission order per requester (the resolve worker is FIFO). On crash
  paths, dispatched-but-unresolved handles are released through the
  engine's `abandon_batch` (when it has one) so a shared engine that
  outlives a dead scheduler never leaks in-flight leases. Handle
  resolution order is a per-scheduler property only — the engine accepts
  any interleaving, so several schedulers can share one engine.
* **Lifecycle** — `shutdown(drain=True)` stops admission and lets the
  executor finish everything queued AND everything in the pipeline
  (graceful drain); an exception escaping batch execution — in either
  thread — marks the scheduler DOWN: the crashed batch, everything
  queued, and every dispatched-but-unresolved handle fail fast with
  `SchedulerDown` (`-32052`), later submits are rejected immediately,
  `/healthz` reports 503 with `executor_alive: false`
  (engine_api/server.py `_healthz_payload`), and the crash flight
  record names the pipeline STAGE that died (pack/dispatch/resolve).

`verify_many()` is the synchronous offline face of the same machinery:
bench.py, the spec runner (`--sched`), and tests push whole witness
spans through the identical admission/assembly/executor code and get an
(n,) bool verdict array back — the batching code measured offline is the
batching code serving traffic.

Observability (phant_tpu/obs/, PR 4): every job carries the submitting
request's `trace_id` (utils/trace.py trace_context — the Engine API server
opens one per POST), admissions/sheds/batch transitions land in the flight
recorder ring, and the executor attaches a per-batch record (`batch_id`,
`batch_size`, `bucket_bytes`, `backend`, cache hit/miss deltas,
`queue_wait_ms`) to each job it resolves — `verify_traced()` hands it back
so the request's span stays joinable to the batch that served it. An obs
watchdog thread per scheduler flags the in-flight batch out-living its
deadline (`sched.watchdog_stalls` + a `sched.stall` flight event); an
executor crash additionally dumps the ring to build/flight/ (the
postmortem artifact a dead server leaves behind).

Thread-safety: one lock (`_lock`) guards the queue and lifecycle state;
`_cond` wraps that same lock, so every wait/notify runs under it. The
registry's and flight recorder's own locks never take ours, so metric and
flight publishes cannot deadlock against admission (same discipline as
ops/witness_engine.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.obs.flight import flight
from phant_tpu.obs.watchdog import Watchdog
from phant_tpu.utils.trace import current_trace_id, metrics

log = logging.getLogger("phant_tpu.serving")


class SchedulerError(Exception):
    """Base for scheduler rejections; carries the JSON-RPC error code and
    HTTP status the Engine API server maps the rejection to."""

    code = -32000
    http_status = 503


class QueueFull(SchedulerError):
    """Admission queue at `queue_depth`: overload, shed the request."""

    code = -32050


class DeadlineExpired(SchedulerError):
    """The request's deadline passed before the executor reached it."""

    code = -32051


class SchedulerDown(SchedulerError):
    """The executor has crashed or the scheduler is shutting down."""

    code = -32052


def _default_pipeline_depth() -> int:
    """PHANT_SCHED_PIPELINE_DEPTH, default 2 (overlap pack of batch N+1
    with resolve of batch N). Depth 1 is the pre-pipeline serialized
    behavior: the executor runs pack -> dispatch -> resolve inline."""
    return int(os.environ.get("PHANT_SCHED_PIPELINE_DEPTH", "2"))


@dataclass
class SchedulerConfig:
    """Knobs, surfaced as `--sched-*` CLI flags (phant_tpu/__main__.py)."""

    max_batch: int = 128  # requests per assembled witness batch
    max_wait_ms: float = 5.0  # assembly wait for an under-full batch
    queue_depth: int = 512  # admission-queue bound (overload -> QueueFull)
    deadline_ms: float = 30_000.0  # default per-request deadline; <=0 = none
    # witness batches in flight between pack and resolve (>=2 pipelines:
    # the executor packs/dispatches batch N+1 while the resolve worker
    # reads back + joins batch N); 1 = today's serialized execution
    pipeline_depth: int = field(default_factory=_default_pipeline_depth)


_WITNESS = "witness"
_SERIAL = "serial"

#: batch-size histogram buckets (requests per engine dispatch)
_BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _pow2ceil(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _safe_resolve(future: Future, result) -> None:
    """set_result tolerating a concurrent _die: with two scheduler threads,
    the resolve worker can complete a batch in the same instant the
    executor fails everything — losing that race must not raise
    InvalidStateError out of the winner."""
    try:
        future.set_result(result)
    except Exception:
        pass  # already failed by _die; the waiter got the crash


def _safe_fail(future: Future, exc: BaseException) -> None:
    if not future.done():
        try:
            future.set_exception(exc)
        except Exception:
            pass  # resolved in the race window; the waiter got a verdict


def _abandon_handle(engine, handle) -> None:
    """Release a dispatched-but-unresolved engine handle on a crash path.
    The shared engine outlives a dead scheduler; a leaked handle would
    pin its in-flight count and defer generation flushes forever
    (ops/witness_engine.py abandon_batch). Best-effort: the scheduler is
    already dying, a second failure here must not mask the first."""
    abandon = getattr(engine, "abandon_batch", None)
    if abandon is None:
        return
    try:
        abandon(handle)
    except Exception:
        log.warning("abandon_batch failed on a crash path", exc_info=True)


@dataclass
class _Job:
    kind: str
    future: Future
    admitted: float  # monotonic admission time
    deadline: Optional[float]  # monotonic expiry, None = no deadline
    # witness lane
    root: bytes = b""
    nodes: Sequence[bytes] = ()
    nbytes: int = 0
    bucket: int = 0
    # serial lane
    fn: Optional[Callable] = None
    # observability: the submitting request's trace context, and the batch
    # record the executor attaches before resolving the future (set-then-
    # resolve ordering means a waiter that saw result() also sees meta)
    trace_id: Optional[str] = None
    meta: Optional[dict] = None


class VerificationScheduler:
    """Continuous-batching scheduler over a `WitnessEngine`.

    `engine` defaults to the process-shared memoized engine
    (stateless.shared_witness_engine), resolved lazily at first execution
    so constructing a scheduler never imports jax-adjacent modules.
    """

    def __init__(
        self,
        engine: Optional[object] = None,
        config: Optional[SchedulerConfig] = None,
    ):
        self.config = config or SchedulerConfig()
        # config is immutable after construction; the locked regions read
        # these unpacked copies so `self.config` itself stays a lock-free
        # introspection surface (state(), _deadline())
        self._max_batch = self.config.max_batch
        self._max_wait_s = self.config.max_wait_ms / 1e3
        self._queue_depth = self.config.queue_depth
        self._pipe_depth = max(1, self.config.pipeline_depth)
        self._engine = engine
        # chaos drill (obs): PHANT_SCHED_CHAOS_CRASH=1 makes the FIRST
        # witness batch crash the executor — the supported way to fire-
        # drill the postmortem path (flight dump, /healthz 503, -32052
        # fail-fast) against a live server / the real CLI
        import os

        self._chaos_crash = os.environ.get("PHANT_SCHED_CHAOS_CRASH") == "1"
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Job] = []
        self._closed = False
        self._dead: Optional[BaseException] = None
        # observability: monotone batch ids + the in-flight descriptors the
        # obs watchdog polls, oldest first (all guarded by _lock). With
        # pipelining, up to pipeline_depth witness batches are in flight.
        self._batch_seq = 0
        self._inflight_list: List[dict] = []
        # pipeline state (guarded by _lock): items awaiting the resolve
        # worker, whether it is mid-resolve, and the stage the executor is
        # in (named by the crash record when the executor dies)
        self._resolve_q: List[dict] = []
        self._resolving = False
        self._exec_stage = "pack"
        self.stats = {
            "requests": 0,
            "batches": 0,
            "serial_jobs": 0,
            "coalesced": 0,
            "batched_requests": 0,
            "max_batch_seen": 0,
            "pipelined_batches": 0,
            "rejected": 0,
        }
        metrics.gauge_set("sched.pipeline_depth", self._pipe_depth)
        self._thread = threading.Thread(
            target=self._run, name="phant-sched-exec", daemon=True
        )
        self._thread.start()
        self._resolve_thread: Optional[threading.Thread] = None
        if self._pipe_depth > 1:
            self._resolve_thread = threading.Thread(
                target=self._resolve_run, name="phant-sched-resolve", daemon=True
            )
            self._resolve_thread.start()
        self._watchdog = Watchdog(self.inflight_state).start()

    # -- context manager (offline verify_many use) ---------------------------

    def __enter__(self) -> "VerificationScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- admission -----------------------------------------------------------

    def _witness_job(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float],
    ) -> _Job:
        nodes = list(nodes)
        nbytes = sum(map(len, nodes))
        return _Job(
            kind=_WITNESS,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            root=root,
            nodes=nodes,
            nbytes=nbytes,
            bucket=_pow2ceil(nbytes),
            trace_id=current_trace_id(),
        )

    def submit_witness(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
        wait_for_space: bool = False,
    ) -> Future:
        """Queue one (root, nodes) linked-multiproof verification; the
        future resolves to the bool verdict. `wait_for_space` blocks on a
        full queue instead of rejecting (offline verify_many); the online
        serving path never waits — overload must shed, not stack."""
        job = self._witness_job(root, nodes, deadline_s)
        self._admit(job, wait_for_space)
        return job.future

    def verify_traced(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
    ) -> Tuple[bool, Optional[dict]]:
        """One witness verification through the batching path, returning
        (verdict, batch record). The record — `batch_id`, `batch_size`,
        `bucket_bytes`, `backend`, cache hit/miss deltas, `queue_wait_ms` —
        is what joins the caller's span to the shared engine dispatch that
        served it (stateless.verify_witness_nodes folds it into the open
        `verify_block` span). Scheduler rejections raise as usual."""
        job = self._witness_job(root, nodes, deadline_s)
        self._admit(job, False)
        return bool(job.future.result()), job.meta

    def submit_serial(
        self, fn: Callable, deadline_s: Optional[float] = None
    ) -> Future:
        """Queue an exclusive job: the executor runs `fn()` with nothing
        else in flight — the replacement for the server's global execution
        lock (state-mutating newPayload execution). `fn`'s return value
        resolves the future; an exception from `fn` is request-scoped and
        lands on the future (it does NOT kill the executor)."""
        job = _Job(
            kind=_SERIAL,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            fn=fn,
            trace_id=current_trace_id(),
        )
        self._admit(job, False)
        return job.future

    def _deadline(self, deadline_s: Optional[float]) -> Optional[float]:
        if deadline_s is None:
            d = self.config.deadline_ms / 1e3
        else:
            d = deadline_s
        if d <= 0 or d == float("inf"):
            return None
        return time.monotonic() + d

    def _admit(self, job: _Job, wait_for_space: bool) -> None:
        reason = None
        with self._lock:
            while True:
                if self._dead is not None:
                    reason, err = "down", SchedulerDown(
                        f"scheduler executor is down: {self._dead!r}"
                    )
                    break
                if self._closed:
                    reason, err = "shutdown", SchedulerDown(
                        "scheduler is shutting down"
                    )
                    break
                if len(self._queue) < self._queue_depth:
                    self._queue.append(job)
                    self.stats["requests"] += 1
                    depth = len(self._queue)
                    self._cond.notify_all()
                    break
                if not wait_for_space:
                    reason, err = "queue_full", QueueFull(
                        f"admission queue full ({self._queue_depth})"
                    )
                    break
                self._cond.wait(0.05)
            if reason is not None:
                self.stats["rejected"] += 1
        if reason is not None:
            metrics.count("sched.rejected", reason=reason)
            flight.record(
                "sched.shed", reason=reason, lane=job.kind, trace_id=job.trace_id
            )
            raise err
        metrics.gauge_set("sched.queue_depth", depth)
        flight.record(
            "sched.admit",
            lane=job.kind,
            bucket_bytes=job.bucket if job.kind == _WITNESS else None,
            queue_depth=depth,
            trace_id=job.trace_id,
        )

    # -- the synchronous offline face ---------------------------------------

    def verify_many(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> np.ndarray:
        """(n,) bool verdicts for a span of (root, nodes) witnesses, pushed
        through the SAME admission/assembly/executor path the server uses —
        the offline API for bench.py, the spec runner, and tests. Blocks on
        queue space instead of rejecting (offline callers want completion,
        not load shedding) and applies no deadline."""
        if threading.current_thread() in (self._thread, self._resolve_thread):
            raise RuntimeError(
                "verify_many called from a scheduler thread (deadlock)"
            )
        futs = [
            self.submit_witness(
                root, nodes, deadline_s=float("inf"), wait_for_space=True
            )
            for root, nodes in witnesses
        ]
        return np.fromiter(
            (bool(f.result()) for f in futs), bool, count=len(futs)
        )

    def accepts_witness(self) -> bool:
        """Can the CURRENT thread route a witness verification through this
        scheduler? False on the executor/resolve threads themselves
        (submitting from either would deadlock: they are the consumers)
        and once the scheduler is down or draining — callers fall back to
        the direct engine path."""
        if threading.current_thread() in (self._thread, self._resolve_thread):
            return False
        with self._lock:
            return self._dead is None and not self._closed

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        """Liveness surface for `/healthz` (engine_api/server.py)."""
        with self._lock:
            depth = len(self._queue)
            dead = self._dead
            inflight = len(self._resolve_q) + (1 if self._resolving else 0)
        alive = dead is None and self._thread.is_alive()
        if self._resolve_thread is not None:
            # a dead resolve worker is just as fatal as a dead executor:
            # dispatched handles would never complete
            alive = alive and self._resolve_thread.is_alive()
        out = {
            "queue_depth": depth,
            "executor_alive": alive,
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
            "pipeline_depth": self._pipe_depth,
            "pipeline_inflight": inflight,
        }
        if dead is not None:
            out["error"] = repr(dead)
        return out

    def stats_snapshot(self) -> dict:
        with self._lock:
            st = dict(self.stats)
        b = st["batches"]
        st["mean_batch"] = round(st["batched_requests"] / b, 2) if b else 0.0
        st["pipeline_depth"] = self._pipe_depth
        return st

    def inflight_state(self) -> Optional[dict]:
        """The OLDEST batch currently in flight — `batch_id`, `lane`,
        `stage`, `started`/`deadline` (monotonic), `trace_ids` — or None
        when idle. Polled by the obs watchdog to flag deadline-overrun
        stalls; with pipelining the oldest unresolved batch is the one a
        wedged device call strands first."""
        with self._lock:
            return dict(self._inflight_list[0]) if self._inflight_list else None

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admission; `drain=True` lets the executor finish everything
        already queued before it exits, `drain=False` fails the queue fast.
        Idempotent."""
        with self._lock:
            self._closed = True
            dropped = [] if drain else list(self._queue)
            if not drain:
                self._queue.clear()
            self._cond.notify_all()
        for job in dropped:
            job.future.set_exception(
                SchedulerDown("scheduler shut down before execution")
            )
        self._thread.join(timeout)
        if self._resolve_thread is not None:
            self._resolve_thread.join(timeout)
        self._watchdog.stop(1.0)
        metrics.gauge_set("sched.queue_depth", 0)

    # -- executor ------------------------------------------------------------

    def _run(self) -> None:
        batch: List[_Job] = []
        try:
            while True:
                batch = self._next_batch()
                if batch is None:
                    # graceful exit: every dispatched handle must resolve
                    # before the executor reports done (shutdown drains the
                    # admission queue AND the in-flight pipeline)
                    self._drain_pipeline()
                    with self._lock:
                        self._exec_done = True
                        self._cond.notify_all()
                    return
                self._execute(batch)
                batch = []
        except BaseException as e:  # systemic: engine/internal failure
            self._die(e, batch or [], stage=self._exec_stage)

    _exec_done = False  # executor returned cleanly (resolve worker exits)

    def _drain_pipeline(self) -> None:
        """Block until every dispatched handle has resolved (or the
        scheduler died). Called by the executor before serial jobs —
        the serial lane stays exclusive with ALL witness work, not just
        the executor's own — and on graceful shutdown."""
        with self._lock:
            while (self._resolve_q or self._resolving) and self._dead is None:
                self._cond.wait(0.05)

    def _next_batch(self) -> Optional[List[_Job]]:
        with self._lock:
            while True:
                self._expire_locked()
                if self._dead is not None:
                    # the resolve worker died and failed everything: exit
                    # instead of idling in wait() until shutdown
                    return None
                if self._queue:
                    break
                if self._closed:
                    return None
                self._cond.wait()
            head = self._queue.pop(0)
            if head.kind == _SERIAL:
                batch = [head]
            else:
                batch = self._assemble_locked(head)
            depth = len(self._queue)
            self._cond.notify_all()  # wake submitters waiting for space
        metrics.gauge_set("sched.queue_depth", depth)
        return batch

    def _assemble_locked(self, head: _Job) -> List[_Job]:
        """Coalesce same-bucket witness jobs behind `head` under the
        max_batch / max_wait policy. Caller holds `_lock`; the cond wait
        releases it so submitters keep admitting while we wait."""
        batch = [head]
        wait_until = head.admitted + self._max_wait_s
        while True:
            i = 0
            while i < len(self._queue) and len(batch) < self._max_batch:
                j = self._queue[i]
                if j.kind == _WITNESS and j.bucket == head.bucket:
                    batch.append(self._queue.pop(i))
                else:
                    i += 1
            if len(batch) >= self._max_batch or self._closed:
                break
            now = time.monotonic()
            if now >= wait_until:
                break
            self._cond.wait(wait_until - now)
        return batch

    def _shed_expired(self, job: _Job) -> None:
        """Deadline shed at execution time: one place keeps the stats
        snapshot and the `sched.rejected` metric in agreement (the soak
        gate and bench artifacts assert on the snapshot)."""
        with self._lock:
            self.stats["rejected"] += 1
        metrics.count("sched.rejected", reason="deadline")
        flight.record(
            "sched.shed", reason="deadline", lane=job.kind, trace_id=job.trace_id
        )
        job.future.set_exception(
            DeadlineExpired("deadline expired while queued")
        )

    def _expire_locked(self) -> None:
        """Fail queued jobs whose deadline has passed (without executing)."""
        now = time.monotonic()
        live: List[_Job] = []
        expired: List[_Job] = []
        for j in self._queue:
            (expired if j.deadline is not None and now > j.deadline else live).append(j)
        if not expired:
            return
        self._queue[:] = live
        self.stats["rejected"] += len(expired)
        for j in expired:
            # set_exception never raises here: these futures have no
            # waiter-side cancellation path
            j.future.set_exception(
                DeadlineExpired("deadline expired while queued")
            )
            metrics.count("sched.rejected", reason="deadline")
            flight.record(
                "sched.shed", reason="deadline", lane=j.kind, trace_id=j.trace_id
            )

    def _execute(self, batch: List[_Job]) -> None:
        now = time.monotonic()
        for j in batch:
            metrics.observe_hist("sched.queue_wait_seconds", now - j.admitted)
        lane = batch[0].kind
        # the stall bound the obs watchdog polls against: a full execution
        # allowance (config.deadline_ms) from PICKUP time — never the jobs'
        # admission deadlines, or a batch picked up with 0.2s of a 30s
        # deadline left would flag a perfectly healthy executor as stalled
        # and bury the real wedged-device signal
        if self.config.deadline_ms > 0:
            stall_deadline: Optional[float] = now + self.config.deadline_ms / 1e3
        else:
            stall_deadline = None
        trace_ids = [j.trace_id for j in batch]
        pipelined = False
        if lane == _SERIAL:
            # serial exclusivity covers the PIPELINE too: a state mutation
            # must not run while dispatched witness handles are in flight
            self._exec_stage = "serial"
            self._drain_pipeline()
            with self._lock:
                dead = self._dead
            if dead is not None:
                # the drain ended because the scheduler DIED, not because
                # the pipeline emptied: a state mutation must not commit
                # on a server whose /healthz already reports it down
                _safe_fail(
                    batch[0].future,
                    SchedulerDown(f"scheduler executor crashed: {dead!r}"),
                )
                return
            stage = "serial"
        else:
            self._exec_stage = "pack"  # provisional: engine resolution
            engine = self._resolve_engine()
            pipelined = self._pipe_depth > 1 and hasattr(engine, "begin_batch")
            # stage vocabulary: pipelined batches move pack -> dispatch ->
            # resolve; a depth-1/inline batch runs all three fused under
            # "dispatch" (the engine round-trip the executor blocks on).
            # _exec_stage must AGREE with the batch_start record — a
            # depth-1 crash (chaos drill included) has no pack stage
            stage = "pack" if pipelined else "dispatch"
            self._exec_stage = stage
        with self._lock:
            self._batch_seq += 1
            batch_id = self._batch_seq
            self._inflight_list.append(
                {
                    "batch_id": batch_id,
                    "lane": lane,
                    "stage": stage,
                    "started": now,
                    "deadline": stall_deadline,
                    "trace_ids": trace_ids,
                }
            )
        flight.record(
            "sched.batch_start",
            batch_id=batch_id,
            lane=lane,
            stage=stage,
            batch_size=len(batch),
            bucket_bytes=batch[0].bucket if lane == _WITNESS else None,
            trace_ids=trace_ids,
        )
        if pipelined:
            # the descriptor stays in flight until the resolve worker
            # finishes the batch (or _die clears everything)
            self._execute_witness_pipelined(batch, batch_id, engine, now)
            return
        try:
            if lane == _SERIAL:
                self._execute_serial(batch[0], batch_id)
            else:
                self._execute_witness(batch, batch_id, engine, now)
        finally:
            with self._lock:
                self._drop_inflight_locked(batch_id)

    def _drop_inflight_locked(self, batch_id: int) -> None:
        self._inflight_list = [
            d for d in self._inflight_list if d["batch_id"] != batch_id
        ]

    def _execute_serial(self, job: _Job, batch_id: int) -> None:
        metrics.count("sched.batches", lane="serial")
        with self._lock:
            self.stats["serial_jobs"] += 1
        if job.deadline is not None and time.monotonic() > job.deadline:
            self._shed_expired(job)
            return
        t0 = time.monotonic()

        def done(ok: bool, **extra) -> None:
            # the postmortem must distinguish a failed mutation from a
            # successful one — `ok` is the serial lane's n_ok analog
            flight.record(
                "sched.batch_done",
                batch_id=batch_id,
                lane=_SERIAL,
                batch_size=1,
                ok=ok,
                duration_ms=round((time.monotonic() - t0) * 1e3, 3),
                queue_wait_ms=round((t0 - job.admitted) * 1e3, 3),
                trace_ids=[job.trace_id],
                **extra,
            )

        try:
            result = job.fn()
        except Exception as e:  # request-scoped: the job failed, not us
            done(False, error=repr(e)[:160])
            job.future.set_exception(e)
            return
        done(True)
        job.future.set_result(result)

    @staticmethod
    def _engine_cache_stats(engine) -> Optional[dict]:
        """hits/hashed/device/native counters of the engine, or None when
        the engine exposes no stats (custom test doubles)."""
        snap = getattr(engine, "stats_snapshot", None)
        if snap is None:
            return None
        try:
            return snap()
        except Exception:
            return None

    def _shed_or_keep(self, batch: List[_Job], now: float) -> List[_Job]:
        jobs = []
        for j in batch:
            if j.deadline is not None and now > j.deadline:
                self._shed_expired(j)
            else:
                jobs.append(j)
        return jobs

    def _execute_witness(
        self, batch: List[_Job], batch_id: int, engine, picked: float
    ) -> None:
        """Depth-1/inline execution: one verify_batch round-trip on the
        executor thread (pack + dispatch + resolve fused) — exactly the
        pre-pipeline behavior."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            return
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        self._exec_stage = "dispatch"
        s0 = self._engine_cache_stats(engine)
        # the engine/device dispatch this scheduler exists for: one
        # verify_batch over the whole coalesced bucket. An exception here
        # is systemic (malformed witnesses yield False verdicts, and the
        # engine falls back device->native internally), so it propagates
        # to _run and takes the executor down — requests fail fast rather
        # than silently retrying into a broken engine.
        verdicts = engine.verify_batch([(j.root, j.nodes) for j in jobs])
        s1 = self._engine_cache_stats(engine)
        record = {
            "batch_id": batch_id,
            "batch_size": len(jobs),
            "bucket_bytes": jobs[0].bucket,
            "stage": "dispatch",
        }
        if s0 is not None and s1 is not None:
            # deltas are batch-attributable as long as this executor is the
            # engine's only concurrent caller (the serving configuration);
            # a shared offline engine can skew them by other callers' work
            record["cache_hits"] = s1.get("hits", 0) - s0.get("hits", 0)
            record["cache_misses"] = s1.get("hashed", 0) - s0.get("hashed", 0)
            if s1.get("device_batches", 0) > s0.get("device_batches", 0):
                record["backend"] = "device"
            elif s1.get("native_batches", 0) > s0.get("native_batches", 0):
                record["backend"] = "native"
            else:
                record["backend"] = "cached"  # zero novel nodes: no hashing
        self._finish_witness_jobs(jobs, verdicts, record, picked)

    def _execute_witness_pipelined(
        self, batch: List[_Job], batch_id: int, engine, picked: float
    ) -> None:
        """Pack + dispatch on the executor thread, resolve on the resolve
        worker: begin_batch holds the engine lock only for the intern
        scan and enqueues the device keccak with NO host sync, so this
        thread moves straight on to assembling (and packing) the next
        batch while the device computes and the worker resolves."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        # bounded depth: wait for a pipeline slot (stall time is the
        # occupancy signal — a hot resolve stage shows up here). The depth
        # is an immutable config scalar, read lock-free like the others.
        depth = self._pipe_depth
        t_wait = time.perf_counter()
        with self._lock:
            while (
                len(self._resolve_q) + (1 if self._resolving else 0) >= depth
                and self._dead is None
            ):
                self._cond.wait(0.05)
            dead = self._dead
        metrics.observe("sched.pipeline_stall", time.perf_counter() - t_wait)
        if dead is not None:
            # the resolve worker died while we waited: fail this batch the
            # same way _die failed everything else, and stop the executor
            raise SchedulerDown(f"resolve worker is down: {dead!r}")
        # deadlines re-checked AFTER the slot wait: a wedged resolve stage
        # can hold the pipeline full long past a job's deadline, and an
        # expired job must shed (its waiter is gone) rather than spend
        # pack/dispatch/resolve work
        jobs = self._shed_or_keep(jobs, time.monotonic())
        if not jobs:
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        t_pack = time.perf_counter()
        handle = engine.begin_batch([(j.root, j.nodes) for j in jobs])
        item = {
            "jobs": jobs,
            "handle": handle,
            "batch_id": batch_id,
            "picked": picked,
            "pack_ms": round((time.perf_counter() - t_pack) * 1e3, 3),
        }
        with self._lock:
            dead = self._dead
            if dead is None:
                self._resolve_q.append(item)
        if dead is not None:
            # the worker died while we packed: the just-begun handle will
            # never be resolved — release its engine lease before failing
            _abandon_handle(engine, handle)
            raise SchedulerDown(f"resolve worker is down: {dead!r}")
        with self._lock:
            self.stats["pipelined_batches"] += 1
            inflight = len(self._resolve_q) + (1 if self._resolving else 0)
            self._cond.notify_all()
        metrics.gauge_set("sched.pipeline_inflight", inflight)

    def _finish_witness_jobs(
        self, jobs: List[_Job], verdicts, record: dict, picked: float
    ) -> None:
        """Shared completion tail of both witness paths: per-job meta +
        future resolution, the batch_done flight record, and the batching
        metrics/stats."""
        n = len(jobs)
        total = sum(j.nbytes for j in jobs)
        padded = _pow2ceil(total)
        done = time.monotonic()
        for j, ok in zip(jobs, verdicts):
            # meta BEFORE set_result: a waiter that observed the verdict
            # must also observe its batch record (verify_traced)
            j.meta = {
                **record,
                "queue_wait_ms": round((picked - j.admitted) * 1e3, 3),
            }
            _safe_resolve(j.future, bool(ok))
        flight.record(
            "sched.batch_done",
            lane=_WITNESS,
            duration_ms=round((done - picked) * 1e3, 3),
            n_ok=int(sum(bool(ok) for ok in verdicts)),
            trace_ids=[j.trace_id for j in jobs],
            **record,
        )
        metrics.observe_hist("sched.batch_size", n, buckets=_BATCH_BUCKETS)
        metrics.count("sched.batches", lane="witness")
        metrics.gauge_set(
            "sched.padding_waste", round(1.0 - total / padded, 4) if padded else 0.0
        )
        if n > 1:
            metrics.count("sched.coalesced_requests", n)
        with self._lock:
            st = self.stats
            st["batches"] += 1
            st["batched_requests"] += n
            if n > 1:
                st["coalesced"] += n
            if n > st["max_batch_seen"]:
                st["max_batch_seen"] = n

    # -- resolve worker (pipeline_depth > 1) ---------------------------------

    def _resolve_run(self) -> None:
        item: Optional[dict] = None
        try:
            while True:
                with self._lock:
                    while (
                        not self._resolve_q
                        and not self._exec_done
                        and self._dead is None
                    ):
                        self._cond.wait()
                    if self._dead is not None:
                        return  # _die already failed everything queued
                    if not self._resolve_q:
                        return  # executor done and the pipeline is drained
                    item = self._resolve_q.pop(0)
                    self._resolving = True
                    for d in self._inflight_list:
                        if d["batch_id"] == item["batch_id"]:
                            d["stage"] = "resolve"
                    self._cond.notify_all()
                try:
                    self._resolve_one(item)
                finally:
                    with self._lock:
                        self._resolving = False
                        self._drop_inflight_locked(item["batch_id"])
                        inflight = len(self._resolve_q)
                        self._cond.notify_all()
                    metrics.gauge_set("sched.pipeline_inflight", inflight)
                item = None
        except BaseException as e:  # systemic: readback/commit failure
            # resolve_batch releases its own handle on failure; a crash
            # elsewhere in the loop still must not leak it
            if item is not None:
                _abandon_handle(self._engine, item["handle"])
            self._die(e, item["jobs"] if item else [], stage="resolve")

    def _resolve_one(self, item: dict) -> None:
        jobs = item["jobs"]
        handle = item["handle"]
        t0 = time.monotonic()
        verdicts = self._engine.resolve_batch(handle)
        # the batch record comes from the HANDLE, not an engine-stats
        # delta: with batches overlapping in the pipeline, a delta would
        # blend batch N's resolve with batch N+1's pack
        record = {
            "batch_id": item["batch_id"],
            "batch_size": len(jobs),
            "bucket_bytes": jobs[0].bucket,
            "stage": "resolve",
            "pack_ms": item["pack_ms"],
        }
        total = getattr(handle, "total", None)
        miss = getattr(handle, "miss", None)
        # cache_misses = UNIQUE novel nodes hashed (n_novel), matching the
        # inline path's hashed-delta semantics — `miss` also counts
        # within-batch duplicate occurrences and would make identical
        # traffic read differently across pipeline depths
        n_novel = getattr(handle, "n_novel", None)
        if total is not None and miss is not None:
            record["cache_hits"] = total - miss
            record["cache_misses"] = n_novel if n_novel is not None else miss
        if getattr(handle, "device", None) is not None:
            record["backend"] = "device"
        elif n_novel if n_novel is not None else miss:
            record["backend"] = "native"
        else:
            record["backend"] = "cached"  # zero novel nodes: no hashing
        record["resolve_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        self._finish_witness_jobs(jobs, verdicts, record, item["picked"])

    def _resolve_engine(self):
        if self._engine is None:
            from phant_tpu.stateless import shared_witness_engine

            self._engine = shared_witness_engine()
        return self._engine

    def _die(
        self, exc: BaseException, batch: List[_Job], stage: Optional[str] = None
    ) -> None:
        """Mark the scheduler DOWN and fail fast: the crashing batch, every
        queued job, AND every dispatched-but-unresolved pipeline handle.
        `stage` names where execution died — pack/dispatch (executor),
        resolve (resolve worker), serial — so the postmortem pinpoints the
        pipeline stage. Idempotent-by-first-caller: when the second thread
        of a pipelined scheduler trips over the first thread's corpse, it
        only fails its own victims (one crash record, one dump)."""
        with self._lock:
            first = self._dead is None
            if first:
                self._dead = exc
            victims = batch + self._queue
            dropped_items = list(self._resolve_q)
            for item in dropped_items:
                victims.extend(item["jobs"])
            self._queue = []
            self._resolve_q = []
            self._inflight_list = []
            batch_id = self._batch_seq
            self._cond.notify_all()
        engine = self._engine
        for item in dropped_items:
            # never resolved, never will be: release the engine leases so
            # a shared engine keeps evicting after this scheduler's death
            _abandon_handle(engine, item["handle"])
        if first:
            log.error("scheduler executor crashed: %r", exc, exc_info=exc)
            metrics.count("sched.executor_crashes")
            # the postmortem FIRST: record the crash (with the crashing
            # batch's ids and the stage that died) and dump the whole ring
            # to build/flight/ — by the time a waiter observes its
            # SchedulerDown, the artifact already exists
            flight.record(
                "sched.executor_crash",
                batch_id=batch_id,
                stage=stage,
                error=repr(exc),
                crashed_trace_ids=[j.trace_id for j in batch],
                n_failed_fast=len(victims),
            )
            flight.dump("executor_crash")
        for j in victims:
            _safe_fail(
                j.future, SchedulerDown(f"scheduler executor crashed: {exc!r}")
            )
        metrics.gauge_set("sched.queue_depth", 0)
        metrics.gauge_set("sched.pipeline_inflight", 0)
        self._watchdog.stop(0.0)
