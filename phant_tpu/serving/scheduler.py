"""Continuous-batching verification scheduler.

The Engine API server used to execute one request at a time behind a
global lock: concurrent CL requests queued on a mutex and each one paid a
batch-of-1 engine dispatch — the exact opposite of the framework's win
condition (vmapping witness verification across hundreds of blocks per
device dispatch). This module gives the serving path the inference-server
shape instead:

    admission queue  ->  batch assembler  ->  single executor thread

* **Admission: per-tenant lanes + quotas (QoS, serving/qos.py)** — every
  request carries a tenant tag (the Engine API server binds it from the
  `X-Phant-Tenant` header via `tenant_context`; untagged submissions land
  in the `default` lane) and a priority class. Witness jobs queue in a
  per-tenant FIFO lane; the total across lanes is bounded by
  `queue_depth` and each lane by `tenant_quota` (0 = unbounded), so one
  backfill tenant can no longer fill the whole queue. A full lane sheds
  with `QueueFull` (`-32050`, `sched.rejected{reason=tenant_quota,
  tenant=...}`); a full queue sheds `reason=queue_full` — unless the
  arriving job is head-of-chain (`PRIORITY_HEAD`: the serial mutation
  lane, or a witness request marked `X-Phant-Priority: head`), in which
  case a queued victim is evicted to make room (`reason=evicted`, same
  `-32050` code). The shed order is fixed and documented: backfill
  first (a head-class arrival at its tenant quota evicts its OWN
  tenant's newest backfill; a full queue evicts the deepest lane's
  newest backfill), head-class witness jobs only for an arriving SERIAL
  mutation with no backfill left, and the serial mutation lane NEVER —
  a mutation can only be rejected when the queue is full of OTHER
  serial mutations (its own class's backlog). Eviction also never
  touches `wait_for_space` (verify_many) jobs, whose contract is
  completion. Every request still carries a deadline; expiry while
  queued fails with `DeadlineExpired` (`-32051`) without touching the
  engine.
* **Dequeue: priority + weighted fairness** — the serial mutation lane
  preempts all queued witness work (head-of-chain `newPayload` must not
  sit behind a backfill burst); among witness lanes, lanes whose head is
  `PRIORITY_HEAD` are served before backfill lanes, and the tenant is
  chosen by smooth weighted round-robin (qos.WeightedFairPicker,
  `tenant_weights`) so a 10:1 offered-load imbalance cannot starve the
  light tenant — each lane stays FIFO internally.
* **Batch assembler** — coalesces *witness-verification* requests into
  shape buckets (bucket key = total witness bytes rounded up to a power
  of two, the same rounding the device keccak path pads its blob buffer
  to, ops/witness_jax._pow2ceil), so the padded device buffers of one
  batch stay dense; same-bucket jobs coalesce ACROSS tenant lanes (the
  engine dispatch is tenant-blind; fairness is enforced at head pick).
  `sched.padding_waste` reports the unused fraction of the padded
  buffer. Assembly runs under a `max_batch` / ADAPTIVE-wait policy
  (qos.AdaptiveWait): a batch executes as soon as it is full, and an
  under-full batch waits at most `wait_ms(queue_depth)` from its head
  request's admission — the full `max_wait_ms` when the scheduler is
  idle (a lone request gets its coalescing window), decaying to
  `min_wait_ms` as the queue approaches one full batch, because then
  the backlog IS the batch and further waiting is pure added latency.
  The chosen wait is exported as the `sched.adaptive_wait_ms` gauge,
  changes are counted in `sched.adaptive_wait_adjustments` and recorded
  as `sched.adapt_wait` flight events; `adaptive_wait=False` pins the
  static `max_wait_ms` policy (the pre-QoS behavior).
* **Executor** — ONE thread drains buckets into the engine and resolves
  per-request futures. The same thread runs *serial* jobs
  (state-mutating `engine_newPayload*` execution) one at a time, in
  admission order — which is what replaces the server's global execution
  lock: mutation is serialized by the executor, not by a mutex held
  across the whole request.
* **Pipeline** (`pipeline_depth`, default 2 via
  PHANT_SCHED_PIPELINE_DEPTH / `--sched-pipeline-depth`) — with depth
  >= 2 the executor splits witness execution through the engine's
  two-phase API (ops/witness_engine.py `begin_batch`/`resolve_batch`):
  it PACKS batch N+1 (bucket assembly + lock-held intern scan) and
  DISPATCHES its novel-node keccak with no host sync while a dedicated
  *resolve worker* thread RESOLVES batch N (digest readback / GIL-free C
  hashing outside the engine lock, then commit + linkage join). JAX's
  async dispatch means the device was idle during host packing and the
  host idle during device compute — this is the overlap that closes it,
  the same double-buffered-prefetch shape inference servers use. At
  most `pipeline_depth` batches are in flight; the executor blocks on a
  full pipeline (`sched.pipeline_stall` names resolve as the
  bottleneck). Depth 1 — or an engine without `begin_batch` — is the
  pre-pipeline behavior, byte-identical inline verify_batch execution.
  The serial lane drains the WHOLE pipeline first, so mutation stays
  exclusive against in-flight witness work; futures still complete in
  admission order per requester (the resolve worker is FIFO). On crash
  paths, dispatched-but-unresolved handles are released through the
  engine's `abandon_batch` (when it has one) so a shared engine that
  outlives a dead scheduler never leaks in-flight leases. Handle
  resolution order is a per-scheduler property only — the engine accepts
  any interleaving, so several schedulers can share one engine.
* **Mesh dispatch** (`mesh_devices` >= 1 via `--sched-mesh N` /
  PHANT_SCHED_MESH) — admission, tenant-fair head pick, and batch
  assembly stay GLOBAL, but execution fans out to a `MeshExecutorPool`
  (serving/mesh_exec.py): one pipelined executor per mesh device, each
  owning a `WitnessEngine` pinned to that device, with stable
  bucket-affinity routing (a shape keeps hitting the same device's
  intern table) and least-loaded spillover once the home lane backs up.
  `mesh_dispatch="megabatch"` additionally sends a single-bucket batch
  that fills `max_batch` through ONE whole-mesh sharded fused kernel
  call. The serial lane drains the whole pool first (mutation stays
  exclusive against every device), any lane crash takes the scheduler
  down exactly like an executor crash — with every device's
  dispatched-but-unresolved handles abandoned — and batch/stall/crash
  records carry the `device` that ran them.
* **Lifecycle** — `shutdown(drain=True)` stops admission and lets the
  executor finish everything queued AND everything in the pipeline
  (graceful drain); an exception escaping batch execution — in either
  thread — marks the scheduler DOWN: the crashed batch, everything
  queued, and every dispatched-but-unresolved handle fail fast with
  `SchedulerDown` (`-32052`), later submits are rejected immediately,
  `/healthz` reports 503 with `executor_alive: false`
  (engine_api/server.py `_healthz_payload`), and the crash flight
  record names the pipeline STAGE that died (pack/dispatch/resolve).

`verify_many()` is the synchronous offline face of the same machinery:
bench.py, the spec runner (`--sched`), and tests push whole witness
spans through the identical admission/assembly/executor code and get an
(n,) bool verdict array back — the batching code measured offline is the
batching code serving traffic.

Observability (phant_tpu/obs/, PR 4): every job carries the submitting
request's `trace_id` (utils/trace.py trace_context — the Engine API server
opens one per POST), admissions/sheds/batch transitions land in the flight
recorder ring, and the executor attaches a per-batch record (`batch_id`,
`batch_size`, `bucket_bytes`, `backend`, cache hit/miss deltas,
`queue_wait_ms`) to each job it resolves — `verify_traced()` hands it back
so the request's span stays joinable to the batch that served it. An obs
watchdog thread per scheduler flags the in-flight batch out-living its
deadline (`sched.watchdog_stalls` + a `sched.stall` flight event); an
executor crash additionally dumps the ring to build/flight/ (the
postmortem artifact a dead server leaves behind).

Thread-safety: one lock (`_lock`) guards the queue and lifecycle state;
`_cond` wraps that same lock, so every wait/notify runs under it. The
registry's and flight recorder's own locks never take ours, so metric and
flight publishes cannot deadlock against admission (same discipline as
ops/witness_engine.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.obs import critpath, timeline
from phant_tpu.obs.busy import BusyAccountant
from phant_tpu.obs.flight import flight
from phant_tpu.obs.watchdog import Watchdog
from phant_tpu.serving.qos import (
    DEFAULT_TENANT,
    OVERFLOW_TENANT,
    PRIORITY_BACKFILL,
    PRIORITY_HEAD,
    AdaptiveWait,
    WeightedFairPicker,
    current_priority,
    current_tenant,
    parse_weights,
)
from phant_tpu.utils.trace import current_trace_id, metrics

log = logging.getLogger("phant_tpu.serving")


class SchedulerError(Exception):
    """Base for scheduler rejections; carries the JSON-RPC error code and
    HTTP status the Engine API server maps the rejection to."""

    code = -32000
    http_status = 503


class QueueFull(SchedulerError):
    """Admission queue at `queue_depth`: overload, shed the request."""

    code = -32050


class DeadlineExpired(SchedulerError):
    """The request's deadline passed before the executor reached it."""

    code = -32051


class SchedulerDown(SchedulerError):
    """The executor has crashed or the scheduler is shutting down."""

    code = -32052


def _default_pipeline_depth() -> int:
    """PHANT_SCHED_PIPELINE_DEPTH, default 2 (overlap pack of batch N+1
    with resolve of batch N). Depth 1 is the pre-pipeline serialized
    behavior: the executor runs pack -> dispatch -> resolve inline."""
    return int(os.environ.get("PHANT_SCHED_PIPELINE_DEPTH", "2"))


def _default_prefetch() -> bool:
    """PHANT_SCHED_PREFETCH, default on: with pipeline_depth >= 2, a
    dedicated prefetch worker runs batch N+1's witness decode + advisory
    intern-table novelty pre-scan (ops/witness_engine.py prefetch_batch)
    while batch N is in dispatch/resolve — the 4th pipeline stage
    (prefetch -> pack -> dispatch -> resolve). 0 / `--sched-prefetch 0`
    pins the PR-5 3-stage behavior. Prefetch is advisory end to end: the
    pack-time scan under the engine lock stays the authoritative commit,
    so a stale plan costs the perf win and nothing else."""
    return os.environ.get("PHANT_SCHED_PREFETCH", "1") not in ("0", "")


def _default_tenant_quota() -> int:
    """PHANT_SCHED_TENANT_QUOTA: per-tenant queued-witness cap; 0 (the
    default) means only the global queue_depth bounds a lane."""
    return int(os.environ.get("PHANT_SCHED_TENANT_QUOTA", "0"))


def _default_adaptive_wait() -> bool:
    """PHANT_SCHED_ADAPTIVE_WAIT, default on: shrink the assembly wait as
    the queue deepens, widen it when idle (qos.AdaptiveWait). 0 pins the
    static max_wait_ms policy."""
    return os.environ.get("PHANT_SCHED_ADAPTIVE_WAIT", "1") not in ("0", "")


def _default_min_wait_ms() -> float:
    """PHANT_SCHED_MIN_WAIT_MS: the adaptive-wait floor once the queue
    holds a full batch (the backlog IS the batch)."""
    return float(os.environ.get("PHANT_SCHED_MIN_WAIT_MS", "0.2"))


def _default_tenant_weights() -> dict:
    """PHANT_SCHED_TENANT_WEIGHTS (`name:weight,...`): weighted-fair
    dequeue shares; unlisted tenants weigh 1."""
    return parse_weights(os.environ.get("PHANT_SCHED_TENANT_WEIGHTS"))


def _default_max_tenants() -> int:
    """PHANT_SCHED_MAX_TENANTS: distinct tenant lanes tracked before new
    tags fold into the shared OVERFLOW lane — an attacker spraying random
    X-Phant-Tenant headers must not grow per-tenant state (or metric
    cardinality) without bound."""
    return int(os.environ.get("PHANT_SCHED_MAX_TENANTS", "64"))


def _default_mesh_devices() -> int:
    """PHANT_SCHED_MESH (`--sched-mesh N`): per-device executors behind
    the batch assembler. 0 (default) = the single-executor path; N >= 1
    fans dispatch out over a MeshExecutorPool of N device-pinned
    engines (N=1 is a one-lane pool — useful as the A/B control)."""
    return int(os.environ.get("PHANT_SCHED_MESH", "0"))


def _default_mesh_dispatch() -> str:
    """PHANT_SCHED_MESH_DISPATCH: `affinity` (default — bucket-affinity
    routing with spillover) or `megabatch` (a full single-bucket batch
    additionally dispatches as ONE whole-mesh sharded kernel call)."""
    return os.environ.get("PHANT_SCHED_MESH_DISPATCH", "affinity")


def _default_mesh_spill_depth() -> int:
    """PHANT_SCHED_MESH_SPILL: batches a bucket's home device may have
    outstanding before new batches spill to the least-loaded device."""
    return int(os.environ.get("PHANT_SCHED_MESH_SPILL", "2"))


def _default_megabatch_backlog_k() -> int:
    """PHANT_SCHED_MEGABATCH_BACKLOG_K: with `mesh_dispatch=megabatch`,
    ALSO fire the whole-mesh fused dispatch whenever the queued
    same-bucket work (current batch + still-queued same-bucket jobs) is
    >= mesh_width x k — sustained overload engages fusion without the
    operator sizing max_batch. 0 (default) keeps the full-batch-only
    trigger."""
    return int(os.environ.get("PHANT_SCHED_MEGABATCH_BACKLOG_K", "0"))


@dataclass
class SchedulerConfig:
    """Knobs, surfaced as `--sched-*` CLI flags (phant_tpu/__main__.py)."""

    max_batch: int = 128  # requests per assembled witness batch
    max_wait_ms: float = 5.0  # assembly-wait ceiling for an under-full batch
    queue_depth: int = 512  # admission-queue bound (overload -> QueueFull)
    deadline_ms: float = 30_000.0  # default per-request deadline; <=0 = none
    # witness batches in flight between pack and resolve (>=2 pipelines:
    # the executor packs/dispatches batch N+1 while the resolve worker
    # reads back + joins batch N); 1 = today's serialized execution
    pipeline_depth: int = field(default_factory=_default_pipeline_depth)
    # 4th pipeline stage (PR 9): prefetch worker decodes + pre-scans batch
    # N+1 while batch N is in dispatch/resolve. On whenever
    # pipeline_depth >= 2; `--sched-prefetch 0` / PHANT_SCHED_PREFETCH=0
    # opts out (the 3-stage PR-5 pipeline)
    prefetch: bool = field(default_factory=_default_prefetch)
    # --- multi-tenant QoS (serving/qos.py) ---------------------------------
    # per-tenant queued-witness cap (0 = global queue_depth only)
    tenant_quota: int = field(default_factory=_default_tenant_quota)
    # weighted-fair dequeue shares; unlisted tenants weigh 1.0
    tenant_weights: dict = field(default_factory=_default_tenant_weights)
    # queue-depth-adaptive assembly wait (False = static max_wait_ms)
    adaptive_wait: bool = field(default_factory=_default_adaptive_wait)
    # adaptive-wait floor (reached once the queue holds ~one full batch)
    min_wait_ms: float = field(default_factory=_default_min_wait_ms)
    # distinct tenant lanes before fold-over into OVERFLOW_TENANT
    max_tenants: int = field(default_factory=_default_max_tenants)
    # --- mesh dispatch (serving/mesh_exec.py) ------------------------------
    # per-device executors behind the assembler (0 = single-executor path)
    mesh_devices: int = field(default_factory=_default_mesh_devices)
    # "affinity" (bucket->device routing + spillover) or "megabatch"
    mesh_dispatch: str = field(default_factory=_default_mesh_dispatch)
    # home-device backlog at which a batch spills to the least-loaded lane
    mesh_spill_depth: int = field(default_factory=_default_mesh_spill_depth)
    # megabatch backlog trigger: fuse when queued same-bucket work >=
    # mesh width x k (0 = full-batch-only, the pre-trigger behavior)
    megabatch_backlog_k: int = field(default_factory=_default_megabatch_backlog_k)
    # per-lane engine injection (tests/bench: doubles, shared engines);
    # None = one device-pinned WitnessEngine per lane
    mesh_engine_factory: Optional[Callable] = None
    # root-lane engine injection (tests/bench: poisoned engines, forced
    # device floors); None = the process-shared ops/root_engine.py engine
    # (mesh lanes build one PINNED RootEngine per device instead)
    root_engine_factory: Optional[Callable] = None
    # sig-lane engine injection (tests/bench: poisoned engines, forced
    # device floors); None = the process-shared ops/sig_engine.py engine
    # (mesh lanes build one PINNED SigEngine per device instead)
    sig_engine_factory: Optional[Callable] = None


_WITNESS = "witness"
_SERIAL = "serial"
#: post-root lane (PR 11): jobs carry a fused account+storage HashPlan
#: (stateless.WitnessStateDB.post_root_plan) and coalesce per level-shape
#: bucket into ONE ops/root_engine.py dispatch — the same admission /
#: fairness / assembly / pipeline / crash machinery as the witness lane
#: (the RootEngine speaks the WitnessEngine two-phase protocol). Root
#: buckets are NEGATIVE ints (-(level count)) so they can never collide
#: with the witness lane's pow2-byte buckets (>= 1).
_ROOT = "root"
#: sender-recovery lane (PR 14): jobs carry one request's signature rows
#: (signer.TxSigner.signature_rows) and coalesce into ONE merged
#: ops/sig_engine.py ecrecover dispatch — the same admission / fairness /
#: assembly / pipeline / crash machinery as the witness and root lanes
#: (the SigEngine speaks the WitnessEngine two-phase protocol). Rows are
#: freely concatenable (no per-request shape constraint — the kernel
#: pow2-pads the merged batch), so EVERY sig job shares one fixed bucket:
#: a large negative sentinel far below any root bucket (-(level count),
#: bounded by trie depth) and disjoint from witness pow2 buckets (>= 1).
_SIG = "sig"
_SIG_BUCKET = -(1 << 20)

#: _next_batch(block=False) found nothing queued (distinct from None =
#: closed/dead): the prefetching executor re-evaluates its pending work
_NO_BATCH = object()

#: batch-size histogram buckets (requests per engine dispatch)
_BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _pow2ceil(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _safe_resolve(future: Future, result) -> None:
    """set_result tolerating a concurrent _die: with two scheduler threads,
    the resolve worker can complete a batch in the same instant the
    executor fails everything — losing that race must not raise
    InvalidStateError out of the winner."""
    try:
        future.set_result(result)
    except Exception:
        pass  # already failed by _die; the waiter got the crash


def _safe_fail(future: Future, exc: BaseException) -> None:
    if not future.done():
        try:
            future.set_exception(exc)
        except Exception:
            pass  # resolved in the race window; the waiter got a verdict


def batch_record_from_stats(
    batch_id: int, batch_size: int, bucket: int, s0: Optional[dict], s1: Optional[dict]
) -> dict:
    """The inline (fused verify_batch) batch record from an engine-stats
    delta: cache hits/misses plus the backend classification. Shared by
    the single-executor inline path and the mesh lanes' inline path so
    record semantics can never diverge between them. Deltas are
    batch-attributable as long as the caller is the engine's only
    concurrent user (true per executor/lane in the serving shapes)."""
    record = {
        "batch_id": batch_id,
        "batch_size": batch_size,
        "bucket_bytes": bucket,
        "stage": "dispatch",
    }
    if s0 is not None and s1 is not None:
        record["cache_hits"] = s1.get("hits", 0) - s0.get("hits", 0)
        record["cache_misses"] = s1.get("hashed", 0) - s0.get("hashed", 0)
        if s1.get("device_batches", 0) > s0.get("device_batches", 0):
            record["backend"] = "device"
        elif s1.get("native_batches", 0) > s0.get("native_batches", 0):
            record["backend"] = "native"
        else:
            record["backend"] = "cached"  # zero novel nodes: no hashing
    return record


def batch_record_from_handle(
    handle, batch_id: int, batch_size: int, bucket: int
) -> dict:
    """The two-phase batch record from the HANDLE (never an engine-stats
    delta: with batches overlapping in a pipeline, a delta would blend
    batch N's resolve with batch N+1's pack). `cache_misses` is the
    UNIQUE novel count (handle.n_novel) so identical traffic reads the
    same at every depth and lane — `miss` also counts within-batch
    duplicate occurrences. Shared by the resolve worker and the mesh
    lanes."""
    record = {
        "batch_id": batch_id,
        "batch_size": batch_size,
        "bucket_bytes": bucket,
        "stage": "resolve",
    }
    total = getattr(handle, "total", None)
    miss = getattr(handle, "miss", None)
    n_novel = getattr(handle, "n_novel", None)
    if total is not None and miss is not None:
        record["cache_hits"] = total - miss
        record["cache_misses"] = n_novel if n_novel is not None else miss
    if getattr(handle, "resident", None) is not None:
        # device-resident route: verdict + novel hashing on device
        # against the persistent intern table (ops/witness_resident.py)
        record["backend"] = "resident"
    elif getattr(handle, "device", None) is not None:
        record["backend"] = "device"
    elif n_novel if n_novel is not None else miss:
        record["backend"] = "native"
    else:
        record["backend"] = "cached"  # zero novel nodes: no hashing
    return record


def root_record_from_handle(
    handle, batch_id: int, batch_size: int, bucket: int
) -> dict:
    """The root-lane batch record: backend (device dispatch vs the
    offload-gated host walk) and the merged payload come off the
    RootHandle. Shared by the resolve worker and the mesh lanes, like the
    witness record builders above."""
    return {
        "batch_id": batch_id,
        "batch_size": batch_size,
        "bucket_bytes": bucket,
        "stage": "resolve",
        "lane": _ROOT,
        "backend": getattr(handle, "backend", None) or "host",
        "payload_bytes": getattr(handle, "payload", None),
    }


def sig_record_from_handle(
    handle, batch_id: int, batch_size: int, bucket: int
) -> dict:
    """The sig-lane batch record: backend (merged device dispatch vs the
    offload-gated fused native batch / scalar fallback) and the merged
    row count come off the SigHandle. Shared by the resolve worker and
    the mesh lanes, like the witness and root record builders above."""
    return {
        "batch_id": batch_id,
        "batch_size": batch_size,
        "bucket_bytes": bucket,
        "stage": "resolve",
        "lane": _SIG,
        "backend": getattr(handle, "backend", None) or "native",
        "merged_rows": getattr(handle, "n_rows", None),
    }


def _abandon_handle(engine, handle) -> None:
    """Release a dispatched-but-unresolved engine handle on a crash path.
    The shared engine outlives a dead scheduler; a leaked handle would
    pin its in-flight count and defer generation flushes forever
    (ops/witness_engine.py abandon_batch). Best-effort: the scheduler is
    already dying, a second failure here must not mask the first."""
    abandon = getattr(engine, "abandon_batch", None)
    if abandon is None:
        return
    try:
        abandon(handle)
    except Exception:
        log.warning("abandon_batch failed on a crash path", exc_info=True)


@dataclass
class _Job:
    kind: str
    future: Future
    admitted: float  # monotonic admission time
    deadline: Optional[float]  # monotonic expiry, None = no deadline
    # QoS: the tenant lane this job queues in (folded through the
    # max_tenants cap at admission) and its priority class. `sheddable`
    # is False for wait_for_space admissions (verify_many): their
    # contract is completion, so the eviction policy must never pick
    # them as overload victims.
    tenant: str = DEFAULT_TENANT
    priority: int = PRIORITY_BACKFILL
    sheddable: bool = True
    # witness lane
    root: bytes = b""
    nodes: Sequence[bytes] = ()
    nbytes: int = 0
    bucket: int = 0
    # root lane: the request's fused post-root HashPlan
    plan: Optional[object] = None
    # sig lane: the request's signature rows (signer.SigRows)
    rows: Optional[object] = None
    # serial lane
    fn: Optional[Callable] = None
    # observability: the submitting request's trace context, and the batch
    # record the executor attaches before resolving the future (set-then-
    # resolve ordering means a waiter that saw result() also sees meta)
    trace_id: Optional[str] = None
    meta: Optional[dict] = None


class VerificationScheduler:
    """Continuous-batching scheduler over a `WitnessEngine`.

    `engine` defaults to the process-shared memoized engine
    (stateless.shared_witness_engine), resolved lazily at first execution
    so constructing a scheduler never imports jax-adjacent modules.
    """

    def __init__(
        self,
        engine: Optional[object] = None,
        config: Optional[SchedulerConfig] = None,
    ):
        self.config = config or SchedulerConfig()
        # config is immutable after construction; the locked regions read
        # these unpacked copies so `self.config` itself stays a lock-free
        # introspection surface (state(), _deadline())
        self._max_batch = self.config.max_batch
        self._max_wait_s = self.config.max_wait_ms / 1e3
        self._queue_depth = self.config.queue_depth
        self._pipe_depth = max(1, self.config.pipeline_depth)
        self._quota = max(0, self.config.tenant_quota)
        self._max_tenants = max(1, self.config.max_tenants)
        # QoS policy objects (serving/qos.py): both are only ever touched
        # under _lock, so they need no locking of their own
        self._picker = WeightedFairPicker(self.config.tenant_weights)
        self._wait_policy: Optional[AdaptiveWait] = (
            AdaptiveWait(
                self.config.max_wait_ms,
                min_wait_ms=self.config.min_wait_ms,
                full_depth=self.config.max_batch,
            )
            if self.config.adaptive_wait
            else None
        )
        self._engine = engine
        # root-lane engine, resolved lazily on the first root batch (the
        # shared ops/root_engine.py engine unless the config injects one)
        self._root_engine = None
        # sig-lane engine, resolved lazily on the first sig batch (the
        # shared ops/sig_engine.py engine unless the config injects one)
        self._sig_engine = None
        # guards the three lazy engine memos above — dedicated lock, NOT
        # self._lock: the first resolve builds an engine (seconds of
        # compile), and admission must not block behind it. The executor
        # resolves, the resolve worker reads the memo on its fallback
        # path: without the lock that pair is a lockset race (phantsan)
        self._engine_lock = threading.Lock()
        # mesh dispatch: per-device executors behind the assembler. The
        # pool is built here (its engines are jax-free until the device
        # route engages) and the scheduler's own resolve worker is NOT —
        # each mesh lane runs its own begin/resolve pipeline.
        self._pool = None
        if self.config.mesh_devices >= 1:
            from phant_tpu.serving.mesh_exec import MeshExecutorPool

            self._pool = MeshExecutorPool(
                self.config.mesh_devices,
                pipeline_depth=self._pipe_depth,
                spill_depth=self.config.mesh_spill_depth,
                dispatch=self.config.mesh_dispatch,
                max_batch=self._max_batch,
                backlog_k=self.config.megabatch_backlog_k,
                prefetch=self.config.prefetch,
                engine=engine,
                engine_factory=self.config.mesh_engine_factory,
                # root lane: an injected factory is index-blind (doubles);
                # the default builds one PINNED RootEngine per lane
                root_engine_factory=(
                    (lambda _i: self.config.root_engine_factory())
                    if self.config.root_engine_factory is not None
                    else None
                ),
                # sig lane: same shape — injected factories are
                # index-blind, the default pins one SigEngine per lane
                sig_engine_factory=(
                    (lambda _i: self.config.sig_engine_factory())
                    if self.config.sig_engine_factory is not None
                    else None
                ),
                on_done=self._mesh_done,
                on_stage=self._mesh_stage,
                on_skip=self._mesh_skip,
                on_expired=self._shed_expired,
                on_crash=self._mesh_crash,
            )
        # chaos drill (obs): PHANT_SCHED_CHAOS_CRASH=1 makes the FIRST
        # witness batch crash the executor — the supported way to fire-
        # drill the postmortem path (flight dump, /healthz 503, -32052
        # fail-fast) against a live server / the real CLI
        import os

        chaos = os.environ.get("PHANT_SCHED_CHAOS_CRASH")
        self._chaos_crash = chaos == "1"
        # PHANT_SCHED_CHAOS_CRASH=prefetch: the first plan the PREFETCH
        # worker computes raises instead — the fire drill for the
        # 4th-stage crash path (stage-named record, -32052 fail-fast)
        self._chaos_prefetch = chaos == "prefetch"
        # per-lane device-busy accounting (obs/busy.py): the single
        # executor drives ONE device ("0" — lane 0's chip in mesh terms);
        # with a mesh pool the LANES bracket their own devices instead.
        # Gated by the same switch as the critpath rollup
        # (PHANT_OBS_ATTRIBUTION, read once here) so the obs_overhead
        # bench A/B flips the whole attribution layer together.
        self._busy_acct = BusyAccountant(
            "0", enabled=critpath.enabled() and self._pool is None
        )
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # admission state (guarded by _lock): the serial mutation lane is
        # its own strict-FIFO queue (never shed by overload policy, only
        # by deadline/death); witness jobs queue per tenant
        self._serial_q: List[_Job] = []
        self._lanes: dict = {}  # tenant -> List[_Job], FIFO per lane
        self._tenant_stats: dict = {}  # tenant -> admitted/served/shed
        self._last_wait_ms: Optional[float] = None  # adaptive-wait memo
        self._closed = False
        self._dead: Optional[BaseException] = None
        # observability: monotone batch ids + the in-flight descriptors the
        # obs watchdog polls, oldest first (all guarded by _lock). With
        # pipelining, up to pipeline_depth witness batches are in flight.
        self._batch_seq = 0
        self._inflight_list: List[dict] = []
        # pipeline state (guarded by _lock): items awaiting the resolve
        # worker, whether it is mid-resolve, and the stage the executor is
        # in (named by the crash record when the executor dies)
        self._resolve_q: List[dict] = []
        self._resolving = False
        self._exec_stage = "pack"
        # 4-stage pipeline state (guarded by _lock): batches the executor
        # assembled and handed to the prefetch worker. `_prefetch_q` is
        # the worker's input; `_prefetch_pending` is the executor's FIFO
        # of the same items (popped when the plan is consumed) — _die
        # drains BOTH so no future is stranded mid-prefetch. The
        # lookahead bounds how many assembled batches wait on plans.
        self._prefetch_on = (
            self.config.prefetch
            and self._pipe_depth >= 2
            and self._pool is None  # mesh lanes prefetch per lane
        )
        self._prefetch_q: List[dict] = []
        self._prefetch_pending: List[dict] = []
        self._prefetch_lookahead = 2
        self.stats = {
            "requests": 0,
            "batches": 0,
            "serial_jobs": 0,
            "coalesced": 0,
            "batched_requests": 0,
            "max_batch_seen": 0,
            "pipelined_batches": 0,
            # 4-stage pipeline: batches whose decode + novelty pre-scan ran
            # on the prefetch worker (stage 0) before pack consumed the plan
            "prefetched_batches": 0,
            # mesh dispatch: batches routed into the per-device pool, and
            # full single-bucket batches sent as whole-mesh fused calls
            "mesh_batches": 0,
            "megabatches": 0,
            # megabatches fired by the backlog-depth trigger (queued
            # same-bucket work >= mesh width x k) rather than a full batch
            "megabatch_backlog_triggers": 0,
            "rejected": 0,
            # QoS: backfill jobs evicted to admit head-of-chain work, and
            # how often the adaptive policy changed the assembly wait
            "evicted": 0,
            "wait_adjustments": 0,
            # post-root lane (PR 11): batches through ops/root_engine.py
            # and requests that shared a coalesced root dispatch
            "root_batches": 0,
            "root_requests": 0,
            "root_coalesced": 0,
            # sender-recovery lane (PR 14): batches through
            # ops/sig_engine.py and requests that shared a merged
            # ecrecover dispatch
            "sig_batches": 0,
            "sig_requests": 0,
            "sig_coalesced": 0,
        }
        metrics.gauge_set("sched.pipeline_depth", self._pipe_depth)
        self._thread = threading.Thread(
            target=self._run, name="phant-sched-exec", daemon=True
        )
        self._thread.start()
        self._resolve_thread: Optional[threading.Thread] = None
        if self._pipe_depth > 1 and self._pool is None:
            self._resolve_thread = threading.Thread(
                target=self._resolve_run, name="phant-sched-resolve", daemon=True
            )
            self._resolve_thread.start()
        self._prefetch_thread: Optional[threading.Thread] = None
        if self._prefetch_on:
            self._prefetch_thread = threading.Thread(
                target=self._prefetch_run, name="phant-sched-prefetch", daemon=True
            )
            self._prefetch_thread.start()
        self._watchdog = Watchdog(self.inflight_state).start()

    # -- context manager (offline verify_many use) ---------------------------

    def __enter__(self) -> "VerificationScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- admission -----------------------------------------------------------

    def _witness_job(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float],
        tenant: Optional[str],
        priority: Optional[int],
    ) -> _Job:
        nodes = list(nodes)
        nbytes = sum(map(len, nodes))
        return _Job(
            kind=_WITNESS,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            # QoS identity: an explicit argument wins, otherwise the
            # thread's tenant_context (the Engine API server binds one per
            # request, qos.py) — offline callers land in DEFAULT_TENANT
            tenant=tenant if tenant is not None else current_tenant(),
            priority=priority if priority is not None else current_priority(),
            root=root,
            nodes=nodes,
            nbytes=nbytes,
            bucket=_pow2ceil(nbytes),
            trace_id=current_trace_id(),
        )

    def submit_witness(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
        wait_for_space: bool = False,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Future:
        """Queue one (root, nodes) linked-multiproof verification; the
        future resolves to the bool verdict. `wait_for_space` blocks on a
        full queue instead of rejecting (offline verify_many); the online
        serving path never waits — overload must shed, not stack.
        `tenant`/`priority` default to the thread's tenant_context."""
        job = self._witness_job(root, nodes, deadline_s, tenant, priority)
        self._admit(job, wait_for_space)
        return job.future

    def verify_traced(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Tuple[bool, Optional[dict]]:
        """One witness verification through the batching path, returning
        (verdict, batch record). The record — `batch_id`, `batch_size`,
        `bucket_bytes`, `backend`, cache hit/miss deltas, `queue_wait_ms` —
        is what joins the caller's span to the shared engine dispatch that
        served it (stateless.verify_witness_nodes folds it into the open
        `verify_block` span). Scheduler rejections raise as usual."""
        job = self._witness_job(root, nodes, deadline_s, tenant, priority)
        self._admit(job, False)
        return bool(job.future.result()), job.meta

    def submit_serial(
        self,
        fn: Callable,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> Future:
        """Queue an exclusive job: the executor runs `fn()` with nothing
        else in flight — the replacement for the server's global execution
        lock (state-mutating newPayload execution). `fn`'s return value
        resolves the future; an exception from `fn` is request-scoped and
        lands on the future (it does NOT kill the executor). Serial jobs
        are always PRIORITY_HEAD: they preempt queued witness work and are
        never shed to make room for anything."""
        job = _Job(
            kind=_SERIAL,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            tenant=tenant if tenant is not None else current_tenant(),
            priority=PRIORITY_HEAD,
            fn=fn,
            trace_id=current_trace_id(),
        )
        self._admit(job, False)
        return job.future

    # -- root lane (batched post-state roots, PR 11) -------------------------

    def _root_job(
        self,
        plan,
        deadline_s: Optional[float],
        tenant: Optional[str],
        priority: Optional[int],
    ) -> _Job:
        # level-shape bucket: plans with the same depth coalesce into one
        # merged dispatch (pow2 padding absorbs the per-level widths);
        # NEGATIVE so it never collides with the witness pow2 buckets
        from phant_tpu.ops.mpt_jax import plan_payload_bytes

        return _Job(
            kind=_ROOT,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            tenant=tenant if tenant is not None else current_tenant(),
            priority=priority if priority is not None else current_priority(),
            plan=plan,
            nbytes=plan_payload_bytes(plan),
            bucket=-len(plan.levels),
            trace_id=current_trace_id(),
        )

    def submit_root(
        self,
        plan,
        deadline_s: Optional[float] = None,
        wait_for_space: bool = False,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Future:
        """Queue one fused post-root HashPlan (ops/mpt_jax, built by
        stateless.WitnessStateDB.post_root_plan); the future resolves to
        the plan's out-row digests (storage roots in patch order, the
        post root LAST). Admission, per-tenant QoS, deadlines, and
        overload shedding are the witness lane's — same codes, same shed
        order."""
        job = self._root_job(plan, deadline_s, tenant, priority)
        self._admit(job, wait_for_space)
        return job.future

    def root_traced(
        self,
        plan,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Tuple[List[bytes], Optional[dict]]:
        """One post root through the batching path, returning (out
        digests, batch record) — the root twin of verify_traced; the
        record joins the caller's `verify_block` span to the coalesced
        root dispatch that served it."""
        job = self._root_job(plan, deadline_s, tenant, priority)
        self._admit(job, False)
        return job.future.result(), job.meta

    def root_many(self, plans: Sequence) -> List[List[bytes]]:
        """Out digests for a span of plans, pushed through the SAME
        admission/assembly/executor path the server uses — the offline
        face of the root lane (bench, tests). Blocks on queue space and
        applies no deadline, like verify_many."""
        if threading.current_thread() in (
            self._thread,
            self._resolve_thread,
            self._prefetch_thread,
        ):
            raise RuntimeError(
                "root_many called from a scheduler thread (deadlock)"
            )
        futs = [
            self.submit_root(p, deadline_s=float("inf"), wait_for_space=True)
            for p in plans
        ]
        return [f.result() for f in futs]

    def accepts_root(self) -> bool:
        """Can the CURRENT thread route a post root through this
        scheduler? The root lane shares the witness lane's consumers and
        lifecycle, so the answer is the same."""
        return self.accepts_witness()

    def root_backlog(self) -> int:
        """Root jobs currently queued — the lone-request guard's company
        signal (stateless.compute_post_root): with nobody to coalesce
        with, a sub-break-even request skips plan construction entirely
        and keeps the host walk."""
        with self._lock:
            return sum(
                1
                for lane in self._lanes.values()
                for j in lane
                if j.kind == _ROOT
            )

    def _resolve_root_engine(self):
        # config is read OUTSIDE the lock (immutable after __init__; a
        # config touch under _engine_lock would make LOCK demand the lock
        # at every other config read in the class)
        factory = self.config.root_engine_factory
        with self._engine_lock:
            if self._root_engine is None:
                if factory is not None:
                    self._root_engine = factory()
                else:
                    from phant_tpu.ops.root_engine import shared_root_engine

                    self._root_engine = shared_root_engine()
            return self._root_engine

    # -- sig lane (coalesced sender recovery, PR 14) --------------------------

    def _sig_job(
        self,
        rows,
        deadline_s: Optional[float],
        tenant: Optional[str],
        priority: Optional[int],
    ) -> _Job:
        # ONE fixed bucket for every sig job: signature rows concatenate
        # freely (the merged batch pow2-pads inside the kernel), so all
        # concurrent requests' rows coalesce — the whole point of the lane
        return _Job(
            kind=_SIG,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            tenant=tenant if tenant is not None else current_tenant(),
            priority=priority if priority is not None else current_priority(),
            rows=rows,
            nbytes=rows.n,
            bucket=_SIG_BUCKET,
            trace_id=current_trace_id(),
        )

    def submit_sig(
        self,
        rows,
        deadline_s: Optional[float] = None,
        wait_for_space: bool = False,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Future:
        """Queue one request's signature rows (signer.SigRows, built by
        `TxSigner.signature_rows`); the future resolves to the request's
        sender list in tx order (None = invalid signature — the caller
        owns the error attribution, chain.apply_body). Admission,
        per-tenant QoS, deadlines, and overload shedding are the witness
        lane's — same codes, same shed order."""
        job = self._sig_job(rows, deadline_s, tenant, priority)
        self._admit(job, wait_for_space)
        return job.future

    def sig_async(
        self,
        rows,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ):
        """Dispatch one request's sender recovery NOW and return
        `resolve() -> (senders, batch record)` — the split face the
        request path uses (stateless.dispatch_sender_recovery): recovery
        dispatches at decode time and joins just before EVM execution,
        so the merged ecrecover hides under witness verification."""
        job = self._sig_job(rows, deadline_s, tenant, priority)
        self._admit(job, False)

        def resolve():
            return job.future.result(), job.meta

        return resolve

    def sig_traced(
        self,
        rows,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
    ) -> Tuple[List[Optional[bytes]], Optional[dict]]:
        """One request's senders through the batching path, returning
        (senders, batch record) — the sig twin of verify_traced/
        root_traced; the record joins the caller's span to the merged
        ecrecover dispatch that served it."""
        return self.sig_async(rows, deadline_s, tenant, priority)()

    def sig_many(self, rows_list: Sequence) -> List[List[Optional[bytes]]]:
        """Sender slices for a span of requests' rows, pushed through the
        SAME admission/assembly/executor path the server uses — the
        offline face of the sig lane (bench, soak, tests). Blocks on
        queue space and applies no deadline, like verify_many."""
        if threading.current_thread() in (
            self._thread,
            self._resolve_thread,
            self._prefetch_thread,
        ):
            raise RuntimeError(
                "sig_many called from a scheduler thread (deadlock)"
            )
        futs = [
            self.submit_sig(r, deadline_s=float("inf"), wait_for_space=True)
            for r in rows_list
        ]
        return [f.result() for f in futs]

    def accepts_sig(self) -> bool:
        """Can the CURRENT thread route sender recovery through this
        scheduler? The sig lane shares the witness lane's consumers and
        lifecycle, so the answer is the same."""
        return self.accepts_witness()

    def sig_backlog(self) -> int:
        """Signature ROWS currently queued on the sig lane (txs, not
        jobs — sig jobs coalesce freely, so rows are the unit of queued
        device work). The replay engine's lookahead pacer
        (phant_tpu/replay/engine.py) holds segment N+1's dispatch while
        the lane still has more than a segment's worth of rows queued,
        so a deep replay pipeline cannot monopolize the admission queue
        it shares with live serving traffic — the root twin is
        root_backlog (the lone-request guard's company signal)."""
        with self._lock:
            return sum(
                j.nbytes
                for lane in self._lanes.values()
                for j in lane
                if j.kind == _SIG
            )

    def _resolve_sig_engine(self):
        factory = self.config.sig_engine_factory  # outside the lock, as above
        with self._engine_lock:
            if self._sig_engine is None:
                if factory is not None:
                    self._sig_engine = factory()
                else:
                    from phant_tpu.ops.sig_engine import shared_sig_engine

                    self._sig_engine = shared_sig_engine()
            return self._sig_engine

    @staticmethod
    def _payload_of(jobs: List[_Job], kind: str) -> list:
        """The engine-facing batch payload: (root, nodes) tuples for the
        witness lane, HashPlans for the root lane, SigRows for the sig
        lane."""
        if kind == _ROOT:
            return [j.plan for j in jobs]
        if kind == _SIG:
            return [j.rows for j in jobs]
        return [(j.root, j.nodes) for j in jobs]

    def _deadline(self, deadline_s: Optional[float]) -> Optional[float]:
        if deadline_s is None:
            d = self.config.deadline_ms / 1e3
        else:
            d = deadline_s
        if d <= 0 or d == float("inf"):
            return None
        return time.monotonic() + d

    # -- QoS locked helpers --------------------------------------------------

    def _lane_key_locked(self, tenant: str) -> str:
        """Fold a tenant tag through the max_tenants cap: known tenants
        keep their lane, new ones beyond the cap share OVERFLOW_TENANT
        (bounded per-tenant state and metric cardinality under a
        header-spraying client)."""
        if tenant in self._tenant_stats or len(self._tenant_stats) < self._max_tenants:
            return tenant
        return OVERFLOW_TENANT

    def _account_evicted_locked(self, victim: _Job, victims: List[_Job]) -> None:
        """Stats for one eviction victim under the lock; the metric/flight
        publishes and the future failure happen outside it (victims)."""
        self.stats["rejected"] += 1
        self.stats["evicted"] += 1
        self._tenant_locked(victim.tenant)["shed"] += 1
        victims.append(victim)

    def _tenant_locked(self, tenant: str) -> dict:
        st = self._tenant_stats.get(tenant)
        if st is None:
            st = self._tenant_stats[tenant] = {
                "admitted": 0,
                "served": 0,
                "shed": 0,
            }
        return st

    def _qlen_locked(self) -> int:
        return len(self._serial_q) + self._wit_len_locked()

    def _wit_len_locked(self) -> int:
        # lanes are bounded by max_tenants (default 64): summing is O(1)-ish
        return sum(len(lane) for lane in self._lanes.values())

    def _enqueue_locked(self, job: _Job) -> None:
        if job.kind == _SERIAL:
            self._serial_q.append(job)
        else:
            self._lanes.setdefault(job.tenant, []).append(job)

    @staticmethod
    def _evict_from_lane_locked(
        lane: List[_Job], allow_head: bool = False
    ) -> Optional[_Job]:
        """Newest sheddable backfill job of `lane` (newest head-class
        witness job as a fallback when `allow_head`); wait_for_space
        (verify_many) jobs are never victims — their contract is
        completion, not load shedding."""
        for want_backfill in (True, False) if allow_head else (True,):
            for i in range(len(lane) - 1, -1, -1):
                j = lane[i]
                if not j.sheddable:
                    continue
                if (j.priority != PRIORITY_HEAD) == want_backfill:
                    return lane.pop(i)
        return None

    def _evict_witness_locked(self, for_serial: bool) -> Optional[_Job]:
        """Pick the load-shed victim that makes room for an arriving
        head-of-chain job: the NEWEST backfill job of the DEEPEST lane —
        backfill first (deepest lane first: the tenant most over its fair
        share pays). When the arrival is a SERIAL mutation and every
        queued witness job is head-class, the newest head-class witness
        job is evicted instead: the serial lane outranks every witness
        class and must only ever be shed by its OWN backlog. Never
        evicts the serial lane, never a wait_for_space job. None when
        nothing is sheddable."""
        for allow_head in (False, True) if for_serial else (False,):
            deepest = sorted(
                (lane for lane in self._lanes.values() if lane),
                key=len,
                reverse=True,
            )
            for lane in deepest:
                victim = self._evict_from_lane_locked(lane, allow_head=allow_head)
                if victim is not None:
                    return victim
        return None

    def _admit(self, job: _Job, wait_for_space: bool) -> None:
        reason = None
        victims: List[_Job] = []
        lane_depth = None
        job.sheddable = not wait_for_space
        with self._lock:
            job.tenant = self._lane_key_locked(job.tenant)
            while True:
                if self._dead is not None:
                    reason, err = "down", SchedulerDown(
                        f"scheduler executor is down: {self._dead!r}"
                    )
                    break
                if self._closed:
                    reason, err = "shutdown", SchedulerDown(
                        "scheduler is shutting down"
                    )
                    break
                if (
                    job.kind == _WITNESS
                    and self._quota
                    and len(self._lanes.get(job.tenant, ())) >= self._quota
                ):
                    # the per-tenant cap sheds BEFORE the global bound: one
                    # tenant's burst stays that tenant's problem. An
                    # offline wait_for_space caller (verify_many) BLOCKS on
                    # its quota exactly as it blocks on the global bound —
                    # completion, not load shedding, is its contract — and
                    # a HEAD-class arrival evicts its own tenant's newest
                    # backfill job first: head work is only ever shed by
                    # pressure from its own class
                    if wait_for_space:
                        self._cond.wait(0.05)
                        continue
                    if job.priority == PRIORITY_HEAD:
                        v = self._evict_from_lane_locked(
                            self._lanes[job.tenant]
                        )
                        if v is not None:
                            self._account_evicted_locked(v, victims)
                            continue  # lane has room now; re-run the checks
                    reason, err = "tenant_quota", QueueFull(
                        f"tenant {job.tenant!r} queue quota full ({self._quota})"
                    )
                    break
                if self._qlen_locked() < self._queue_depth:
                    self._enqueue_locked(job)
                elif job.priority == PRIORITY_HEAD and (
                    v := self._evict_witness_locked(
                        for_serial=job.kind == _SERIAL
                    )
                ) is not None:
                    # global queue full but the arrival is head-of-chain:
                    # shed the newest backfill job (for a serial mutation,
                    # the newest head-class witness job as a fallback)
                    # instead of the head work — the documented shed order;
                    # same -32050 code, distinct reason so the postmortem
                    # tells them apart
                    self._account_evicted_locked(v, victims)
                    self._enqueue_locked(job)
                elif not wait_for_space:
                    reason, err = "queue_full", QueueFull(
                        f"admission queue full ({self._queue_depth})"
                    )
                    break
                else:
                    self._cond.wait(0.05)
                    continue
                self.stats["requests"] += 1
                self._tenant_locked(job.tenant)["admitted"] += 1
                depth = self._qlen_locked()
                if job.kind == _WITNESS:
                    lane_depth = len(self._lanes[job.tenant])
                self._cond.notify_all()
                break
            if reason is not None:
                self.stats["rejected"] += 1
                self._tenant_locked(job.tenant)["shed"] += 1
        for victim in victims:
            metrics.count("sched.rejected", reason="evicted", tenant=victim.tenant)
            metrics.count("sched.backfill_evictions", tenant=victim.tenant)
            flight.record(
                "sched.shed",
                reason="evicted",
                lane=victim.kind,
                tenant=victim.tenant,
                trace_id=victim.trace_id,
            )
            victim.future.set_exception(
                QueueFull("evicted to admit head-of-chain work")
            )
        if reason is not None:
            metrics.count("sched.rejected", reason=reason, tenant=job.tenant)
            flight.record(
                "sched.shed",
                reason=reason,
                lane=job.kind,
                tenant=job.tenant,
                trace_id=job.trace_id,
            )
            raise err
        metrics.gauge_set("sched.queue_depth", depth)
        if lane_depth is not None:
            metrics.gauge_set(
                "sched.tenant_queue_depth", lane_depth, tenant=job.tenant
            )
        flight.record(
            "sched.admit",
            lane=job.kind,
            tenant=job.tenant,
            priority=job.priority,
            bucket_bytes=job.bucket if job.kind == _WITNESS else None,
            queue_depth=depth,
            trace_id=job.trace_id,
        )

    # -- the synchronous offline face ---------------------------------------

    def verify_many(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> np.ndarray:
        """(n,) bool verdicts for a span of (root, nodes) witnesses, pushed
        through the SAME admission/assembly/executor path the server uses —
        the offline API for bench.py, the spec runner, and tests. Blocks on
        queue space instead of rejecting (offline callers want completion,
        not load shedding) and applies no deadline."""
        if threading.current_thread() in (
            self._thread,
            self._resolve_thread,
            self._prefetch_thread,
        ):
            raise RuntimeError(
                "verify_many called from a scheduler thread (deadlock)"
            )
        futs = [
            self.submit_witness(
                root, nodes, deadline_s=float("inf"), wait_for_space=True
            )
            for root, nodes in witnesses
        ]
        return np.fromiter(
            (bool(f.result()) for f in futs), bool, count=len(futs)
        )

    def accepts_witness(self) -> bool:
        """Can the CURRENT thread route a witness verification through this
        scheduler? False on the executor/resolve threads themselves
        (submitting from either would deadlock: they are the consumers)
        and once the scheduler is down or draining — callers fall back to
        the direct engine path."""
        if threading.current_thread() in (
            self._thread,
            self._resolve_thread,
            self._prefetch_thread,
        ):
            return False
        with self._lock:
            return self._dead is None and not self._closed

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        """Liveness surface for `/healthz` (engine_api/server.py)."""
        with self._lock:
            depth = self._qlen_locked()
            tenant_depths = {
                t: len(lane) for t, lane in self._lanes.items() if lane
            }
            dead = self._dead
            inflight = len(self._resolve_q) + (1 if self._resolving else 0)
            prefetch_pending = len(self._prefetch_pending)
        alive = dead is None and self._thread.is_alive()
        if self._resolve_thread is not None:
            # a dead resolve worker is just as fatal as a dead executor:
            # dispatched handles would never complete
            alive = alive and self._resolve_thread.is_alive()
        if self._prefetch_thread is not None:
            # same for the prefetch worker: pending batches would never
            # get plans and the executor would wait on them forever
            alive = alive and self._prefetch_thread.is_alive()
        mesh = self._pool.state() if self._pool is not None else None
        if mesh is not None:
            # any dead device lane means routed batches would never
            # complete — as fatal as the executor itself (healthz 503)
            alive = alive and mesh["all_alive"]
            inflight = sum(
                d["queued"] + d["inflight"] for d in mesh["per_device"].values()
            )
        out = {
            "queue_depth": depth,
            "tenant_depths": tenant_depths,
            "executor_alive": alive,
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
            # config echoes read off the immutable config, not the
            # unpacked copies the locked regions use (lock-free surface)
            "adaptive_wait": self.config.adaptive_wait,
            "tenant_quota": self.config.tenant_quota,
            "pipeline_depth": self._pipe_depth,
            "pipeline_inflight": inflight,
            # the 4th stage's EFFECTIVE state: the scheduler's own worker,
            # or (mesh mode) the per-lane prefetch the pool runs instead —
            # healthz must not say "off" while every lane prefetches
            "prefetch": self._prefetch_on
            or bool(mesh is not None and mesh.get("prefetch")),
            "prefetch_pending": prefetch_pending,
            # per-lane device-busy (obs/busy.py): "the chip idles 60% at
            # depth 1" read straight off the probe. Reads integrate to
            # now, so idle lanes decay without traffic; mesh mode reports
            # every lane's own accountant instead of the executor's.
            "device_busy_pct": (
                {
                    d: st["busy_pct"]
                    for d, st in mesh["per_device"].items()
                }
                if mesh is not None
                else {self._busy_acct.device: self._busy_acct.pct()}
            ),
        }
        if mesh is not None:
            out["mesh"] = mesh
        if dead is not None:
            out["error"] = repr(dead)
        return out

    def stats_snapshot(self) -> dict:
        with self._lock:
            st = dict(self.stats)
            st["tenants"] = {
                t: dict(ts) for t, ts in self._tenant_stats.items()
            }
        b = st["batches"]
        st["mean_batch"] = round(st["batched_requests"] / b, 2) if b else 0.0
        st["pipeline_depth"] = self._pipe_depth
        if self._pool is not None:
            st["mesh"] = self._pool.stats()
            # mesh mode runs the prefetch stage per LANE (the scheduler's
            # own worker is off) — fold the pool's count into the
            # top-level stat so `prefetched_batches` answers "did the 4th
            # stage run" the same way in every deployment shape
            st["prefetched_batches"] += st["mesh"]["prefetched_batches"]
        return st

    def refresh_busy_gauges(self) -> None:
        """Re-integrate every lane's busy window to NOW and republish the
        `sched.device_busy_pct{device=}` gauges. Called by the /metrics
        scrape path (engine_api/server.py): the gauges otherwise update
        only on batch transitions, and an idle lane's last published
        value would read frozen-busy forever on a metrics-only scraper."""
        if self._pool is not None:
            self._pool.refresh_busy()
        else:
            self._busy_acct.pct()

    def inflight_state(self) -> Optional[dict]:
        """The OLDEST batch currently in flight — `batch_id`, `lane`,
        `stage`, `started`/`deadline` (monotonic), `trace_ids` — or None
        when idle. Polled by the obs watchdog to flag deadline-overrun
        stalls; with pipelining the oldest unresolved batch is the one a
        wedged device call strands first."""
        with self._lock:
            return dict(self._inflight_list[0]) if self._inflight_list else None

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admission; `drain=True` lets the executor finish everything
        already queued before it exits, `drain=False` fails the queue fast.
        Idempotent."""
        with self._lock:
            self._closed = True
            dropped: List[_Job] = []
            if not drain:
                dropped.extend(self._serial_q)
                self._serial_q.clear()
                for lane in self._lanes.values():
                    dropped.extend(lane)
                self._lanes.clear()
            self._cond.notify_all()
        for job in dropped:
            job.future.set_exception(
                SchedulerDown("scheduler shut down before execution")
            )
        self._thread.join(timeout)
        if self._resolve_thread is not None:
            self._resolve_thread.join(timeout)
        if self._prefetch_thread is not None:
            self._prefetch_thread.join(timeout)
        if self._pool is not None:
            # the executor's graceful exit already drained every lane
            # (_drain_pipeline); this stops the lane threads
            self._pool.shutdown(timeout)
        self._watchdog.stop(1.0)
        metrics.gauge_set("sched.queue_depth", 0)

    # -- executor ------------------------------------------------------------

    def _set_exec_stage(self, stage: str) -> None:
        """Crash-record breadcrumb: the executor names the stage it is in
        at each boundary, and _run's except handler reads it for _die.
        Under _lock — writer and reader are different threads when a mesh
        lane or the chaos drill kills the executor mid-batch, and the
        unlocked attribute was a phantsan lockset race."""
        with self._lock:
            self._exec_stage = stage

    def _run(self) -> None:
        batch: List[_Job] = []
        try:
            while True:
                if self._prefetch_on:
                    step = self._next_step_prefetching()
                    if step == "loop":
                        continue
                    if isinstance(step, dict):
                        batch = step["jobs"]
                        self._execute_prefetched(step)
                        batch = []
                        continue
                    batch = step  # serial batch or None (exit)
                else:
                    batch = self._next_batch()
                if batch is None:
                    # graceful exit: every dispatched handle must resolve
                    # before the executor reports done (shutdown drains the
                    # admission queue AND the in-flight pipeline)
                    self._drain_pipeline()
                    with self._lock:
                        self._exec_done = True
                        self._cond.notify_all()
                    return
                self._execute(batch)
                batch = []
        except BaseException as e:  # systemic: engine/internal failure
            with self._lock:
                stage = self._exec_stage
            self._die(e, batch or [], stage=stage)

    _exec_done = False  # executor returned cleanly (resolve worker exits)

    # -- 4th pipeline stage: the prefetch worker (PR 9) ----------------------

    def _next_step_prefetching(self) -> object:
        """One executor decision under the 4-stage pipeline: top up the
        prefetch lookahead from the admission queue, or consume the
        oldest planned batch. Returns "loop" (decision made, go again),
        a pending item dict (execute it), a serial batch, or None
        (graceful exit — pending is empty by then)."""
        with self._lock:
            has_serial = bool(self._serial_q)
            can_assemble = any(self._lanes.values())
            pending = len(self._prefetch_pending)
            # a finished plan beats topping up — but only while the
            # worker still has queued work (pending > 1). At pending == 1
            # the top-up comes FIRST: it hands the worker its next batch
            # before this thread blocks in the pipeline handoff, which is
            # exactly the window the prefetch is meant to hide under
            # (draining to empty here measured hidden_pct 87 -> 0: the
            # worker idled through every handoff stall). The top-up is
            # cheap even off-saturation — assembly breaks its coalescing
            # wait the moment a plan turns ready below.
            head_ready = pending > 1 and self._prefetch_pending[0]["ready"]
            if self._dead is not None:
                return None
        if pending and (
            has_serial
            or head_ready
            or not can_assemble
            or pending >= self._prefetch_lookahead
        ):
            # oldest planned batch first: the serial lane preempts the
            # QUEUE, never work already past admission — and pending must
            # drain before a serial job gets exclusivity anyway
            return self._pop_prefetched()
        batch = self._next_batch(block=(pending == 0))
        if batch is _NO_BATCH:
            return "loop"  # queued work vanished (expiry); re-evaluate
        if batch is None or batch[0].kind == _SERIAL:
            # pending was empty at the snapshot, but a close() or a
            # serial arrival can RACE the two lock windows — and both
            # graceful exit and serial exclusivity require the planned
            # batches executed first (their futures would otherwise
            # strand). Push a raced serial head back (index 0 keeps it
            # the serial queue's head — admission order holds) and
            # drain the oldest plan; the next pass re-takes the serial
            # job / the exit with pending truly empty.
            with self._lock:
                raced = bool(self._prefetch_pending)
                if raced and batch is not None:
                    self._serial_q.insert(0, batch[0])
            if raced:
                return self._pop_prefetched()
            return batch
        self._submit_prefetch(batch)
        return "loop"

    def _submit_prefetch(self, batch: List[_Job]) -> None:
        """Hand one assembled witness batch to the prefetch worker: the
        batch enters the flight list NOW (stage="prefetch" — the obs
        watchdog and stall records see the 4th stage), and the executor
        picks the plan up in FIFO order once the worker finishes it."""
        now = time.monotonic()
        for j in batch:
            metrics.observe_hist("sched.queue_wait_seconds", now - j.admitted)
        if self.config.deadline_ms > 0:
            stall_deadline: Optional[float] = now + self.config.deadline_ms / 1e3
        else:
            stall_deadline = None
        trace_ids = [j.trace_id for j in batch]
        kind = batch[0].kind
        item = {
            "jobs": batch,
            "kind": kind,
            # the SAME list object goes to prefetch_batch and begin_batch:
            # plan identity is how the engine knows the plan matches
            # (witness tuples or root HashPlans alike)
            "payload": self._payload_of(batch, kind),
            "picked": now,
            "plan": None,
            "ready": False,
        }
        with self._lock:
            self._batch_seq += 1
            item["batch_id"] = batch_id = self._batch_seq
            self._inflight_list.append(
                {
                    "batch_id": batch_id,
                    "lane": kind,
                    "stage": "prefetch",
                    "device": None,
                    "started": now,
                    "deadline": stall_deadline,
                    "trace_ids": trace_ids,
                }
            )
            self._prefetch_q.append(item)
            self._prefetch_pending.append(item)
            depth = len(self._prefetch_pending)
            self._cond.notify_all()
        metrics.gauge_set("sched.prefetch_depth", depth)
        flight.record(
            "sched.batch_start",
            batch_id=batch_id,
            lane=kind,
            stage="prefetch",
            batch_size=len(batch),
            bucket_bytes=batch[0].bucket,
            tenants=sorted({j.tenant for j in batch}),
            trace_ids=trace_ids,
        )

    def _pop_prefetched(self) -> dict:
        """The oldest pending batch, once its plan is ready. The wait here
        is the overlap audit: time the executor spends blocked on a plan
        is prefetch cost that did NOT hide under dispatch/resolve
        (sched.prefetch_wait vs the witness_engine.prefetch phase)."""
        t0 = time.perf_counter()
        with self._lock:
            # _die may have emptied _prefetch_pending between the
            # caller's pending>0 check and this lock acquisition — the
            # combined condition re-checks emptiness so a crash lands on
            # the SchedulerDown below, not an IndexError
            while self._dead is None and not (
                self._prefetch_pending and self._prefetch_pending[0]["ready"]
            ):
                self._cond.wait(0.05)
            dead = self._dead
            if dead is None:
                item = self._prefetch_pending.pop(0)
                depth = len(self._prefetch_pending)
        metrics.observe("sched.prefetch_wait", time.perf_counter() - t0)
        if dead is not None:
            raise SchedulerDown(f"prefetch worker is down: {dead!r}")
        metrics.gauge_set("sched.prefetch_depth", depth)
        return item

    def _execute_prefetched(self, item: dict) -> None:
        """Pack + dispatch one PREFETCHED batch (its flight descriptor and
        batch_start record exist since _submit_prefetch): the 4-stage
        twin of _execute_witness_pipelined, consuming the worker's plan
        so pack's under-lock work shrinks to the re-check + commit."""
        batch_id = item["batch_id"]
        self._set_exec_stage("pack")
        with self._lock:
            for d in self._inflight_list:
                if d["batch_id"] == batch_id:
                    d["stage"] = "pack"
        plan = item["plan"]
        try:
            self._execute_prefetched_inner(item, plan)
        except BaseException:
            # an exception leaving this frame lands in _die, which can no
            # longer see this item (popped from _prefetch_pending): give
            # the plan's staging leases back before propagating. release()
            # is idempotent and consumption nulls the plan's lease fields,
            # so a plan begin_batch already consumed/released is a no-op.
            if plan is not None:
                plan.release()
            raise

    def _execute_prefetched_inner(self, item: dict, plan) -> None:
        batch_id = item["batch_id"]
        jobs = self._shed_or_keep(item["jobs"], time.monotonic())
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        kind = item.get("kind", _WITNESS)
        if kind in (_ROOT, _SIG):
            # root/sig batches always have a two-phase engine; a fully-
            # shed batch just releases the prefetch merge
            if not jobs:
                if plan is not None:
                    plan.release()
                with self._lock:
                    self._drop_inflight_locked(batch_id)
                return
            self._pipeline_handoff(
                jobs,
                batch_id,
                self._resolve_root_engine()
                if kind == _ROOT
                else self._resolve_sig_engine(),
                item["picked"],
                plan=plan,
                prefetch_ms=item.get("prefetch_ms"),
                plan_payload=item["payload"],
                plan_njobs=len(item["jobs"]),
                kind=kind,
            )
            return
        engine = self._resolve_engine()
        if not jobs or not (
            self._pipe_depth > 1 and hasattr(engine, "begin_batch")
        ):
            # everything expired, or a begin-less engine double: release
            # the unused plan's staging leases and (if any jobs survive)
            # fall back to the inline path — _execute_witness IS that
            # path (one copy; its re-shed of already-kept jobs is a no-op
            # and its chaos check is unreachable past the one above)
            if plan is not None:
                plan.release()
            if jobs:
                with self._lock:
                    for d in self._inflight_list:
                        if d["batch_id"] == batch_id:
                            d["stage"] = "dispatch"
                self._execute_witness(jobs, batch_id, engine, item["picked"])
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        self._pipeline_handoff(
            jobs,
            batch_id,
            engine,
            item["picked"],
            plan=plan,
            prefetch_ms=item.get("prefetch_ms"),
            plan_payload=item["payload"],
            plan_njobs=len(item["jobs"]),
        )

    def _pipeline_handoff(
        self,
        jobs: List[_Job],
        batch_id: int,
        engine,
        picked: float,
        plan=None,
        prefetch_ms: Optional[float] = None,
        plan_payload=None,
        plan_njobs: int = 0,
        kind: str = _WITNESS,
    ) -> None:
        """Shared tail of the pipelined witness paths (3- and 4-stage):
        wait for a pipeline slot, re-shed expired jobs, begin_batch —
        consuming the prefetch plan when one rode along — and hand the
        handle to the resolve worker. The bounded depth is the stall
        signal: a hot resolve stage shows up as sched.pipeline_stall."""
        depth = self._pipe_depth
        t_wait = time.perf_counter()
        with self._lock:
            while (
                len(self._resolve_q) + (1 if self._resolving else 0) >= depth
                and self._dead is None
            ):
                self._cond.wait(0.05)
            dead = self._dead
        metrics.observe("sched.pipeline_stall", time.perf_counter() - t_wait)
        if dead is not None:
            # the resolve worker died while we waited: fail this batch the
            # same way _die failed everything else, and stop the executor
            if plan is not None:
                plan.release()
            raise SchedulerDown(f"resolve worker is down: {dead!r}")
        # deadlines re-checked AFTER the slot wait: a wedged resolve stage
        # can hold the pipeline full long past a job's deadline, and an
        # expired job must shed (its waiter is gone) rather than spend
        # pack/dispatch/resolve work
        jobs = self._shed_or_keep(jobs, time.monotonic())
        if not jobs:
            if plan is not None:
                plan.release()
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        if plan_payload is not None and len(jobs) == plan_njobs:
            # the SAME list object the plan was computed over — identity
            # is how begin_batch knows the plan matches; any shed along
            # the way invalidates it and begin_batch drops it, correctly
            payload = plan_payload
        else:
            payload = self._payload_of(jobs, kind)
        t_pack = time.perf_counter()
        if plan is not None:
            handle = engine.begin_batch(payload, prefetch=plan)
        else:
            handle = engine.begin_batch(payload)
        # device-busy: the dispatch is enqueued — the lane's device owns
        # this batch until the resolve worker finishes it (obs/busy.py;
        # every exit path below pairs this with an end())
        self._busy_acct.begin()
        pipe_item = {
            "jobs": jobs,
            "handle": handle,
            "batch_id": batch_id,
            "picked": picked,
            "kind": kind,
            "engine": engine,
            "pack_ms": round((time.perf_counter() - t_pack) * 1e3, 3),
        }
        if prefetch_ms is not None:
            pipe_item["prefetch_ms"] = prefetch_ms
        with self._lock:
            dead = self._dead
            if dead is None:
                self._resolve_q.append(pipe_item)
        if dead is not None:
            # the worker died while we packed: the just-begun handle will
            # never be resolved — release its engine lease before failing
            _abandon_handle(engine, handle)
            self._busy_acct.end()
            raise SchedulerDown(f"resolve worker is down: {dead!r}")
        with self._lock:
            self.stats["pipelined_batches"] += 1
            inflight = len(self._resolve_q) + (1 if self._resolving else 0)
            self._cond.notify_all()
        metrics.gauge_set("sched.pipeline_inflight", inflight)

    def _prefetch_run(self) -> None:
        """The prefetch worker: witness decode + advisory novelty
        pre-scan for each assembled batch (ops/witness_engine.py
        prefetch_batch — lock-free against the committed tables), while
        the executor packs/dispatches earlier batches and the resolve
        worker resolves still-earlier ones. A crash here is systemic
        (_die, stage="prefetch"): in-flight work fails fast with -32052,
        exactly like the other stages."""
        item: Optional[dict] = None
        try:
            while True:
                with self._lock:
                    while (
                        not self._prefetch_q
                        and not self._exec_done
                        and self._dead is None
                    ):
                        self._cond.wait()
                    if self._dead is not None:
                        return  # _die already failed everything queued
                    if not self._prefetch_q:
                        return  # executor done; pending is drained
                    item = self._prefetch_q.pop(0)
                if self._chaos_prefetch:
                    raise RuntimeError(
                        "chaos drill: PHANT_SCHED_CHAOS_CRASH=prefetch "
                        "induced prefetch-stage crash"
                    )
                if item.get("kind") == _ROOT:
                    # root lane: the 4th stage runs the PLAN LOWERING —
                    # merging the batch's HashPlans into the pooled
                    # staging blob (ops/root_engine.py prefetch_batch)
                    engine = self._resolve_root_engine()
                elif item.get("kind") == _SIG:
                    # sig lane: the 4th stage runs the ROW LOWERING —
                    # concatenating the batch's signature rows and the
                    # u256 -> limb encode (ops/sig_engine.py
                    # prefetch_batch)
                    engine = self._resolve_sig_engine()
                else:
                    engine = self._resolve_engine()
                pf = getattr(engine, "prefetch_batch", None)
                plan = None
                if pf is not None:
                    t0 = time.perf_counter()
                    plan = pf(item["payload"])
                    pf_ms = round((time.perf_counter() - t0) * 1e3, 3)
                with self._lock:
                    orphaned = self._dead is not None
                    if not orphaned:
                        item["plan"] = plan
                        if pf is not None:
                            # a prefetch-less engine double still flows
                            # through the worker, but nothing was decoded
                            # or pre-scanned — stats/metrics must not
                            # report a 4th stage that never ran
                            item["prefetch_ms"] = pf_ms
                            self.stats["prefetched_batches"] += 1
                        item["ready"] = True
                        self._cond.notify_all()
                if orphaned:
                    # _die ran while this plan was computing: it cleared
                    # _prefetch_pending and saw plan=None on this item,
                    # so nobody else will release these staging leases —
                    # drop them back to the pool here, or the shared
                    # engine's _staging loses them for good
                    if plan is not None:
                        plan.release()
                    return
                if pf is not None:
                    metrics.count("sched.prefetch_batches")
                item = None
        except BaseException as e:  # systemic: prefetch-stage failure
            self._die(e, item["jobs"] if item else [], stage="prefetch")

    def _drain_pipeline(self) -> None:
        """Block until every dispatched handle has resolved (or the
        scheduler died). Called by the executor before serial jobs —
        the serial lane stays exclusive with ALL witness work, not just
        the executor's own — and on graceful shutdown. With mesh dispatch
        the barrier covers every DEVICE lane: a state mutation must not
        run while any chip still holds in-flight witness work."""
        with self._lock:
            while (self._resolve_q or self._resolving) and self._dead is None:
                self._cond.wait(0.05)
        if self._pool is not None:
            self._pool.drain()

    def _next_batch(self, block: bool = True):
        with self._lock:
            while True:
                self._expire_locked()
                if self._dead is not None:
                    # the resolve worker died and failed everything: exit
                    # instead of idling in wait() until shutdown
                    return None
                if self._serial_q or any(self._lanes.values()):
                    break
                if self._closed:
                    return None
                if not block:
                    # prefetching executor with planned batches pending:
                    # it must not idle here while a ready plan waits
                    return _NO_BATCH
                self._cond.wait()
            if self._serial_q:
                # priority order: the serial mutation lane (head-of-chain
                # newPayload/forkchoiceUpdated) preempts ALL queued
                # witness work — a chain-head update must never sit
                # behind a backfill burst
                head = self._serial_q.pop(0)
                batch = [head]
            else:
                head = self._pick_witness_locked()
                batch = self._assemble_locked(head)
            depth = self._qlen_locked()
            tenant_depths = {
                j.tenant: len(self._lanes.get(j.tenant, ())) for j in batch
            }
            self._cond.notify_all()  # wake submitters waiting for space
        metrics.gauge_set("sched.queue_depth", depth)
        for tenant, lane_depth in tenant_depths.items():
            metrics.gauge_set("sched.tenant_queue_depth", lane_depth, tenant=tenant)
        return batch

    def _pick_witness_locked(self) -> _Job:
        """Choose the next witness head: lanes whose head request is
        PRIORITY_HEAD beat backfill lanes, and the tenant among the
        eligible class comes from the smooth-weighted-round-robin picker
        — fairness is across lanes; each lane stays FIFO internally.
        Caller holds `_lock` and guarantees at least one non-empty lane."""
        cands = [t for t, lane in self._lanes.items() if lane]
        head_cands = [
            t for t in cands if self._lanes[t][0].priority == PRIORITY_HEAD
        ]
        tenant = self._picker.pick(head_cands or cands)
        return self._lanes[tenant].pop(0)

    def _assembly_wait_s_locked(self) -> float:
        """The adaptive batching wait (qos.AdaptiveWait): re-evaluated on
        every assembly pass against the CURRENT queue depth, exported as
        the `sched.adaptive_wait_ms` gauge, with changes counted and
        flight-recorded. Static max_wait_ms when adaptive_wait is off."""
        if self._wait_policy is None:
            return self._max_wait_s
        chosen_ms = round(self._wait_policy.wait_ms(self._wit_len_locked()), 2)
        if chosen_ms != self._last_wait_ms:
            if self._last_wait_ms is not None:
                self.stats["wait_adjustments"] += 1
                metrics.count("sched.adaptive_wait_adjustments")
                flight.record(
                    "sched.adapt_wait",
                    wait_ms=chosen_ms,
                    prev_wait_ms=self._last_wait_ms,
                    queue_depth=self._wit_len_locked(),
                )
            self._last_wait_ms = chosen_ms
            metrics.gauge_set("sched.adaptive_wait_ms", chosen_ms)
        return chosen_ms / 1e3

    def _assemble_locked(self, head: _Job) -> List[_Job]:
        """Coalesce same-bucket witness jobs behind `head` under the
        max_batch / adaptive-wait policy. Same-bucket jobs join from
        EVERY tenant lane (the engine dispatch is tenant-blind; fairness
        was already enforced by the head pick), each lane drained FIFO.
        Caller holds `_lock`; the cond wait releases it so submitters
        keep admitting while we wait."""
        batch = [head]
        # evaluate the adaptive policy once per batch up front (so the
        # exported gauge tracks every batch, including the full-backlog
        # ones that never reach the wait below), then again on every pass
        self._assembly_wait_s_locked()
        while True:
            for lane in self._lanes.values():
                i = 0
                while i < len(lane) and len(batch) < self._max_batch:
                    if lane[i].bucket == head.bucket:
                        batch.append(lane.pop(i))
                    else:
                        i += 1
                if len(batch) >= self._max_batch:
                    break
            if len(batch) >= self._max_batch or self._closed:
                break
            if self._prefetch_pending and self._prefetch_pending[0]["ready"]:
                # 4-stage pipeline: a finished plan is waiting on this
                # thread — dispatching it beats further coalescing here
                # (waiting out the window would serialize the whole
                # pipeline behind one batch's assembly, the exact
                # bubble the prefetch stage exists to remove)
                break
            # the wait window shrinks as the queue deepens (a full
            # backlog needs no coalescing delay) and is re-evaluated
            # after every wakeup — a burst landing mid-wait cuts the
            # remaining window short
            wait_until = head.admitted + self._assembly_wait_s_locked()
            now = time.monotonic()
            if now >= wait_until:
                break
            self._cond.wait(wait_until - now)
        return batch

    def _shed_expired(self, job: _Job) -> None:
        """Deadline shed at execution time: one place keeps the stats
        snapshot and the `sched.rejected` metric in agreement (the soak
        gate and bench artifacts assert on the snapshot)."""
        with self._lock:
            self.stats["rejected"] += 1
            self._tenant_locked(job.tenant)["shed"] += 1
        metrics.count("sched.rejected", reason="deadline", tenant=job.tenant)
        flight.record(
            "sched.shed",
            reason="deadline",
            lane=job.kind,
            tenant=job.tenant,
            trace_id=job.trace_id,
        )
        job.future.set_exception(
            DeadlineExpired("deadline expired while queued")
        )

    def _expire_locked(self) -> None:
        """Fail queued jobs whose deadline has passed (without executing)."""
        now = time.monotonic()
        expired: List[_Job] = []
        for q in (self._serial_q, *self._lanes.values()):
            live = [
                j for j in q if j.deadline is None or now <= j.deadline
            ]
            if len(live) != len(q):
                expired.extend(
                    j for j in q if j.deadline is not None and now > j.deadline
                )
                q[:] = live
        if not expired:
            return
        self.stats["rejected"] += len(expired)
        for j in expired:
            self._tenant_locked(j.tenant)["shed"] += 1
            # set_exception never raises here: these futures have no
            # waiter-side cancellation path
            j.future.set_exception(
                DeadlineExpired("deadline expired while queued")
            )
            metrics.count("sched.rejected", reason="deadline", tenant=j.tenant)
            flight.record(
                "sched.shed",
                reason="deadline",
                lane=j.kind,
                tenant=j.tenant,
                trace_id=j.trace_id,
            )

    def _execute(self, batch: List[_Job]) -> None:
        now = time.monotonic()
        for j in batch:
            metrics.observe_hist("sched.queue_wait_seconds", now - j.admitted)
        lane = batch[0].kind
        # the stall bound the obs watchdog polls against: a full execution
        # allowance (config.deadline_ms) from PICKUP time — never the jobs'
        # admission deadlines, or a batch picked up with 0.2s of a 30s
        # deadline left would flag a perfectly healthy executor as stalled
        # and bury the real wedged-device signal
        if self.config.deadline_ms > 0:
            stall_deadline: Optional[float] = now + self.config.deadline_ms / 1e3
        else:
            stall_deadline = None
        trace_ids = [j.trace_id for j in batch]
        pipelined = False
        if lane == _SERIAL:
            # serial exclusivity covers the PIPELINE too: a state mutation
            # must not run while dispatched witness handles are in flight
            self._set_exec_stage("serial")
            self._drain_pipeline()
            with self._lock:
                dead = self._dead
            if dead is not None:
                # the drain ended because the scheduler DIED, not because
                # the pipeline emptied: a state mutation must not commit
                # on a server whose /healthz already reports it down
                _safe_fail(
                    batch[0].future,
                    SchedulerDown(f"scheduler executor crashed: {dead!r}"),
                )
                return
            stage = "serial"
        elif self._pool is not None:
            # mesh fan-out: the lane executor advances the stage (and
            # names its device) once the batch is routed; "dispatch" is
            # what an un-routed mesh batch is doing from this thread's
            # point of view
            engine = None
            pipelined = False
            stage = "dispatch"
            self._set_exec_stage(stage)
        else:
            self._set_exec_stage("pack")  # provisional: engine resolution
            if lane == _ROOT:
                engine = self._resolve_root_engine()
            elif lane == _SIG:
                engine = self._resolve_sig_engine()
            else:
                engine = self._resolve_engine()
            pipelined = self._pipe_depth > 1 and hasattr(engine, "begin_batch")
            # stage vocabulary: pipelined batches move pack -> dispatch ->
            # resolve; a depth-1/inline batch runs all three fused under
            # "dispatch" (the engine round-trip the executor blocks on).
            # _exec_stage must AGREE with the batch_start record — a
            # depth-1 crash (chaos drill included) has no pack stage
            stage = "pack" if pipelined else "dispatch"
            self._set_exec_stage(stage)
        with self._lock:
            self._batch_seq += 1
            batch_id = self._batch_seq
            self._inflight_list.append(
                {
                    "batch_id": batch_id,
                    "lane": lane,
                    "stage": stage,
                    "device": None,  # set by the mesh pool once routed
                    "started": now,
                    "deadline": stall_deadline,
                    "trace_ids": trace_ids,
                }
            )
        flight.record(
            "sched.batch_start",
            batch_id=batch_id,
            lane=lane,
            stage=stage,
            batch_size=len(batch),
            bucket_bytes=batch[0].bucket if lane == _WITNESS else None,
            tenants=sorted({j.tenant for j in batch}),
            trace_ids=trace_ids,
        )
        if pipelined:
            # the descriptor stays in flight until the resolve worker
            # finishes the batch (or _die clears everything)
            self._execute_witness_pipelined(batch, batch_id, engine, now, kind=lane)
            return
        if lane in (_WITNESS, _ROOT, _SIG) and self._pool is not None:
            # the descriptor stays in flight until the mesh lane finishes
            # the batch (_mesh_done/_mesh_skip) or _die clears everything
            if lane in (_ROOT, _SIG):
                self._execute_lane_mesh(batch, batch_id, now)
            else:
                self._execute_witness_mesh(batch, batch_id, now)
            return
        try:
            if lane == _SERIAL:
                self._execute_serial(batch[0], batch_id)
            elif lane == _ROOT:
                self._execute_roots(batch, batch_id, engine, now)
            elif lane == _SIG:
                self._execute_sigs(batch, batch_id, engine, now)
            else:
                self._execute_witness(batch, batch_id, engine, now)
        finally:
            with self._lock:
                self._drop_inflight_locked(batch_id)

    def _drop_inflight_locked(self, batch_id: int) -> None:
        self._inflight_list = [
            d for d in self._inflight_list if d["batch_id"] != batch_id
        ]

    def _execute_serial(self, job: _Job, batch_id: int) -> None:
        metrics.count("sched.batches", lane="serial")
        metrics.count("sched.tenant_served", tenant=job.tenant)
        with self._lock:
            self.stats["serial_jobs"] += 1
            self._tenant_locked(job.tenant)["served"] += 1
        if job.deadline is not None and time.monotonic() > job.deadline:
            self._shed_expired(job)
            return
        t0 = time.monotonic()

        def done(ok: bool, **extra) -> None:
            # the postmortem must distinguish a failed mutation from a
            # successful one — `ok` is the serial lane's n_ok analog
            flight.record(
                "sched.batch_done",
                batch_id=batch_id,
                lane=_SERIAL,
                batch_size=1,
                tenants=[job.tenant],
                ok=ok,
                duration_ms=round((time.monotonic() - t0) * 1e3, 3),
                queue_wait_ms=round((t0 - job.admitted) * 1e3, 3),
                trace_ids=[job.trace_id],
                **extra,
            )

        try:
            result = job.fn()
        except Exception as e:  # request-scoped: the job failed, not us
            done(False, error=repr(e)[:160])
            job.future.set_exception(e)
            return
        done(True)
        job.future.set_result(result)

    @staticmethod
    def _engine_cache_stats(engine) -> Optional[dict]:
        """hits/hashed/device/native counters of the engine, or None when
        the engine exposes no stats (custom test doubles)."""
        snap = getattr(engine, "stats_snapshot", None)
        if snap is None:
            return None
        try:
            return snap()
        except Exception:
            return None

    def _shed_or_keep(self, batch: List[_Job], now: float) -> List[_Job]:
        jobs = []
        for j in batch:
            if j.deadline is not None and now > j.deadline:
                self._shed_expired(j)
            else:
                jobs.append(j)
        return jobs

    def _execute_witness(
        self, batch: List[_Job], batch_id: int, engine, picked: float
    ) -> None:
        """Depth-1/inline execution: one verify_batch round-trip on the
        executor thread (pack + dispatch + resolve fused) — exactly the
        pre-pipeline behavior."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            return
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        self._set_exec_stage("dispatch")
        s0 = self._engine_cache_stats(engine)
        # the engine/device dispatch this scheduler exists for: one
        # verify_batch over the whole coalesced bucket. An exception here
        # is systemic (malformed witnesses yield False verdicts, and the
        # engine falls back device->native internally), so it propagates
        # to _run and takes the executor down — requests fail fast rather
        # than silently retrying into a broken engine.
        self._busy_acct.begin()
        try:
            verdicts = engine.verify_batch([(j.root, j.nodes) for j in jobs])
        finally:
            self._busy_acct.end()
        s1 = self._engine_cache_stats(engine)
        record = batch_record_from_stats(
            batch_id, len(jobs), jobs[0].bucket, s0, s1
        )
        self._finish_witness_jobs(jobs, verdicts, record, picked)

    def _execute_witness_pipelined(
        self,
        batch: List[_Job],
        batch_id: int,
        engine,
        picked: float,
        kind: str = _WITNESS,
    ) -> None:
        """Pack + dispatch on the executor thread, resolve on the resolve
        worker: begin_batch holds the engine lock only for the intern
        scan (witness lane) or runs the plan merge (root lane) and
        enqueues the device work with NO host sync, so this thread moves
        straight on to assembling (and packing) the next batch while the
        device computes and the worker resolves."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        self._pipeline_handoff(jobs, batch_id, engine, picked, kind=kind)

    def _execute_roots(
        self, batch: List[_Job], batch_id: int, engine, picked: float
    ) -> None:
        """Depth-1/inline root execution: one begin+resolve round trip on
        the executor thread (the root_many shape) — the coalesced batch
        still merges into ONE dispatch; only the pipeline overlap is
        absent."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            return
        self._set_exec_stage("dispatch")
        self._busy_acct.begin()
        try:
            handle = engine.begin_batch([j.plan for j in jobs])
            results = engine.resolve_batch(handle)
        finally:
            self._busy_acct.end()
        record = root_record_from_handle(
            handle, batch_id, len(jobs), jobs[0].bucket
        )
        record["stage"] = "dispatch"  # fused begin+resolve, like depth-1
        self._finish_root_jobs(jobs, results, record, picked)

    def _finish_plan_jobs(
        self,
        jobs: List[_Job],
        results,
        record: dict,
        picked: float,
        lane: str,
        emit: Callable[[int], None],
    ) -> None:
        """Shared completion tail of the root AND sig lanes: per-job meta
        + future resolution (each future gets ITS request's result
        slice), the batch_done record, and the coalescing metrics/stats
        — one definition so the two lanes can never diverge (the
        copy-divergence class this repo keeps eliminating). `emit(n)`
        publishes the lane's own counters: metric names must stay string
        LITERALS at their emit site (the METRICNAME contract), so each
        lane wrapper passes a closure instead of a name."""
        n = len(jobs)
        done = time.monotonic()
        served: dict = {}
        for j in jobs:
            served[j.tenant] = served.get(j.tenant, 0) + 1
        flight.record(
            "sched.batch_done",
            duration_ms=round((done - picked) * 1e3, 3),
            n_ok=n,
            tenants=sorted(served),
            trace_ids=[j.trace_id for j in jobs],
            **record,
        )
        # timeline tap: the [picked, done] interval lands on the lane's
        # track, keyed by batch_id — the `f` side of the flow stitching
        timeline.record_batch(
            record,
            lane=lane,
            duration_ms=round((done - picked) * 1e3, 3),
            trace_ids=[j.trace_id for j in jobs],
        )
        metrics.observe_hist("sched.batch_size", n, buckets=_BATCH_BUCKETS)
        metrics.count("sched.batches", lane=lane)
        emit(n)
        for tenant, cnt in served.items():
            metrics.count("sched.tenant_served", cnt, tenant=tenant)
        with self._lock:
            st = self.stats
            st["batches"] += 1
            st["batched_requests"] += n
            st[lane + "_batches"] += 1
            st[lane + "_requests"] += n
            if n > 1:
                st[lane + "_coalesced"] += n
                st["coalesced"] += n
        # futures resolve LAST: the future is the publication point, so a
        # waiter that observed its result must also observe the batch in
        # stats_snapshot()/metrics/flight (phantsan caught the inversion —
        # resolve-then-count let a freshly-unblocked caller read a
        # snapshot the batch had not reached yet)
        for j, result in zip(jobs, results):
            # meta BEFORE set_result (the *_traced ordering contract)
            j.meta = {
                **record,
                "tenant": j.tenant,
                "queue_wait_ms": round((picked - j.admitted) * 1e3, 3),
            }
            _safe_resolve(j.future, result)
            if n > st["max_batch_seen"]:
                st["max_batch_seen"] = n
            for tenant, cnt in served.items():
                self._tenant_locked(tenant)["served"] += cnt

    def _finish_root_jobs(
        self, jobs: List[_Job], results, record: dict, picked: float
    ) -> None:
        """Root-lane completion: each future gets ITS plan's out digests
        (storage roots in patch order, post root last)."""

        def emit(n: int) -> None:
            metrics.count(
                "sched.root_batches", backend=record.get("backend", "host")
            )
            if n > 1:
                metrics.count("sched.root_coalesced", n)

        self._finish_plan_jobs(jobs, results, record, picked, _ROOT, emit)

    def _execute_sigs(
        self, batch: List[_Job], batch_id: int, engine, picked: float
    ) -> None:
        """Depth-1/inline sig execution: one begin+resolve round trip on
        the executor thread (the sig_many shape) — the coalesced batch
        still merges into ONE dispatch; only the pipeline overlap is
        absent."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            return
        self._set_exec_stage("dispatch")
        self._busy_acct.begin()
        try:
            handle = engine.begin_batch([j.rows for j in jobs])
            results = engine.resolve_batch(handle)
        finally:
            self._busy_acct.end()
        record = sig_record_from_handle(
            handle, batch_id, len(jobs), jobs[0].bucket
        )
        record["stage"] = "dispatch"  # fused begin+resolve, like depth-1
        self._finish_sig_jobs(jobs, results, record, picked)

    def _finish_sig_jobs(
        self, jobs: List[_Job], results, record: dict, picked: float
    ) -> None:
        """Sig-lane completion: each future gets ITS request's sender
        slice (tx order; None = invalid signature)."""

        def emit(n: int) -> None:
            metrics.count(
                "sched.sig_batches", backend=record.get("backend", "native")
            )
            if n > 1:
                metrics.count("sched.sig_coalesced", n)

        self._finish_plan_jobs(jobs, results, record, picked, _SIG, emit)

    # -- mesh dispatch (mesh_devices >= 1, serving/mesh_exec.py) -------------

    def _execute_witness_mesh(
        self, batch: List[_Job], batch_id: int, picked: float
    ) -> None:
        """Fan one assembled batch out to the per-device pool: the
        whole-mesh megabatch path when the batch fills `max_batch` from a
        single bucket (megabatch mode), bucket-affinity routing with
        spillover otherwise. Affinity batches complete asynchronously on
        their lane (_mesh_done drops the descriptor); this thread goes
        straight back to assembling the next batch — that overlap is the
        mesh pipeline."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        pool = self._pool
        # backlog-depth trigger input: the same-bucket jobs STILL queued
        # after assembly (assembly drained the bucket up to max_batch, so
        # a non-zero leftover means sustained same-shape pressure). The
        # queue walk holds the global lock — only pay it when the
        # trigger can actually consume it (megabatch mode, k > 0), never
        # on the default affinity hot path.
        backlog = 0
        if pool.backlog_wanted():
            bucket = jobs[0].bucket
            with self._lock:
                backlog = sum(
                    1
                    for lane in self._lanes.values()
                    for qj in lane
                    if qj.kind == _WITNESS and qj.bucket == bucket
                )
        why = pool.megabatch_wanted(len(jobs), backlog)
        if why:
            from phant_tpu.serving.mesh_exec import MegabatchUnsupported

            try:
                verdicts, record = pool.run_megabatch(jobs, batch_id)
            except MegabatchUnsupported:
                pass  # this batch can't take the fused path: route it
            else:
                with self._lock:
                    self.stats["megabatches"] += 1
                    if why == "backlog":
                        self.stats["megabatch_backlog_triggers"] += 1
                if why == "backlog":
                    # fusion engaged by sustained overload, not a full
                    # batch — the trigger the operator tunes with
                    # --sched-megabatch-backlog-k
                    metrics.count("sched.megabatch_backlog_triggers")
                self._finish_witness_jobs(jobs, verdicts, record, picked)
                with self._lock:
                    self._drop_inflight_locked(batch_id)
                return
        device = pool.submit(jobs, batch_id, picked)
        if device is None:
            # a lane crashed while we waited for a slot: stop the executor
            # the same way a dead resolve worker does
            raise SchedulerDown("mesh executor pool is down")
        with self._lock:
            self.stats["mesh_batches"] += 1
            for d in self._inflight_list:
                if d["batch_id"] == batch_id:
                    d["device"] = device

    def _execute_lane_mesh(
        self, batch: List[_Job], batch_id: int, picked: float
    ) -> None:
        """Fan one root or sig batch out to the per-device pool:
        bucket-affinity routing (a level shape keeps hitting the same
        lane's pinned RootEngine; every sig batch shares one bucket, so
        one lane's pinned SigEngine keeps its compiled ecrecover shapes
        warm, with spillover as the load balancer) with the same
        backpressure as witness batches. Root/sig batches never take the
        megabatch path — the lane's merged dispatch IS the fusion."""
        jobs = self._shed_or_keep(batch, picked)
        if not jobs:
            with self._lock:
                self._drop_inflight_locked(batch_id)
            return
        device = self._pool.submit(jobs, batch_id, picked)
        if device is None:
            raise SchedulerDown("mesh executor pool is down")
        with self._lock:
            self.stats["mesh_batches"] += 1
            for d in self._inflight_list:
                if d["batch_id"] == batch_id:
                    d["device"] = device

    def _mesh_done(self, jobs, verdicts, record, picked, batch_id) -> None:
        """Lane completion (pool thread): the shared completion tail —
        witness, root, or sig by the jobs' kind — then the watchdog
        descriptor drops."""
        if jobs and jobs[0].kind == _ROOT:
            self._finish_root_jobs(jobs, verdicts, record, picked)
        elif jobs and jobs[0].kind == _SIG:
            self._finish_sig_jobs(jobs, verdicts, record, picked)
        else:
            self._finish_witness_jobs(jobs, verdicts, record, picked)
        with self._lock:
            self._drop_inflight_locked(batch_id)
            self._cond.notify_all()

    def _mesh_skip(self, batch_id) -> None:
        """Every job of a routed batch expired on its lane: nothing ran."""
        with self._lock:
            self._drop_inflight_locked(batch_id)
            self._cond.notify_all()

    def _mesh_stage(self, batch_id, stage, device) -> None:
        """Stage tracking for the obs watchdog: the lane reports which
        pipeline stage a routed batch is in, and on which device — a
        wedged device call shows up as a stall record NAMING the device."""
        with self._lock:
            for d in self._inflight_list:
                if d["batch_id"] == batch_id:
                    d["stage"] = stage
                    d["device"] = device

    def _mesh_crash(self, exc, jobs, stage, device) -> None:
        """A lane crashed (pool thread): scheduler-wide death, stage and
        device named in the crash record."""
        self._die(exc, list(jobs), stage=stage, device=device)

    def _finish_witness_jobs(
        self, jobs: List[_Job], verdicts, record: dict, picked: float
    ) -> None:
        """Shared completion tail of both witness paths: per-job meta +
        future resolution, the batch_done flight record, and the batching
        metrics/stats."""
        n = len(jobs)
        total = sum(j.nbytes for j in jobs)
        padded = _pow2ceil(total)
        done = time.monotonic()
        served: dict = {}
        for j in jobs:
            served[j.tenant] = served.get(j.tenant, 0) + 1
        flight.record(
            "sched.batch_done",
            lane=_WITNESS,
            duration_ms=round((done - picked) * 1e3, 3),
            n_ok=int(sum(bool(ok) for ok in verdicts)),
            tenants=sorted(served),
            trace_ids=[j.trace_id for j in jobs],
            **record,
        )
        # timeline tap: every witness completion funnels here (inline,
        # pipelined, mesh lane, megabatch) — one tap covers them all
        timeline.record_batch(
            record,
            lane=_WITNESS,
            duration_ms=round((done - picked) * 1e3, 3),
            trace_ids=[j.trace_id for j in jobs],
        )
        metrics.observe_hist("sched.batch_size", n, buckets=_BATCH_BUCKETS)
        metrics.count("sched.batches", lane="witness")
        for tenant, cnt in served.items():
            # the per-tenant progress counter the no-starvation gates
            # (loadgen, soak) watch
            metrics.count("sched.tenant_served", cnt, tenant=tenant)
        metrics.gauge_set(
            "sched.padding_waste", round(1.0 - total / padded, 4) if padded else 0.0
        )
        if n > 1:
            metrics.count("sched.coalesced_requests", n)
        with self._lock:
            st = self.stats
            st["batches"] += 1
            st["batched_requests"] += n
            if n > 1:
                st["coalesced"] += n
            if n > st["max_batch_seen"]:
                st["max_batch_seen"] = n
            for tenant, cnt in served.items():
                self._tenant_locked(tenant)["served"] += cnt
        # futures resolve LAST (see _finish_plan_jobs): a waiter that saw
        # its verdict must also see the batch in stats and metrics
        for j, ok in zip(jobs, verdicts):
            # meta BEFORE set_result: a waiter that observed the verdict
            # must also observe its batch record (verify_traced)
            j.meta = {
                **record,
                "tenant": j.tenant,
                "queue_wait_ms": round((picked - j.admitted) * 1e3, 3),
            }
            _safe_resolve(j.future, bool(ok))

    # -- resolve worker (pipeline_depth > 1) ---------------------------------

    def _resolve_run(self) -> None:
        item: Optional[dict] = None
        try:
            while True:
                with self._lock:
                    while (
                        not self._resolve_q
                        and not self._exec_done
                        and self._dead is None
                    ):
                        self._cond.wait()
                    if self._dead is not None:
                        return  # _die already failed everything queued
                    if not self._resolve_q:
                        return  # executor done and the pipeline is drained
                    item = self._resolve_q.pop(0)
                    self._resolving = True
                    for d in self._inflight_list:
                        if d["batch_id"] == item["batch_id"]:
                            d["stage"] = "resolve"
                    self._cond.notify_all()
                try:
                    self._resolve_one(item)
                finally:
                    with self._lock:
                        self._resolving = False
                        self._drop_inflight_locked(item["batch_id"])
                        inflight = len(self._resolve_q)
                        self._cond.notify_all()
                    metrics.gauge_set("sched.pipeline_inflight", inflight)
                item = None
        except BaseException as e:  # systemic: readback/commit failure
            # resolve_batch releases its own handle on failure; a crash
            # elsewhere in the loop still must not leak it
            if item is not None:
                _abandon_handle(
                    item.get("engine") or self._resolve_engine(), item["handle"]
                )
            self._die(e, item["jobs"] if item else [], stage="resolve")

    def _resolve_one(self, item: dict) -> None:
        try:
            self._resolve_one_inner(item)
        finally:
            # device-busy: the [begin, resolve] interval closes whether
            # the readback succeeded or the crash path takes over
            self._busy_acct.end()

    def _resolve_one_inner(self, item: dict) -> None:
        jobs = item["jobs"]
        handle = item["handle"]
        engine = item.get("engine") or self._resolve_engine()
        t0 = time.monotonic()
        if item.get("kind") == _ROOT:
            results = engine.resolve_batch(handle)
            record = root_record_from_handle(
                handle, item["batch_id"], len(jobs), jobs[0].bucket
            )
            finish = self._finish_root_jobs
        elif item.get("kind") == _SIG:
            results = engine.resolve_batch(handle)
            record = sig_record_from_handle(
                handle, item["batch_id"], len(jobs), jobs[0].bucket
            )
            finish = self._finish_sig_jobs
        else:
            results = engine.resolve_batch(handle)
            record = batch_record_from_handle(
                handle, item["batch_id"], len(jobs), jobs[0].bucket
            )
            finish = self._finish_witness_jobs
        record["pack_ms"] = item["pack_ms"]
        if "prefetch_ms" in item:
            record["prefetch_ms"] = item["prefetch_ms"]
        record["resolve_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        finish(jobs, results, record, item["picked"])

    def _resolve_engine(self):
        with self._engine_lock:
            if self._engine is None:
                from phant_tpu.stateless import shared_witness_engine

                self._engine = shared_witness_engine()
            return self._engine

    def _die(
        self,
        exc: BaseException,
        batch: List[_Job],
        stage: Optional[str] = None,
        device=None,
    ) -> None:
        """Mark the scheduler DOWN and fail fast: the crashing batch, every
        queued job, AND every dispatched-but-unresolved pipeline handle.
        `stage` names where execution died — pack/dispatch (executor),
        resolve (resolve worker), serial — so the postmortem pinpoints the
        pipeline stage; `device` names the mesh lane when one crashed.
        With mesh dispatch the pool dies too: queued-but-unbegun batches
        fail fast here, and every surviving lane abandons its own
        dispatched handles (no engine leaks a lease). Idempotent-by-
        first-caller: when the second thread of a pipelined scheduler
        trips over the first thread's corpse, it only fails its own
        victims (one crash record, one dump)."""
        with self._lock:
            first = self._dead is None
            if first:
                self._dead = exc
            victims = list(batch) + self._serial_q
            for lane in self._lanes.values():
                victims.extend(lane)
            dropped_items = list(self._resolve_q)
            for item in dropped_items:
                victims.extend(item["jobs"])
            # batches mid-prefetch (queued for the worker or awaiting
            # pickup) fail fast too; their plans' staging leases release
            # outside the lock. The crashing batch may still sit in
            # _prefetch_pending — _safe_fail tolerates the double-fail.
            dropped_plans = list(self._prefetch_pending)
            for item in dropped_plans:
                victims.extend(item["jobs"])
            self._serial_q = []
            self._lanes = {}
            self._resolve_q = []
            self._prefetch_q = []
            self._prefetch_pending = []
            self._inflight_list = []
            batch_id = self._batch_seq
            self._cond.notify_all()
        for item in dropped_items:
            # never resolved, never will be: release the engine leases so
            # a shared engine keeps evicting after this scheduler's death
            # (each pipe item carries ITS engine — witness or root), and
            # close each one's device-busy interval (begun at handoff)
            _abandon_handle(item.get("engine") or self._resolve_engine(), item["handle"])
            self._busy_acct.end()
        for item in dropped_plans:
            plan = item.get("plan")
            if plan is not None:
                try:
                    plan.release()  # unconsumed staging leases -> pool
                except Exception:
                    log.warning("plan release failed on a crash path", exc_info=True)
        pool_failed = 0
        if self._pool is not None:
            # queued-but-unbegun mesh batches fail fast here; lanes
            # abandon their own begun handles as they observe the death
            pool_failed = self._pool.kill(exc)
        if first:
            log.error("scheduler executor crashed: %r", exc, exc_info=exc)
            metrics.count("sched.executor_crashes")
            # the postmortem FIRST: record the crash (with the crashing
            # batch's ids and the stage that died) and dump the whole ring
            # to build/flight/ — by the time a waiter observes its
            # SchedulerDown, the artifact already exists
            flight.record(
                "sched.executor_crash",
                batch_id=batch_id,
                stage=stage,
                device=device,
                error=repr(exc),
                crashed_trace_ids=[j.trace_id for j in batch],
                n_failed_fast=len(victims) + pool_failed,
            )
            flight.dump("executor_crash")
        for j in victims:
            _safe_fail(
                j.future, SchedulerDown(f"scheduler executor crashed: {exc!r}")
            )
        metrics.gauge_set("sched.queue_depth", 0)
        metrics.gauge_set("sched.pipeline_inflight", 0)
        self._watchdog.stop(0.0)
