"""Continuous-batching verification scheduler.

The Engine API server used to execute one request at a time behind a
global lock: concurrent CL requests queued on a mutex and each one paid a
batch-of-1 engine dispatch — the exact opposite of the framework's win
condition (vmapping witness verification across hundreds of blocks per
device dispatch). This module gives the serving path the inference-server
shape instead:

    admission queue  ->  batch assembler  ->  single executor thread

* **Admission queue** — bounded (`queue_depth`); a full queue REJECTS the
  request with `QueueFull` (JSON-RPC `-32050`, counted in
  `sched.rejected{reason=queue_full}`) instead of building unbounded
  latency. Every request carries a deadline; a request whose deadline
  passes while queued fails with `DeadlineExpired` (`-32051`) without
  ever touching the engine.
* **Batch assembler** — coalesces concurrent *witness-verification*
  requests into shape buckets (bucket key = total witness bytes rounded
  up to a power of two, the same rounding the device keccak path pads
  its blob buffer to, ops/witness_jax._pow2ceil), so the padded device
  buffers of one batch stay dense; `sched.padding_waste` reports the
  unused fraction of the padded buffer the last batch would occupy.
  Assembly runs under a `max_batch` / `max_wait_ms` policy: a batch
  executes as soon as it is full, and an under-full batch waits at most
  `max_wait_ms` from its head request's admission. Under load the
  executor's busy period makes that wait moot (the backlog that formed
  while the previous batch executed IS the next batch); the wait only
  costs anything for a request arriving at an idle executor, which is
  why it bounds — and is the whole of — the serial-client latency tax.
* **Executor** — ONE thread drains buckets into
  `WitnessEngine.verify_batch` (the amortized engine/device dispatch)
  and resolves per-request futures. The same thread runs *serial* jobs
  (state-mutating `engine_newPayload*` execution) one at a time, in
  admission order — which is what replaces the server's global execution
  lock: mutation is serialized by the executor, not by a mutex held
  across the whole request.
* **Lifecycle** — `shutdown(drain=True)` stops admission and lets the
  executor finish everything queued (graceful drain); an exception
  escaping batch execution marks the scheduler DOWN: the crashed batch
  and everything queued fail fast with `SchedulerDown` (`-32052`), later
  submits are rejected immediately, and `/healthz` reports 503 with
  `executor_alive: false` (engine_api/server.py `_healthz_payload`).

`verify_many()` is the synchronous offline face of the same machinery:
bench.py, the spec runner (`--sched`), and tests push whole witness
spans through the identical admission/assembly/executor code and get an
(n,) bool verdict array back — the batching code measured offline is the
batching code serving traffic.

Observability (phant_tpu/obs/, PR 4): every job carries the submitting
request's `trace_id` (utils/trace.py trace_context — the Engine API server
opens one per POST), admissions/sheds/batch transitions land in the flight
recorder ring, and the executor attaches a per-batch record (`batch_id`,
`batch_size`, `bucket_bytes`, `backend`, cache hit/miss deltas,
`queue_wait_ms`) to each job it resolves — `verify_traced()` hands it back
so the request's span stays joinable to the batch that served it. An obs
watchdog thread per scheduler flags the in-flight batch out-living its
deadline (`sched.watchdog_stalls` + a `sched.stall` flight event); an
executor crash additionally dumps the ring to build/flight/ (the
postmortem artifact a dead server leaves behind).

Thread-safety: one lock (`_lock`) guards the queue and lifecycle state;
`_cond` wraps that same lock, so every wait/notify runs under it. The
registry's and flight recorder's own locks never take ours, so metric and
flight publishes cannot deadlock against admission (same discipline as
ops/witness_engine.py).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.obs.flight import flight
from phant_tpu.obs.watchdog import Watchdog
from phant_tpu.utils.trace import current_trace_id, metrics

log = logging.getLogger("phant_tpu.serving")


class SchedulerError(Exception):
    """Base for scheduler rejections; carries the JSON-RPC error code and
    HTTP status the Engine API server maps the rejection to."""

    code = -32000
    http_status = 503


class QueueFull(SchedulerError):
    """Admission queue at `queue_depth`: overload, shed the request."""

    code = -32050


class DeadlineExpired(SchedulerError):
    """The request's deadline passed before the executor reached it."""

    code = -32051


class SchedulerDown(SchedulerError):
    """The executor has crashed or the scheduler is shutting down."""

    code = -32052


@dataclass
class SchedulerConfig:
    """Knobs, surfaced as `--sched-*` CLI flags (phant_tpu/__main__.py)."""

    max_batch: int = 128  # requests per assembled witness batch
    max_wait_ms: float = 5.0  # assembly wait for an under-full batch
    queue_depth: int = 512  # admission-queue bound (overload -> QueueFull)
    deadline_ms: float = 30_000.0  # default per-request deadline; <=0 = none


_WITNESS = "witness"
_SERIAL = "serial"

#: batch-size histogram buckets (requests per engine dispatch)
_BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _pow2ceil(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


@dataclass
class _Job:
    kind: str
    future: Future
    admitted: float  # monotonic admission time
    deadline: Optional[float]  # monotonic expiry, None = no deadline
    # witness lane
    root: bytes = b""
    nodes: Sequence[bytes] = ()
    nbytes: int = 0
    bucket: int = 0
    # serial lane
    fn: Optional[Callable] = None
    # observability: the submitting request's trace context, and the batch
    # record the executor attaches before resolving the future (set-then-
    # resolve ordering means a waiter that saw result() also sees meta)
    trace_id: Optional[str] = None
    meta: Optional[dict] = None


class VerificationScheduler:
    """Continuous-batching scheduler over a `WitnessEngine`.

    `engine` defaults to the process-shared memoized engine
    (stateless.shared_witness_engine), resolved lazily at first execution
    so constructing a scheduler never imports jax-adjacent modules.
    """

    def __init__(
        self,
        engine: Optional[object] = None,
        config: Optional[SchedulerConfig] = None,
    ):
        self.config = config or SchedulerConfig()
        # config is immutable after construction; the locked regions read
        # these unpacked copies so `self.config` itself stays a lock-free
        # introspection surface (state(), _deadline())
        self._max_batch = self.config.max_batch
        self._max_wait_s = self.config.max_wait_ms / 1e3
        self._queue_depth = self.config.queue_depth
        self._engine = engine
        # chaos drill (obs): PHANT_SCHED_CHAOS_CRASH=1 makes the FIRST
        # witness batch crash the executor — the supported way to fire-
        # drill the postmortem path (flight dump, /healthz 503, -32052
        # fail-fast) against a live server / the real CLI
        import os

        self._chaos_crash = os.environ.get("PHANT_SCHED_CHAOS_CRASH") == "1"
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Job] = []
        self._closed = False
        self._dead: Optional[BaseException] = None
        # observability: monotone batch ids + the in-flight descriptor the
        # obs watchdog polls (both guarded by _lock)
        self._batch_seq = 0
        self._inflight: Optional[dict] = None
        self.stats = {
            "requests": 0,
            "batches": 0,
            "serial_jobs": 0,
            "coalesced": 0,
            "batched_requests": 0,
            "max_batch_seen": 0,
            "rejected": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name="phant-sched-exec", daemon=True
        )
        self._thread.start()
        self._watchdog = Watchdog(self.inflight_state).start()

    # -- context manager (offline verify_many use) ---------------------------

    def __enter__(self) -> "VerificationScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- admission -----------------------------------------------------------

    def _witness_job(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float],
    ) -> _Job:
        nodes = list(nodes)
        nbytes = sum(map(len, nodes))
        return _Job(
            kind=_WITNESS,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            root=root,
            nodes=nodes,
            nbytes=nbytes,
            bucket=_pow2ceil(nbytes),
            trace_id=current_trace_id(),
        )

    def submit_witness(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
        wait_for_space: bool = False,
    ) -> Future:
        """Queue one (root, nodes) linked-multiproof verification; the
        future resolves to the bool verdict. `wait_for_space` blocks on a
        full queue instead of rejecting (offline verify_many); the online
        serving path never waits — overload must shed, not stack."""
        job = self._witness_job(root, nodes, deadline_s)
        self._admit(job, wait_for_space)
        return job.future

    def verify_traced(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
    ) -> Tuple[bool, Optional[dict]]:
        """One witness verification through the batching path, returning
        (verdict, batch record). The record — `batch_id`, `batch_size`,
        `bucket_bytes`, `backend`, cache hit/miss deltas, `queue_wait_ms` —
        is what joins the caller's span to the shared engine dispatch that
        served it (stateless.verify_witness_nodes folds it into the open
        `verify_block` span). Scheduler rejections raise as usual."""
        job = self._witness_job(root, nodes, deadline_s)
        self._admit(job, False)
        return bool(job.future.result()), job.meta

    def submit_serial(
        self, fn: Callable, deadline_s: Optional[float] = None
    ) -> Future:
        """Queue an exclusive job: the executor runs `fn()` with nothing
        else in flight — the replacement for the server's global execution
        lock (state-mutating newPayload execution). `fn`'s return value
        resolves the future; an exception from `fn` is request-scoped and
        lands on the future (it does NOT kill the executor)."""
        job = _Job(
            kind=_SERIAL,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            fn=fn,
            trace_id=current_trace_id(),
        )
        self._admit(job, False)
        return job.future

    def _deadline(self, deadline_s: Optional[float]) -> Optional[float]:
        if deadline_s is None:
            d = self.config.deadline_ms / 1e3
        else:
            d = deadline_s
        if d <= 0 or d == float("inf"):
            return None
        return time.monotonic() + d

    def _admit(self, job: _Job, wait_for_space: bool) -> None:
        reason = None
        with self._lock:
            while True:
                if self._dead is not None:
                    reason, err = "down", SchedulerDown(
                        f"scheduler executor is down: {self._dead!r}"
                    )
                    break
                if self._closed:
                    reason, err = "shutdown", SchedulerDown(
                        "scheduler is shutting down"
                    )
                    break
                if len(self._queue) < self._queue_depth:
                    self._queue.append(job)
                    self.stats["requests"] += 1
                    depth = len(self._queue)
                    self._cond.notify_all()
                    break
                if not wait_for_space:
                    reason, err = "queue_full", QueueFull(
                        f"admission queue full ({self._queue_depth})"
                    )
                    break
                self._cond.wait(0.05)
            if reason is not None:
                self.stats["rejected"] += 1
        if reason is not None:
            metrics.count("sched.rejected", reason=reason)
            flight.record(
                "sched.shed", reason=reason, lane=job.kind, trace_id=job.trace_id
            )
            raise err
        metrics.gauge_set("sched.queue_depth", depth)
        flight.record(
            "sched.admit",
            lane=job.kind,
            bucket_bytes=job.bucket if job.kind == _WITNESS else None,
            queue_depth=depth,
            trace_id=job.trace_id,
        )

    # -- the synchronous offline face ---------------------------------------

    def verify_many(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> np.ndarray:
        """(n,) bool verdicts for a span of (root, nodes) witnesses, pushed
        through the SAME admission/assembly/executor path the server uses —
        the offline API for bench.py, the spec runner, and tests. Blocks on
        queue space instead of rejecting (offline callers want completion,
        not load shedding) and applies no deadline."""
        if threading.current_thread() is self._thread:
            raise RuntimeError(
                "verify_many called from the executor thread (deadlock)"
            )
        futs = [
            self.submit_witness(
                root, nodes, deadline_s=float("inf"), wait_for_space=True
            )
            for root, nodes in witnesses
        ]
        return np.fromiter(
            (bool(f.result()) for f in futs), bool, count=len(futs)
        )

    def accepts_witness(self) -> bool:
        """Can the CURRENT thread route a witness verification through this
        scheduler? False on the executor thread itself (submitting from it
        would deadlock: it is the only consumer) and once the scheduler is
        down or draining — callers fall back to the direct engine path."""
        if threading.current_thread() is self._thread:
            return False
        with self._lock:
            return self._dead is None and not self._closed

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        """Liveness surface for `/healthz` (engine_api/server.py)."""
        with self._lock:
            depth = len(self._queue)
            dead = self._dead
        alive = dead is None and self._thread.is_alive()
        out = {
            "queue_depth": depth,
            "executor_alive": alive,
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
        }
        if dead is not None:
            out["error"] = repr(dead)
        return out

    def stats_snapshot(self) -> dict:
        with self._lock:
            st = dict(self.stats)
        b = st["batches"]
        st["mean_batch"] = round(st["batched_requests"] / b, 2) if b else 0.0
        return st

    def inflight_state(self) -> Optional[dict]:
        """The batch the executor is inside right now — `batch_id`, `lane`,
        `started`/`deadline` (monotonic), `trace_ids` — or None when idle.
        Polled by the obs watchdog to flag deadline-overrun stalls."""
        with self._lock:
            return dict(self._inflight) if self._inflight is not None else None

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admission; `drain=True` lets the executor finish everything
        already queued before it exits, `drain=False` fails the queue fast.
        Idempotent."""
        with self._lock:
            self._closed = True
            dropped = [] if drain else list(self._queue)
            if not drain:
                self._queue.clear()
            self._cond.notify_all()
        for job in dropped:
            job.future.set_exception(
                SchedulerDown("scheduler shut down before execution")
            )
        self._thread.join(timeout)
        self._watchdog.stop(1.0)
        metrics.gauge_set("sched.queue_depth", 0)

    # -- executor ------------------------------------------------------------

    def _run(self) -> None:
        batch: List[_Job] = []
        try:
            while True:
                batch = self._next_batch()
                if batch is None:
                    return
                self._execute(batch)
                batch = []
        except BaseException as e:  # systemic: engine/internal failure
            self._die(e, batch or [])

    def _next_batch(self) -> Optional[List[_Job]]:
        with self._lock:
            while True:
                self._expire_locked()
                if self._queue:
                    break
                if self._closed:
                    return None
                self._cond.wait()
            head = self._queue.pop(0)
            if head.kind == _SERIAL:
                batch = [head]
            else:
                batch = self._assemble_locked(head)
            depth = len(self._queue)
            self._cond.notify_all()  # wake submitters waiting for space
        metrics.gauge_set("sched.queue_depth", depth)
        return batch

    def _assemble_locked(self, head: _Job) -> List[_Job]:
        """Coalesce same-bucket witness jobs behind `head` under the
        max_batch / max_wait policy. Caller holds `_lock`; the cond wait
        releases it so submitters keep admitting while we wait."""
        batch = [head]
        wait_until = head.admitted + self._max_wait_s
        while True:
            i = 0
            while i < len(self._queue) and len(batch) < self._max_batch:
                j = self._queue[i]
                if j.kind == _WITNESS and j.bucket == head.bucket:
                    batch.append(self._queue.pop(i))
                else:
                    i += 1
            if len(batch) >= self._max_batch or self._closed:
                break
            now = time.monotonic()
            if now >= wait_until:
                break
            self._cond.wait(wait_until - now)
        return batch

    def _shed_expired(self, job: _Job) -> None:
        """Deadline shed at execution time: one place keeps the stats
        snapshot and the `sched.rejected` metric in agreement (the soak
        gate and bench artifacts assert on the snapshot)."""
        with self._lock:
            self.stats["rejected"] += 1
        metrics.count("sched.rejected", reason="deadline")
        flight.record(
            "sched.shed", reason="deadline", lane=job.kind, trace_id=job.trace_id
        )
        job.future.set_exception(
            DeadlineExpired("deadline expired while queued")
        )

    def _expire_locked(self) -> None:
        """Fail queued jobs whose deadline has passed (without executing)."""
        now = time.monotonic()
        live: List[_Job] = []
        expired: List[_Job] = []
        for j in self._queue:
            (expired if j.deadline is not None and now > j.deadline else live).append(j)
        if not expired:
            return
        self._queue[:] = live
        self.stats["rejected"] += len(expired)
        for j in expired:
            # set_exception never raises here: these futures have no
            # waiter-side cancellation path
            j.future.set_exception(
                DeadlineExpired("deadline expired while queued")
            )
            metrics.count("sched.rejected", reason="deadline")
            flight.record(
                "sched.shed", reason="deadline", lane=j.kind, trace_id=j.trace_id
            )

    def _execute(self, batch: List[_Job]) -> None:
        now = time.monotonic()
        for j in batch:
            metrics.observe_hist("sched.queue_wait_seconds", now - j.admitted)
        lane = batch[0].kind
        # the stall bound the obs watchdog polls against: a full execution
        # allowance (config.deadline_ms) from PICKUP time — never the jobs'
        # admission deadlines, or a batch picked up with 0.2s of a 30s
        # deadline left would flag a perfectly healthy executor as stalled
        # and bury the real wedged-device signal
        if self.config.deadline_ms > 0:
            stall_deadline: Optional[float] = now + self.config.deadline_ms / 1e3
        else:
            stall_deadline = None
        trace_ids = [j.trace_id for j in batch]
        with self._lock:
            self._batch_seq += 1
            batch_id = self._batch_seq
            self._inflight = {
                "batch_id": batch_id,
                "lane": lane,
                "started": now,
                "deadline": stall_deadline,
                "trace_ids": trace_ids,
            }
        flight.record(
            "sched.batch_start",
            batch_id=batch_id,
            lane=lane,
            batch_size=len(batch),
            bucket_bytes=batch[0].bucket if lane == _WITNESS else None,
            trace_ids=trace_ids,
        )
        try:
            if lane == _SERIAL:
                self._execute_serial(batch[0], batch_id)
            else:
                self._execute_witness(batch, batch_id)
        finally:
            with self._lock:
                self._inflight = None

    def _execute_serial(self, job: _Job, batch_id: int) -> None:
        metrics.count("sched.batches", lane="serial")
        with self._lock:
            self.stats["serial_jobs"] += 1
        if job.deadline is not None and time.monotonic() > job.deadline:
            self._shed_expired(job)
            return
        t0 = time.monotonic()

        def done(ok: bool, **extra) -> None:
            # the postmortem must distinguish a failed mutation from a
            # successful one — `ok` is the serial lane's n_ok analog
            flight.record(
                "sched.batch_done",
                batch_id=batch_id,
                lane=_SERIAL,
                batch_size=1,
                ok=ok,
                duration_ms=round((time.monotonic() - t0) * 1e3, 3),
                queue_wait_ms=round((t0 - job.admitted) * 1e3, 3),
                trace_ids=[job.trace_id],
                **extra,
            )

        try:
            result = job.fn()
        except Exception as e:  # request-scoped: the job failed, not us
            done(False, error=repr(e)[:160])
            job.future.set_exception(e)
            return
        done(True)
        job.future.set_result(result)

    @staticmethod
    def _engine_cache_stats(engine) -> Optional[dict]:
        """hits/hashed/device/native counters of the engine, or None when
        the engine exposes no stats (custom test doubles)."""
        snap = getattr(engine, "stats_snapshot", None)
        if snap is None:
            return None
        try:
            return snap()
        except Exception:
            return None

    def _execute_witness(self, batch: List[_Job], batch_id: int) -> None:
        now = time.monotonic()
        jobs = []
        for j in batch:
            if j.deadline is not None and now > j.deadline:
                self._shed_expired(j)
            else:
                jobs.append(j)
        if not jobs:
            return
        n = len(jobs)
        total = sum(j.nbytes for j in jobs)
        padded = _pow2ceil(total)
        if self._chaos_crash:
            raise RuntimeError(
                "chaos drill: PHANT_SCHED_CHAOS_CRASH=1 induced executor crash"
            )
        engine = self._resolve_engine()
        s0 = self._engine_cache_stats(engine)
        # the engine/device dispatch this scheduler exists for: one
        # verify_batch over the whole coalesced bucket. An exception here
        # is systemic (malformed witnesses yield False verdicts, and the
        # engine falls back device->native internally), so it propagates
        # to _run and takes the executor down — requests fail fast rather
        # than silently retrying into a broken engine.
        verdicts = engine.verify_batch([(j.root, j.nodes) for j in jobs])
        s1 = self._engine_cache_stats(engine)
        record = {
            "batch_id": batch_id,
            "batch_size": n,
            "bucket_bytes": jobs[0].bucket,
        }
        if s0 is not None and s1 is not None:
            # deltas are batch-attributable as long as this executor is the
            # engine's only concurrent caller (the serving configuration);
            # a shared offline engine can skew them by other callers' work
            record["cache_hits"] = s1.get("hits", 0) - s0.get("hits", 0)
            record["cache_misses"] = s1.get("hashed", 0) - s0.get("hashed", 0)
            if s1.get("device_batches", 0) > s0.get("device_batches", 0):
                record["backend"] = "device"
            elif s1.get("native_batches", 0) > s0.get("native_batches", 0):
                record["backend"] = "native"
            else:
                record["backend"] = "cached"  # zero novel nodes: no hashing
        done = time.monotonic()
        for j, ok in zip(jobs, verdicts):
            # meta BEFORE set_result: a waiter that observed the verdict
            # must also observe its batch record (verify_traced)
            j.meta = {
                **record,
                "queue_wait_ms": round((now - j.admitted) * 1e3, 3),
            }
            j.future.set_result(bool(ok))
        flight.record(
            "sched.batch_done",
            lane=_WITNESS,
            duration_ms=round((done - now) * 1e3, 3),
            n_ok=int(sum(bool(ok) for ok in verdicts)),
            trace_ids=[j.trace_id for j in jobs],
            **record,
        )
        metrics.observe_hist("sched.batch_size", n, buckets=_BATCH_BUCKETS)
        metrics.count("sched.batches", lane="witness")
        metrics.gauge_set(
            "sched.padding_waste", round(1.0 - total / padded, 4) if padded else 0.0
        )
        if n > 1:
            metrics.count("sched.coalesced_requests", n)
        with self._lock:
            st = self.stats
            st["batches"] += 1
            st["batched_requests"] += n
            if n > 1:
                st["coalesced"] += n
            if n > st["max_batch_seen"]:
                st["max_batch_seen"] = n

    def _resolve_engine(self):
        if self._engine is None:
            from phant_tpu.stateless import shared_witness_engine

            self._engine = shared_witness_engine()
        return self._engine

    def _die(self, exc: BaseException, batch: List[_Job]) -> None:
        log.error("scheduler executor crashed: %r", exc, exc_info=exc)
        metrics.count("sched.executor_crashes")
        with self._lock:
            self._dead = exc
            victims = batch + self._queue
            self._queue = []
            batch_id = self._batch_seq
            self._cond.notify_all()
        # the postmortem FIRST: record the crash (with the crashing batch's
        # ids) and dump the whole ring to build/flight/ — by the time a
        # waiter observes its SchedulerDown, the artifact already exists
        flight.record(
            "sched.executor_crash",
            batch_id=batch_id,
            error=repr(exc),
            crashed_trace_ids=[j.trace_id for j in batch],
            n_failed_fast=len(victims),
        )
        flight.dump("executor_crash")
        for j in victims:
            if not j.future.done():
                j.future.set_exception(
                    SchedulerDown(f"scheduler executor crashed: {exc!r}")
                )
        metrics.gauge_set("sched.queue_depth", 0)
        self._watchdog.stop(0.0)
