"""Continuous-batching verification scheduler.

The Engine API server used to execute one request at a time behind a
global lock: concurrent CL requests queued on a mutex and each one paid a
batch-of-1 engine dispatch — the exact opposite of the framework's win
condition (vmapping witness verification across hundreds of blocks per
device dispatch). This module gives the serving path the inference-server
shape instead:

    admission queue  ->  batch assembler  ->  single executor thread

* **Admission queue** — bounded (`queue_depth`); a full queue REJECTS the
  request with `QueueFull` (JSON-RPC `-32050`, counted in
  `sched.rejected{reason=queue_full}`) instead of building unbounded
  latency. Every request carries a deadline; a request whose deadline
  passes while queued fails with `DeadlineExpired` (`-32051`) without
  ever touching the engine.
* **Batch assembler** — coalesces concurrent *witness-verification*
  requests into shape buckets (bucket key = total witness bytes rounded
  up to a power of two, the same rounding the device keccak path pads
  its blob buffer to, ops/witness_jax._pow2ceil), so the padded device
  buffers of one batch stay dense; `sched.padding_waste` reports the
  unused fraction of the padded buffer the last batch would occupy.
  Assembly runs under a `max_batch` / `max_wait_ms` policy: a batch
  executes as soon as it is full, and an under-full batch waits at most
  `max_wait_ms` from its head request's admission. Under load the
  executor's busy period makes that wait moot (the backlog that formed
  while the previous batch executed IS the next batch); the wait only
  costs anything for a request arriving at an idle executor, which is
  why it bounds — and is the whole of — the serial-client latency tax.
* **Executor** — ONE thread drains buckets into
  `WitnessEngine.verify_batch` (the amortized engine/device dispatch)
  and resolves per-request futures. The same thread runs *serial* jobs
  (state-mutating `engine_newPayload*` execution) one at a time, in
  admission order — which is what replaces the server's global execution
  lock: mutation is serialized by the executor, not by a mutex held
  across the whole request.
* **Lifecycle** — `shutdown(drain=True)` stops admission and lets the
  executor finish everything queued (graceful drain); an exception
  escaping batch execution marks the scheduler DOWN: the crashed batch
  and everything queued fail fast with `SchedulerDown` (`-32052`), later
  submits are rejected immediately, and `/healthz` reports 503 with
  `executor_alive: false` (engine_api/server.py `_healthz_payload`).

`verify_many()` is the synchronous offline face of the same machinery:
bench.py, the spec runner (`--sched`), and tests push whole witness
spans through the identical admission/assembly/executor code and get an
(n,) bool verdict array back — the batching code measured offline is the
batching code serving traffic.

Thread-safety: one lock (`_lock`) guards the queue and lifecycle state;
`_cond` wraps that same lock, so every wait/notify runs under it. The
registry's own lock never takes ours, so metric publishes cannot deadlock
against admission (same discipline as ops/witness_engine.py).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.utils.trace import metrics

log = logging.getLogger("phant_tpu.serving")


class SchedulerError(Exception):
    """Base for scheduler rejections; carries the JSON-RPC error code and
    HTTP status the Engine API server maps the rejection to."""

    code = -32000
    http_status = 503


class QueueFull(SchedulerError):
    """Admission queue at `queue_depth`: overload, shed the request."""

    code = -32050


class DeadlineExpired(SchedulerError):
    """The request's deadline passed before the executor reached it."""

    code = -32051


class SchedulerDown(SchedulerError):
    """The executor has crashed or the scheduler is shutting down."""

    code = -32052


@dataclass
class SchedulerConfig:
    """Knobs, surfaced as `--sched-*` CLI flags (phant_tpu/__main__.py)."""

    max_batch: int = 128  # requests per assembled witness batch
    max_wait_ms: float = 5.0  # assembly wait for an under-full batch
    queue_depth: int = 512  # admission-queue bound (overload -> QueueFull)
    deadline_ms: float = 30_000.0  # default per-request deadline; <=0 = none


_WITNESS = "witness"
_SERIAL = "serial"

#: batch-size histogram buckets (requests per engine dispatch)
_BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _pow2ceil(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


@dataclass
class _Job:
    kind: str
    future: Future
    admitted: float  # monotonic admission time
    deadline: Optional[float]  # monotonic expiry, None = no deadline
    # witness lane
    root: bytes = b""
    nodes: Sequence[bytes] = ()
    nbytes: int = 0
    bucket: int = 0
    # serial lane
    fn: Optional[Callable] = None


class VerificationScheduler:
    """Continuous-batching scheduler over a `WitnessEngine`.

    `engine` defaults to the process-shared memoized engine
    (stateless.shared_witness_engine), resolved lazily at first execution
    so constructing a scheduler never imports jax-adjacent modules.
    """

    def __init__(
        self,
        engine: Optional[object] = None,
        config: Optional[SchedulerConfig] = None,
    ):
        self.config = config or SchedulerConfig()
        # config is immutable after construction; the locked regions read
        # these unpacked copies so `self.config` itself stays a lock-free
        # introspection surface (state(), _deadline())
        self._max_batch = self.config.max_batch
        self._max_wait_s = self.config.max_wait_ms / 1e3
        self._queue_depth = self.config.queue_depth
        self._engine = engine
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Job] = []
        self._closed = False
        self._dead: Optional[BaseException] = None
        self.stats = {
            "requests": 0,
            "batches": 0,
            "serial_jobs": 0,
            "coalesced": 0,
            "batched_requests": 0,
            "max_batch_seen": 0,
            "rejected": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name="phant-sched-exec", daemon=True
        )
        self._thread.start()

    # -- context manager (offline verify_many use) ---------------------------

    def __enter__(self) -> "VerificationScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- admission -----------------------------------------------------------

    def submit_witness(
        self,
        root: bytes,
        nodes: Sequence[bytes],
        deadline_s: Optional[float] = None,
        wait_for_space: bool = False,
    ) -> Future:
        """Queue one (root, nodes) linked-multiproof verification; the
        future resolves to the bool verdict. `wait_for_space` blocks on a
        full queue instead of rejecting (offline verify_many); the online
        serving path never waits — overload must shed, not stack."""
        nodes = list(nodes)
        nbytes = sum(map(len, nodes))
        job = _Job(
            kind=_WITNESS,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            root=root,
            nodes=nodes,
            nbytes=nbytes,
            bucket=_pow2ceil(nbytes),
        )
        return self._admit(job, wait_for_space)

    def submit_serial(
        self, fn: Callable, deadline_s: Optional[float] = None
    ) -> Future:
        """Queue an exclusive job: the executor runs `fn()` with nothing
        else in flight — the replacement for the server's global execution
        lock (state-mutating newPayload execution). `fn`'s return value
        resolves the future; an exception from `fn` is request-scoped and
        lands on the future (it does NOT kill the executor)."""
        job = _Job(
            kind=_SERIAL,
            future=Future(),
            admitted=time.monotonic(),
            deadline=self._deadline(deadline_s),
            fn=fn,
        )
        return self._admit(job, False)

    def _deadline(self, deadline_s: Optional[float]) -> Optional[float]:
        if deadline_s is None:
            d = self.config.deadline_ms / 1e3
        else:
            d = deadline_s
        if d <= 0 or d == float("inf"):
            return None
        return time.monotonic() + d

    def _admit(self, job: _Job, wait_for_space: bool) -> Future:
        reason = None
        with self._lock:
            while True:
                if self._dead is not None:
                    reason, err = "down", SchedulerDown(
                        f"scheduler executor is down: {self._dead!r}"
                    )
                    break
                if self._closed:
                    reason, err = "shutdown", SchedulerDown(
                        "scheduler is shutting down"
                    )
                    break
                if len(self._queue) < self._queue_depth:
                    self._queue.append(job)
                    self.stats["requests"] += 1
                    depth = len(self._queue)
                    self._cond.notify_all()
                    break
                if not wait_for_space:
                    reason, err = "queue_full", QueueFull(
                        f"admission queue full ({self._queue_depth})"
                    )
                    break
                self._cond.wait(0.05)
            if reason is not None:
                self.stats["rejected"] += 1
        if reason is not None:
            metrics.count("sched.rejected", reason=reason)
            raise err
        metrics.gauge_set("sched.queue_depth", depth)
        return job.future

    # -- the synchronous offline face ---------------------------------------

    def verify_many(
        self, witnesses: Sequence[Tuple[bytes, Sequence[bytes]]]
    ) -> np.ndarray:
        """(n,) bool verdicts for a span of (root, nodes) witnesses, pushed
        through the SAME admission/assembly/executor path the server uses —
        the offline API for bench.py, the spec runner, and tests. Blocks on
        queue space instead of rejecting (offline callers want completion,
        not load shedding) and applies no deadline."""
        if threading.current_thread() is self._thread:
            raise RuntimeError(
                "verify_many called from the executor thread (deadlock)"
            )
        futs = [
            self.submit_witness(
                root, nodes, deadline_s=float("inf"), wait_for_space=True
            )
            for root, nodes in witnesses
        ]
        return np.fromiter(
            (bool(f.result()) for f in futs), bool, count=len(futs)
        )

    def accepts_witness(self) -> bool:
        """Can the CURRENT thread route a witness verification through this
        scheduler? False on the executor thread itself (submitting from it
        would deadlock: it is the only consumer) and once the scheduler is
        down or draining — callers fall back to the direct engine path."""
        if threading.current_thread() is self._thread:
            return False
        with self._lock:
            return self._dead is None and not self._closed

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        """Liveness surface for `/healthz` (engine_api/server.py)."""
        with self._lock:
            depth = len(self._queue)
            dead = self._dead
        alive = dead is None and self._thread.is_alive()
        out = {
            "queue_depth": depth,
            "executor_alive": alive,
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
        }
        if dead is not None:
            out["error"] = repr(dead)
        return out

    def stats_snapshot(self) -> dict:
        with self._lock:
            st = dict(self.stats)
        b = st["batches"]
        st["mean_batch"] = round(st["batched_requests"] / b, 2) if b else 0.0
        return st

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admission; `drain=True` lets the executor finish everything
        already queued before it exits, `drain=False` fails the queue fast.
        Idempotent."""
        with self._lock:
            self._closed = True
            dropped = [] if drain else list(self._queue)
            if not drain:
                self._queue.clear()
            self._cond.notify_all()
        for job in dropped:
            job.future.set_exception(
                SchedulerDown("scheduler shut down before execution")
            )
        self._thread.join(timeout)
        metrics.gauge_set("sched.queue_depth", 0)

    # -- executor ------------------------------------------------------------

    def _run(self) -> None:
        batch: List[_Job] = []
        try:
            while True:
                batch = self._next_batch()
                if batch is None:
                    return
                self._execute(batch)
                batch = []
        except BaseException as e:  # systemic: engine/internal failure
            self._die(e, batch or [])

    def _next_batch(self) -> Optional[List[_Job]]:
        with self._lock:
            while True:
                self._expire_locked()
                if self._queue:
                    break
                if self._closed:
                    return None
                self._cond.wait()
            head = self._queue.pop(0)
            if head.kind == _SERIAL:
                batch = [head]
            else:
                batch = self._assemble_locked(head)
            depth = len(self._queue)
            self._cond.notify_all()  # wake submitters waiting for space
        metrics.gauge_set("sched.queue_depth", depth)
        return batch

    def _assemble_locked(self, head: _Job) -> List[_Job]:
        """Coalesce same-bucket witness jobs behind `head` under the
        max_batch / max_wait policy. Caller holds `_lock`; the cond wait
        releases it so submitters keep admitting while we wait."""
        batch = [head]
        wait_until = head.admitted + self._max_wait_s
        while True:
            i = 0
            while i < len(self._queue) and len(batch) < self._max_batch:
                j = self._queue[i]
                if j.kind == _WITNESS and j.bucket == head.bucket:
                    batch.append(self._queue.pop(i))
                else:
                    i += 1
            if len(batch) >= self._max_batch or self._closed:
                break
            now = time.monotonic()
            if now >= wait_until:
                break
            self._cond.wait(wait_until - now)
        return batch

    def _shed_expired(self, job: _Job) -> None:
        """Deadline shed at execution time: one place keeps the stats
        snapshot and the `sched.rejected` metric in agreement (the soak
        gate and bench artifacts assert on the snapshot)."""
        with self._lock:
            self.stats["rejected"] += 1
        metrics.count("sched.rejected", reason="deadline")
        job.future.set_exception(
            DeadlineExpired("deadline expired while queued")
        )

    def _expire_locked(self) -> None:
        """Fail queued jobs whose deadline has passed (without executing)."""
        now = time.monotonic()
        live: List[_Job] = []
        expired: List[_Job] = []
        for j in self._queue:
            (expired if j.deadline is not None and now > j.deadline else live).append(j)
        if not expired:
            return
        self._queue[:] = live
        self.stats["rejected"] += len(expired)
        for j in expired:
            # set_exception never raises here: these futures have no
            # waiter-side cancellation path
            j.future.set_exception(
                DeadlineExpired("deadline expired while queued")
            )
            metrics.count("sched.rejected", reason="deadline")

    def _execute(self, batch: List[_Job]) -> None:
        now = time.monotonic()
        for j in batch:
            metrics.observe_hist("sched.queue_wait_seconds", now - j.admitted)
        if batch[0].kind == _SERIAL:
            self._execute_serial(batch[0])
        else:
            self._execute_witness(batch)

    def _execute_serial(self, job: _Job) -> None:
        metrics.count("sched.batches", lane="serial")
        with self._lock:
            self.stats["serial_jobs"] += 1
        if job.deadline is not None and time.monotonic() > job.deadline:
            self._shed_expired(job)
            return
        try:
            result = job.fn()
        except Exception as e:  # request-scoped: the job failed, not us
            job.future.set_exception(e)
            return
        job.future.set_result(result)

    def _execute_witness(self, batch: List[_Job]) -> None:
        now = time.monotonic()
        jobs = []
        for j in batch:
            if j.deadline is not None and now > j.deadline:
                self._shed_expired(j)
            else:
                jobs.append(j)
        if not jobs:
            return
        n = len(jobs)
        total = sum(j.nbytes for j in jobs)
        padded = _pow2ceil(total)
        # the engine/device dispatch this scheduler exists for: one
        # verify_batch over the whole coalesced bucket. An exception here
        # is systemic (malformed witnesses yield False verdicts, and the
        # engine falls back device->native internally), so it propagates
        # to _run and takes the executor down — requests fail fast rather
        # than silently retrying into a broken engine.
        verdicts = self._resolve_engine().verify_batch(
            [(j.root, j.nodes) for j in jobs]
        )
        for j, ok in zip(jobs, verdicts):
            j.future.set_result(bool(ok))
        metrics.observe_hist("sched.batch_size", n, buckets=_BATCH_BUCKETS)
        metrics.count("sched.batches", lane="witness")
        metrics.gauge_set(
            "sched.padding_waste", round(1.0 - total / padded, 4) if padded else 0.0
        )
        if n > 1:
            metrics.count("sched.coalesced_requests", n)
        with self._lock:
            st = self.stats
            st["batches"] += 1
            st["batched_requests"] += n
            if n > 1:
                st["coalesced"] += n
            if n > st["max_batch_seen"]:
                st["max_batch_seen"] = n

    def _resolve_engine(self):
        if self._engine is None:
            from phant_tpu.stateless import shared_witness_engine

            self._engine = shared_witness_engine()
        return self._engine

    def _die(self, exc: BaseException, batch: List[_Job]) -> None:
        log.error("scheduler executor crashed: %r", exc, exc_info=exc)
        metrics.count("sched.executor_crashes")
        with self._lock:
            self._dead = exc
            victims = batch + self._queue
            self._queue = []
            self._cond.notify_all()
        for j in victims:
            if not j.future.done():
                j.future.set_exception(
                    SchedulerDown(f"scheduler executor crashed: {exc!r}")
                )
        metrics.gauge_set("sched.queue_depth", 0)
