"""Mesh-sharded serving execution: one pipelined executor per device.

The continuous-batching scheduler (serving/scheduler.py) assembles
shape-bucketed witness batches — but until this module every assembled
batch executed through ONE executor against ONE engine on ONE device,
while `phant_tpu/parallel/mesh.py` already proved near-linear weak scaling
for the sharded witness/ecrecover kernels. `MeshExecutorPool` closes that
gap for the SERVING path:

* **N executors, one per mesh device**, each owning a `WitnessEngine`
  pinned to that device (`device_index=i`, ops/witness_engine.py): the
  engine's intern table and its novel-node device dispatches live on one
  chip. Each executor runs the PR-5 two-phase protocol as a depth-bounded
  software pipeline in its own thread — begin (pack + async dispatch)
  batch N+1, then resolve batch N — so host packing on lane A overlaps
  device compute on lanes A..N simultaneously.
* **Bucket-affinity routing** — a STABLE hash (splitmix64 over the shape
  bucket) maps each bucket to a home device, so a given witness shape
  keeps hitting the same device's intern table across batches and
  restarts. This is what preserves the cross-block node reuse the
  Patricia-trie analysis (PAPERS.md 2408.14217) quantifies: hit rate is a
  property of the TABLE, and affinity keeps the table warm. When the home
  device's backlog exceeds `spill_depth`, the batch spills to the
  least-loaded device instead — under single-bucket saturation spillover
  IS the load balancer (a re-hash on a cold table costs less than an
  idle mesh), and the per-device dispatch counters make the tradeoff
  visible.
* **Megabatch dispatch** (`dispatch="megabatch"`) — when one bucket fills
  the assembler's whole `max_batch`, the pool can instead dispatch the
  batch as ONE device-sharded kernel call over the whole mesh
  (parallel/mesh.py witness_verify_fused_sharded): the fused cold path,
  no memoization, every device computing one slice of the same batch.
  That trades the intern tables for full-mesh utilization — right when
  the backlog is deep and novel-dense, wrong for steady-state reuse-heavy
  traffic, which is why it is a mode, not the default. Unsupported
  batches (oversized nodes, non-power-of-two mesh, no jax devices) fall
  back to affinity routing.
* **Crash semantics** match the scheduler's: any executor crash marks the
  WHOLE scheduler down (`on_crash` -> `_die`), and every lane abandons
  its dispatched-but-unresolved handles through `engine.abandon_batch`
  so no engine leaks in-flight leases (a leaked lease defers generation
  flushes forever — the PR-5 review lesson, now per device).
* **Prewarm** — pool start compiles the sharded serving executables once
  (parallel/mesh.py prewarm_sharded, via the AOT executable memo) when
  the device backend is live, so the process-global compile-cache
  suspension windows fire at boot instead of per-dispatch mid-traffic.

Observability: `sched.device_queue_depth{device=}` /
`sched.device_dispatch{device=}` / `sched.device_stall` /
`sched.mesh_megabatches` metrics, and every batch/stall/crash record the
scheduler emits for a mesh batch carries the `device` that ran it.

Thread-safety: one lock (`_lock`) + its Condition guard the queues,
per-device load counts, and lifecycle flags; `*_locked` helpers touch
them. Engine calls, metric publishes, and the scheduler callbacks all run
OUTSIDE the lock (the engine and registry carry their own locks — same
discipline as scheduler.py).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from phant_tpu.obs import critpath
from phant_tpu.obs.busy import BusyAccountant
from phant_tpu.utils.trace import metrics

log = logging.getLogger("phant_tpu.serving.mesh")

_M64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer: a stable, well-distributed 64-bit mix."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return (x ^ (x >> 31)) & _M64


def affinity_device(bucket: int, n_devices: int) -> int:
    """The stable bucket -> home-device map. Pure and process-independent
    (no PYTHONHASHSEED dependence): the same bucket lands on the same
    device across batches, restarts, and hosts — the property the
    per-device intern tables' hit rates ride on. Buckets are powers of
    two, so the raw value is mixed first (a plain modulo would alias
    every bucket of one residue class onto one device)."""
    if n_devices <= 1:
        return 0
    return _mix64(int(bucket)) % n_devices


class MegabatchUnsupported(Exception):
    """This batch cannot take the whole-mesh fused path; route by
    affinity instead (oversized nodes, non-pow2 mesh, jax absent)."""


def _default_engine_factory(index: int):
    """Per-device engine, sized exactly like the process-shared one
    (stateless.shared_witness_engine) but pinned to mesh device `index`."""
    import os

    from phant_tpu.ops.witness_engine import WitnessEngine

    return WitnessEngine(
        max_nodes=int(os.environ.get("PHANT_WITNESS_CACHE", 1 << 20)),
        device_batch_floor=int(os.environ.get("PHANT_TPU_MIN_KECCAK", -1)),
        device_index=index,
    )


def _default_root_engine_factory(index: int):
    """Per-device ROOT engine (ops/root_engine.py), pinned to mesh device
    `index`: a root batch routed to this lane merges + hashes on the
    lane's own chip — the post-root twin of the pinned witness engine."""
    from phant_tpu.ops.root_engine import RootEngine

    return RootEngine(device_index=index)


def _default_sig_engine_factory(index: int):
    """Per-device SIG engine (ops/sig_engine.py), pinned to mesh device
    `index`: a sender-recovery batch routed to this lane runs its merged
    ecrecover on the lane's own chip — the sig twin of the pinned
    witness/root engines."""
    from phant_tpu.ops.sig_engine import SigEngine

    return SigEngine(device_index=index)


def _abandon(engine, handle) -> None:
    """Best-effort lease release on a crash path — the scheduler's helper,
    imported lazily (scheduler.py is always loaded before a pool exists;
    a top-level import would be the one cycle in the package)."""
    from phant_tpu.serving.scheduler import _abandon_handle

    _abandon_handle(engine, handle)


def _engine_stats(engine) -> Optional[dict]:
    snap = getattr(engine, "stats_snapshot", None)
    if snap is None:
        return None
    try:
        return snap()
    except Exception:
        return None


class _PoolDead(Exception):
    """Internal: another lane crashed; this lane must clean up and exit."""


class MeshExecutorPool:
    """N per-device pipelined executors behind the verification scheduler.

    The scheduler keeps global admission, tenant-fair head pick, and batch
    assembly; only DISPATCH fans out here. `submit()` routes one assembled
    same-bucket batch to a device lane (affinity + spillover) and blocks
    for backpressure when every lane is full — the scheduler's admission
    queue, not a hidden pool queue, is where overload must land.

    `engine` shares ONE engine across all lanes (the two-phase API accepts
    any handle interleaving, so this is sound — one intern table, no
    affinity benefit); the default builds one pinned engine per device
    (`engine_factory`). Callbacks (`on_done`/`on_stage`/`on_skip`/
    `on_expired`/`on_crash`) are the scheduler's completion, stage-
    tracking, deadline-shed, and death hooks; all fire on pool threads.
    """

    def __init__(
        self,
        n_devices: int,
        *,
        pipeline_depth: int = 2,
        spill_depth: int = 2,
        dispatch: str = "affinity",
        max_batch: int = 128,
        backlog_k: int = 0,
        prefetch: bool = True,
        engine: Optional[object] = None,
        engine_factory: Optional[Callable[[int], object]] = None,
        root_engine_factory: Optional[Callable[[int], object]] = None,
        sig_engine_factory: Optional[Callable[[int], object]] = None,
        on_done: Callable = None,
        on_stage: Callable = None,
        on_skip: Callable = None,
        on_expired: Callable = None,
        on_crash: Callable = None,
        prewarm: bool = True,
    ):
        if n_devices < 1:
            raise ValueError(f"mesh pool needs >= 1 device, got {n_devices}")
        if dispatch not in ("affinity", "megabatch"):
            raise ValueError(f"mesh dispatch must be affinity|megabatch, got {dispatch!r}")
        self._n = n_devices
        self._depth = max(1, pipeline_depth)
        self._spill = max(1, spill_depth)
        # hard per-lane bound: queued + begun-not-finished. Above it the
        # submitter waits — backpressure flows to the admission queue.
        self._bound = self._spill + self._depth
        self._dispatch_mode = dispatch
        self._max_batch = max_batch
        self._backlog_k = max(0, backlog_k)
        # per-lane prefetch stage (PR 9): with a two-phase engine, the
        # lane runs the witness decode + advisory novelty pre-scan
        # (engine.prefetch_batch) before pack — on the lane thread, which
        # is exactly when the lane's PREVIOUS batch is computing on its
        # device (dispatch) or resolving, so the decode hides under them
        self._prefetch = prefetch and self._depth > 1
        if engine_factory is None:
            if engine is not None:
                engine_factory = lambda _i: engine
            else:
                engine_factory = _default_engine_factory
        self._engines = [engine_factory(i) for i in range(self._n)]
        # root lane: one pinned RootEngine per device, built LAZILY on the
        # first root batch a lane sees (construction may touch jax) and
        # only ever from its own lane thread — no lock needed
        self._root_factory = root_engine_factory or _default_root_engine_factory
        self._root_engines: List[Optional[object]] = [None] * self._n
        # sig lane: one pinned SigEngine per device, same lazy lane-thread
        # construction discipline as the root engines above
        self._sig_factory = sig_engine_factory or _default_sig_engine_factory
        self._sig_engines: List[Optional[object]] = [None] * self._n
        self._on_done = on_done or (lambda *a: None)
        self._on_stage = on_stage or (lambda *a: None)
        self._on_skip = on_skip or (lambda *a: None)
        self._on_expired = on_expired or (lambda *a: None)
        self._on_crash = on_crash or (lambda *a: None)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # per-device state, all guarded by _lock
        self._queues: List[List[dict]] = [[] for _ in range(self._n)]
        self._inflight_n = [0] * self._n  # taken-but-unfinished batches
        self._dispatches = [0] * self._n
        self._served = [0] * self._n
        self._spills = 0
        self._megabatches = 0
        self._prefetched = 0
        self._closed = False
        self._dead: Optional[BaseException] = None
        self._mega_mesh = None  # memoized (mesh, ok) probe for megabatch
        # per-lane device-busy accounting (obs/busy.py): each lane
        # integrates its own [begin, resolve] union; megabatches occupy
        # every chip at once and ride a dedicated device="mesh" series.
        # Same switch as the critpath rollup (PHANT_OBS_ATTRIBUTION).
        busy_on = critpath.enabled()
        self._busy = [
            BusyAccountant(str(i), enabled=busy_on) for i in range(self._n)
        ]
        self._mega_busy = BusyAccountant("mesh", enabled=busy_on)
        self._threads = [
            threading.Thread(
                target=self._run_executor,
                args=(i,),
                name=f"phant-mesh-exec-{i}",
                daemon=True,
            )
            for i in range(self._n)
        ]
        for t in self._threads:
            t.start()
        metrics.gauge_set("sched.mesh_devices", self._n)
        if prewarm:
            threading.Thread(
                target=self._prewarm, name="phant-mesh-prewarm", daemon=True
            ).start()

    # -- routing -------------------------------------------------------------

    def _load_locked(self, d: int) -> int:
        return len(self._queues[d]) + self._inflight_n[d]

    def submit(self, jobs: Sequence, batch_id: int, picked: float) -> Optional[int]:
        """Route one assembled same-bucket batch to a device lane; returns
        the device index, or None when the pool is dead (the caller raises
        SchedulerDown). Blocks while every lane is at its bound — the
        wait is exported as `sched.device_stall`, the mesh twin of
        `sched.pipeline_stall`."""
        bucket = jobs[0].bucket
        item = {"jobs": list(jobs), "batch_id": batch_id, "picked": picked}
        # immutable pool shape, read lock-free (write-once in __init__ —
        # the locked regions below only ever see these locals)
        n, spill, bound = self._n, self._spill, self._bound
        home = affinity_device(bucket, n)
        t0 = time.perf_counter()
        with self._lock:
            while True:
                if self._dead is not None:
                    return None
                d = home
                if self._load_locked(d) >= spill:
                    # home lane is backed up: spill to the least-loaded
                    # device (ties break on the lowest index — stable)
                    d = min(range(n), key=self._load_locked)
                if self._load_locked(d) < bound:
                    break
                self._cond.wait(0.05)
            if d != home:
                self._spills += 1
            self._queues[d].append(item)
            self._dispatches[d] += 1
            depth = len(self._queues[d])
            self._cond.notify_all()
        metrics.observe("sched.device_stall", time.perf_counter() - t0)
        metrics.count("sched.device_dispatch", device=str(d))
        metrics.gauge_set("sched.device_queue_depth", depth, device=str(d))
        return d

    # -- megabatch (whole-mesh fused dispatch) -------------------------------

    def backlog_wanted(self) -> bool:
        """Would `megabatch_wanted` ever read a backlog count? The
        scheduler's same-bucket backlog scan walks every queued job
        under the global lock — it must only run when the trigger can
        actually consume it (megabatch mode with k > 0), never on the
        default affinity hot path."""
        return self._dispatch_mode == "megabatch" and self._backlog_k > 0

    def megabatch_wanted(self, n_jobs: int, backlog: int = 0) -> str:
        """Should this single-bucket batch take the whole-mesh fused
        path? Returns a truthy REASON ("full" / "backlog") or "".

        * "full" — `megabatch` mode and the bucket FILLED the assembler
          (`max_batch` same-shape jobs at once): the pre-trigger
          behavior.
        * "backlog" — `backlog_k > 0` and the queued same-bucket work
          (this batch plus `backlog` still-queued same-bucket jobs) is
          >= mesh_width x k: sustained same-shape overload engages
          fusion WITHOUT the operator sizing max_batch
          (`--sched-megabatch-backlog-k`; counted by the scheduler in
          `sched.megabatch_backlog_triggers`)."""
        if self._dispatch_mode != "megabatch":
            return ""
        if n_jobs >= max(self._max_batch, self._n):
            return "full"
        if self._backlog_k > 0 and n_jobs + backlog >= self._n * self._backlog_k:
            return "backlog"
        return ""

    def _megabatch_mesh(self):
        """The whole-mesh Mesh for fused dispatch, probed once. Raises
        MegabatchUnsupported (memoized as failure) when jax cannot supply
        the devices or the mesh size is not a power of two (the fused
        pack pads node counts to powers of two; a non-pow2 mesh cannot
        evenly shard them)."""
        if self._mega_mesh is None:
            ok, mesh = False, None
            if self._n & (self._n - 1) == 0:
                try:
                    from phant_tpu.parallel.mesh import make_mesh

                    mesh = make_mesh(self._n)
                    ok = True
                except Exception:
                    log.warning(
                        "megabatch disabled: no %d-device mesh", self._n,
                        exc_info=True,
                    )
            self._mega_mesh = (ok, mesh)
        ok, mesh = self._mega_mesh
        if not ok:
            raise MegabatchUnsupported(f"no {self._n}-device mesh")
        return mesh

    def run_megabatch(self, jobs: Sequence, batch_id: int):
        """(verdicts, record): ONE device-sharded fused verification of the
        whole batch across the mesh (witness_verify_fused_sharded — cold
        path, no intern tables). Runs on the CALLER's thread: the dispatch
        occupies every device, so there is nothing to overlap with.
        Raises MegabatchUnsupported when this batch cannot take the fused
        path; the caller falls back to affinity routing."""
        mesh = self._megabatch_mesh()
        from phant_tpu.ops.witness_jax import (
            WITNESS_MAX_CHUNKS,
            _pow2ceil,
            pack_witness_fused,
            roots_to_words,
        )
        from phant_tpu.parallel.mesh import witness_verify_fused_sharded

        node_lists = [list(j.nodes) for j in jobs]
        try:
            blob, meta16 = pack_witness_fused(
                node_lists, WITNESS_MAX_CHUNKS, min_pad=self._n
            )
        except ValueError as e:
            # oversized node / uint16 overflow: the kernel cannot express
            # this batch — not an executor failure
            raise MegabatchUnsupported(str(e)) from None
        # pow2-pad the blob byte axis too, so repeat megabatches land on a
        # small set of compiled shapes (the AOT executable memo keys on
        # shape — an unpadded ragged blob would compile per batch)
        padded = np.zeros(_pow2ceil(len(blob)), np.uint8)
        padded[: len(blob)] = blob
        roots = roots_to_words([j.root for j in jobs])
        t0 = time.monotonic()
        # device-busy: the fused dispatch occupies the WHOLE mesh —
        # integrated on the device="mesh" series, not any one lane's
        self._mega_busy.begin()
        try:
            out = witness_verify_fused_sharded(
                mesh,
                padded,
                meta16,
                roots,
                max_chunks=WITNESS_MAX_CHUNKS,
                n_blocks=len(jobs),
            )
            # the verdict readback is this batch's resolve — an honest sync
            # (HOSTSYNC's cross-module taint does not reach here; comment,
            # not a dead disable annotation)
            verdicts = np.asarray(out)
        finally:
            self._mega_busy.end()
        with self._lock:
            self._megabatches += 1
            n_mega = self._megabatches
        metrics.count("sched.mesh_megabatches")
        metrics.count("sched.device_dispatch", device="mesh")
        record = {
            "batch_id": batch_id,
            "batch_size": len(jobs),
            "bucket_bytes": jobs[0].bucket,
            "stage": "dispatch",
            "backend": "mesh_fused",
            "device": "mesh",
            "mesh_devices": self._n,
            "resolve_ms": round((time.monotonic() - t0) * 1e3, 3),
        }
        log.debug("megabatch %d: %d blocks over %d devices", n_mega, len(jobs), self._n)
        return verdicts, record

    # -- per-device executor -------------------------------------------------

    def _live_jobs(self, item: dict) -> Optional[list]:
        """Deadline re-check at pickup time on the LANE: a batch can sit in
        a backed-up lane past its jobs' deadlines, and an expired job must
        shed (its waiter is gone) rather than spend engine work — the same
        contract as the scheduler's post-slot-wait re-check."""
        now = time.monotonic()
        live = [j for j in item["jobs"] if j.deadline is None or now <= j.deadline]
        if len(live) != len(item["jobs"]):
            for j in item["jobs"]:
                if j.deadline is not None and now > j.deadline:
                    self._on_expired(j)
        return live or None

    def _root_engine_for(self, i: int):
        """The lane's pinned RootEngine, built lazily on its first root
        batch (only ever touched from lane thread `i`)."""
        eng = self._root_engines[i]
        if eng is None:
            eng = self._root_engines[i] = self._root_factory(i)
        return eng

    def _sig_engine_for(self, i: int):
        """The lane's pinned SigEngine, built lazily on its first sig
        batch (only ever touched from lane thread `i`)."""
        eng = self._sig_engines[i]
        if eng is None:
            eng = self._sig_engines[i] = self._sig_factory(i)
        return eng

    def _run_executor(self, i: int) -> None:
        engine = self._engines[i]
        # immutable pipeline depth, read lock-free (write-once in __init__)
        depth_cap = self._depth
        two_phase = depth_cap > 1 and hasattr(engine, "begin_batch")
        # [(item, handle, engine)] begun, unresolved — a root batch's
        # handle belongs to the lane's RootEngine, a witness batch's to
        # the lane's WitnessEngine; crash paths abandon each against ITS
        # engine
        inflight: List[tuple] = []
        cur: Optional[dict] = None
        stage = "pack"
        try:
            while True:
                item = None
                with self._lock:
                    while True:
                        if self._dead is not None:
                            # carry the crash out of the locked region
                            raise _PoolDead(self._dead)
                        if self._queues[i] and (
                            not two_phase or len(inflight) < depth_cap
                        ):
                            item = self._queues[i].pop(0)
                            self._inflight_n[i] += 1
                            break
                        if inflight:
                            break  # nothing takeable: drain own pipeline
                        if self._closed:
                            return
                        self._cond.wait(0.1)
                    depth = len(self._queues[i])
                    self._cond.notify_all()  # a slot freed: wake submitters
                metrics.gauge_set(
                    "sched.device_queue_depth", depth, device=str(i)
                )
                if item is not None:
                    jobs = self._live_jobs(item)
                    if jobs is None:
                        self._finish_accounting(i)
                        self._on_skip(item["batch_id"])
                        continue
                    item["jobs"] = jobs
                    # lazy import like every scheduler symbol here (the
                    # package-cycle discipline, see _abandon)
                    from phant_tpu.serving.scheduler import _ROOT, _SIG

                    is_root = jobs[0].kind == _ROOT
                    is_sig = jobs[0].kind == _SIG
                    if is_root:
                        eng = self._root_engine_for(i)
                    elif is_sig:
                        eng = self._sig_engine_for(i)
                    else:
                        eng = engine
                    cur, stage = item, "pack"
                    if two_phase or ((is_root or is_sig) and depth_cap > 1):
                        # the SAME payload list goes to prefetch and
                        # begin: plan identity is the engine's match check
                        # (witness tuples / root HashPlans / SigRows alike)
                        if is_root:
                            wits = [j.plan for j in jobs]
                        elif is_sig:
                            wits = [j.rows for j in jobs]
                        else:
                            wits = [(j.root, j.nodes) for j in jobs]
                        plan = None
                        pf = getattr(eng, "prefetch_batch", None)
                        if self._prefetch and pf is not None:
                            stage = "prefetch"
                            self._on_stage(item["batch_id"], "prefetch", i)
                            t0 = time.perf_counter()
                            plan = pf(wits)
                            item["prefetch_ms"] = round(
                                (time.perf_counter() - t0) * 1e3, 3
                            )
                            metrics.count("sched.prefetch_batches")
                            with self._lock:
                                self._prefetched += 1
                        stage = "pack"
                        self._on_stage(item["batch_id"], "pack", i)
                        t0 = time.perf_counter()
                        try:
                            if plan is not None:
                                handle = eng.begin_batch(
                                    wits, prefetch=plan
                                )
                            else:
                                handle = eng.begin_batch(wits)
                        except BaseException:
                            # a lane death here reaches _die, which never
                            # sees lane-local plans: return the staging
                            # leases before propagating (idempotent; a
                            # consumed/released plan is a no-op)
                            if plan is not None:
                                plan.release()
                            raise
                        item["pack_ms"] = round(
                            (time.perf_counter() - t0) * 1e3, 3
                        )
                        # device-busy: dispatch enqueued on this lane's
                        # chip; the resolve below (or a crash-path
                        # cleanup) closes the interval
                        self._busy[i].begin()
                        inflight.append((item, handle, eng))
                        stage = "dispatch"
                        self._on_stage(item["batch_id"], "dispatch", i)
                        cur = None
                        with self._lock:
                            more = bool(self._queues[i]) and len(inflight) < depth_cap
                        if more:
                            # overlap: begin the NEXT batch while this
                            # one's device dispatch computes
                            continue
                    else:
                        stage = "dispatch"
                        self._on_stage(item["batch_id"], "dispatch", i)
                        self._busy[i].begin()
                        try:
                            if is_root:
                                verdicts, record = self._roots_inline(eng, item)
                            elif is_sig:
                                verdicts, record = self._sigs_inline(eng, item)
                            else:
                                verdicts, record = self._verify_inline(eng, item)
                        finally:
                            self._busy[i].end()
                        cur = None
                        self._finish(i, item, verdicts, record)
                        continue
                if inflight:
                    item2, handle, eng2 = inflight.pop(0)
                    cur, stage = item2, "resolve"
                    self._on_stage(item2["batch_id"], "resolve", i)
                    t0 = time.monotonic()
                    try:
                        verdicts = eng2.resolve_batch(handle)
                    finally:
                        # the [begin, resolve] interval closes on the
                        # crash path too (the handle is abandoned there)
                        self._busy[i].end()
                    record = self._record_from_handle(handle, item2)
                    record["resolve_ms"] = round(
                        (time.monotonic() - t0) * 1e3, 3
                    )
                    cur = None
                    self._finish(i, item2, verdicts, record)
        except _PoolDead as dead:
            # another lane crashed: abandon this lane's handles (the
            # engines outlive the pool — leases must not leak; each open
            # busy interval closes with its handle) and fail the
            # begun-but-unresolved jobs nobody else knows about
            self._cleanup_inflight(inflight, dead.args[0], self._busy[i])
            return
        except BaseException as e:  # systemic: this lane crashed
            for it, h, hg in inflight:
                _abandon(hg, h)
                self._busy[i].end()
                if it is not cur:
                    self._fail_jobs(it["jobs"], e)
            # the crashing batch's jobs ride to scheduler._die via
            # on_crash (it fails their futures with the crash record)
            self._on_crash(e, cur["jobs"] if cur else [], stage, i)

    def _cleanup_inflight(self, inflight, exc, busy=None) -> None:
        for it, h, hg in inflight:
            _abandon(hg, h)
            if busy is not None:
                busy.end()
            self._fail_jobs(it["jobs"], exc)

    def _fail_jobs(self, jobs, exc) -> None:
        from phant_tpu.serving.scheduler import SchedulerDown

        for j in jobs:
            if not j.future.done():
                try:
                    j.future.set_exception(
                        SchedulerDown(f"mesh executor crashed: {exc!r}")
                    )
                except Exception:
                    pass  # lost the race to another failure path

    def _finish_accounting(self, i: int) -> None:
        with self._lock:
            self._inflight_n[i] -= 1
            self._cond.notify_all()

    def _finish(self, i: int, item: dict, verdicts, record: dict) -> None:
        record["device"] = i
        # stage timings measured on the lane thread ride the record so
        # the timeline's batch sub-slices (and critpath's tiling) see
        # the mesh path too — prefetch_ms used to be dropped here
        for key in ("pack_ms", "prefetch_ms"):
            if key in item:
                record.setdefault(key, item[key])
        with self._lock:
            self._inflight_n[i] -= 1
            self._served[i] += 1
            self._cond.notify_all()
        self._on_done(item["jobs"], verdicts, record, item["picked"], item["batch_id"])

    @staticmethod
    def _verify_inline(engine, item: dict):
        """Depth-1 (or no-begin_batch engine) lane execution: one fused
        verify_batch round trip, record from the engine-stats delta —
        sound per lane because each lane is its engine's only caller.
        The record builders are the SCHEDULER's (lazy import): record
        semantics must be identical at every depth and lane."""
        from phant_tpu.serving.scheduler import batch_record_from_stats

        jobs = item["jobs"]
        s0 = _engine_stats(engine)
        verdicts = engine.verify_batch([(j.root, j.nodes) for j in jobs])
        s1 = _engine_stats(engine)
        record = batch_record_from_stats(
            item["batch_id"], len(jobs), jobs[0].bucket, s0, s1
        )
        return verdicts, record

    @staticmethod
    def _lane_inline(engine, item: dict, payload, record_builder):
        """Depth-1 root/sig-lane execution: one fused begin+resolve
        against the lane's pinned engine (the root_many/sig_many shape)
        — one definition for both lanes; the callers supply the payload
        list and the scheduler's record builder."""
        jobs = item["jobs"]
        handle = engine.begin_batch(payload)
        results = engine.resolve_batch(handle)
        record = record_builder(
            handle, item["batch_id"], len(jobs), jobs[0].bucket
        )
        record["stage"] = "dispatch"
        return results, record

    def _roots_inline(self, engine, item: dict):
        from phant_tpu.serving.scheduler import root_record_from_handle

        return self._lane_inline(
            engine,
            item,
            [j.plan for j in item["jobs"]],
            root_record_from_handle,
        )

    def _sigs_inline(self, engine, item: dict):
        from phant_tpu.serving.scheduler import sig_record_from_handle

        return self._lane_inline(
            engine,
            item,
            [j.rows for j in item["jobs"]],
            sig_record_from_handle,
        )

    @staticmethod
    def _record_from_handle(handle, item: dict) -> dict:
        from phant_tpu.serving.scheduler import (
            _ROOT,
            _SIG,
            batch_record_from_handle,
            root_record_from_handle,
            sig_record_from_handle,
        )

        jobs = item["jobs"]
        if jobs and jobs[0].kind == _ROOT:
            builder = root_record_from_handle
        elif jobs and jobs[0].kind == _SIG:
            builder = sig_record_from_handle
        else:
            builder = batch_record_from_handle
        record = builder(handle, item["batch_id"], len(jobs), jobs[0].bucket)
        if "prefetch_ms" in item:
            record["prefetch_ms"] = item["prefetch_ms"]
        return record

    # -- lifecycle -----------------------------------------------------------

    def _prewarm(self) -> None:
        """Background boot prewarm: compile the sharded serving executables
        once (parallel/mesh.py prewarm_sharded) when the device backend is
        live, so no serving batch pays a cold shard_map compile — and the
        compile-cache suspension windows all fire before traffic."""
        try:
            from phant_tpu.backend import crypto_backend, jax_device_ok

            if crypto_backend() != "tpu" or not jax_device_ok():
                return
            from phant_tpu.parallel.mesh import make_mesh, prewarm_sharded

            compiled = prewarm_sharded(make_mesh(self._n))
            log.info("mesh prewarm: %d sharded executables compiled", compiled)
        except Exception:
            # prewarm is an optimization, never a liveness dependency
            log.warning("mesh prewarm failed", exc_info=True)

    def drain(self) -> None:
        """Block until every lane is idle (queues empty, nothing begun and
        unresolved) or the pool is dead — the serial mutation lane's
        exclusivity barrier and the graceful-shutdown wait."""
        n = self._n
        with self._lock:
            while self._dead is None and (
                any(self._queues[d] or self._inflight_n[d] for d in range(n))
            ):
                self._cond.wait(0.05)

    def kill(self, exc: BaseException) -> int:
        """Mark the pool dead (scheduler `_die`): queued-but-unbegun
        batches fail fast here; each lane thread abandons its OWN begun
        handles and fails their jobs when it observes the death. Returns
        how many queued jobs were failed fast. Idempotent."""
        with self._lock:
            if self._dead is None:
                self._dead = exc
            dropped: List[dict] = []
            for q in self._queues:
                dropped.extend(q)
                q.clear()
            self._cond.notify_all()
        n = 0
        for item in dropped:
            self._fail_jobs(item["jobs"], exc)
            n += len(item["jobs"])
        for d in range(self._n):
            metrics.gauge_set("sched.device_queue_depth", 0, device=str(d))
        return n

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop the lanes after the queues drain; `drain()` first for a
        graceful stop (the scheduler's shutdown path does)."""
        with self._lock:
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout)

    # -- introspection -------------------------------------------------------

    def alive(self) -> bool:
        with self._lock:
            dead = self._dead
        return dead is None and all(t.is_alive() for t in self._threads)

    def state(self) -> dict:
        """Per-device liveness + load for `/healthz` (the scheduler embeds
        this under `scheduler.mesh`)."""
        # thread liveness and the pool shape are lock-free reads (threads
        # list is write-once; is_alive is the interpreter's own state)
        alive_list = [t.is_alive() for t in self._threads]
        n = self._n
        # busy pct reads integrate to now (their own per-accountant locks;
        # taken OUTSIDE _lock, same discipline as every metric publish)
        busy = [self._busy[d].pct() for d in range(n)]
        with self._lock:
            per_device = {
                str(d): {
                    "alive": alive_list[d],
                    "queued": len(self._queues[d]),
                    "inflight": self._inflight_n[d],
                    "dispatches": self._dispatches[d],
                    "busy_pct": busy[d],
                }
                for d in range(n)
            }
            dead = self._dead
        out = {
            "devices": n,
            "dispatch": self._dispatch_mode,
            "prefetch": self._prefetch,
            "all_alive": dead is None and all(alive_list),
            "per_device": per_device,
        }
        if dead is not None:
            out["error"] = repr(dead)
        return out

    def stats(self) -> dict:
        n = self._n
        with self._lock:
            return {
                "devices": n,
                "dispatches": list(self._dispatches),
                "served": list(self._served),
                "spills": self._spills,
                "megabatches": self._megabatches,
                "prefetched_batches": self._prefetched,
            }

    def refresh_busy(self) -> None:
        """Re-integrate + republish every lane's (and the megabatch
        series') busy gauge — the pool half of the scheduler's
        refresh_busy_gauges."""
        for acct in self._busy:
            acct.pct()
        self._mega_busy.pct()

    def engines(self) -> list:
        """The per-lane engines (tests assert lease accounting on them)."""
        return list(self._engines)

    def lane_engines(self, kind: str = "witness") -> list:
        """Per-lane engine snapshot by lane kind: "witness" = the pinned
        WitnessEngines (always built), "root"/"sig" = the lazily-built
        pinned RootEngines/SigEngines with None for lanes whose first
        batch of that kind hasn't arrived. Replay's mesh fan-out test
        reads this to assert per-lane RESIDENT intern tables — segments
        sharded across lanes must populate each lane's own engine, not
        funnel through a shared one."""
        if kind == "witness":
            return list(self._engines)
        if kind == "root":
            return list(self._root_engines)
        if kind == "sig":
            return list(self._sig_engines)
        raise ValueError(f"unknown lane kind {kind!r}")
