"""RLP (Recursive Length Prefix) encoding/decoding.

A from-scratch implementation of Ethereum's canonical serialization format
(yellow-paper appendix B). The reference client consumes RLP through the
external `zig-rlp` dependency (reference: build.zig.zon:5-8, used throughout
src/types/*.zig); here it is a first-class module because every hot-loop
input (MPT node bodies, tx signing payloads, header hashes) is RLP, and the
TPU packer (phant_tpu/ops) needs byte-exact control over node encodings.

Items are `bytes` or (recursively) lists of items. Integers are encoded
big-endian with no leading zeros via :func:`encode_uint`.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple, Union

RLPItem = Union[bytes, List["RLPItem"]]

__all__ = [
    "encode",
    "encode_uint",
    "decode",
    "decode_uint",
    "RLPItem",
    "DecodeError",
]


class DecodeError(ValueError):
    """Raised on malformed or non-canonical RLP input."""


def encode_uint(value: int) -> bytes:
    """Minimal big-endian byte encoding of a non-negative integer (0 -> b'')."""
    if value < 0:
        raise ValueError("cannot RLP-encode negative integer")
    if value == 0:
        return b""
    return value.to_bytes((value.bit_length() + 7) // 8, "big")


def decode_uint(data: bytes) -> int:
    if data[:1] == b"\x00":
        raise DecodeError("non-canonical integer (leading zero)")
    return int.from_bytes(data, "big")


def _encode_length(length: int, short_offset: int) -> bytes:
    if length <= 55:
        return bytes([short_offset + length])
    length_bytes = encode_uint(length)
    return bytes([short_offset + 55 + len(length_bytes)]) + length_bytes


def encode(item: RLPItem) -> bytes:
    if isinstance(item, (bytes, bytearray, memoryview)):
        data = bytes(item)
        if len(data) == 1 and data[0] < 0x80:
            return data
        return _encode_length(len(data), 0x80) + data
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(sub) for sub in item)
        return _encode_length(len(payload), 0xC0) + payload
    if isinstance(item, int):
        # Convenience: ints encode as their minimal big-endian bytes.
        return encode(encode_uint(item))
    raise TypeError(f"cannot RLP-encode {type(item).__name__}")


_MAX_DEPTH = 64  # nesting cap; untrusted input must not drive Python recursion


def _decode_at(data: bytes, pos: int, depth: int = 0) -> Tuple[RLPItem, int]:
    if depth > _MAX_DEPTH:
        raise DecodeError("RLP nesting too deep")
    if pos >= len(data):
        raise DecodeError("unexpected end of input")
    prefix = data[pos]
    if prefix < 0x80:  # single byte
        return bytes([prefix]), pos + 1
    if prefix <= 0xB7:  # short string
        length = prefix - 0x80
        end = pos + 1 + length
        if end > len(data):
            raise DecodeError("string extends past end of input")
        payload = data[pos + 1 : end]
        if length == 1 and payload[0] < 0x80:
            raise DecodeError("non-canonical single byte")
        return payload, end
    if prefix <= 0xBF:  # long string
        lenlen = prefix - 0xB7
        if pos + 1 + lenlen > len(data):
            raise DecodeError("length extends past end of input")
        length_bytes = data[pos + 1 : pos + 1 + lenlen]
        if length_bytes[:1] == b"\x00":
            raise DecodeError("non-canonical length (leading zero)")
        length = int.from_bytes(length_bytes, "big")
        if length <= 55:
            raise DecodeError("non-canonical length (should be short form)")
        start = pos + 1 + lenlen
        end = start + length
        if end > len(data):
            raise DecodeError("string extends past end of input")
        return data[start:end], end
    if prefix <= 0xF7:  # short list
        length = prefix - 0xC0
        end = pos + 1 + length
        if end > len(data):
            raise DecodeError("list extends past end of input")
        return _decode_list_payload(data, pos + 1, end, depth), end
    # long list
    lenlen = prefix - 0xF7
    if pos + 1 + lenlen > len(data):
        raise DecodeError("length extends past end of input")
    length_bytes = data[pos + 1 : pos + 1 + lenlen]
    if length_bytes[:1] == b"\x00":
        raise DecodeError("non-canonical length (leading zero)")
    length = int.from_bytes(length_bytes, "big")
    if length <= 55:
        raise DecodeError("non-canonical length (should be short form)")
    start = pos + 1 + lenlen
    end = start + length
    if end > len(data):
        raise DecodeError("list extends past end of input")
    return _decode_list_payload(data, start, end, depth), end


def _decode_list_payload(data: bytes, start: int, end: int, depth: int) -> List[RLPItem]:
    items: List[RLPItem] = []
    pos = start
    while pos < end:
        item, pos = _decode_at(data, pos, depth + 1)
        if pos > end:
            raise DecodeError("item extends past end of list")
        items.append(item)
    return items


def decode(data: bytes, *, strict: bool = True) -> RLPItem:
    """Decode a single RLP item; with strict=True, trailing bytes are an error."""
    item, consumed = _decode_at(bytes(data), 0)
    if strict and consumed != len(data):
        raise DecodeError(f"trailing bytes after RLP item ({len(data) - consumed})")
    return item


def decode_prefix(data: bytes) -> Tuple[RLPItem, int]:
    """Decode the first RLP item, returning (item, bytes_consumed)."""
    return _decode_at(bytes(data), 0)
