"""EVM call/create messages, environment, and execution results.

Equivalent surface to the reference's Environment/Message
(reference: src/blockchain/types.zig:13-33) and MessageCallOutput
(reference: src/blockchain/vm.zig:560-566).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from phant_tpu.state.statedb import StateDB

# EVM revisions (the reference hardcodes EVMC_SHANGHAI with a TODO,
# src/blockchain/vm.zig:472; this framework dispatches per fork)
REVISION_SHANGHAI = 0
REVISION_CANCUN = 1
REVISION_PRAGUE = 2


@dataclass
class Environment:
    """Per-tx EVM environment (reference: src/blockchain/types.zig:13-25)."""

    state: "StateDB"
    origin: bytes = b"\x00" * 20
    coinbase: bytes = b"\x00" * 20
    block_number: int = 0
    gas_limit: int = 30_000_000
    gas_price: int = 0
    timestamp: int = 0
    prev_randao: bytes = b"\x00" * 32
    difficulty: int = 0
    base_fee: int = 0
    chain_id: int = 1
    block_hash_fn: Optional[Callable[[int], bytes]] = None  # fork BLOCKHASH
    revision: int = REVISION_SHANGHAI
    # EIP-4844 (Cancun): the tx's blob versioned hashes + block blob base fee
    blob_hashes: tuple = ()
    blob_base_fee: int = 0

    def get_block_hash(self, number: int) -> bytes:
        if self.block_hash_fn is None:
            return b"\x00" * 32
        return self.block_hash_fn(number)


@dataclass
class Message:
    """One call or create (reference: src/blockchain/types.zig:27-33)."""

    caller: bytes
    target: Optional[bytes]  # None => contract creation
    value: int
    data: bytes
    gas: int
    is_static: bool = False
    depth: int = 0
    # for CALLCODE/DELEGATECALL the executing address differs from code source
    code_address: Optional[bytes] = None
    salt: Optional[bytes] = None  # CREATE2
    # DELEGATECALL carries the parent's value for CALLVALUE but must not move
    # funds again (reference: vm.zig:444-466 only transfers for CALL kinds)
    transfers_value: bool = True


@dataclass
class ExecResult:
    """Frame outcome (reference: src/blockchain/vm.zig:560-566)."""

    success: bool
    gas_left: int
    output: bytes = b""
    error: Optional[str] = None
    create_address: Optional[bytes] = None

    @property
    def is_revert(self) -> bool:
        return not self.success and self.error == "revert"


class EVMError(Exception):
    """Exceptional halt: consumes all frame gas."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason
