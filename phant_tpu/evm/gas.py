"""Gas schedule (Shanghai revision) and memory-expansion accounting.

Constants mirror the reference's params (reference:
src/blockchain/params.zig:5-39) plus the opcode-level costs evmone applies
internally; collected here because this framework owns its interpreter.
"""

from __future__ import annotations

# --- intrinsic tx costs (reference: src/blockchain/params.zig:5-17) -------
TX_BASE_COST = 21_000
TX_DATA_COST_ZERO = 4
TX_DATA_COST_NONZERO = 16
TX_CREATE_COST = 32_000
TX_ACCESS_LIST_ADDRESS_COST = 2_400
TX_ACCESS_LIST_STORAGE_KEY_COST = 1_900

# --- EIP-2929 access costs -------------------------------------------------
COLD_ACCOUNT_ACCESS = 2_600
WARM_ACCOUNT_ACCESS = 100
COLD_SLOAD = 2_100
WARM_SLOAD = 100

# --- storage (EIP-2200 + 3529) --------------------------------------------
SSTORE_SET = 20_000
SSTORE_RESET = 2_900  # 5000 - COLD_SLOAD
SSTORE_SENTRY = 2_300
SSTORE_CLEARS_REFUND = 4_800  # EIP-3529

# --- create ----------------------------------------------------------------
CREATE_GAS = 32_000
CODE_DEPOSIT_PER_BYTE = 200
MAX_CODE_SIZE = 0x6000  # EIP-170 (reference: params.zig:30)
MAX_INITCODE_SIZE = 2 * MAX_CODE_SIZE  # EIP-3860
INITCODE_WORD_COST = 2  # EIP-3860

# --- calls -----------------------------------------------------------------
CALL_VALUE_GAS = 9_000
CALL_STIPEND = 2_300
NEW_ACCOUNT_GAS = 25_000
MAX_CALL_DEPTH = 1024  # reference: params.zig:33

# --- misc opcode costs ------------------------------------------------------
KECCAK256_GAS = 30
KECCAK256_WORD_GAS = 6
COPY_WORD_GAS = 3
LOG_GAS = 375
LOG_TOPIC_GAS = 375
LOG_DATA_GAS = 8
EXP_GAS = 10
EXP_BYTE_GAS = 50
SELFDESTRUCT_GAS = 5_000
MEMORY_GAS = 3
QUAD_COEFF_DIV = 512
REFUND_QUOTIENT = 5  # EIP-3529 (gas_used // 5 cap, reference: blockchain.zig:315)

GWEI = 10**9

U256_MAX = (1 << 256) - 1


def memory_cost(size_bytes: int) -> int:
    """Total cost of having `size_bytes` of memory (yellow paper C_mem)."""
    words = (size_bytes + 31) // 32
    return MEMORY_GAS * words + (words * words) // QUAD_COEFF_DIV


def copy_cost(length: int) -> int:
    return COPY_WORD_GAS * ((length + 31) // 32)


def intrinsic_gas(
    data: bytes,
    is_create: bool,
    access_list,
    init_code_len: int = 0,
    n_authorizations: int = 0,
) -> int:
    """Intrinsic cost before execution (reference:
    src/blockchain/blockchain.zig:355-377, incl. EIP-3860 word cost;
    EIP-7702 charges PER_EMPTY_ACCOUNT_COST per authorization tuple up
    front, refunded down to PER_AUTH_BASE_COST for existing authorities)."""
    gas = TX_BASE_COST
    for byte in data:
        gas += TX_DATA_COST_ZERO if byte == 0 else TX_DATA_COST_NONZERO
    if is_create:
        gas += TX_CREATE_COST
        gas += INITCODE_WORD_COST * ((init_code_len + 31) // 32)
    for _, keys in access_list:
        gas += TX_ACCESS_LIST_ADDRESS_COST
        gas += TX_ACCESS_LIST_STORAGE_KEY_COST * len(keys)
    gas += PER_EMPTY_ACCOUNT_COST * n_authorizations
    return gas

# --- Cancun (EIP-4844 / 1153 / 5656 / 7516; beyond the reference's
# Shanghai pin, src/blockchain/vm.zig:472) ---
TLOAD_GAS = 100
TSTORE_GAS = 100
BLOBHASH_GAS = 3
BLOBBASEFEE_GAS = 2
GAS_PER_BLOB = 1 << 17
TARGET_BLOB_GAS_PER_BLOCK = 3 * GAS_PER_BLOB
MAX_BLOB_GAS_PER_BLOCK = 6 * GAS_PER_BLOB
MIN_BLOB_BASE_FEE = 1
BLOB_BASE_FEE_UPDATE_FRACTION = 3_338_477

# Prague blob schedule (EIP-7691: throughput raised to 6 target / 9 max,
# steeper fee response)
PRAGUE_TARGET_BLOB_GAS_PER_BLOCK = 6 * GAS_PER_BLOB
PRAGUE_MAX_BLOB_GAS_PER_BLOCK = 9 * GAS_PER_BLOB
PRAGUE_BLOB_BASE_FEE_UPDATE_FRACTION = 5_007_716


def blob_schedule(fork_name: str) -> tuple:
    """(max_blob_gas, target_blob_gas, base_fee_update_fraction) for the
    active fork — EIP-7691 changed all three at Prague."""
    if fork_name in ("prague", "osaka"):
        return (
            PRAGUE_MAX_BLOB_GAS_PER_BLOCK,
            PRAGUE_TARGET_BLOB_GAS_PER_BLOCK,
            PRAGUE_BLOB_BASE_FEE_UPDATE_FRACTION,
        )
    return (
        MAX_BLOB_GAS_PER_BLOCK,
        TARGET_BLOB_GAS_PER_BLOCK,
        BLOB_BASE_FEE_UPDATE_FRACTION,
    )


def fake_exponential(factor: int, numerator: int, denominator: int) -> int:
    """EIP-4844 blob base-fee curve: factor * e**(numerator/denominator)
    by Taylor expansion, exact integer arithmetic (consensus-critical)."""
    i = 1
    output = 0
    numerator_accum = factor * denominator
    while numerator_accum > 0:
        output += numerator_accum
        numerator_accum = (numerator_accum * numerator) // (denominator * i)
        i += 1
    return output // denominator


def blob_base_fee(
    excess_blob_gas: int, fraction: int = BLOB_BASE_FEE_UPDATE_FRACTION
) -> int:
    return fake_exponential(MIN_BLOB_BASE_FEE, excess_blob_gas, fraction)


def calc_excess_blob_gas(
    parent_excess: int,
    parent_blob_gas_used: int,
    target: int = TARGET_BLOB_GAS_PER_BLOCK,
) -> int:
    total = parent_excess + parent_blob_gas_used
    if total < target:
        return 0
    return total - target


# --- Prague EIP-7702 set-code transactions ---
PER_AUTH_BASE_COST = 12_500  # floor cost per authorization tuple
PER_EMPTY_ACCOUNT_COST = 25_000  # charged up front per tuple (intrinsic)
DELEGATION_PREFIX = b"\xef\x01\x00"  # designator: 0xef0100 || address
DELEGATION_MARKER = b"\xef\x01"  # what EXTCODE* see on a delegated account
# keccak256(DELEGATION_MARKER), precomputed: EXTCODEHASH of any delegated
# account (a constant; recomputing it per opcode would be waste)
DELEGATION_MARKER_HASH = bytes.fromhex(
    "eadcdba66a79ab5dce91622d1d75c8cff5cff0b96944c3bf1072cd08ce018329"
)


# --- Prague EIP-7623 calldata floor pricing ---
STANDARD_TOKEN_COST = 4
TOTAL_COST_FLOOR_PER_TOKEN = 10


def calldata_tokens(data: bytes) -> int:
    """EIP-7623 token count: 1 per zero byte, 4 per nonzero byte (so the
    pre-7623 calldata charge is exactly STANDARD_TOKEN_COST per token)."""
    zeros = data.count(0)  # C-speed; this runs per tx in the block loop
    return zeros + 4 * (len(data) - zeros)


def calldata_floor_gas(data: bytes) -> int:
    """The EIP-7623 minimum a transaction must pay: 21000 + 10/token.
    Applied as max(execution gas used, floor) after refunds, Prague on."""
    return TX_BASE_COST + TOTAL_COST_FLOOR_PER_TOKEN * calldata_tokens(data)


def is_delegation_designator(code: bytes) -> bool:
    """The consensus-critical EIP-7702 designator predicate — the ONE
    definition both EVM backends and the tx-processing layer share."""
    return len(code) == 23 and code[:3] == DELEGATION_PREFIX


def delegation_target(code: bytes) -> bytes:
    return bytes(code[3:23])
