"""Gas schedule (Shanghai revision) and memory-expansion accounting.

Constants mirror the reference's params (reference:
src/blockchain/params.zig:5-39) plus the opcode-level costs evmone applies
internally; collected here because this framework owns its interpreter.
"""

from __future__ import annotations

# --- intrinsic tx costs (reference: src/blockchain/params.zig:5-17) -------
TX_BASE_COST = 21_000
TX_DATA_COST_ZERO = 4
TX_DATA_COST_NONZERO = 16
TX_CREATE_COST = 32_000
TX_ACCESS_LIST_ADDRESS_COST = 2_400
TX_ACCESS_LIST_STORAGE_KEY_COST = 1_900

# --- EIP-2929 access costs -------------------------------------------------
COLD_ACCOUNT_ACCESS = 2_600
WARM_ACCOUNT_ACCESS = 100
COLD_SLOAD = 2_100
WARM_SLOAD = 100

# --- storage (EIP-2200 + 3529) --------------------------------------------
SSTORE_SET = 20_000
SSTORE_RESET = 2_900  # 5000 - COLD_SLOAD
SSTORE_SENTRY = 2_300
SSTORE_CLEARS_REFUND = 4_800  # EIP-3529

# --- create ----------------------------------------------------------------
CREATE_GAS = 32_000
CODE_DEPOSIT_PER_BYTE = 200
MAX_CODE_SIZE = 0x6000  # EIP-170 (reference: params.zig:30)
MAX_INITCODE_SIZE = 2 * MAX_CODE_SIZE  # EIP-3860
INITCODE_WORD_COST = 2  # EIP-3860

# --- calls -----------------------------------------------------------------
CALL_VALUE_GAS = 9_000
CALL_STIPEND = 2_300
NEW_ACCOUNT_GAS = 25_000
MAX_CALL_DEPTH = 1024  # reference: params.zig:33

# --- misc opcode costs ------------------------------------------------------
KECCAK256_GAS = 30
KECCAK256_WORD_GAS = 6
COPY_WORD_GAS = 3
LOG_GAS = 375
LOG_TOPIC_GAS = 375
LOG_DATA_GAS = 8
EXP_GAS = 10
EXP_BYTE_GAS = 50
SELFDESTRUCT_GAS = 5_000
MEMORY_GAS = 3
QUAD_COEFF_DIV = 512
REFUND_QUOTIENT = 5  # EIP-3529 (gas_used // 5 cap, reference: blockchain.zig:315)

GWEI = 10**9

U256_MAX = (1 << 256) - 1


def memory_cost(size_bytes: int) -> int:
    """Total cost of having `size_bytes` of memory (yellow paper C_mem)."""
    words = (size_bytes + 31) // 32
    return MEMORY_GAS * words + (words * words) // QUAD_COEFF_DIV


def copy_cost(length: int) -> int:
    return COPY_WORD_GAS * ((length + 31) // 32)


def intrinsic_gas(data: bytes, is_create: bool, access_list, init_code_len: int = 0) -> int:
    """Intrinsic cost before execution (reference:
    src/blockchain/blockchain.zig:355-377, incl. EIP-3860 word cost)."""
    gas = TX_BASE_COST
    for byte in data:
        gas += TX_DATA_COST_ZERO if byte == 0 else TX_DATA_COST_NONZERO
    if is_create:
        gas += TX_CREATE_COST
        gas += INITCODE_WORD_COST * ((init_code_len + 31) // 32)
    for _, keys in access_list:
        gas += TX_ACCESS_LIST_ADDRESS_COST
        gas += TX_ACCESS_LIST_STORAGE_KEY_COST * len(keys)
    return gas
