"""EVM bytecode interpreter, Shanghai revision.

The reference embeds evmone (C++) behind the EVMC ABI and implements the
host side over its StateDB (reference: src/blockchain/vm.zig:33-558). This
framework owns a from-scratch interpreter with the same observable
semantics: full Shanghai opcode set, EIP-2929 warm/cold accounting,
EIP-2200/3529 SSTORE lattice (reference: vm.zig:180-264 implements the same
lattice through EVMC storage-status codes), EIP-150 63/64 forwarding,
CREATE/CREATE2 with EIP-3860/3541/170 rules, and static-call protection.

Layout: `Evm.execute_message` is the reference's processMessageCall
(vm.zig:67-124); `Evm._call` is the recursive host `call` (vm.zig:382-522)
using journal snapshots instead of the reference's full deep clone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from phant_tpu.crypto.keccak import keccak256
from phant_tpu.evm import gas as G
from phant_tpu.evm.message import (
    Environment,
    EVMError,
    ExecResult,
    Message,
    REVISION_CANCUN,
    REVISION_PRAGUE,
)
from phant_tpu.evm.precompiles import active_precompiles
from phant_tpu.types.receipt import Log
from phant_tpu import rlp

U256 = (1 << 256) - 1
SIGN_BIT = 1 << 255

# Nested EVM calls cost ~6 Python frames per EVM depth; MAX_CALL_DEPTH=1024
# needs ~6200 frames. Raise the interpreter limit once, with headroom.
import sys

if sys.getrecursionlimit() < 20_000:
    sys.setrecursionlimit(20_000)


def create_address(sender: bytes, nonce: int) -> bytes:
    """CREATE: keccak(rlp([sender, nonce]))[12:]
    (reference: src/common/contract.zig:8-24)."""
    return keccak256(rlp.encode([sender, rlp.encode_uint(nonce)]))[12:]


def create2_address(sender: bytes, salt: bytes, init_code: bytes) -> bytes:
    """CREATE2: keccak(0xff ‖ sender ‖ salt ‖ keccak(init))[12:]
    (reference: src/common/contract.zig:26-40)."""
    return keccak256(b"\xff" + sender + salt + keccak256(init_code))[12:]


def valid_jumpdests(code: bytes) -> Set[int]:
    dests = set()
    i, n = 0, len(code)
    while i < n:
        op = code[i]
        if op == 0x5B:
            dests.add(i)
        if 0x60 <= op <= 0x7F:  # PUSH1..PUSH32
            i += op - 0x5F
        i += 1
    return dests


@dataclass
class Frame:
    msg: Message
    code: bytes
    gas: int
    address: bytes  # executing address (storage/balance context)
    stack: List[int] = field(default_factory=list)
    memory: bytearray = field(default_factory=bytearray)
    pc: int = 0
    return_data: bytes = b""
    jumpdests: Set[int] = field(default_factory=set)

    def push(self, v: int) -> None:
        if len(self.stack) >= 1024:
            raise EVMError("stack overflow")
        self.stack.append(v)

    def pop(self) -> int:
        if not self.stack:
            raise EVMError("stack underflow")
        return self.stack.pop()

    def use_gas(self, amount: int) -> None:
        if self.gas < amount:
            raise EVMError("out of gas")
        self.gas -= amount

    def expand_memory(self, offset: int, size: int) -> None:
        """Charge and grow memory to cover [offset, offset+size)."""
        if size == 0:
            return
        if offset > 2**32 or size > 2**32:
            raise EVMError("out of gas")  # absurd offsets: cost overflows
        new_size = offset + size
        cur = len(self.memory)
        if new_size <= cur:
            return
        new_words = (new_size + 31) // 32
        self.use_gas(G.memory_cost(new_words * 32) - G.memory_cost(cur))
        self.memory.extend(b"\x00" * (new_words * 32 - cur))

    def mread(self, offset: int, size: int) -> bytes:
        if size == 0:
            return b""
        return bytes(self.memory[offset : offset + size])

    def mwrite(self, offset: int, data: bytes) -> None:
        if data:
            self.memory[offset : offset + len(data)] = data


def _to_signed(x: int) -> int:
    return x - (1 << 256) if x & SIGN_BIT else x


def _to_unsigned(x: int) -> int:
    return x & U256


def _addr_to_int(addr: bytes) -> int:
    return int.from_bytes(addr, "big")


def _int_to_addr(v: int) -> bytes:
    return (v & ((1 << 160) - 1)).to_bytes(20, "big")


class Evm:
    """One EVM instance bound to an Environment (reference: vm.zig:33-65)."""

    def __init__(self, env: Environment):
        self.env = env
        self.state = env.state
        # optional per-instruction tracer: fn(pc, op, gas, depth, stack_size).
        # Same hook shape on both backends (native/evm.cc PhantHost.trace),
        # so a fixture divergence is localized by diffing the two traces.
        self.tracer = None

    # ------------------------------------------------------------------
    # top level (reference: VM.processMessageCall vm.zig:67-124)
    # ------------------------------------------------------------------

    def execute_message(self, msg: Message) -> ExecResult:
        if msg.target is None:
            nonce = self.state.get_nonce(msg.caller)
            # top-level create: sender nonce was already bumped by tx
            # processing, so the address derives from nonce-1
            addr = create_address(msg.caller, nonce - 1)
            return self._create(msg, addr)
        return self._call_inner(msg)

    # ------------------------------------------------------------------
    # call path (reference: EVMOneHost.call vm.zig:382-522)
    # ------------------------------------------------------------------

    def _call_inner(self, msg: Message) -> ExecResult:
        state = self.state
        snapshot = state.snapshot()  # journal mark (reference deep-clones)
        target = msg.target
        assert target is not None
        code_addr = msg.code_address if msg.code_address is not None else target

        state.touch(target)
        # value transfer (reference: vm.zig:444-466); DELEGATECALL carries the
        # parent's value for CALLVALUE but moves no funds
        if msg.value and msg.transfers_value:
            if state.get_balance(msg.caller) < msg.value:
                return ExecResult(False, msg.gas, error="insufficient balance")
            state.sub_balance(msg.caller, msg.value)
            state.add_balance(target, msg.value)

        precompiles = active_precompiles(self.env.revision)
        if code_addr in precompiles:
            result = precompiles[code_addr](msg.data, msg.gas)
            if not result.success:
                state.revert_to(snapshot)
            return result

        code = state.get_code(code_addr)
        # EIP-7702 delegation: 0xef0100‖address executes the delegate's
        # code in the account's own context. Resolved ONE level (a chain
        # of designators executes the raw designator bytes, which halt on
        # 0xEF). The gas for the delegate's access is the CALLER's cost
        # (delegation_access_cost in the CALL family / free warm-add at
        # the tx top level) — resolution here is charge-free. This is the
        # single code-fetch point for both backends (the native core's
        # nested calls re-enter here via the host `call` callback), so
        # delegation behaves identically everywhere. INVARIANT: every
        # entry path into execute_message must have already charged AND
        # warmed the delegate (chain.py tx top level; CALL family via
        # delegation_access_cost) — a new entry path that skips that gets
        # a silent free warm-add here.
        if self.env.revision >= REVISION_PRAGUE and G.is_delegation_designator(
            code
        ):
            delegate = G.delegation_target(code)
            state.access_address(delegate)  # idempotent (already warmed)
            delegated = state.get_code(delegate)
            if not G.is_delegation_designator(delegated):
                code = delegated
        if not code:
            return ExecResult(True, msg.gas)

        result = self._execute_code(code, msg, target)
        if not result.success:
            state.revert_to(snapshot)
        return result

    # ------------------------------------------------------------------
    # create path (reference: vm.zig:478-516 + contract deposit rules)
    # ------------------------------------------------------------------

    def _create(self, msg: Message, addr: bytes) -> ExecResult:
        state = self.state
        if state.get_balance(msg.caller) < msg.value:
            return ExecResult(False, msg.gas, error="insufficient balance")

        # address collision (existing code or nonce) burns the gas
        existing = state.get_account(addr)
        if existing is not None and (existing.code or existing.nonce):
            return ExecResult(False, 0, error="create collision")

        snapshot = state.snapshot()
        state.access_address(addr)
        acct = state.create_account(addr)
        state.mark_created(addr)
        state.set_nonce(addr, 1)  # EIP-161
        state.touch(addr)
        if msg.value:
            state.sub_balance(msg.caller, msg.value)
            state.add_balance(addr, msg.value)

        # init code runs with empty calldata
        init_msg = Message(
            caller=msg.caller, target=addr, value=msg.value, data=b"",
            gas=msg.gas, is_static=msg.is_static, depth=msg.depth,
        )
        result = self._execute_code(msg.data, init_msg, addr)
        if not result.success:
            state.revert_to(snapshot)
            result.create_address = None
            return result

        deposit_code = result.output
        # EIP-3541: new code must not start with 0xEF (reference: vm.zig:496-500)
        if deposit_code[:1] == b"\xef":
            state.revert_to(snapshot)
            return ExecResult(False, 0, error="EF code prefix")
        # EIP-170 max code size (reference: vm.zig:501-505)
        if len(deposit_code) > G.MAX_CODE_SIZE:
            state.revert_to(snapshot)
            return ExecResult(False, 0, error="code too large")
        deposit_gas = len(deposit_code) * G.CODE_DEPOSIT_PER_BYTE
        if result.gas_left < deposit_gas:
            state.revert_to(snapshot)
            return ExecResult(False, 0, error="out of gas (deposit)")
        result.gas_left -= deposit_gas
        state.set_code(addr, deposit_code)
        return ExecResult(True, result.gas_left, b"", create_address=addr)

    # ------------------------------------------------------------------
    # interpreter loop
    # ------------------------------------------------------------------

    def _execute_code(self, code: bytes, msg: Message, address: bytes) -> ExecResult:
        """Run one frame of bytecode on the selected EVM backend: the C++
        core (native/evm.cc, mirroring the reference's evmone-behind-EVMC
        split) or this module's Python interpreter."""
        from phant_tpu.backend import evm_backend

        if evm_backend() == "native":
            from phant_tpu.evm.native_vm import execute_native

            result = execute_native(self, code, msg, address)
            if result is not None:
                return result  # None: toolchain unavailable, fall through
        frame = Frame(
            msg=msg, code=code, gas=msg.gas, address=address,
            jumpdests=valid_jumpdests(code),
        )
        return self._run(frame)

    def _run(self, frame: Frame) -> ExecResult:
        try:
            return self._run_unsafe(frame)
        except RecursionError:
            # ~6 Python frames per EVM depth; the limit below makes legal
            # depth-1024 chains fit, so reaching here is exceptional
            return ExecResult(False, 0, error="python recursion limit")
        except EVMError as e:
            if e.reason == "revert-op":
                return ExecResult(False, frame.gas, frame.return_data, error="revert")
            return ExecResult(False, 0, error=e.reason)

    def _run_unsafe(self, frame: Frame) -> ExecResult:
        stack = frame.stack
        state = self.state
        env = self.env
        code = frame.code
        n = len(code)
        tracer = self.tracer
        while frame.pc < n:
            op = code[frame.pc]
            if tracer is not None:
                tracer(frame.pc, op, frame.gas, frame.msg.depth, len(stack))
            frame.pc += 1
            # ---- push family (most common) ----
            if 0x60 <= op <= 0x7F:
                width = op - 0x5F
                frame.use_gas(3)
                imm = code[frame.pc : frame.pc + width]
                if len(imm) < width:  # code is zero-extended past its end
                    imm = imm.ljust(width, b"\x00")
                frame.push(int.from_bytes(imm, "big"))
                frame.pc += width
                continue
            if 0x80 <= op <= 0x8F:  # DUP1..16
                frame.use_gas(3)
                i = op - 0x7F
                if len(stack) < i:
                    raise EVMError("stack underflow")
                frame.push(stack[-i])
                continue
            if 0x90 <= op <= 0x9F:  # SWAP1..16
                frame.use_gas(3)
                i = op - 0x8F
                if len(stack) < i + 1:
                    raise EVMError("stack underflow")
                stack[-1], stack[-i - 1] = stack[-i - 1], stack[-1]
                continue

            handler = _DISPATCH.get(op)
            if handler is None:
                raise EVMError(f"invalid opcode 0x{op:02x}")
            result = handler(self, frame)
            if result is not None:
                return result
        return ExecResult(True, frame.gas)

    # ------------------------------------------------------------------
    # nested call/create from opcodes
    # ------------------------------------------------------------------

    def _nested_call(self, msg: Message) -> ExecResult:
        if msg.depth > G.MAX_CALL_DEPTH:
            return ExecResult(False, msg.gas, error="call depth exceeded")
        return self._call_inner(msg)

    def _nested_create(self, msg: Message, addr: bytes) -> ExecResult:
        if msg.depth > G.MAX_CALL_DEPTH:
            return ExecResult(False, msg.gas, error="call depth exceeded")
        nonce = self.state.get_nonce(msg.caller)
        if nonce >= 2**64 - 1:
            return ExecResult(False, msg.gas, error="nonce overflow")
        self.state.increment_nonce(msg.caller)
        return self._create(msg, addr)


# ===========================================================================
# opcode handlers — each returns None to continue or an ExecResult to halt
# ===========================================================================

_DISPATCH: Dict[int, object] = {}


def op(code: int, base_gas: int = 0):
    def deco(fn):
        if base_gas:
            def wrapped(evm, frame, _fn=fn, _g=base_gas):
                frame.use_gas(_g)
                return _fn(evm, frame)
            _DISPATCH[code] = wrapped
        else:
            _DISPATCH[code] = fn
        return fn
    return deco


# ---- 0x00s: control / arithmetic ----


@op(0x00)
def _stop(evm, frame):
    return ExecResult(True, frame.gas)


@op(0x01, 3)
def _add(evm, frame):
    frame.push((frame.pop() + frame.pop()) & U256)


@op(0x02, 5)
def _mul(evm, frame):
    frame.push((frame.pop() * frame.pop()) & U256)


@op(0x03, 3)
def _sub(evm, frame):
    a, b = frame.pop(), frame.pop()
    frame.push((a - b) & U256)


@op(0x04, 5)
def _div(evm, frame):
    a, b = frame.pop(), frame.pop()
    frame.push(a // b if b else 0)


@op(0x05, 5)
def _sdiv(evm, frame):
    a, b = _to_signed(frame.pop()), _to_signed(frame.pop())
    if b == 0:
        frame.push(0)
    else:
        q = abs(a) // abs(b)
        frame.push(_to_unsigned(-q if (a < 0) != (b < 0) else q))


@op(0x06, 5)
def _mod(evm, frame):
    a, b = frame.pop(), frame.pop()
    frame.push(a % b if b else 0)


@op(0x07, 5)
def _smod(evm, frame):
    a, b = _to_signed(frame.pop()), _to_signed(frame.pop())
    if b == 0:
        frame.push(0)
    else:
        r = abs(a) % abs(b)
        frame.push(_to_unsigned(-r if a < 0 else r))


@op(0x08, 8)
def _addmod(evm, frame):
    a, b, m = frame.pop(), frame.pop(), frame.pop()
    frame.push((a + b) % m if m else 0)


@op(0x09, 8)
def _mulmod(evm, frame):
    a, b, m = frame.pop(), frame.pop(), frame.pop()
    frame.push((a * b) % m if m else 0)


@op(0x0A)
def _exp(evm, frame):
    base, exp = frame.pop(), frame.pop()
    byte_len = (exp.bit_length() + 7) // 8
    frame.use_gas(G.EXP_GAS + G.EXP_BYTE_GAS * byte_len)
    frame.push(pow(base, exp, 1 << 256))


@op(0x0B, 5)
def _signextend(evm, frame):
    k, v = frame.pop(), frame.pop()
    if k < 31:
        bit = 8 * (k + 1) - 1
        if v & (1 << bit):
            v |= U256 ^ ((1 << (bit + 1)) - 1)
        else:
            v &= (1 << (bit + 1)) - 1
    frame.push(v)


# ---- 0x10s: comparison / bitwise ----


@op(0x10, 3)
def _lt(evm, frame):
    frame.push(1 if frame.pop() < frame.pop() else 0)


@op(0x11, 3)
def _gt(evm, frame):
    frame.push(1 if frame.pop() > frame.pop() else 0)


@op(0x12, 3)
def _slt(evm, frame):
    frame.push(1 if _to_signed(frame.pop()) < _to_signed(frame.pop()) else 0)


@op(0x13, 3)
def _sgt(evm, frame):
    frame.push(1 if _to_signed(frame.pop()) > _to_signed(frame.pop()) else 0)


@op(0x14, 3)
def _eq(evm, frame):
    frame.push(1 if frame.pop() == frame.pop() else 0)


@op(0x15, 3)
def _iszero(evm, frame):
    frame.push(1 if frame.pop() == 0 else 0)


@op(0x16, 3)
def _and(evm, frame):
    frame.push(frame.pop() & frame.pop())


@op(0x17, 3)
def _or(evm, frame):
    frame.push(frame.pop() | frame.pop())


@op(0x18, 3)
def _xor(evm, frame):
    frame.push(frame.pop() ^ frame.pop())


@op(0x19, 3)
def _not(evm, frame):
    frame.push(frame.pop() ^ U256)


@op(0x1A, 3)
def _byte(evm, frame):
    i, v = frame.pop(), frame.pop()
    frame.push((v >> (8 * (31 - i))) & 0xFF if i < 32 else 0)


@op(0x1B, 3)
def _shl(evm, frame):
    shift, v = frame.pop(), frame.pop()
    frame.push((v << shift) & U256 if shift < 256 else 0)


@op(0x1C, 3)
def _shr(evm, frame):
    shift, v = frame.pop(), frame.pop()
    frame.push(v >> shift if shift < 256 else 0)


@op(0x1D, 3)
def _sar(evm, frame):
    shift, v = frame.pop(), _to_signed(frame.pop())
    if shift >= 256:
        frame.push(U256 if v < 0 else 0)
    else:
        frame.push(_to_unsigned(v >> shift))


# ---- 0x20: keccak ----


@op(0x20)
def _keccak256(evm, frame):
    offset, size = frame.pop(), frame.pop()
    frame.use_gas(G.KECCAK256_GAS + G.KECCAK256_WORD_GAS * ((size + 31) // 32))
    frame.expand_memory(offset, size)
    frame.push(int.from_bytes(keccak256(frame.mread(offset, size)), "big"))


# ---- 0x30s: environment ----


@op(0x30, 2)
def _address(evm, frame):
    frame.push(_addr_to_int(frame.address))


@op(0x31)
def _balance(evm, frame):
    addr = _int_to_addr(frame.pop())
    warm = evm.state.access_address(addr)
    frame.use_gas(G.WARM_ACCOUNT_ACCESS if warm else G.COLD_ACCOUNT_ACCESS)
    frame.push(evm.state.get_balance(addr))


@op(0x32, 2)
def _origin(evm, frame):
    frame.push(_addr_to_int(evm.env.origin))


@op(0x33, 2)
def _caller(evm, frame):
    frame.push(_addr_to_int(frame.msg.caller))


@op(0x34, 2)
def _callvalue(evm, frame):
    frame.push(frame.msg.value)


@op(0x35, 3)
def _calldataload(evm, frame):
    i = frame.pop()
    data = frame.msg.data
    frame.push(int.from_bytes(data[i : i + 32].ljust(32, b"\x00"), "big") if i < len(data) else 0)


@op(0x36, 2)
def _calldatasize(evm, frame):
    frame.push(len(frame.msg.data))


@op(0x37)
def _calldatacopy(evm, frame):
    dest, src, size = frame.pop(), frame.pop(), frame.pop()
    frame.use_gas(3 + G.copy_cost(size))
    frame.expand_memory(dest, size)
    data = frame.msg.data[src : src + size] if src < len(frame.msg.data) else b""
    frame.mwrite(dest, data.ljust(size, b"\x00"))


@op(0x38, 2)
def _codesize(evm, frame):
    frame.push(len(frame.code))


@op(0x39)
def _codecopy(evm, frame):
    dest, src, size = frame.pop(), frame.pop(), frame.pop()
    frame.use_gas(3 + G.copy_cost(size))
    frame.expand_memory(dest, size)
    data = frame.code[src : src + size] if src < len(frame.code) else b""
    frame.mwrite(dest, data.ljust(size, b"\x00"))


@op(0x3A, 2)
def _gasprice(evm, frame):
    frame.push(evm.env.gas_price)



def _visible_code(evm, addr: bytes) -> bytes:
    """Code as seen by the EXTCODE* instructions: a delegated account
    (EIP-7702 designator 0xef0100‖address) exposes only the 2-byte marker
    0xef01 — the delegate address is deliberately opaque to contracts."""
    code = evm.state.get_code(addr)
    if evm.env.revision >= REVISION_PRAGUE and G.is_delegation_designator(code):
        return G.DELEGATION_MARKER
    return code


def visible_code_hash(evm, addr: bytes):
    """EXTCODEHASH semantics shared by both backends: None for an empty
    account (the opcode pushes 0), the precomputed marker hash for a
    delegated account, the stored code hash otherwise."""
    if evm.state.is_empty(addr):
        return None
    if _visible_code(evm, addr) == G.DELEGATION_MARKER:
        return G.DELEGATION_MARKER_HASH
    return evm.state.get_account(addr).code_hash()


def delegation_access_cost(evm, code_addr: bytes) -> int:
    """EIP-7702 surcharge for calling through a delegated account: warms
    the delegate and returns its warm/cold access cost (0 when the target
    is not delegated or pre-Prague). Shared by both backends' CALL-family
    gas accounting — the python opcodes directly, the native core via the
    delegate_access_cost host callback."""
    if evm.env.revision < REVISION_PRAGUE:
        return 0
    code = evm.state.get_code(code_addr)
    if not G.is_delegation_designator(code):
        return 0
    warm = evm.state.access_address(G.delegation_target(code))
    return G.WARM_ACCOUNT_ACCESS if warm else G.COLD_ACCOUNT_ACCESS


@op(0x3B)
def _extcodesize(evm, frame):
    addr = _int_to_addr(frame.pop())
    warm = evm.state.access_address(addr)
    frame.use_gas(G.WARM_ACCOUNT_ACCESS if warm else G.COLD_ACCOUNT_ACCESS)
    frame.push(len(_visible_code(evm, addr)))


@op(0x3C)
def _extcodecopy(evm, frame):
    addr = _int_to_addr(frame.pop())
    dest, src, size = frame.pop(), frame.pop(), frame.pop()
    warm = evm.state.access_address(addr)
    frame.use_gas((G.WARM_ACCOUNT_ACCESS if warm else G.COLD_ACCOUNT_ACCESS) + G.copy_cost(size))
    frame.expand_memory(dest, size)
    ext = _visible_code(evm, addr)
    data = ext[src : src + size] if src < len(ext) else b""
    frame.mwrite(dest, data.ljust(size, b"\x00"))


@op(0x3D, 2)
def _returndatasize(evm, frame):
    frame.push(len(frame.return_data))


@op(0x3E)
def _returndatacopy(evm, frame):
    dest, src, size = frame.pop(), frame.pop(), frame.pop()
    frame.use_gas(3 + G.copy_cost(size))
    if src + size > len(frame.return_data):
        raise EVMError("returndata out of bounds")
    frame.expand_memory(dest, size)
    frame.mwrite(dest, frame.return_data[src : src + size])


@op(0x3F)
def _extcodehash(evm, frame):
    addr = _int_to_addr(frame.pop())
    warm = evm.state.access_address(addr)
    frame.use_gas(G.WARM_ACCOUNT_ACCESS if warm else G.COLD_ACCOUNT_ACCESS)
    h = visible_code_hash(evm, addr)
    frame.push(0 if h is None else int.from_bytes(h, "big"))


# ---- 0x40s: block ----


@op(0x40, 20)
def _blockhash(evm, frame):
    number = frame.pop()
    current = evm.env.block_number
    if number >= current or current - number > 256:
        frame.push(0)
    else:
        frame.push(int.from_bytes(evm.env.get_block_hash(number), "big"))


@op(0x41, 2)
def _coinbase(evm, frame):
    frame.push(_addr_to_int(evm.env.coinbase))


@op(0x42, 2)
def _timestamp(evm, frame):
    frame.push(evm.env.timestamp)


@op(0x43, 2)
def _number(evm, frame):
    frame.push(evm.env.block_number)


@op(0x44, 2)
def _prevrandao(evm, frame):
    frame.push(int.from_bytes(evm.env.prev_randao, "big"))


@op(0x45, 2)
def _gaslimit(evm, frame):
    frame.push(evm.env.gas_limit)


@op(0x46, 2)
def _chainid(evm, frame):
    frame.push(evm.env.chain_id)


@op(0x47, 5)
def _selfbalance(evm, frame):
    frame.push(evm.state.get_balance(frame.address))


@op(0x48, 2)
def _basefee(evm, frame):
    frame.push(evm.env.base_fee)


def _require_cancun(evm) -> None:
    """Cancun opcodes are invalid bytes under earlier revisions — fork
    dispatch the reference TODO-pins away (src/blockchain/vm.zig:472)."""
    if evm.env.revision < REVISION_CANCUN:
        raise EVMError("invalid opcode (pre-Cancun)")


@op(0x49)
def _blobhash(evm, frame):
    """EIP-4844 BLOBHASH: tx's i-th blob versioned hash, else 0."""
    _require_cancun(evm)
    frame.use_gas(G.BLOBHASH_GAS)
    i = frame.pop()
    hashes = evm.env.blob_hashes
    frame.push(int.from_bytes(hashes[i], "big") if i < len(hashes) else 0)


@op(0x4A)
def _blobbasefee(evm, frame):
    """EIP-7516 BLOBBASEFEE: the block's blob base fee."""
    _require_cancun(evm)
    frame.use_gas(G.BLOBBASEFEE_GAS)
    frame.push(evm.env.blob_base_fee)


# ---- 0x50s: stack/memory/storage/flow ----


@op(0x50, 2)
def _pop_op(evm, frame):
    frame.pop()


@op(0x51)
def _mload(evm, frame):
    offset = frame.pop()
    frame.use_gas(3)
    frame.expand_memory(offset, 32)
    frame.push(int.from_bytes(frame.mread(offset, 32), "big"))


@op(0x52)
def _mstore(evm, frame):
    offset, value = frame.pop(), frame.pop()
    frame.use_gas(3)
    frame.expand_memory(offset, 32)
    frame.mwrite(offset, value.to_bytes(32, "big"))


@op(0x53)
def _mstore8(evm, frame):
    offset, value = frame.pop(), frame.pop()
    frame.use_gas(3)
    frame.expand_memory(offset, 1)
    frame.memory[offset] = value & 0xFF


@op(0x54)
def _sload(evm, frame):
    slot = frame.pop()
    warm = evm.state.access_storage_key(frame.address, slot)
    frame.use_gas(G.WARM_SLOAD if warm else G.COLD_SLOAD)
    frame.push(evm.state.get_storage(frame.address, slot))


@op(0x55)
def _sstore(evm, frame):
    if frame.msg.is_static:
        raise EVMError("static call state change")
    # EIP-2200 sentry (reference lattice: vm.zig:192-254)
    if frame.gas <= G.SSTORE_SENTRY:
        raise EVMError("out of gas")
    slot, new = frame.pop(), frame.pop()
    state = evm.state
    addr = frame.address
    cost = 0
    if not state.access_storage_key(addr, slot):
        cost += G.COLD_SLOAD
    current = state.get_storage(addr, slot)
    original = state.get_original_storage(addr, slot)
    if current == new:
        cost += G.WARM_SLOAD
    elif current == original:
        cost += G.SSTORE_SET if original == 0 else G.SSTORE_RESET
    else:
        cost += G.WARM_SLOAD
    frame.use_gas(cost)
    # refunds (EIP-3529)
    if current != new:
        if current == original:
            if original != 0 and new == 0:
                state.add_refund(G.SSTORE_CLEARS_REFUND)
        else:
            if original != 0:
                if current == 0:
                    state.add_refund(-G.SSTORE_CLEARS_REFUND)
                elif new == 0:
                    state.add_refund(G.SSTORE_CLEARS_REFUND)
            if new == original:
                if original == 0:
                    state.add_refund(G.SSTORE_SET - G.WARM_SLOAD)
                else:
                    state.add_refund(G.SSTORE_RESET - G.WARM_SLOAD)
        state.set_storage(addr, slot, new)


@op(0x56, 8)
def _jump(evm, frame):
    dest = frame.pop()
    if dest not in frame.jumpdests:
        raise EVMError("invalid jump")
    frame.pc = dest  # land on the JUMPDEST, which charges its own 1 gas


@op(0x57, 10)
def _jumpi(evm, frame):
    dest, cond = frame.pop(), frame.pop()
    if cond:
        if dest not in frame.jumpdests:
            raise EVMError("invalid jump")
        frame.pc = dest


@op(0x58, 2)
def _pc(evm, frame):
    frame.push(frame.pc - 1)


@op(0x59, 2)
def _msize(evm, frame):
    frame.push(len(frame.memory))


@op(0x5A, 2)
def _gas(evm, frame):
    frame.push(frame.gas)


@op(0x5B, 1)
def _jumpdest(evm, frame):
    pass


@op(0x5C)
def _tload(evm, frame):
    """EIP-1153 TLOAD (Cancun): transient storage read, flat warm cost."""
    _require_cancun(evm)
    frame.use_gas(G.TLOAD_GAS)
    slot = frame.pop()
    frame.push(evm.state.get_transient(frame.address, slot))


@op(0x5D)
def _tstore(evm, frame):
    """EIP-1153 TSTORE (Cancun): journaled for reverts, cleared per tx."""
    _require_cancun(evm)
    if frame.msg.is_static:
        raise EVMError("static call state change")
    frame.use_gas(G.TSTORE_GAS)
    slot, value = frame.pop(), frame.pop()
    evm.state.set_transient(frame.address, slot, value)


@op(0x5E)
def _mcopy(evm, frame):
    """EIP-5656 MCOPY (Cancun): memory-to-memory copy, overlap-safe."""
    _require_cancun(evm)
    dest, src, size = frame.pop(), frame.pop(), frame.pop()
    frame.use_gas(3 + G.copy_cost(size))
    if size:
        # one expansion covering both ranges (charged on the larger end)
        frame.expand_memory(max(dest, src), size)
        data = frame.mread(src, size)
        frame.mwrite(dest, data)


@op(0x5F, 2)
def _push0(evm, frame):
    """EIP-3855 (Shanghai)."""
    frame.push(0)


# ---- 0xA0s: logs ----


def _log(evm, frame, topic_count: int):
    if frame.msg.is_static:
        raise EVMError("static call state change")
    offset, size = frame.pop(), frame.pop()
    topics = tuple(frame.pop().to_bytes(32, "big") for _ in range(topic_count))
    frame.use_gas(G.LOG_GAS + G.LOG_TOPIC_GAS * topic_count + G.LOG_DATA_GAS * size)
    frame.expand_memory(offset, size)
    evm.state.add_log(Log(address=frame.address, topics=topics, data=frame.mread(offset, size)))


for _i in range(5):
    _DISPATCH[0xA0 + _i] = (lambda i: lambda evm, frame: _log(evm, frame, i))(_i)


# ---- 0xF0s: calls / create / halt ----


@op(0xF0)
def _create_op(evm, frame):
    if frame.msg.is_static:
        raise EVMError("static call state change")
    value, offset, size = frame.pop(), frame.pop(), frame.pop()
    if size > G.MAX_INITCODE_SIZE:  # EIP-3860
        raise EVMError("initcode too large")
    frame.use_gas(G.CREATE_GAS + G.INITCODE_WORD_COST * ((size + 31) // 32))
    frame.expand_memory(offset, size)
    init_code = frame.mread(offset, size)
    frame.return_data = b""
    if value > evm.state.get_balance(frame.address):
        frame.push(0)
        return
    gas_for_child = frame.gas - frame.gas // 64  # EIP-150
    frame.gas -= gas_for_child
    addr = create_address(frame.address, evm.state.get_nonce(frame.address))
    msg = Message(
        caller=frame.address, target=None, value=value, data=init_code,
        gas=gas_for_child, is_static=False, depth=frame.msg.depth + 1,
    )
    result = evm._nested_create(msg, addr)
    frame.gas += result.gas_left
    if result.success:
        frame.push(_addr_to_int(result.create_address))
    else:
        if result.is_revert:
            frame.return_data = result.output
        frame.push(0)


@op(0xF5)
def _create2_op(evm, frame):
    if frame.msg.is_static:
        raise EVMError("static call state change")
    value, offset, size, salt = frame.pop(), frame.pop(), frame.pop(), frame.pop()
    if size > G.MAX_INITCODE_SIZE:
        raise EVMError("initcode too large")
    words = (size + 31) // 32
    frame.use_gas(G.CREATE_GAS + (G.INITCODE_WORD_COST + G.KECCAK256_WORD_GAS) * words)
    frame.expand_memory(offset, size)
    init_code = frame.mread(offset, size)
    frame.return_data = b""
    if value > evm.state.get_balance(frame.address):
        frame.push(0)
        return
    gas_for_child = frame.gas - frame.gas // 64
    frame.gas -= gas_for_child
    addr = create2_address(frame.address, salt.to_bytes(32, "big"), init_code)
    msg = Message(
        caller=frame.address, target=None, value=value, data=init_code,
        gas=gas_for_child, is_static=False, depth=frame.msg.depth + 1,
    )
    result = evm._nested_create(msg, addr)
    frame.gas += result.gas_left
    if result.success:
        frame.push(_addr_to_int(result.create_address))
    else:
        if result.is_revert:
            frame.return_data = result.output
        frame.push(0)


def _call_family(evm, frame, kind: str):
    gas_req = frame.pop()
    addr = _int_to_addr(frame.pop())
    if kind in ("call", "callcode"):
        value = frame.pop()
    else:
        value = 0
    in_off, in_size, ret_off, ret_size = frame.pop(), frame.pop(), frame.pop(), frame.pop()

    if kind == "call" and value and frame.msg.is_static:
        raise EVMError("static call state change")

    warm = evm.state.access_address(addr)
    access_cost = G.WARM_ACCOUNT_ACCESS if warm else G.COLD_ACCOUNT_ACCESS
    frame.use_gas(access_cost)
    # EIP-7702: a delegated code target charges the delegate's warm/cold
    # access to THIS instruction (caller side, before the 63/64 split)
    frame.use_gas(delegation_access_cost(evm, addr))
    frame.expand_memory(in_off, in_size)
    frame.expand_memory(ret_off, ret_size)

    extra = 0
    if value:
        extra += G.CALL_VALUE_GAS
        if kind == "call" and evm.state.is_empty(addr):
            extra += G.NEW_ACCOUNT_GAS
    frame.use_gas(extra)

    gas_for_child = min(gas_req, frame.gas - frame.gas // 64)  # EIP-150
    frame.use_gas(gas_for_child)
    if value:
        gas_for_child += G.CALL_STIPEND

    args = frame.mread(in_off, in_size)
    frame.return_data = b""

    if value and kind in ("call", "callcode") and evm.state.get_balance(frame.address) < value:
        frame.gas += gas_for_child
        frame.push(0)
        return

    if kind == "call":
        msg = Message(
            caller=frame.address, target=addr, value=value, data=args,
            gas=gas_for_child, is_static=frame.msg.is_static,
            depth=frame.msg.depth + 1,
        )
    elif kind == "callcode":
        msg = Message(
            caller=frame.address, target=frame.address, value=value, data=args,
            gas=gas_for_child, is_static=frame.msg.is_static,
            depth=frame.msg.depth + 1, code_address=addr,
        )
    elif kind == "delegatecall":
        msg = Message(
            caller=frame.msg.caller, target=frame.address, value=frame.msg.value,
            data=args, gas=gas_for_child, is_static=frame.msg.is_static,
            depth=frame.msg.depth + 1, code_address=addr, transfers_value=False,
        )
    else:  # staticcall
        msg = Message(
            caller=frame.address, target=addr, value=0, data=args,
            gas=gas_for_child, is_static=True, depth=frame.msg.depth + 1,
        )
    result = evm._nested_call(msg)
    frame.return_data = result.output
    frame.gas += result.gas_left
    if ret_size and result.output:
        frame.mwrite(ret_off, result.output[:ret_size])
    frame.push(1 if result.success else 0)


@op(0xF1)
def _call_op(evm, frame):
    _call_family(evm, frame, "call")


@op(0xF2)
def _callcode_op(evm, frame):
    _call_family(evm, frame, "callcode")


@op(0xF4)
def _delegatecall_op(evm, frame):
    _call_family(evm, frame, "delegatecall")


@op(0xFA)
def _staticcall_op(evm, frame):
    _call_family(evm, frame, "staticcall")


@op(0xF3)
def _return(evm, frame):
    offset, size = frame.pop(), frame.pop()
    frame.expand_memory(offset, size)
    return ExecResult(True, frame.gas, frame.mread(offset, size))


@op(0xFD)
def _revert(evm, frame):
    offset, size = frame.pop(), frame.pop()
    frame.expand_memory(offset, size)
    frame.return_data = frame.mread(offset, size)
    raise EVMError("revert-op")


@op(0xFE)
def _invalid(evm, frame):
    raise EVMError("designated invalid opcode")


@op(0xFF)
def _selfdestruct(evm, frame):
    if frame.msg.is_static:
        raise EVMError("static call state change")
    beneficiary = _int_to_addr(frame.pop())
    frame.use_gas(G.SELFDESTRUCT_GAS)
    if not evm.state.access_address(beneficiary):
        frame.use_gas(G.COLD_ACCOUNT_ACCESS)
    balance = evm.state.get_balance(frame.address)
    if balance and evm.state.is_empty(beneficiary):
        frame.use_gas(G.NEW_ACCOUNT_GAS)
    evm.state.add_balance(beneficiary, balance)
    evm.state.set_balance(frame.address, 0)
    evm.state.touch(beneficiary)
    evm.state.mark_selfdestruct(frame.address)
    return ExecResult(True, frame.gas)
