"""Precompiled contracts: 0x01..0x09 (Shanghai), +0x0A (Cancun),
+0x0B..0x11 (Prague, in precompiles_bls.py).

The reference only lists the nine Shanghai addresses for EIP-2929
warm-set prefill (reference: src/blockchain/params.zig:19-29) and relies
on evmone for behavior; here each is implemented natively in Python
(bn254 pairing in phant_tpu/crypto/bn254.py, BLS12-381/KZG in
phant_tpu/crypto/bls12_381.py + kzg.py).  Both EVM backends dispatch
through this module (the C++ core's host split leaves precompiles to the
host, native/evm.cc:1378-1381).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List

from phant_tpu.crypto import secp256k1
from phant_tpu.evm.message import REVISION_CANCUN, REVISION_PRAGUE, ExecResult


def _addr(n: int) -> bytes:
    return n.to_bytes(20, "big")


def precompile_addresses(revision: int = 0) -> List[bytes]:
    """Active precompile addresses for the revision (EIP-2929 prefill and
    dispatch share this one definition so they cannot diverge)."""
    hi = 9
    if revision >= REVISION_CANCUN:
        hi = 10
    if revision >= REVISION_PRAGUE:
        hi = 17
    return [_addr(i) for i in range(1, hi + 1)]


def _words(n: int) -> int:
    return (n + 31) // 32


# --- 0x01 ecrecover --------------------------------------------------------


def _ecrecover(data: bytes, gas: int) -> ExecResult:
    GAS = 3000
    if gas < GAS:
        return ExecResult(False, 0, error="out of gas")
    gas -= GAS
    data = data[:128].ljust(128, b"\x00")
    h, v_b, r_b, s_b = data[:32], data[32:64], data[64:96], data[96:128]
    v = int.from_bytes(v_b, "big")
    r = int.from_bytes(r_b, "big")
    s = int.from_bytes(s_b, "big")
    if v not in (27, 28) or not (1 <= r < secp256k1.N) or not (1 <= s < secp256k1.N):
        return ExecResult(True, gas, b"")
    try:
        pub = secp256k1.recover_pubkey(h, r, s, v - 27)
    except secp256k1.SignatureError:
        return ExecResult(True, gas, b"")
    from phant_tpu.crypto.keccak import keccak256

    address = keccak256(pub[1:])[12:]
    return ExecResult(True, gas, address.rjust(32, b"\x00"))


# --- 0x02 sha256 / 0x03 ripemd160 / 0x04 identity --------------------------


def _sha256(data: bytes, gas: int) -> ExecResult:
    cost = 60 + 12 * _words(len(data))
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    return ExecResult(True, gas - cost, hashlib.sha256(data).digest())


def _ripemd160(data: bytes, gas: int) -> ExecResult:
    cost = 600 + 120 * _words(len(data))
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    try:
        digest = hashlib.new("ripemd160", data).digest()
    except ValueError:  # OpenSSL without ripemd160
        from phant_tpu.crypto.ripemd160 import ripemd160 as _rmd

        digest = _rmd(data)
    return ExecResult(True, gas - cost, digest.rjust(32, b"\x00"))


def _identity(data: bytes, gas: int) -> ExecResult:
    cost = 15 + 3 * _words(len(data))
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    return ExecResult(True, gas - cost, data)


# --- 0x05 modexp (EIP-2565) ------------------------------------------------


def _modexp(data: bytes, gas: int) -> ExecResult:
    def read(off: int, size: int) -> bytes:
        chunk = data[off : off + size]
        return chunk.ljust(size, b"\x00")

    b_len = int.from_bytes(read(0, 32), "big")
    e_len = int.from_bytes(read(32, 32), "big")
    m_len = int.from_bytes(read(64, 32), "big")

    # EIP-2565 gas — computed from lengths + exponent head ONLY, before any
    # large operand is materialized, so gas (not an artificial cap) bounds work
    max_len = max(b_len, m_len)
    mult_complexity = ((max_len + 7) // 8) ** 2
    e_head = int.from_bytes(read(96 + b_len, min(e_len, 32)), "big")
    if e_len <= 32:
        iter_count = max(e_head.bit_length() - 1, 0)
    else:
        iter_count = 8 * (e_len - 32) + max(e_head.bit_length() - 1, 0)
    iter_count = max(iter_count, 1)
    cost = max(200, mult_complexity * iter_count // 3)
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")

    b = int.from_bytes(read(96, b_len), "big")
    e = int.from_bytes(read(96 + b_len, e_len), "big")
    m = int.from_bytes(read(96 + b_len + e_len, m_len), "big")
    if m == 0:
        out = b"\x00" * m_len
    else:
        out = pow(b, e, m).to_bytes(m_len, "big")
    return ExecResult(True, gas - cost, out)


# --- 0x06/0x07/0x08 alt_bn128 ---------------------------------------------


def _bn_add(data: bytes, gas: int) -> ExecResult:
    cost = 150
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    from phant_tpu.crypto import bn254

    try:
        out = bn254.ec_add_bytes(data)
    except bn254.BN254Error:
        return ExecResult(False, 0, error="bn254 invalid point")
    return ExecResult(True, gas - cost, out)


def _bn_mul(data: bytes, gas: int) -> ExecResult:
    cost = 6000
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    from phant_tpu.crypto import bn254

    try:
        out = bn254.ec_mul_bytes(data)
    except bn254.BN254Error:
        return ExecResult(False, 0, error="bn254 invalid point")
    return ExecResult(True, gas - cost, out)


def _bn_pairing(data: bytes, gas: int) -> ExecResult:
    if len(data) % 192:
        return ExecResult(False, 0, error="bn254 pairing input length")
    k = len(data) // 192
    cost = 45_000 + 34_000 * k
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    from phant_tpu.crypto import bn254

    try:
        ok = bn254.pairing_check_bytes(data)
    except bn254.BN254Error:
        return ExecResult(False, 0, error="bn254 invalid point")
    return ExecResult(True, gas - cost, (1 if ok else 0).to_bytes(32, "big"))


# --- 0x09 blake2f (EIP-152) ------------------------------------------------

_BLAKE2B_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_BLAKE2B_SIGMA = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
]

_M64 = (1 << 64) - 1


def _rotr64(x: int, n: int) -> int:
    return ((x >> n) | (x << (64 - n))) & _M64


def _blake2_g(v, a, b, c, d, x, y):
    v[a] = (v[a] + v[b] + x) & _M64
    v[d] = _rotr64(v[d] ^ v[a], 32)
    v[c] = (v[c] + v[d]) & _M64
    v[b] = _rotr64(v[b] ^ v[c], 24)
    v[a] = (v[a] + v[b] + y) & _M64
    v[d] = _rotr64(v[d] ^ v[a], 16)
    v[c] = (v[c] + v[d]) & _M64
    v[b] = _rotr64(v[b] ^ v[c], 63)


def _blake2f(data: bytes, gas: int) -> ExecResult:
    if len(data) != 213:
        return ExecResult(False, 0, error="blake2f input length")
    rounds = int.from_bytes(data[0:4], "big")
    if gas < rounds:
        return ExecResult(False, 0, error="out of gas")
    final = data[212]
    if final not in (0, 1):
        return ExecResult(False, 0, error="blake2f final flag")
    h = [int.from_bytes(data[4 + 8 * i : 12 + 8 * i], "little") for i in range(8)]
    m = [int.from_bytes(data[68 + 8 * i : 76 + 8 * i], "little") for i in range(16)]
    t0 = int.from_bytes(data[196:204], "little")
    t1 = int.from_bytes(data[204:212], "little")

    v = h[:] + _BLAKE2B_IV[:]
    v[12] ^= t0
    v[13] ^= t1
    if final:
        v[14] ^= _M64
    for r in range(rounds):
        s = _BLAKE2B_SIGMA[r % 10]
        _blake2_g(v, 0, 4, 8, 12, m[s[0]], m[s[1]])
        _blake2_g(v, 1, 5, 9, 13, m[s[2]], m[s[3]])
        _blake2_g(v, 2, 6, 10, 14, m[s[4]], m[s[5]])
        _blake2_g(v, 3, 7, 11, 15, m[s[6]], m[s[7]])
        _blake2_g(v, 0, 5, 10, 15, m[s[8]], m[s[9]])
        _blake2_g(v, 1, 6, 11, 12, m[s[10]], m[s[11]])
        _blake2_g(v, 2, 7, 8, 13, m[s[12]], m[s[13]])
        _blake2_g(v, 3, 4, 9, 14, m[s[14]], m[s[15]])
    out = b"".join(
        ((h[i] ^ v[i] ^ v[i + 8]) & _M64).to_bytes(8, "little") for i in range(8)
    )
    return ExecResult(True, gas - rounds, out)


PRECOMPILES: Dict[bytes, Callable[[bytes, int], ExecResult]] = {
    _addr(1): _ecrecover,
    _addr(2): _sha256,
    _addr(3): _ripemd160,
    _addr(4): _identity,
    _addr(5): _modexp,
    _addr(6): _bn_add,
    _addr(7): _bn_mul,
    _addr(8): _bn_pairing,
    _addr(9): _blake2f,
}


import functools


@functools.lru_cache(maxsize=None)
def active_precompiles(
    revision: int,
) -> Dict[bytes, Callable[[bytes, int], ExecResult]]:
    """Dispatch table for the revision, memoized (this is looked up per
    message frame in the EVM hot path).  Calling a future fork's address
    under an older revision is an ordinary (empty-account) call."""
    if revision < REVISION_CANCUN:
        return PRECOMPILES
    from phant_tpu.evm import precompiles_bls as pb

    table = dict(PRECOMPILES)
    table[_addr(0x0A)] = pb.point_evaluation
    if revision >= REVISION_PRAGUE:
        table[_addr(0x0B)] = pb.bls_g1_add
        table[_addr(0x0C)] = pb.bls_g1_msm
        table[_addr(0x0D)] = pb.bls_g2_add
        table[_addr(0x0E)] = pb.bls_g2_msm
        table[_addr(0x0F)] = pb.bls_pairing
        table[_addr(0x10)] = pb.bls_map_fp_to_g1
        table[_addr(0x11)] = pb.bls_map_fp2_to_g2
    return table
