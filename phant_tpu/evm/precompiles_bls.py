"""Cancun/Prague precompiles: 0x0A point evaluation + 0x0B..0x11 EIP-2537.

The reference stops at 0x09 (src/blockchain/params.zig:30-39); these are
the fork-mandated additions for the Cancun (EIP-4844) and Prague
(EIP-2537) revisions, implemented over phant_tpu/crypto/bls12_381.py.

Consensus-data caveats (zero-egress build environment, documented in
README):
- 0x0A needs the ceremony's [tau]_2 — loadable, insecure dev setup
  otherwise (phant_tpu/crypto/kzg.py).
- 0x10/0x11 (map-to-curve) need the RFC 9380 SSWU isogeny constant
  tables, which are public but too large to re-derive offline; without
  PHANT_BLS_SSWU_CONSTS they raise ConsensusDataUnavailable, which aborts
  block validation loudly instead of guessing a post-state.
- The MSM discount tables are embedded best-effort (flagged below) and
  overridable via PHANT_BLS_DISCOUNT_TABLE.
"""

from __future__ import annotations

import json
import os
import threading
from typing import List, Optional, Tuple

from phant_tpu.crypto import bls12_381 as bls
from phant_tpu.evm.message import ExecResult


class ConsensusDataUnavailable(Exception):
    """Validation cannot proceed: a consensus constant is not on this host.

    Raised (not returned as a call failure) because both success and
    failure of the call are consensus-visible — guessing either would be
    silent divergence. Propagates out of the EVM and aborts the block."""


# --- gas schedule (EIP-2537 final) -----------------------------------------

G1ADD_GAS = 375
G2ADD_GAS = 600
G1MUL_GAS = 12000
G2MUL_GAS = 22500
PAIRING_BASE_GAS = 37700
PAIRING_PER_PAIR_GAS = 32600
MAP_FP_GAS = 5500
MAP_FP2_GAS = 23800
MSM_MULTIPLIER = 1000

# MSM discount tables, indexed by min(k, 128) - 1.  Only the anchor
# entries are embedded: k=1 (1000 = no discount, MSM == MUL cost, defined
# by the EIP's formula) and the k>=128 saturation values.  The 126
# mid-curve entries are published constants that cannot be verified in
# this zero-egress build, and the tree's policy is that an unverifiable
# consensus constant must fail LOUDLY, not guess (a wrong discount is a
# silent gas divergence) — supply the full tables via
# PHANT_BLS_DISCOUNT_TABLE={"g1":[...128 ints],"g2":[...]} to enable
# 2 <= k <= 127 MSMs.
_G1_DISCOUNT_TAIL = 519
_G2_DISCOUNT_TAIL = 524


def _load_discounts() -> Optional[Tuple[List[int], List[int]]]:
    src = os.environ.get("PHANT_BLS_DISCOUNT_TABLE")
    if not src:
        return None
    with open(src) as f:
        data = json.load(f)
    g1, g2 = list(data["g1"]), list(data["g2"])
    if len(g1) != 128 or len(g2) != 128:
        raise ValueError("discount tables must have 128 entries each")
    return g1, g2


_DISCOUNTS: Optional[Tuple[List[int], List[int]]] = None
_DISCOUNTS_LOADED = False
_discounts_lock = threading.Lock()


def _discounts() -> Optional[Tuple[List[int], List[int]]]:
    """Lazy discount-table load, lock-serialized (phantlint LOCK): the
    LOADED flag and the table are two globals — an unserialized race can
    publish the flag before the table is visible to another thread."""
    global _DISCOUNTS, _DISCOUNTS_LOADED
    if not _DISCOUNTS_LOADED:
        with _discounts_lock:
            if not _DISCOUNTS_LOADED:
                _DISCOUNTS = _load_discounts()
                _DISCOUNTS_LOADED = True
    return _DISCOUNTS


def msm_gas(k: int, g2: bool) -> int:
    if k == 0:
        return 0
    per = G2MUL_GAS if g2 else G1MUL_GAS
    if k == 1:
        disc = 1000
    elif k >= 128:
        disc = _G2_DISCOUNT_TAIL if g2 else _G1_DISCOUNT_TAIL
    else:
        tables = _discounts()
        if tables is None:
            raise ConsensusDataUnavailable(
                f"MSM gas for k={k} needs the EIP-2537 discount table "
                "(unverifiable in this build; set PHANT_BLS_DISCOUNT_TABLE)"
            )
        disc = tables[1 if g2 else 0][k - 1]
    return k * per * disc // MSM_MULTIPLIER


# --- field-element / point codecs (EIP-2537 padded encoding) ---------------


class _Malformed(ValueError):
    pass


def _read_fp(data: bytes) -> int:
    """64-byte padded base-field element: 16 zero bytes || 48-byte BE."""
    if len(data) != 64 or data[:16] != bytes(16):
        raise _Malformed("bad fp padding")
    v = int.from_bytes(data[16:], "big")
    if v >= bls.P:
        raise _Malformed("fp not canonical")
    return v


def _write_fp(v: int) -> bytes:
    return bytes(16) + v.to_bytes(48, "big")


def _read_g1(data: bytes, subgroup: bool) -> bls.G1Point:
    if len(data) != 128:
        raise _Malformed("G1 point must be 128 bytes")
    x = _read_fp(data[:64])
    y = _read_fp(data[64:])
    if x == 0 and y == 0:
        return None
    pt = (x, y)
    if not bls.g1_is_on_curve(pt):
        raise _Malformed("G1 point not on curve")
    if subgroup and not bls.g1_in_subgroup(pt):
        raise _Malformed("G1 point not in subgroup")
    return pt


def _write_g1(pt: bls.G1Point) -> bytes:
    if pt is None:
        return bytes(128)
    return _write_fp(pt[0]) + _write_fp(pt[1])


def _read_g2(data: bytes, subgroup: bool) -> bls.G2Point:
    if len(data) != 256:
        raise _Malformed("G2 point must be 256 bytes")
    x = (_read_fp(data[0:64]), _read_fp(data[64:128]))
    y = (_read_fp(data[128:192]), _read_fp(data[192:256]))
    if bls.fq2_is_zero(x) and bls.fq2_is_zero(y):
        return None
    pt = (x, y)
    if not bls.g2_is_on_curve(pt):
        raise _Malformed("G2 point not on curve")
    if subgroup and not bls.g2_in_subgroup(pt):
        raise _Malformed("G2 point not in subgroup")
    return pt


def _write_g2(pt: bls.G2Point) -> bytes:
    if pt is None:
        return bytes(256)
    x, y = pt
    return _write_fp(x[0]) + _write_fp(x[1]) + _write_fp(y[0]) + _write_fp(y[1])


# --- 0x0A: EIP-4844 point evaluation ---------------------------------------

POINT_EVALUATION_GAS = 50000
_POINT_EVAL_OUTPUT = (4096).to_bytes(32, "big") + bls.R.to_bytes(32, "big")


def point_evaluation(data: bytes, gas: int) -> ExecResult:
    from phant_tpu.crypto import kzg

    # Public-network guard (ADVICE high): on a chain whose config names a
    # known public network (Blockchain.__init__ -> kzg.set_public_network),
    # the dev setup's tau is a PUBLIC constant — anyone can forge a proof
    # against it, so "verification" would be consensus theater. Raise (not
    # a call failure): success and failure are both consensus-visible, and
    # the tree's policy for unverifiable consensus data is a loud abort.
    # Config-less fixture chains keep the dev tau.
    net = kzg.public_network()
    if net is not None and kzg.configured_source() == "insecure-dev":
        raise ConsensusDataUnavailable(
            f"KZG trusted setup: refusing the insecure dev setup on public "
            f"network {net!r}; supply the ceremony [tau]_2 via "
            f"PHANT_KZG_SETUP_G2"
        )
    if gas < POINT_EVALUATION_GAS:
        return ExecResult(False, 0, error="out of gas")
    gas -= POINT_EVALUATION_GAS
    if len(data) != 192:
        return ExecResult(False, 0, error="point evaluation input length")
    versioned_hash = data[0:32]
    z = data[32:64]
    y = data[64:96]
    commitment = data[96:144]
    proof = data[144:192]
    if kzg.kzg_to_versioned_hash(commitment) != versioned_hash:
        return ExecResult(False, 0, error="versioned hash mismatch")
    try:
        ok = kzg.verify_kzg_proof(commitment, z, y, proof)
    except kzg.KZGProofError as e:
        return ExecResult(False, 0, error=f"kzg: {e}")
    if not ok:
        return ExecResult(False, 0, error="kzg proof invalid")
    return ExecResult(True, gas, _POINT_EVAL_OUTPUT)


# --- 0x0B..0x0F: EIP-2537 add/msm/pairing ----------------------------------


def bls_g1_add(data: bytes, gas: int) -> ExecResult:
    if gas < G1ADD_GAS:
        return ExecResult(False, 0, error="out of gas")
    gas -= G1ADD_GAS
    if len(data) != 256:
        return ExecResult(False, 0, error="g1add input length")
    try:
        a = _read_g1(data[:128], subgroup=False)
        b = _read_g1(data[128:], subgroup=False)
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    return ExecResult(True, gas, _write_g1(bls.g1_add(a, b)))


def bls_g2_add(data: bytes, gas: int) -> ExecResult:
    if gas < G2ADD_GAS:
        return ExecResult(False, 0, error="out of gas")
    gas -= G2ADD_GAS
    if len(data) != 512:
        return ExecResult(False, 0, error="g2add input length")
    try:
        a = _read_g2(data[:256], subgroup=False)
        b = _read_g2(data[256:], subgroup=False)
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    return ExecResult(True, gas, _write_g2(bls.g2_add(a, b)))


def bls_g1_msm(data: bytes, gas: int) -> ExecResult:
    PAIR = 160  # 128-byte point + 32-byte scalar
    if len(data) == 0 or len(data) % PAIR:
        return ExecResult(False, 0, error="g1msm input length")
    k = len(data) // PAIR
    cost = msm_gas(k, g2=False)
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    gas -= cost
    acc: bls.G1Point = None
    try:
        for i in range(k):
            chunk = data[i * PAIR : (i + 1) * PAIR]
            pt = _read_g1(chunk[:128], subgroup=True)
            scalar = int.from_bytes(chunk[128:], "big")
            acc = bls.g1_add(acc, bls.g1_mul(pt, scalar % bls.R))
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    return ExecResult(True, gas, _write_g1(acc))


def bls_g2_msm(data: bytes, gas: int) -> ExecResult:
    PAIR = 288  # 256-byte point + 32-byte scalar
    if len(data) == 0 or len(data) % PAIR:
        return ExecResult(False, 0, error="g2msm input length")
    k = len(data) // PAIR
    cost = msm_gas(k, g2=True)
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    gas -= cost
    acc: bls.G2Point = None
    try:
        for i in range(k):
            chunk = data[i * PAIR : (i + 1) * PAIR]
            pt = _read_g2(chunk[:256], subgroup=True)
            scalar = int.from_bytes(chunk[256:], "big")
            acc = bls.g2_add(acc, bls.g2_mul(pt, scalar % bls.R))
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    return ExecResult(True, gas, _write_g2(acc))


def bls_pairing(data: bytes, gas: int) -> ExecResult:
    PAIR = 384  # 128-byte G1 + 256-byte G2
    if len(data) == 0 or len(data) % PAIR:
        return ExecResult(False, 0, error="pairing input length")
    k = len(data) // PAIR
    cost = PAIRING_BASE_GAS + PAIRING_PER_PAIR_GAS * k
    if gas < cost:
        return ExecResult(False, 0, error="out of gas")
    gas -= cost
    pairs = []
    try:
        for i in range(k):
            chunk = data[i * PAIR : (i + 1) * PAIR]
            g1 = _read_g1(chunk[:128], subgroup=True)
            g2 = _read_g2(chunk[128:], subgroup=True)
            pairs.append((g1, g2))
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    ok = bls.pairing_check(pairs)
    return ExecResult(True, gas, (1 if ok else 0).to_bytes(32, "big"))


# --- 0x10/0x11: map-to-curve (gated on RFC 9380 constants) -----------------


def bls_map_fp_to_g1(data: bytes, gas: int) -> ExecResult:
    if gas < MAP_FP_GAS:
        return ExecResult(False, 0, error="out of gas")
    gas -= MAP_FP_GAS
    if len(data) != 64:
        return ExecResult(False, 0, error="map_fp input length")
    try:
        _read_fp(data)
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    # the input is well-formed, so a correct post-state exists — but
    # computing it needs the RFC 9380 SSWU 11-isogeny coefficient tables
    # (public constants that can be neither re-derived nor trusted from
    # memory in this zero-egress build). Refuse loudly rather than guess.
    raise ConsensusDataUnavailable(
        "map_fp_to_g1 needs the RFC 9380 SSWU isogeny constants "
        "(unavailable in this build; see README 'Consensus data')"
    )


def bls_map_fp2_to_g2(data: bytes, gas: int) -> ExecResult:
    if gas < MAP_FP2_GAS:
        return ExecResult(False, 0, error="out of gas")
    gas -= MAP_FP2_GAS
    if len(data) != 128:
        return ExecResult(False, 0, error="map_fp2 input length")
    try:
        _read_fp(data[:64])
        _read_fp(data[64:])
    except _Malformed as e:
        return ExecResult(False, 0, error=str(e))
    raise ConsensusDataUnavailable(
        "map_fp2_to_g2 needs the RFC 9380 SSWU isogeny constants "
        "(unavailable in this build; see README 'Consensus data')"
    )
