"""ctypes bridge to the native C++ EVM core (native/evm.cc).

Architecture mirror of the reference: evmone (C++) executes bytecode while
the client provides a host vtable over its StateDB (reference:
src/blockchain/vm.zig:40-55 installs 14 host callbacks; nested calls
re-enter the interpreter through the host, vm.zig:382-522). Here the host
side is this module: every callback closes over the Python `Evm`/`StateDB`,
and nested CALL*/CREATE* ops route back through `Evm._nested_call` /
`_nested_create`, which re-enter the C++ core for child frames.

Enabled via `--evm_backend=native` (phant_tpu.backend); falls back to the
pure-Python interpreter when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes as ct
import threading
from typing import Optional

from phant_tpu.evm import gas as G
from phant_tpu.evm.interpreter import _visible_code, delegation_access_cost
from phant_tpu.evm.message import ExecResult, Message
from phant_tpu.types.receipt import Log

_ADDR = ct.c_uint8 * 20
_B32 = ct.c_uint8 * 32

KIND_CALL, KIND_CALLCODE, KIND_DELEGATECALL, KIND_STATICCALL = 0, 1, 2, 3
KIND_CREATE, KIND_CREATE2 = 4, 5


class PhantTxContext(ct.Structure):
    _fields_ = [
        ("origin", _ADDR),
        ("coinbase", _ADDR),
        ("block_number", ct.c_uint64),
        ("timestamp", ct.c_uint64),
        ("gas_limit", ct.c_uint64),
        ("chain_id", ct.c_uint64),
        ("gas_price", _B32),
        ("prev_randao", _B32),
        ("base_fee", _B32),
        # Cancun extensions (must mirror native/evm.cc PhantTxContext)
        ("revision", ct.c_uint64),
        ("blob_base_fee", _B32),
        ("blob_hashes", ct.POINTER(ct.c_uint8)),
        ("n_blob_hashes", ct.c_uint64),
    ]


class PhantMsg(ct.Structure):
    _fields_ = [
        ("kind", ct.c_int32),
        ("is_static", ct.c_int32),
        ("depth", ct.c_int32),
        ("gas", ct.c_int64),
        ("caller", _ADDR),
        ("target", _ADDR),
        ("code_address", _ADDR),
        ("value", _B32),
        ("data", ct.POINTER(ct.c_uint8)),
        ("data_len", ct.c_uint64),
        ("salt", _B32),
    ]


class PhantResult(ct.Structure):
    _fields_ = [
        ("status", ct.c_int32),
        ("gas_left", ct.c_int64),
        ("output", ct.POINTER(ct.c_uint8)),
        ("output_len", ct.c_uint64),
        ("create_address", _ADDR),
    ]


_CB = {
    "access_account": ct.CFUNCTYPE(ct.c_int32, ct.c_void_p, ct.POINTER(ct.c_uint8)),
    "access_storage": ct.CFUNCTYPE(
        ct.c_int32, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8)
    ),
    "get_storage": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
        ct.POINTER(ct.c_uint8),
    ),
    "get_original_storage": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
        ct.POINTER(ct.c_uint8),
    ),
    "set_storage": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
        ct.POINTER(ct.c_uint8),
    ),
    "get_balance": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8)
    ),
    "get_code_size": ct.CFUNCTYPE(ct.c_uint64, ct.c_void_p, ct.POINTER(ct.c_uint8)),
    "copy_code": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.c_uint64,
        ct.POINTER(ct.c_uint8), ct.c_uint64,
    ),
    "get_code_hash": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8)
    ),
    "is_empty": ct.CFUNCTYPE(ct.c_int32, ct.c_void_p, ct.POINTER(ct.c_uint8)),
    "get_block_hash": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.c_uint64, ct.POINTER(ct.c_uint8)
    ),
    "emit_log": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
        ct.c_uint64, ct.POINTER(ct.c_uint8), ct.c_int32,
    ),
    "add_refund": ct.CFUNCTYPE(None, ct.c_void_p, ct.c_int64),
    "selfdestruct": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8)
    ),
    "call": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(PhantMsg), ct.POINTER(PhantResult)
    ),
    # EIP-1153 transient storage (Cancun); appended after `call` to keep
    # the vtable layout a strict prefix of the pre-Cancun one
    "get_transient": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
        ct.POINTER(ct.c_uint8),
    ),
    "set_transient": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.POINTER(ct.c_uint8), ct.POINTER(ct.c_uint8),
        ct.POINTER(ct.c_uint8),
    ),
    # optional per-instruction tracer (installed only when Evm.tracer is
    # set; NULL otherwise so the C loop pays one predictable branch)
    "trace": ct.CFUNCTYPE(
        None, ct.c_void_p, ct.c_uint64, ct.c_int32, ct.c_int64, ct.c_int32,
        ct.c_int32,
    ),
    # EIP-7702 (Prague): extra CALL-family charge for delegated code
    # targets; appended LAST to keep older vtable layouts a strict prefix
    "delegate_access_cost": ct.CFUNCTYPE(
        ct.c_int64, ct.c_void_p, ct.POINTER(ct.c_uint8)
    ),
}


class PhantHost(ct.Structure):
    _fields_ = [("ctx", ct.c_void_p)] + [(name, fn) for name, fn in _CB.items()]


def _bytes20(p) -> bytes:
    return ct.string_at(p, 20)


def _bytes32_int(p) -> int:
    return int.from_bytes(ct.string_at(p, 32), "big")


def _write32(dst, value: int) -> None:
    ct.memmove(dst, value.to_bytes(32, "big"), 32)


_lib = None
_lib_failed = False
_load_lock = threading.Lock()


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    # lock-serialized (phantlint LOCK): two request threads racing the
    # argtypes/restype setup would mutate shared ctypes function objects
    # mid-call. Acquisition order is _load_lock -> native._lock (inside
    # load_native); nothing takes them in reverse.
    with _load_lock:
        return _load_locked()


def _load_locked():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    from phant_tpu.utils.native import load_native

    native = load_native()
    if native is None:
        _lib_failed = True
        return None
    lib = native._lib
    lib.phant_evm_execute.argtypes = [
        ct.POINTER(PhantHost), ct.POINTER(PhantTxContext), ct.POINTER(PhantMsg),
        ct.POINTER(ct.c_uint8), ct.c_uint64, ct.POINTER(PhantResult),
    ]
    lib.phant_evm_execute.restype = ct.c_int32
    lib.phant_evm_free.argtypes = [ct.POINTER(ct.c_uint8)]
    lib.phant_evm_free.restype = None
    _lib = lib
    return _lib


class NativeSession:
    """Host vtable bound to one Evm instance (one per Environment)."""

    def __init__(self, evm):
        self.evm = evm
        self.state = evm.state
        env = evm.env
        self.txc = PhantTxContext()
        ct.memmove(self.txc.origin, env.origin, 20)
        ct.memmove(self.txc.coinbase, env.coinbase, 20)
        self.txc.block_number = env.block_number
        self.txc.timestamp = env.timestamp
        self.txc.gas_limit = env.gas_limit
        self.txc.chain_id = env.chain_id
        ct.memmove(self.txc.gas_price, env.gas_price.to_bytes(32, "big"), 32)
        ct.memmove(self.txc.prev_randao, env.prev_randao, 32)
        ct.memmove(self.txc.base_fee, env.base_fee.to_bytes(32, "big"), 32)
        self.txc.revision = env.revision
        ct.memmove(
            self.txc.blob_base_fee, env.blob_base_fee.to_bytes(32, "big"), 32
        )
        if env.blob_hashes:
            raw = b"".join(env.blob_hashes)
            self._blob_buf = ct.create_string_buffer(raw, len(raw))
            self.txc.blob_hashes = ct.cast(
                self._blob_buf, ct.POINTER(ct.c_uint8)
            )
            self.txc.n_blob_hashes = len(env.blob_hashes)
        else:
            self.txc.blob_hashes = None
            self.txc.n_blob_hashes = 0

        # single-slot holder for the child-output buffer crossing the C
        # boundary: the C++ caller copies it immediately after host->call
        # returns, so only the most recent buffer must stay alive
        self._last_output = None
        self._pending_exc: Optional[BaseException] = None
        self._cbs = {}  # prevent GC of CFUNCTYPE trampolines
        self.host = PhantHost()
        self.host.ctx = None
        # int-returning callbacks need an explicit safe default; void ones
        # return None regardless
        int_cbs = {
            "access_account",
            "access_storage",
            "get_code_size",
            "is_empty",
            "delegate_access_cost",
        }
        for name in _CB:
            if name == "trace" and getattr(evm, "tracer", None) is None:
                # leave the vtable slot NULL: the C loop skips tracing
                setattr(self.host, name, _CB[name]())
                continue
            raw = getattr(self, "_cb_" + name)
            guarded = self._guard(raw, 0 if name in int_cbs else None)
            cb = _CB[name](guarded)
            self._cbs[name] = cb
            setattr(self.host, name, cb)

    def _guard(self, fn, default):
        """No exception may unwind through the C frame: ctypes would swallow
        it and C++ would keep running on garbage. Stash the first error and
        re-raise it from execute() once the C++ stack has unwound."""

        def wrapped(*args):
            try:
                return fn(*args)
            except BaseException as e:
                if self._pending_exc is None:
                    self._pending_exc = e
                return default

        return wrapped

    # --- state callbacks (the reference's EVMOneHost equivalents) ---------

    def _cb_access_account(self, _ctx, addr) -> int:
        return 1 if self.state.access_address(_bytes20(addr)) else 0

    def _cb_access_storage(self, _ctx, addr, key) -> int:
        return 1 if self.state.access_storage_key(_bytes20(addr), _bytes32_int(key)) else 0

    def _cb_get_storage(self, _ctx, addr, key, out) -> None:
        _write32(out, self.state.get_storage(_bytes20(addr), _bytes32_int(key)))

    def _cb_get_original_storage(self, _ctx, addr, key, out) -> None:
        _write32(out, self.state.get_original_storage(_bytes20(addr), _bytes32_int(key)))

    def _cb_set_storage(self, _ctx, addr, key, val) -> None:
        self.state.set_storage(_bytes20(addr), _bytes32_int(key), _bytes32_int(val))

    def _cb_get_balance(self, _ctx, addr, out) -> None:
        _write32(out, self.state.get_balance(_bytes20(addr)))

    def _cb_get_code_size(self, _ctx, addr) -> int:
        return len(_visible_code(self.evm, _bytes20(addr)))

    def _cb_copy_code(self, _ctx, addr, offset, out, size) -> None:
        code = _visible_code(self.evm, _bytes20(addr))
        chunk = code[offset : offset + size]
        if chunk:
            ct.memmove(out, chunk, len(chunk))

    def _cb_get_code_hash(self, _ctx, addr, out) -> None:
        address = _bytes20(addr)
        acct = self.state.get_account(address)
        if acct is None:
            ct.memmove(out, b"\x00" * 32, 32)
            return
        code = _visible_code(self.evm, address)
        if code == G.DELEGATION_MARKER:  # delegated: hash of the marker
            ct.memmove(out, G.DELEGATION_MARKER_HASH, 32)
        else:
            ct.memmove(out, acct.code_hash(), 32)

    def _cb_delegate_access_cost(self, _ctx, addr) -> int:
        return delegation_access_cost(self.evm, _bytes20(addr))

    def _cb_is_empty(self, _ctx, addr) -> int:
        return 1 if self.state.is_empty(_bytes20(addr)) else 0

    def _cb_get_block_hash(self, _ctx, number, out) -> None:
        ct.memmove(out, self.evm.env.get_block_hash(number), 32)

    def _cb_emit_log(self, _ctx, addr, data, data_len, topics, ntopics) -> None:
        payload = ct.string_at(data, data_len) if data_len else b""
        tops = tuple(
            ct.string_at(ct.addressof(topics.contents) + 32 * i, 32)
            for i in range(ntopics)
        )
        self.state.add_log(Log(address=_bytes20(addr), topics=tops, data=payload))

    def _cb_add_refund(self, _ctx, delta) -> None:
        self.state.add_refund(delta)

    def _cb_get_transient(self, _ctx, addr, key, out) -> None:
        _write32(out, self.state.get_transient(_bytes20(addr), _bytes32_int(key)))

    def _cb_set_transient(self, _ctx, addr, key, val) -> None:
        self.state.set_transient(
            _bytes20(addr), _bytes32_int(key), _bytes32_int(val)
        )

    def _cb_trace(self, _ctx, pc, op, gas, depth, stack_size) -> None:
        self.evm.tracer(pc, op, gas, depth, stack_size)

    def _cb_selfdestruct(self, _ctx, addr, beneficiary) -> None:
        # state effects of SELFDESTRUCT (interpreter.py _selfdestruct)
        a, b = _bytes20(addr), _bytes20(beneficiary)
        balance = self.state.get_balance(a)
        self.state.add_balance(b, balance)
        self.state.set_balance(a, 0)
        self.state.touch(b)
        self.state.mark_selfdestruct(a)

    # --- nested call/create: re-enters Evm, which re-enters C++ -----------

    def _cb_call(self, _ctx, msg_p, res_p) -> None:
        from phant_tpu.evm.interpreter import create2_address, create_address

        m = msg_p.contents
        res = res_p.contents
        if self._pending_exc is not None:
            # a host callback already failed: abort fast, don't run children
            res.status = 2
            res.gas_left = 0
            res.output = None
            res.output_len = 0
            return
        data = ct.string_at(m.data, m.data_len) if m.data_len else b""
        kind = m.kind
        caller = bytes(m.caller)
        try:
            if kind in (KIND_CREATE, KIND_CREATE2):
                msg = Message(
                    caller=caller, target=None,
                    value=_bytes32_int(m.value), data=data, gas=m.gas,
                    is_static=False, depth=m.depth,
                )
                if kind == KIND_CREATE2:
                    addr = create2_address(caller, bytes(m.salt), data)
                else:
                    addr = create_address(caller, self.state.get_nonce(caller))
                result = self.evm._nested_create(msg, addr)
            else:
                msg = Message(
                    caller=caller,
                    target=bytes(m.target),
                    value=_bytes32_int(m.value),
                    data=data,
                    gas=m.gas,
                    is_static=bool(m.is_static),
                    depth=m.depth,
                    code_address=(
                        bytes(m.code_address)
                        if kind in (KIND_CALLCODE, KIND_DELEGATECALL)
                        else None
                    ),
                    transfers_value=kind != KIND_DELEGATECALL,
                )
                result = self.evm._nested_call(msg)
        except BaseException as e:  # must never unwind through the C frame
            # stash and re-raise from NativeSession.execute once the C++
            # stack has unwound — a host-side bug must not be mistaken for
            # an in-EVM call failure (the first/innermost error wins)
            if self._pending_exc is None:
                self._pending_exc = e
            res.status = 2
            res.gas_left = 0
            res.output = None
            res.output_len = 0
            return

        res.status = 0 if result.success else (1 if result.is_revert else 2)
        res.gas_left = result.gas_left
        if result.output:
            buf = ct.create_string_buffer(result.output, len(result.output))
            self._last_output = buf
            res.output = ct.cast(buf, ct.POINTER(ct.c_uint8))
            res.output_len = len(result.output)
        else:
            res.output = None
            res.output_len = 0
        if result.create_address:
            ct.memmove(res.create_address, result.create_address, 20)

    # --- frame execution ---------------------------------------------------

    def execute(self, code: bytes, msg: Message, address: bytes) -> ExecResult:
        lib = _load()
        assert lib is not None
        cmsg = PhantMsg()
        cmsg.kind = KIND_CALL
        cmsg.is_static = 1 if msg.is_static else 0
        cmsg.depth = msg.depth
        cmsg.gas = msg.gas
        ct.memmove(cmsg.caller, msg.caller, 20)
        ct.memmove(cmsg.target, address, 20)
        ct.memmove(cmsg.value, msg.value.to_bytes(32, "big"), 32)
        if msg.data:
            data_buf = ct.create_string_buffer(msg.data, len(msg.data))
            cmsg.data = ct.cast(data_buf, ct.POINTER(ct.c_uint8))
        else:
            cmsg.data = None
        cmsg.data_len = len(msg.data)

        res = PhantResult()
        lib.phant_evm_execute(
            ct.byref(self.host), ct.byref(self.txc), ct.byref(cmsg),
            ct.cast(code, ct.POINTER(ct.c_uint8)) if code else None,
            len(code), ct.byref(res),
        )
        output = ct.string_at(res.output, res.output_len) if res.output_len else b""
        if res.output:
            lib.phant_evm_free(res.output)
        if self._pending_exc is not None:
            exc = self._pending_exc
            self._pending_exc = None
            raise exc
        if res.status == 0:
            return ExecResult(True, res.gas_left, output)
        if res.status == 1:
            return ExecResult(False, res.gas_left, output, error="revert")
        return ExecResult(False, 0, error="native evm failure")


def native_available() -> bool:
    return _load() is not None


def execute_native(evm, code: bytes, msg: Message, address: bytes) -> Optional[ExecResult]:
    """Run one frame natively; None if the native lib is unavailable."""
    if _load() is None:
        return None
    session = getattr(evm, "_native_session", None)
    if session is None:
        session = NativeSession(evm)
        evm._native_session = session
    return session.execute(code, msg, address)
