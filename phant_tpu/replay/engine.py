"""Historical chain replay as a first-class megabatch workload.

`Blockchain.run_blocks` imports one block at a time; the serving stack
(serving/scheduler.py) batches *across concurrent requests*. Catch-up
sync has no concurrent requests — but it holds a whole chain SEGMENT in
hand, and a segment is a better batch than any traffic mix:

  * the segment's full tx list goes through the sig lane as ONE merged
    ecrecover launch (`TxSigner.signature_rows` over K blocks' txs,
    one `sig_async` job — the lane's single-bucket coalescing was built
    for exactly this, and closes the r14 "merge across blocks" open);
  * witnessed fixtures drive all K blocks' linked-multiproof checks
    through the witness lane together, where they coalesce into
    megabatches against per-lane resident intern tables (mesh fan-out:
    a scheduler with `mesh_devices` >= 1 shards them over
    MeshExecutorPool lanes — affinity + spill routing, no replay-side
    special case);
  * deferred-root mode hashes K consecutive block states as ONE vmapped
    device program (replay/lowering.py over `StateDB.flush_root_trie`
    plans) instead of K host walks.

The segment pipeline reuses the scheduler's 4-stage vocabulary —
prefetch (build segment N+1's merged sig rows), pack (submit its
witness megabatch), dispatch (launch its merged ecrecover), resolve
(join + EVM-execute segment N) — with the same failure semantics: a
scheduler death fails IN-FLIGHT work only (`SchedulerDown`, code
-32052), recorded as a stage-named `replay.segment_crash` flight
record, and the segment degrades to the local fused batch over rows
already built (sender recovery always has a correct local fallback, so
the lanes may only ever help). A consensus-invalid block fails exactly
that block (`replay.block_failed`, stage-named) and stops the import at
it — earlier blocks stand, the same contract as `run_blocks`.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from phant_tpu.blockchain.chain import BlockError
from phant_tpu.obs.flight import flight
from phant_tpu.utils.trace import metrics

STAGE_PREFETCH = "prefetch"
STAGE_PACK = "pack"
STAGE_DISPATCH = "dispatch"
STAGE_RESOLVE = "resolve"

#: default blocks per segment (`--segment` / PHANT_REPLAY_SEGMENT)
DEFAULT_SEGMENT_BLOCKS = 32


def _default_depth() -> int:
    """PHANT_REPLAY_DEPTH: segments in flight (1 = fully inline, no
    prefetch worker; >= 2 = segment N+1's prefetch/pack/dispatch run
    under segment N's EVM execution)."""
    try:
        return max(1, int(os.environ.get("PHANT_REPLAY_DEPTH", "2")))
    except ValueError:
        return 2


@dataclass
class BlockVerdict:
    """Per-block outcome; `error` carries the BlockError text on failure
    (byte-compatible with what serial `run_blocks` raises)."""

    index: int
    block_number: int
    ok: bool
    error: Optional[str] = None


@dataclass
class ReplayReport:
    """One `ReplayEngine.run` outcome. `verdicts` covers every block up
    to and including the first failure (import stops there — the
    run_blocks contract); `final_state_root` is the host-walked root of
    the state actually reached."""

    verdicts: List[BlockVerdict] = field(default_factory=list)
    final_state_root: bytes = b""
    segments: int = 0
    blocks_ok: int = 0
    txs: int = 0
    stats: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return bool(self.verdicts) and all(v.ok for v in self.verdicts)


class _Segment:
    __slots__ = (
        "index",
        "start",
        "blocks",
        "witnesses",
        "counts",
        "rows",
        "sig_kind",
        "sig_handle",
        "witness_futs",
        "prepare_error",
        "prepare_stage",
    )

    def __init__(self, index, start, blocks, witnesses):
        self.index = index
        self.start = start
        self.blocks = blocks
        self.witnesses = witnesses
        self.counts = [len(b.transactions) for b in blocks]
        self.rows = None
        self.sig_kind = None  # "lane" | "local"
        self.sig_handle = None
        self.witness_futs = None  # None | list[Future] | ("local", ...)
        self.prepare_error = None
        self.prepare_stage = None


class ReplayEngine:
    """Drives a chain through the serving lanes at segment batch shapes.

    `run(chain, blocks, witnesses=None)` imports `blocks` onto `chain`
    (a blockchain whose state is at the parent of `blocks[0]`) and
    returns a ReplayReport. The scheduler is discovered per run
    (serving.active_scheduler); with none installed every stage has a
    local megabatch fallback, so the engine is byte-identical to serial
    `run_blocks` by construction — the differential tests pin it.
    Replay work is tagged tenant `replay` at backfill priority: live
    serving traffic preempts catch-up under the standard QoS weights."""

    def __init__(
        self,
        segment_blocks: int = DEFAULT_SEGMENT_BLOCKS,
        pipeline_depth: Optional[int] = None,
        root_mode: Optional[str] = None,
        tenant: str = "replay",
    ):
        if segment_blocks < 1:
            raise ValueError("segment_blocks must be >= 1")
        self.segment_blocks = segment_blocks
        self.pipeline_depth = (
            pipeline_depth if pipeline_depth is not None else _default_depth()
        )
        if root_mode not in (None, "host", "defer"):
            raise ValueError(f"unknown root_mode {root_mode!r}")
        self.root_mode = root_mode
        self.tenant = tenant
        self._local_witness_engine = None

    # -- stage helpers -------------------------------------------------------

    def _scheduler(self):
        from phant_tpu.serving import active_scheduler

        return active_scheduler()

    def _priority(self):
        from phant_tpu.serving import PRIORITY_BACKFILL

        return PRIORITY_BACKFILL

    def _record_crash(self, seg: _Segment, stage: str, exc: BaseException):
        """Stage-named crash record: the scheduler failed IN-FLIGHT work
        for this segment (its own `sched.executor_crash` record and
        flight dump carry the executor side); the segment degrades to
        local fallbacks and the import continues."""
        metrics.count("replay.lane_fallbacks", stage=stage)
        flight.record(
            "replay.segment_crash",
            segment=seg.index,
            start_block=seg.start,
            stage=stage,
            code=getattr(exc, "code", None),
            error=repr(exc),
        )

    def _prepare(self, signer, seg: _Segment, degraded: bool = False):
        """prefetch + pack + dispatch for one segment. Runs on the
        lookahead worker at depth >= 2 (under the PREVIOUS segment's EVM
        execution) or inline at depth 1. `degraded` skips the scheduler
        lanes entirely (a prior stage already recorded its death)."""
        from phant_tpu.serving.scheduler import SchedulerError

        txs = [tx for b in seg.blocks for tx in b.transactions]

        # prefetch: the merged signing-hash pass for the whole segment —
        # one SigRows for K blocks (host keccak over RLP, off the
        # critical path at depth >= 2)
        with metrics.phase("replay.prefetch"):
            seg.rows = signer.signature_rows(txs)

        sched = None if degraded else self._scheduler()

        # pack: the segment's witness megabatch — all K blocks'
        # linked-multiproof checks enter the witness lane together and
        # coalesce (mesh schedulers shard them over per-lane resident
        # intern tables)
        if seg.witnesses is not None:
            with metrics.phase("replay.pack"):
                futs = None
                if sched is not None and sched.accepts_witness():
                    try:
                        futs = [
                            sched.submit_witness(
                                root,
                                nodes,
                                deadline_s=float("inf"),
                                wait_for_space=True,
                                tenant=self.tenant,
                                priority=self._priority(),
                            )
                            for root, nodes in seg.witnesses
                        ]
                    except SchedulerError as exc:
                        self._record_crash(seg, STAGE_PACK, exc)
                        futs = None
                seg.witness_futs = futs  # None -> local verify at resolve

        # dispatch: the merged ecrecover launch. Backlog pacing keeps a
        # deep replay pipeline from monopolizing the admission queue it
        # shares with live traffic (sig_backlog is rows, not jobs).
        with metrics.phase("replay.dispatch"):
            if sched is not None and sched.accepts_sig() and seg.rows.n:
                deadline = time.monotonic() + 0.25
                while (
                    sched.sig_backlog() > 4 * seg.rows.n
                    and time.monotonic() < deadline
                ):
                    time.sleep(0.001)
                try:
                    seg.sig_kind = "lane"
                    seg.sig_handle = sched.sig_async(
                        seg.rows,
                        deadline_s=float("inf"),
                        tenant=self.tenant,
                        priority=self._priority(),
                    )
                    return
                except SchedulerError as exc:
                    self._record_crash(seg, STAGE_DISPATCH, exc)
            seg.sig_kind = "local"
            seg.sig_handle = signer.recover_rows_async(seg.rows)

    def _resolve_senders(self, signer, seg: _Segment):
        """Join the segment's merged recovery; a lane that died in
        flight (-32052) degrades to the local fused batch over the rows
        ALREADY built — in-flight-only failure, no second signing-hash
        pass."""
        from phant_tpu.serving.scheduler import SchedulerError

        t0 = time.perf_counter()
        try:
            if seg.sig_kind == "lane":
                try:
                    senders, _meta = seg.sig_handle()
                    return senders
                except SchedulerError as exc:
                    self._record_crash(seg, STAGE_RESOLVE, exc)
                    return signer.recover_rows_async(seg.rows, force_cpu=True)()
            try:
                return seg.sig_handle()
            except Exception:
                # a dead device surfaces here; pin this call to the CPU
                return signer.recover_rows_async(seg.rows, force_cpu=True)()
        finally:
            metrics.observe("replay.sig_wait", time.perf_counter() - t0)

    def _local_witness_verify(self, witnesses) -> List[bool]:
        """No-scheduler (or crashed-lane) fallback: the segment still
        verifies as ONE local megabatch on a private engine."""
        if self._local_witness_engine is None:
            from phant_tpu.ops.witness_engine import WitnessEngine

            self._local_witness_engine = WitnessEngine()
        verdicts = self._local_witness_engine.verify_batch(
            [(root, nodes) for root, nodes in witnesses]
        )
        return [bool(v) for v in verdicts]

    def _resolve_witnesses(self, seg: _Segment) -> Optional[int]:
        """Join the segment's witness verdicts; returns the in-segment
        index of the first failed block, or None when all pass."""
        if seg.witnesses is None:
            return None
        from phant_tpu.serving.scheduler import SchedulerError

        t0 = time.perf_counter()
        try:
            if seg.witness_futs is not None:
                verdicts: List[bool] = []
                for k, fut in enumerate(seg.witness_futs):
                    try:
                        verdicts.append(bool(fut.result()))
                    except SchedulerError as exc:
                        self._record_crash(seg, STAGE_RESOLVE, exc)
                        verdicts.extend(
                            self._local_witness_verify(seg.witnesses[k:])
                        )
                        break
            else:
                verdicts = self._local_witness_verify(seg.witnesses)
        finally:
            metrics.observe("replay.witness_wait", time.perf_counter() - t0)
        for k, ok in enumerate(verdicts):
            if not ok:
                return k
        return None

    # -- the run loop --------------------------------------------------------

    def run(self, chain, blocks: Sequence, witnesses=None) -> ReplayReport:
        """Import `blocks` onto `chain` through the segment pipeline.
        `witnesses`: optional per-block (claimed_root, nodes) list
        (fixture.attach_witnesses) verified as segment megabatches."""
        from phant_tpu.replay.lowering import device_roots_wanted

        report = ReplayReport()
        if not blocks:
            report.final_state_root = chain.state.state_root()
            return report

        root_mode = self.root_mode
        if root_mode is None:
            root_mode = "defer" if device_roots_wanted() else "host"
        verify_roots = chain.verify_state_root
        if root_mode == "defer" and verify_roots:
            # the engine owns root verification at segment granularity;
            # restore the chain's own per-block check on exit
            chain.verify_state_root = False

        metrics.gauge_set("replay.segment_blocks", self.segment_blocks)
        metrics.gauge_set("replay.pipeline_depth", self.pipeline_depth)

        segments = [
            _Segment(
                i // self.segment_blocks,
                i,
                list(blocks[i : i + self.segment_blocks]),
                None if witnesses is None else list(
                    witnesses[i : i + self.segment_blocks]
                ),
            )
            for i in range(0, len(blocks), self.segment_blocks)
        ]
        signer = chain.signer
        stats = {
            "segments": 0,
            "lane_sig_segments": 0,
            "local_sig_segments": 0,
            "witness_blocks": 0,
            "device_root_groups": 0,
            "device_roots": 0,
            "host_roots": 0,
        }

        stop = threading.Event()
        ready: "queue.Queue" = queue.Queue(
            maxsize=max(1, self.pipeline_depth - 1)
        )
        worker = None
        if self.pipeline_depth >= 2 and len(segments) > 1:

            def _lookahead():
                for seg in segments:
                    if stop.is_set():
                        break
                    try:
                        self._prepare(signer, seg)
                    except BaseException as exc:
                        seg.prepare_error = exc
                        seg.prepare_stage = STAGE_PREFETCH
                    while not stop.is_set():
                        try:
                            ready.put(seg, timeout=0.05)
                            break
                        except queue.Full:
                            continue

            worker = threading.Thread(
                target=_lookahead, name="replay-prefetch", daemon=True
            )
            worker.start()

        try:
            for seg in segments:
                if worker is not None:
                    got = ready.get()
                    assert got is seg  # strictly in order
                else:
                    try:
                        self._prepare(signer, seg)
                    except BaseException as exc:
                        seg.prepare_error = exc
                        seg.prepare_stage = STAGE_PREFETCH
                if seg.prepare_error is not None:
                    # lookahead died mid-stage: record it, then rebuild
                    # this segment inline with the lanes bypassed
                    self._record_crash(
                        seg, seg.prepare_stage or STAGE_PREFETCH,
                        seg.prepare_error,
                    )
                    self._prepare(signer, seg, degraded=True)
                done = self._run_segment(
                    chain, seg, report, stats, root_mode, verify_roots
                )
                if not done:
                    break
        finally:
            stop.set()
            if worker is not None:
                while worker.is_alive():
                    try:  # unblock a put-blocked worker
                        ready.get_nowait()
                    except queue.Empty:
                        pass
                    worker.join(timeout=0.05)
            if root_mode == "defer":
                chain.verify_state_root = verify_roots

        report.final_state_root = chain.state.state_root()
        report.blocks_ok = sum(1 for v in report.verdicts if v.ok)
        report.segments = stats["segments"]
        report.stats = stats
        return report

    def _run_segment(
        self, chain, seg: _Segment, report, stats, root_mode, verify_roots
    ) -> bool:
        """Resolve + execute one segment; False stops the import (a
        block failed — earlier blocks stand, run_blocks semantics)."""
        t_seg = time.perf_counter()
        bad_witness = self._resolve_witnesses(seg)
        senders = self._resolve_senders(signer=chain.signer, seg=seg)
        stats["segments"] += 1
        stats["lane_sig_segments" if seg.sig_kind == "lane" else
              "local_sig_segments"] += 1
        if seg.witnesses is not None:
            stats["witness_blocks"] += len(seg.witnesses)

        plans: List = []
        fallbacks: List = []
        executed = 0  # blocks of THIS segment executed OK
        failed: Optional[Tuple[int, str]] = None
        pos = 0
        for k, block in enumerate(seg.blocks):
            idx = seg.start + k
            n = seg.counts[k]
            if bad_witness is not None and k >= bad_witness:
                failed = (k, "witness verification failed")
                break
            try:
                chain.run_block(block, senders=senders[pos : pos + n])
            except BlockError as e:
                failed = (k, str(e))
                break
            pos += n
            executed += 1
            report.txs += n
            if root_mode == "defer" and verify_roots:
                from phant_tpu.ops.mpt_jax import build_hash_plan

                trie = chain.state.flush_root_trie()
                plan = build_hash_plan(trie)
                plans.append(plan)
                # unplannable block: capture the host root NOW (the trie
                # mutates again next block)
                fallbacks.append(
                    (lambda r=trie.root_hash(): r) if plan is None else None
                )

        # deferred segment roots: one vmapped device program per
        # structure-sharing run, host walk for the rest
        if root_mode == "defer" and verify_roots and plans:
            from phant_tpu.replay.lowering import (
                lower_segment_plans,
                resolve_segment_roots,
            )

            t0 = time.perf_counter()
            handles = lower_segment_plans(plans)
            roots, rstats = resolve_segment_roots(handles, fallbacks)
            metrics.observe("replay.root_wait", time.perf_counter() - t0)
            if rstats["device_groups"]:
                metrics.count(
                    "replay.root_groups", rstats["device_groups"],
                    backend="device",
                )
            if rstats["host_roots"]:
                metrics.count(
                    "replay.root_groups", rstats["host_roots"], backend="host"
                )
            stats["device_root_groups"] += rstats["device_groups"]
            stats["device_roots"] += rstats["device_roots"]
            stats["host_roots"] += rstats["host_roots"]
            for k in range(executed):
                header = seg.blocks[k].header
                if roots[k] != header.state_root:
                    failed = (
                        k,
                        f"state root mismatch: {roots[k].hex()} != "
                        f"{header.state_root.hex()}",
                    )
                    executed = k
                    break

        for k in range(executed):
            report.verdicts.append(
                BlockVerdict(
                    index=seg.start + k,
                    block_number=seg.blocks[k].header.block_number,
                    ok=True,
                )
            )
        metrics.count("replay.blocks", executed)
        metrics.count("replay.txs", sum(seg.counts[:executed]))
        metrics.count("replay.segments")
        metrics.observe("replay.segment_seconds", time.perf_counter() - t_seg)

        if failed is not None:
            k, err = failed
            block = seg.blocks[k]
            report.verdicts.append(
                BlockVerdict(
                    index=seg.start + k,
                    block_number=block.header.block_number,
                    ok=False,
                    error=err,
                )
            )
            # stage-named record: the block failed at the segment's
            # resolve stage (join + execute + root check); earlier
            # blocks stand and the import stops here, exactly like a
            # BlockError out of serial run_blocks
            flight.record(
                "replay.block_failed",
                segment=seg.index,
                block_index=seg.start + k,
                block_number=block.header.block_number,
                stage=STAGE_RESOLVE,
                error=err,
            )
            metrics.count("replay.block_failures")
            return False
        return True


def replay_fixture(
    fix,
    segment_blocks: int = DEFAULT_SEGMENT_BLOCKS,
    pipeline_depth: Optional[int] = None,
    root_mode: Optional[str] = None,
    verify_state_root: bool = True,
    use_witnesses: bool = True,
) -> ReplayReport:
    """Convenience: replay a fixture (fixture.load_fixture /
    from_bench_tuple) on a fresh chain through the segment pipeline."""
    chain = fix.fresh_chain(verify_state_root=verify_state_root)
    eng = ReplayEngine(
        segment_blocks=segment_blocks,
        pipeline_depth=pipeline_depth,
        root_mode=root_mode,
    )
    return eng.run(
        chain,
        fix.blocks,
        witnesses=fix.witnesses if use_witnesses else None,
    )
