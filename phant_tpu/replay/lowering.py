"""Segment-level root lowering: K block states hashed in ONE dispatch.

The serving root lane coalesces *across concurrent requests*
(ops/root_engine.py); historical replay has no concurrency to borrow —
its batch axis is the segment itself. A segment's per-block state tries
differ only in leaf *values* whenever no account was born or died and no
RLP field changed width, so consecutive blocks' HashPlans share one
level layout and vmap through `_hash_plans_batched` (ops/mpt_jax.py) as
a single fused device program. This module owns that lowering:

  * `group_segment_plans` splits a segment's plans into maximal
    structure-sharing runs (`plans_share_structure`) — an account
    birth/death or a width change simply ends the run, it never fails
    the segment;
  * `lower_segment_plans` dispatches every multi-plan run as one
    batched device call and defers singletons/unplannable blocks to the
    host walk — pure enqueue, no device sync (phantlint HOSTSYNC scopes
    this function: a reintroduced `.item()` in the megabatch loop is a
    gate-red regression);
  * `resolve_segment_roots` is the one honest sync point, reading all
    runs back after the EVM has moved on to the next segment.

Env: `PHANT_REPLAY_ROOT` (`0`/`host` pins the host walk, `1`/`device`
forces batched device dispatch — tests and the XLA-CPU proxy; `auto`
engages it exactly when the device route exists, the same shape as
PHANT_BATCHED_SIG/PHANT_BATCHED_ROOT).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from phant_tpu.ops.mpt_jax import (
    MPT_MAX_CHUNKS,
    HashPlan,
    _hash_plans_batched,
    execute_plan_host,
    plans_share_structure,
)


def device_roots_wanted() -> bool:
    """Route deferred segment roots to the batched device executor?
    Same 0/1/auto shape as stateless._batched_sig_wanted: the env pin is
    for tests and the XLA-CPU proxy, auto keys on a live device."""
    env = os.environ.get("PHANT_REPLAY_ROOT", "auto")
    if env in ("0", "off", "host", ""):
        return False
    if env in ("1", "device"):
        return True
    from phant_tpu.backend import crypto_backend, jax_device_ok

    return crypto_backend() == "tpu" and jax_device_ok()


def group_segment_plans(
    plans: Sequence[Optional[HashPlan]],
) -> List[Tuple[int, int]]:
    """Maximal [start, end) runs of consecutive structure-sharing plans.
    A None plan (embedded/oversized nodes — build_hash_plan declined) is
    always a singleton run; runs never merge across it."""
    runs: List[Tuple[int, int]] = []
    i = 0
    while i < len(plans):
        j = i + 1
        while (
            j < len(plans)
            and plans[i] is not None
            and plans[j] is not None
            and plans_share_structure(plans[i], plans[j])
        ):
            j += 1
        runs.append((i, j))
        i = j
    return runs


def lower_segment_plans(plans: Sequence[Optional[HashPlan]]) -> List[tuple]:
    """Dispatch a segment's per-block root plans: every run of >= 2
    structure-sharing plans becomes ONE vmapped `_hash_plans_batched`
    device program (K roots, one host->device round trip); singletons
    and unplannable blocks defer to the host walk at resolve time (the
    per-root RTT is exactly what the offload gate rejects at K=1).

    Returns opaque handles for `resolve_segment_roots`. This function is
    pure enqueue — it must never synchronize on device values (HOSTSYNC
    gate); the readback lives in resolve, after the EVM has moved on."""
    import jax.numpy as jnp

    handles: List[tuple] = []
    if not plans:
        return handles
    device_ok = device_roots_wanted()
    for i, j in group_segment_plans(plans):
        run = list(plans[i:j])
        if device_ok and run[0] is not None and (j - i) >= 2:
            blobs = jnp.asarray(np.stack([p.blob for p in run]))  # phantlint: disable=JNPHOSTLOOP — ONE stacked upload per structure-run (the merge is the point); runs per segment are bounded by plan-shape diversity, not block count
            # per-LEVEL metadata uploads, bounded by trie depth — the
            # node axis ships in the one stacked blob above
            levels_d = tuple(
                tuple(jnp.asarray(a) for a in lvl) for lvl in run[0].levels  # phantlint: disable=JNPHOSTLOOP — bounded per-level metadata upload
            )
            out = _hash_plans_batched(blobs, levels_d, max_chunks=MPT_MAX_CHUNKS)
            handles.append(("device", i, j, out))
        else:
            handles.append(("host", i, j, run))
    return handles


def resolve_segment_roots(
    handles: Sequence[tuple],
    fallbacks: Optional[Sequence[Optional[Callable[[], bytes]]]] = None,
) -> Tuple[List[Optional[bytes]], dict]:
    """Materialize every lowered run's roots, in block order.

    `fallbacks[k]` supplies the root for an unplannable block k (the
    replay engine captures `trie.root_hash` thunks at flush time). The
    device readback here is the segment's product — the one deliberate
    sync per segment, not an accidental one."""
    roots: List[Optional[bytes]] = []
    stats = {"device_groups": 0, "device_roots": 0, "host_roots": 0}
    for kind, i, j, payload in handles:
        if kind == "device":
            arr = np.asarray(payload, dtype="<u4")  # phantlint: disable=HOSTSYNC — segment root readback is the product
            for k in range(arr.shape[0]):
                roots.append(arr[k].tobytes())
            stats["device_groups"] += 1
            stats["device_roots"] += j - i
        else:
            for k, p in enumerate(payload, start=i):
                if p is not None:
                    roots.append(execute_plan_host(p))
                elif fallbacks is not None and fallbacks[k] is not None:
                    roots.append(fallbacks[k]())
                else:
                    roots.append(None)
                stats["host_roots"] += 1
    return roots, stats
