"""Replay fixture chains — the on-disk unit `python -m phant_tpu.replay`
consumes.

A fixture is a pickled dict carrying a genesis header, the genesis
account set, and an ordered block list (the same picklable shapes
bench.py's `_build_replay_chain` caches), optionally enriched with
per-block witnesses: `(claimed_root, nodes)` pairs generated against
each block's PARENT state under a named commitment scheme
(phant_tpu/commitment/). Witnessed fixtures let the replay engine drive
segment ingestion through the scheduler's witness lane — K blocks'
linked-multiproof checks coalescing into megabatches — in addition to
the sig/root megabatches an unwitnessed fixture already exercises.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

FORMAT = "phant-replay-fixture"
VERSION = 1


@dataclass
class ReplayFixture:
    """One replayable chain segment: genesis + blocks (+ witnesses)."""

    chain_id: int
    genesis: object  # types.block.BlockHeader
    genesis_accounts: Dict[bytes, object]  # address -> types.account.Account
    blocks: List[object]  # types.block.Block, ascending
    #: per-block (claimed_root, nodes) against the PARENT state, or None
    witnesses: Optional[List[Tuple[bytes, List[bytes]]]] = None
    #: commitment scheme the witnesses were generated under
    scheme: Optional[str] = None

    def fresh_state(self):
        from phant_tpu.state.statedb import StateDB

        return StateDB(
            {a: acct.copy() for a, acct in self.genesis_accounts.items()}
        )

    def fresh_chain(self, verify_state_root: bool = True):
        from phant_tpu.blockchain.chain import Blockchain

        return Blockchain(
            self.chain_id,
            self.fresh_state(),
            self.genesis,
            verify_state_root=verify_state_root,
        )

    @property
    def total_txs(self) -> int:
        return sum(len(b.transactions) for b in self.blocks)


def from_bench_tuple(built: tuple, chain_id: int = 1) -> ReplayFixture:
    """Adapt bench.py's `_build_replay_chain` cache tuple
    `(genesis, blocks, genesis_accounts, total_txs, n_calls)` — the one
    synthetic-chain builder in the tree stays the one in bench.py."""
    genesis, blocks, genesis_accounts, _total_txs, _n_calls = built
    return ReplayFixture(
        chain_id=chain_id,
        genesis=genesis,
        genesis_accounts=genesis_accounts,
        blocks=list(blocks),
    )


def attach_witnesses(fix: ReplayFixture, scheme=None) -> ReplayFixture:
    """Enrich a fixture with per-block full-state witnesses under
    `scheme` (default: the active PHANT_COMMITMENT scheme). Each block's
    claimed root commits its PARENT state — under the hexary mpt scheme
    that is byte-identical to the parent header's state_root; the binary
    scheme's roots are its own (the header chain stays hexary, the
    witness lane only checks linkage against the claimed root). Builds
    by replaying on a throwaway chain; O(blocks x state), fixture-prep
    cost, never on a replay path."""
    from phant_tpu.commitment import active_scheme

    sch = scheme if scheme is not None else active_scheme()
    chain = fix.fresh_chain(verify_state_root=False)
    witnesses: List[Tuple[bytes, List[bytes]]] = []
    for block in fix.blocks:
        root, nodes, _codes = sch.witness_of_state(chain.state.accounts)
        witnesses.append((root, list(nodes)))
        chain.run_block(block)
    fix.witnesses = witnesses
    fix.scheme = sch.name
    return fix


def save_fixture(path: str, fix: ReplayFixture) -> None:
    payload = {
        "format": FORMAT,
        "version": VERSION,
        "chain_id": fix.chain_id,
        "genesis": fix.genesis,
        "genesis_accounts": fix.genesis_accounts,
        "blocks": fix.blocks,
        "witnesses": fix.witnesses,
        "scheme": fix.scheme,
    }
    with open(path, "wb") as f:
        pickle.dump(payload, f)


def load_fixture(path: str) -> ReplayFixture:
    """Load a fixture file; the raw bench `_build_replay_chain` tuple is
    accepted too (a cached bench chain replays as-is)."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if isinstance(payload, tuple):
        return from_bench_tuple(payload)
    if not isinstance(payload, dict) or payload.get("format") != FORMAT:
        raise ValueError(f"{path}: not a {FORMAT} file")
    if payload.get("version") != VERSION:
        raise ValueError(
            f"{path}: fixture version {payload.get('version')!r} "
            f"(supported: {VERSION})"
        )
    return ReplayFixture(
        chain_id=payload["chain_id"],
        genesis=payload["genesis"],
        genesis_accounts=payload["genesis_accounts"],
        blocks=list(payload["blocks"]),
        witnesses=payload.get("witnesses"),
        scheme=payload.get("scheme"),
    )
