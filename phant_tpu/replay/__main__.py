"""CLI: replay a fixture chain through the segment pipeline.

    python -m phant_tpu.replay <fixture-chain> --segment K

The fixture is a `phant_tpu.replay.fixture` pickle (or a raw bench
`_build_replay_chain` cache tuple). `--scheduler` installs a
VerificationScheduler so segments ride the real sig/witness lanes
(`--mesh N` puts a MeshExecutorPool behind it); without it every stage
uses its local megabatch fallback. `--serial-check` re-imports the same
chain through serial `run_blocks` and asserts final-state-root
byte-identity — the CLI face of the differential contract the tests and
the `replay_sync` bench section pin.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m phant_tpu.replay", description=__doc__
    )
    ap.add_argument("fixture", help="fixture-chain file (replay/fixture.py)")
    ap.add_argument(
        "--segment",
        type=int,
        default=None,
        help="blocks per segment (default: PHANT_REPLAY_SEGMENT or 32)",
    )
    ap.add_argument(
        "--depth",
        type=int,
        default=None,
        help="segments in flight (default: PHANT_REPLAY_DEPTH or 2)",
    )
    ap.add_argument(
        "--root",
        choices=("auto", "host", "defer"),
        default="auto",
        help="segment root mode: host walk per block, or deferred "
        "device megabatches per segment (auto keys on a live device)",
    )
    ap.add_argument(
        "--no-witnesses",
        action="store_true",
        help="ignore fixture witnesses (sig/root megabatches only)",
    )
    ap.add_argument(
        "--scheduler",
        action="store_true",
        help="install a VerificationScheduler (sig + witness lanes)",
    )
    ap.add_argument(
        "--mesh",
        type=int,
        default=0,
        metavar="N",
        help="with --scheduler: N per-device mesh lanes",
    )
    ap.add_argument(
        "--serial-check",
        action="store_true",
        help="also run serial run_blocks; assert final-root identity",
    )
    ap.add_argument(
        "--stats", action="store_true", help="print replay.* metrics"
    )
    args = ap.parse_args(argv)

    from phant_tpu.replay import DEFAULT_SEGMENT_BLOCKS, ReplayEngine, load_fixture

    segment = args.segment
    if segment is None:
        segment = int(
            os.environ.get("PHANT_REPLAY_SEGMENT", str(DEFAULT_SEGMENT_BLOCKS))
        )
    fix = load_fixture(args.fixture)
    print(
        f"[replay] {args.fixture}: {len(fix.blocks)} blocks, "
        f"{fix.total_txs} txs, segment={segment}"
        + (f", witnesses({fix.scheme})" if fix.witnesses else "")
    )

    root_mode = None if args.root == "auto" else args.root
    sched = None
    if args.scheduler:
        # the lane decision is stateless._batched_sig_wanted; on a pure
        # CPU host the lane must be asked for explicitly
        os.environ.setdefault("PHANT_BATCHED_SIG", "1")
        from phant_tpu import serving
        from phant_tpu.ops.sig_engine import SigEngine
        from phant_tpu.ops.witness_engine import WitnessEngine

        sched = serving.VerificationScheduler(
            engine=WitnessEngine(),
            config=serving.SchedulerConfig(
                max_batch=max(16, segment),
                max_wait_ms=20.0,
                pipeline_depth=2,
                mesh_devices=args.mesh,
                sig_engine_factory=lambda: SigEngine(device_floor=0),
            ),
        )
        serving.install(sched)

    try:
        chain = fix.fresh_chain()
        eng = ReplayEngine(
            segment_blocks=segment,
            pipeline_depth=args.depth,
            root_mode=root_mode,
        )
        t0 = time.perf_counter()
        report = eng.run(
            chain,
            fix.blocks,
            witnesses=None if args.no_witnesses else fix.witnesses,
        )
        dt = time.perf_counter() - t0
        bps = report.blocks_ok / dt if dt > 0 else 0.0
        print(
            f"[replay] {report.blocks_ok}/{len(fix.blocks)} blocks ok in "
            f"{dt:.3f}s ({bps:.1f} blocks/s, {report.segments} segments, "
            f"{report.txs} txs)"
        )
        print(f"[replay] final state root {report.final_state_root.hex()}")
        for v in report.verdicts:
            if not v.ok:
                print(
                    f"[replay] block #{v.block_number} (index {v.index}) "
                    f"FAILED: {v.error}"
                )
        if args.stats:
            from phant_tpu.utils.trace import metrics

            snap = metrics.snapshot()
            for family in ("counters", "gauges", "timers", "histograms"):
                for name, val in sorted(snap.get(family, {}).items()):
                    if str(name).startswith("replay."):
                        print(f"[replay] {name} = {val}")
        if args.serial_check:
            serial_chain = fix.fresh_chain()
            t0 = time.perf_counter()
            try:
                serial_chain.run_blocks(fix.blocks)
                serial_ok = True
            except Exception as exc:
                serial_ok = False
                print(f"[replay] serial run_blocks stopped: {exc}")
            sdt = time.perf_counter() - t0
            serial_root = serial_chain.state.state_root()
            print(
                f"[replay] serial run_blocks: {sdt:.3f}s; final root "
                f"{serial_root.hex()}"
            )
            if serial_root != report.final_state_root or (
                serial_ok is not report.ok
            ):
                print("[replay] MISMATCH vs serial run_blocks")
                return 2
            print("[replay] serial-check: final-state-root identity OK")
        return 0 if report.ok else 1
    finally:
        if sched is not None:
            from phant_tpu import serving

            serving.uninstall(sched)
            sched.shutdown()


if __name__ == "__main__":
    sys.exit(main())
