"""Historical chain replay as a megabatch workload (catch-up sync).

The product surface ROADMAP calls "how fast can a fresh node catch up":
`ReplayEngine` holds a whole chain segment and drives it through the
serving stack's witness/root/sig lanes at far-past-serving batch shapes
— one merged ecrecover launch per segment, witness megabatches against
per-lane resident intern tables, K block-state roots per vmapped device
program — with a prefetch pipeline that builds segment N+1's inputs
under segment N's EVM execution. `python -m phant_tpu.replay
<fixture-chain> --segment K` is the CLI face; bench.py's `replay_sync`
section is the committed number.
"""

from phant_tpu.replay.engine import (
    DEFAULT_SEGMENT_BLOCKS,
    BlockVerdict,
    ReplayEngine,
    ReplayReport,
    replay_fixture,
)
from phant_tpu.replay.fixture import (
    ReplayFixture,
    attach_witnesses,
    from_bench_tuple,
    load_fixture,
    save_fixture,
)

__all__ = [
    "DEFAULT_SEGMENT_BLOCKS",
    "BlockVerdict",
    "ReplayEngine",
    "ReplayReport",
    "ReplayFixture",
    "attach_witnesses",
    "from_bench_tuple",
    "load_fixture",
    "replay_fixture",
    "save_fixture",
]
