"""Multi-chip scaling: device meshes, sharded kernels, multi-host init.

The reference is single-process and has no distributed backend (SURVEY §2:
its only network surface is the HTTP Engine API, reference:
src/main.zig:143-149). This framework's scale-out axis is data parallelism
over blocks/nodes/signatures: a `jax.sharding.Mesh` with one `dp` axis,
`shard_map`-ped kernels whose per-shard partial results are combined with
XLA collectives over ICI (within a slice) / DCN (across slices), and
`jax.distributed` for multi-host process groups — the TPU-native
equivalent of a NCCL/MPI backend.

Tested on a virtual 8-device CPU mesh (tests/test_parallel.py); the driver
dry-runs the same path via __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from phant_tpu.crypto.keccak import RATE
from phant_tpu.ops.witness_jax import (
    WITNESS_MAX_CHUNKS,
    _digests_from_rows,
    _extract_ref_positions,
    _gather_node_rows,
    _gather_refs,
    _ref_words_from_rows,
    linked_verdict,
    witness_digests,
)

if hasattr(jax, "shard_map"):  # jax >= 0.8 moved shard_map out of experimental
    shard_map = jax.shard_map
else:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore


import contextlib
import threading

# serializes the cache-suspension window below: the config flip is
# process-global, so concurrent sharded compiles must take turns. A
# single-device compile racing the window at worst skips one persistent-
# cache write (benign; its in-memory executable is unaffected) — there is
# no corruption mode, which is what makes the sharded path default-safe
# in threaded servers.
_CACHE_TOGGLE_LOCK = threading.RLock()

# AOT-compiled sharded executables, keyed by (kernel, mesh devices, static
# params, input shapes/dtypes). Every sharded entry point below used to
# build a FRESH closure and jax.jit it per call, which meant (a) a full
# re-trace on every call and (b) the process-global cache-suspension
# window toggling around every one of them — under mesh-sharded SERVING
# that toggle would fire per dispatched batch forever, and any concurrent
# single-device compile would lose its persistent-cache write each time.
# The memo compiles once per key (inside the suspension window) via the
# AOT path (jit().lower().compile()); steady-state calls hit the compiled
# executable directly and never touch the cache config again.
# MeshExecutorPool pre-warms the serving kernels at start
# (prewarm_sharded), so a serving process pays its suspension windows at
# boot, not mid-traffic.
_EXEC_CACHE: dict = {}
_EXEC_LOCK = threading.Lock()


def _mesh_key(mesh: "Mesh") -> tuple:
    return (mesh.axis_names, tuple(d.id for d in mesh.devices.flat))


def _arg_key(args) -> tuple:
    return tuple((tuple(a.shape), str(a.dtype)) for a in args)


def _compiled_call(key: tuple, build, args):
    """Run `jax.jit(build())` AOT-compiled and memoized under `key`.

    `args` must already be device_put with the shardings the traceable
    expects — the lowered executable bakes them in, and the memo key
    carries the mesh device ids + input shapes/dtypes so a shape or mesh
    change compiles a fresh executable. The whole miss path (including
    the compile) runs under _EXEC_LOCK: first-compiles were already
    serialized by the cache-toggle lock, and a lock-free read of the
    shared dict would be exactly the unlocked-shared-state hazard
    phantlint's LOCK rule exists to catch."""
    with _EXEC_LOCK:
        fn = _EXEC_CACHE.get(key)
        if fn is None:
            with _no_compile_cache():
                fn = jax.jit(build()).lower(*args).compile()
            _EXEC_CACHE[key] = fn
    return fn(*args)


@contextlib.contextmanager
def _no_compile_cache():
    """Serializing multi-device (shard_map) executables SEGFAULTS this
    image's jaxlib in the persistent compilation cache's write path
    (reproduced deterministically with a fresh single-writer cache dir), so
    every sharded compile below runs with the cache suspended. Single-device
    kernels keep the cache — their serialization is fine."""
    with _CACHE_TOGGLE_LOCK:
        try:
            prev = jax.config.jax_compilation_cache_dir
        except AttributeError:  # pragma: no cover - much older jax
            yield
            return
        if prev is None:
            yield
            return
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            yield
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    """1-D device mesh over the first n (default: all) local devices."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices but jax sees {len(devices)} "
                f"({devices[0].platform}); set JAX_PLATFORMS=cpu and "
                f"--xla_force_host_platform_device_count for a virtual mesh"
            )
        devices = devices[:n_devices]
    # jax.devices() yields Device HANDLES, not device arrays — no data
    # moves here (HOSTSYNC's taint heuristic cannot tell the difference)
    return Mesh(np.array(devices), axis_names=(axis,))  # phantlint: disable=HOSTSYNC — device handles, not arrays


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host process group (the NCCL/MPI-equivalent bootstrap):
    after this, jax.devices() spans every host's chips and the collectives
    in the sharded kernels ride ICI/DCN. No-op arguments let TPU pods
    auto-detect their topology."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


# ---------------------------------------------------------------------------
# sharded witness verification (dp over the node axis)
# ---------------------------------------------------------------------------


def witness_verify_fused_sharded(
    mesh: Mesh,
    blob,
    meta16,
    roots,
    *,
    max_chunks: int = WITNESS_MAX_CHUNKS,
    n_blocks: Optional[int] = None,
):
    """The flagship fused kernel (on-device RLP ref extraction,
    phant_tpu/ops/witness_jax.py witness_verify_fused) with the node axis
    sharded over `dp`. Each shard gathers its node rows from the replicated
    blob, hashes them, and parses its own nodes' child refs on device; node
    lengths are all_gather-ed once for the global offset prefix-sum, and the
    per-shard ref slices are all_gather-ed for the linkage join (a node's
    parent may sit on any shard — these are the collectives that ride ICI).
    Per-block partials combine with pmax (root hit) / pmin (all linked).

    The node axis must be divisible by the mesh size (pack_witness_fused
    pads to powers of two)."""
    if n_blocks is None:
        n_blocks = int(roots.shape[0])
    axis = mesh.axis_names[0]

    def build():
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(None, axis), P()),
            out_specs=P(),
        )
        def inner(blob_s, meta_s, roots_s):
            lens_l = meta_s[0].astype(jnp.int32)
            block_l = meta_s[1].astype(jnp.int32)
            nloc = lens_l.shape[0]
            lens_all = jax.lax.all_gather(lens_l, axis, axis=0, tiled=True)
            off_all = jnp.cumsum(lens_all) - lens_all  # exclusive global offsets
            i = jax.lax.axis_index(axis)
            offsets_l = jax.lax.dynamic_slice(off_all, (i * nloc,), (nloc,))
            data = _gather_node_rows(blob_s, offsets_l, lens_l, max_chunks * RATE)
            digests = _digests_from_rows(data, lens_l, max_chunks=max_chunks)
            ref_pos = _extract_ref_positions(data, lens_l)
            refs_l = _ref_words_from_rows(data, ref_pos).reshape(-1, 8)
            live_l = (ref_pos >= 0).reshape(-1)
            rblock_l = jnp.broadcast_to(block_l[:, None], ref_pos.shape).reshape(-1)
            refs = jax.lax.all_gather(refs_l, axis, axis=0, tiled=True)
            ref_block = jax.lax.all_gather(rblock_l, axis, axis=0, tiled=True)
            ref_live = jax.lax.all_gather(live_l, axis, axis=0, tiled=True)
            root_hit, all_ok = linked_verdict(
                digests, lens_l, block_l, refs, ref_block, ref_live, roots_s, n_blocks
            )
            return jnp.stack(
                [jax.lax.pmax(root_hit, axis), jax.lax.pmin(all_ok, axis)]
            )

        return inner

    repl = NamedSharding(mesh, P())
    col = NamedSharding(mesh, P(None, axis))
    args = (
        jax.device_put(jnp.asarray(blob), repl),
        jax.device_put(jnp.asarray(meta16), col),
        jax.device_put(jnp.asarray(roots), repl),
    )
    key = ("fused", _mesh_key(mesh), max_chunks, n_blocks) + _arg_key(args)
    out = _compiled_call(key, build, args)
    return (out[0] > 0) & (out[1] > 0)


def witness_verify_linked_sharded(
    mesh: Mesh,
    blob,
    meta,
    ref_meta,
    roots,
    *,
    max_chunks: int = WITNESS_MAX_CHUNKS,
    n_blocks: Optional[int] = None,
):
    """Full (linked) multiproof verification with BOTH the node axis and the
    ref axis sharded over `dp`. Each shard hashes its nodes and gathers its
    slice of child refs from the replicated blob; the ref slices are then
    `all_gather`-ed over the mesh (a small array — this is the collective
    that rides ICI) because a node's parent may sit on any shard. Per-block
    partials combine with pmax (root hit) / pmin (all nodes linked).

    Node and ref axes must be divisible by the mesh size (pack_witness pads
    both to powers of two).
    """
    if n_blocks is None:
        n_blocks = int(roots.shape[0])
    axis = mesh.axis_names[0]

    def build():
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(None, axis), P(None, axis), P()),
            out_specs=P(),
        )
        def inner(blob_s, meta_s, ref_s, roots_s):
            offsets, lens, block_id = meta_s[0], meta_s[1], meta_s[2]
            digests = witness_digests(blob_s, offsets, lens, max_chunks=max_chunks)
            refs_local = _gather_refs(blob_s, ref_s[0])
            refs = jax.lax.all_gather(refs_local, axis, axis=0, tiled=True)
            ref_block = jax.lax.all_gather(ref_s[1], axis, axis=0, tiled=True)
            ref_live = jax.lax.all_gather(ref_s[0] >= 0, axis, axis=0, tiled=True)
            root_hit, all_ok = linked_verdict(
                digests, lens, block_id, refs, ref_block, ref_live, roots_s, n_blocks
            )
            return jnp.stack([jax.lax.pmax(root_hit, axis), jax.lax.pmin(all_ok, axis)])

        return inner

    repl = NamedSharding(mesh, P())
    col = NamedSharding(mesh, P(None, axis))
    args = (
        jax.device_put(jnp.asarray(blob), repl),
        jax.device_put(jnp.asarray(meta), col),
        jax.device_put(jnp.asarray(ref_meta), col),
        jax.device_put(jnp.asarray(roots), repl),
    )
    key = ("linked", _mesh_key(mesh), max_chunks, n_blocks) + _arg_key(args)
    out = _compiled_call(key, build, args)
    return (out[0] > 0) & (out[1] > 0)


def witness_digests_sharded(mesh: Mesh, blob, offsets, lens, *, max_chunks: int = WITNESS_MAX_CHUNKS):
    """The witness engine's novel-batch keccak (ops/witness_engine.py
    _hash_batch_device) with the NODE axis sharded over `dp`: the blob is
    replicated, each shard hashes its slice of nodes, outputs stay sharded
    (no collective — hashing is embarrassingly parallel; the engine's
    linkage join runs on host integers). This is the steady-state
    multi-chip path: novel nodes per block are few, so one mesh dispatch
    hashes a whole prefetch window's novelty.

    The node axis must be divisible by the mesh size (callers pad to
    powers of two)."""
    axis = mesh.axis_names[0]

    def build():
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(axis),
        )
        def inner(blob_s, off_s, lens_s):
            return witness_digests(blob_s, off_s, lens_s, max_chunks=max_chunks)

        return inner

    repl = NamedSharding(mesh, P())
    col = NamedSharding(mesh, P(axis))
    args = (
        jax.device_put(jnp.asarray(blob), repl),
        jax.device_put(jnp.asarray(offsets), col),
        jax.device_put(jnp.asarray(lens), col),
    )
    key = ("digests", _mesh_key(mesh), max_chunks) + _arg_key(args)
    return _compiled_call(key, build, args)


# ---------------------------------------------------------------------------
# sharded ecrecover (dp over the signature axis)
# ---------------------------------------------------------------------------


def ecrecover_sharded(mesh: Mesh, e, r, s, parity):
    """Batched ecrecover with the signature axis sharded over `dp`. Each
    shard runs the full fused kernel on its slice; outputs shard the same
    way (no collective needed — recovery is embarrassingly parallel).

    Batch size must be divisible by the mesh size (ecrecover_batch buckets
    to powers of two, so any power-of-two mesh divides it).
    """
    from phant_tpu.ops.secp256k1_jax import ecrecover_kernel

    axis = mesh.axis_names[0]

    def build():
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis)),
        )
        def inner(e_s, r_s, s_s, p_s):
            return ecrecover_kernel(e_s, r_s, s_s, p_s)

        return inner

    shard = NamedSharding(mesh, P(axis))
    # four FIXED kernel arguments, not a data axis — each upload is one
    # sharded array carrying the whole batch
    args = [jax.device_put(jnp.asarray(v), shard) for v in (e, r, s, parity)]  # phantlint: disable=JNPHOSTLOOP — fixed argument tuple, not per-element
    key = ("ecrecover", _mesh_key(mesh)) + _arg_key(args)
    return _compiled_call(key, build, args)


def ecrecover_glv_sharded(mesh: Mesh, r, parity, mags, signs):
    """The GLV half-width ladder (ops/secp256k1_jax.ecrecover_kernel_glv)
    with the signature axis sharded over `dp` — same embarrassingly
    parallel layout as ecrecover_sharded, ~2x the per-chip throughput.
    Returns (digests, valid, degenerate); degenerate elements must replay
    on the exact CPU path, exactly as in the single-chip dispatch.

    PRECONDITION: mags/signs must come from pack_glv_inputs (which screens
    0 < r,s < N) — the kernel cannot detect an out-of-range s itself."""
    from phant_tpu.ops.secp256k1_jax import ecrecover_kernel_glv

    axis = mesh.axis_names[0]

    def build():
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P(axis)),
        )
        def inner(r_s, p_s, m_s, s_s):
            return ecrecover_kernel_glv(r_s, p_s, m_s, s_s)

        return inner

    shard = NamedSharding(mesh, P(axis))
    args = [
        jax.device_put(jnp.asarray(v), shard) for v in (r, parity, mags, signs)  # phantlint: disable=JNPHOSTLOOP — fixed argument tuple, not per-element
    ]
    key = ("ecrecover_glv", _mesh_key(mesh)) + _arg_key(args)
    return _compiled_call(key, build, args)


# ---------------------------------------------------------------------------
# serving prewarm
# ---------------------------------------------------------------------------


def prewarm_sharded(
    mesh: Mesh, *, max_chunks: int = WITNESS_MAX_CHUNKS, n_blocks: int = 8
) -> int:
    """Compile the serving-path sharded executables once, at startup.

    MeshExecutorPool calls this when the mesh serving path comes up so the
    first served batch doesn't pay a multi-second cold shard_map compile
    mid-traffic, and so the compile-cache suspension windows
    (_no_compile_cache — a process-global config toggle) fire at BOOT,
    where no single-device compile is racing them. Production shapes that
    differ from the prewarm shapes still compile once each on first hit
    (bucketing keeps that set small); what the executable memo guarantees
    is that STEADY-STATE sharded dispatches never toggle the cache at all.
    Returns the number of executables compiled (0 when both were already
    warm)."""
    n = int(mesh.devices.size)
    before = len(_EXEC_CACHE)
    # tiny all-pad shapes: verdicts are meaningless (and ignored) — the
    # point is the compile, and pad rows (len 0) are a layout every kernel
    # already handles
    B = 2 * n
    blob = np.zeros(
        1 << (B * 64 + max_chunks * RATE - 1).bit_length(), np.uint8
    )
    offsets = np.zeros(B, np.int32)
    lens = np.zeros(B, np.int32)
    # one-shot boot prewarm: the forced syncs below ARE the point (not on
    # any hot path phantlint HOSTSYNC scopes to)
    np.asarray(witness_digests_sharded(mesh, blob, offsets, lens, max_chunks=max_chunks))
    meta16 = np.zeros((2, B), np.uint16)
    roots = np.zeros((n_blocks, 8), np.uint32)
    np.asarray(
        witness_verify_fused_sharded(
            mesh, blob, meta16, roots, max_chunks=max_chunks, n_blocks=n_blocks
        )
    )
    return len(_EXEC_CACHE) - before
