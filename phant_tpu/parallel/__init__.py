"""Multi-chip / multi-host scaling (device meshes + sharded kernels)."""

from phant_tpu.parallel.mesh import (
    ecrecover_glv_sharded,
    ecrecover_sharded,
    init_distributed,
    make_mesh,
    shard_map,
    witness_digests_sharded,
    witness_verify_fused_sharded,
    witness_verify_linked_sharded,
)

__all__ = [
    "ecrecover_glv_sharded",
    "ecrecover_sharded",
    "init_distributed",
    "make_mesh",
    "shard_map",
    "witness_digests_sharded",
    "witness_verify_fused_sharded",
    "witness_verify_linked_sharded",
]
