"""Crypto-backend selection (`--crypto_backend=cpu|tpu`).

The reference has no such switch (its crypto is always native CPU,
reference: src/crypto/hasher.zig, src/crypto/ecdsa.zig); this framework's
north star adds a TPU device path for the stateless hot loop (batched
keccak / MPT witness verify / ecrecover, see phant_tpu/ops/). The selected
backend is process-global, mirroring how the reference picks its chain
config once at startup (reference: src/main.zig:109-118).
"""

from __future__ import annotations

_CRYPTO_BACKEND = "cpu"
_VALID = ("cpu", "tpu")

# EVM bytecode execution backend: "python" (phant_tpu/evm/interpreter.py) or
# "native" (the C++ core in native/evm.cc, the reference's evmone analog).
_EVM_BACKEND = "python"
_VALID_EVM = ("python", "native")


def set_crypto_backend(name: str) -> None:
    global _CRYPTO_BACKEND
    if name not in _VALID:
        raise ValueError(f"crypto backend must be one of {_VALID}, got {name!r}")
    _CRYPTO_BACKEND = name


def crypto_backend() -> str:
    return _CRYPTO_BACKEND


_JAX_DEVICE_OK: bool | None = None


def jax_device_ok() -> bool:
    """Whether running the jax kernels is sensible on this host.

    The jax ecrecover kernel on a plain CPU is ~40x slower than the fused
    native batch — if `--crypto_backend=tpu` is set but no accelerator is
    attached, block validation must fall back to the native path rather than
    quietly regress. An accelerator counts; so does an explicitly requested
    CPU-mesh run (PHANT_ALLOW_JAX_CPU=1, used by the differential test suite
    and the multi-chip dryrun, where the virtual CPU mesh is the point).
    """
    global _JAX_DEVICE_OK
    import os

    if os.environ.get("PHANT_ALLOW_JAX_CPU", "0") not in ("", "0"):
        return True
    if _JAX_DEVICE_OK is None:
        try:
            import jax

            _JAX_DEVICE_OK = jax.default_backend() != "cpu"
        except Exception:
            _JAX_DEVICE_OK = False
    return _JAX_DEVICE_OK


def set_evm_backend(name: str) -> None:
    global _EVM_BACKEND
    if name not in _VALID_EVM:
        raise ValueError(f"evm backend must be one of {_VALID_EVM}, got {name!r}")
    _EVM_BACKEND = name


def evm_backend() -> str:
    return _EVM_BACKEND
