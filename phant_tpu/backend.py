"""Crypto-backend selection (`--crypto_backend=cpu|tpu`).

The reference has no such switch (its crypto is always native CPU,
reference: src/crypto/hasher.zig, src/crypto/ecdsa.zig); this framework's
north star adds a TPU device path for the stateless hot loop (batched
keccak / MPT witness verify / ecrecover, see phant_tpu/ops/). The selected
backend is process-global, mirroring how the reference picks its chain
config once at startup (reference: src/main.zig:109-118).
"""

from __future__ import annotations

_CRYPTO_BACKEND = "cpu"
_VALID = ("cpu", "tpu")


def set_crypto_backend(name: str) -> None:
    global _CRYPTO_BACKEND
    if name not in _VALID:
        raise ValueError(f"crypto backend must be one of {_VALID}, got {name!r}")
    _CRYPTO_BACKEND = name


def crypto_backend() -> str:
    return _CRYPTO_BACKEND
