"""Crypto-backend selection (`--crypto_backend=cpu|tpu`).

The reference has no such switch (its crypto is always native CPU,
reference: src/crypto/hasher.zig, src/crypto/ecdsa.zig); this framework's
north star adds a TPU device path for the stateless hot loop (batched
keccak / MPT witness verify / ecrecover, see phant_tpu/ops/). The selected
backend is process-global, mirroring how the reference picks its chain
config once at startup (reference: src/main.zig:109-118).
"""

from __future__ import annotations

_CRYPTO_BACKEND = "cpu"
_VALID = ("cpu", "tpu")

# EVM bytecode execution backend: "python" (phant_tpu/evm/interpreter.py) or
# "native" (the C++ core in native/evm.cc, the reference's evmone analog).
_EVM_BACKEND = "python"
_VALID_EVM = ("python", "native")


def set_crypto_backend(name: str) -> None:
    global _CRYPTO_BACKEND
    if name not in _VALID:
        raise ValueError(f"crypto backend must be one of {_VALID}, got {name!r}")
    _CRYPTO_BACKEND = name


def crypto_backend() -> str:
    return _CRYPTO_BACKEND


def set_evm_backend(name: str) -> None:
    global _EVM_BACKEND
    if name not in _VALID_EVM:
        raise ValueError(f"evm backend must be one of {_VALID_EVM}, got {name!r}")
    _EVM_BACKEND = name


def evm_backend() -> str:
    return _EVM_BACKEND
