"""Crypto-backend selection (`--crypto_backend=cpu|tpu`).

The reference has no such switch (its crypto is always native CPU,
reference: src/crypto/hasher.zig, src/crypto/ecdsa.zig); this framework's
north star adds a TPU device path for the stateless hot loop (batched
keccak / MPT witness verify / ecrecover, see phant_tpu/ops/). The selected
backend is process-global, mirroring how the reference picks its chain
config once at startup (reference: src/main.zig:109-118).
"""

from __future__ import annotations

import threading

_CRYPTO_BACKEND = "cpu"
_VALID = ("cpu", "tpu")

# Engine API handler threads race into the lazy probes below (phantlint
# LOCK): the link probe is ~0.3s and writes TWO related globals (profile
# + failure-backoff deadline), so an unserialized race is double probing
# at best and torn routing state at worst. One lock for all of them.
_probe_lock = threading.Lock()

# EVM bytecode execution backend: "python" (phant_tpu/evm/interpreter.py) or
# "native" (the C++ core in native/evm.cc, the reference's evmone analog).
_EVM_BACKEND = "python"
_VALID_EVM = ("python", "native")


def set_crypto_backend(name: str) -> None:
    global _CRYPTO_BACKEND
    if name not in _VALID:
        raise ValueError(f"crypto backend must be one of {_VALID}, got {name!r}")
    from phant_tpu.utils.trace import metrics

    metrics.count("backend.selected", backend=name)
    _CRYPTO_BACKEND = name


def crypto_backend() -> str:
    return _CRYPTO_BACKEND


_JAX_DEVICE_OK: bool | None = None


def jax_device_ok() -> bool:
    """Whether running the jax kernels is sensible on this host.

    The jax ecrecover kernel on a plain CPU is ~40x slower than the fused
    native batch — if `--crypto_backend=tpu` is set but no accelerator is
    attached, block validation must fall back to the native path rather than
    quietly regress. An accelerator counts; so does an explicitly requested
    CPU-mesh run (PHANT_ALLOW_JAX_CPU=1, used by the differential test suite
    and the multi-chip dryrun, where the virtual CPU mesh is the point).
    """
    global _JAX_DEVICE_OK
    import os

    if os.environ.get("PHANT_ALLOW_JAX_CPU", "0") not in ("", "0"):
        return True
    if _JAX_DEVICE_OK is None:
        with _probe_lock:
            if _JAX_DEVICE_OK is None:
                try:
                    import jax

                    _JAX_DEVICE_OK = jax.default_backend() != "cpu"
                except Exception:
                    _JAX_DEVICE_OK = False
    return _JAX_DEVICE_OK


_LINK_PROFILE: tuple | None = None
_LINK_FAIL_UNTIL: float | None = None  # monotonic deadline of the backoff
_LINK_FAIL_TTL_S = 60.0


def device_link_profile() -> tuple:
    """(upload_bytes_per_sec, roundtrip_sec), measured once per process.

    The offload cost model needs real link numbers: a locally attached TPU
    uploads at GB/s with sub-ms dispatch, while a tunneled development chip
    can be ~20 MB/s with ~50ms round trips — three orders of magnitude that
    flip which batch sizes are worth shipping. Probing costs ~0.3s once.
    Overridable for tests/ops via PHANT_LINK_MBPS / PHANT_LINK_RTT_MS."""
    if _LINK_PROFILE is not None:  # lock-free fast path: write-once tuple
        return _LINK_PROFILE
    # serialize the probe (phantlint LOCK): concurrent handler threads must
    # wait for one measurement, not run N tunnelled probes and tear the
    # profile/backoff pair
    with _probe_lock:
        return _device_link_profile_locked()


def _device_link_profile_locked() -> tuple:
    global _LINK_PROFILE, _LINK_FAIL_UNTIL
    import os
    import time as _time

    if _LINK_PROFILE is not None:
        return _LINK_PROFILE
    if _LINK_FAIL_UNTIL is not None and _time.monotonic() < _LINK_FAIL_UNTIL:
        return (1.0, 3600.0)  # recent probe failure: don't re-pay it yet
    mbps = os.environ.get("PHANT_LINK_MBPS")
    rtt = os.environ.get("PHANT_LINK_RTT_MS")
    if mbps and rtt:
        _LINK_PROFILE = (float(mbps) * 1e6, float(rtt) / 1e3)
        return _LINK_PROFILE
    try:
        import time

        import jax.numpy as jnp
        import numpy as np

        tiny = jnp.zeros((8,), jnp.uint32)
        # the probe MEASURES the round trip — the sync is the point here
        int(jnp.sum(tiny))  # warm dispatch path # phantlint: disable=HOSTSYNC
        # best-of-3 samples: a single scheduler hiccup must not skew
        # routing for the whole process lifetime
        lat = min(
            _timed(lambda: int(jnp.sum(tiny)), time) for _ in range(3)  # phantlint: disable=HOSTSYNC — timed probe
        )
        # random payloads, DISTINCT pre-generated buffer per sample: a
        # compressing transport must not flatter the probe, jax dedupes a
        # repeated transfer of the same host buffer (observed: the second
        # sample of one array measured ~0s -> a petabytes/s "link"), and
        # RNG generation must stay OUTSIDE the timed window.
        # TWO sizes, bandwidth from the SLOPE: a single small transfer
        # minus RTT is meaningless on a relay-buffered tunnel (observed:
        # 1MB "measured" 576 MB/s on a ~40 MB/s link because the relay
        # acks the write into its buffer; the r4 gate was structurally
        # closed so the poisoned number never routed anything — the open
        # gate made it ship 39MB state-root plans into a 700s timeout).
        # The big buffer must be large enough that transfer time >> RTT.
        rng = np.random.default_rng(0)
        size_small = 1 << 20
        size_big = 12 << 20
        warm_buf = rng.integers(0, 256, size_small, dtype=np.uint8)
        # DISTINCT buffer per sample, not one buffer timed 3x: jax dedupes
        # a repeated transfer of the same host buffer, so samples 2 and 3
        # of a reused array measure ~0s and the min() elects a petabytes/s
        # "link" (exactly the flattery the comment above warns about). All
        # RNG generation stays OUTSIDE the timed window.
        bufs_small = [
            rng.integers(0, 256, size_small, dtype=np.uint8) for _ in range(3)
        ]
        bufs_big = [
            rng.integers(0, 256, size_big, dtype=np.uint8) for _ in range(3)
        ]
        # sum the WHOLE buffer: consuming only a slice lets the transport
        # defer most of the transfer (observed: a sliced readback clocked
        # the 1MB upload at the 50 GB/s sanity clamp). The on-device sum
        # is noise next to any real link time.
        int(jnp.sum(jnp.asarray(warm_buf)))  # warm transfer path # phantlint: disable=HOSTSYNC
        # min-of-3 per size (same rationale as the latency probe: one
        # scheduler hiccup must not skew routing for the process lifetime)
        t_small = min(
            _timed(lambda b=b: int(jnp.sum(jnp.asarray(b))), time)  # phantlint: disable=HOSTSYNC — timed probe
            for b in bufs_small
        )
        t_big = min(
            _timed(lambda b=b: int(jnp.sum(jnp.asarray(b))), time)  # phantlint: disable=HOSTSYNC — timed probe
            for b in bufs_big
        )
        # slope over the size delta cancels RTT and fixed dispatch costs.
        # A non-positive slope means the probe is unusable (a hiccup ate
        # t_small) — report a dead link for the TTL rather than clamp to
        # a ceiling the tunnel cannot possibly have.
        delta = t_big - t_small
        if delta <= 0:
            _LINK_FAIL_UNTIL = _time.monotonic() + _LINK_FAIL_TTL_S
            return (1.0, 3600.0)
        # floor at a 50 GB/s physical ceiling (no real link is faster)
        up = max(delta, (size_big - size_small) / 50e9)
        _LINK_PROFILE = ((size_big - size_small) / up, lat)
    except Exception:
        # probe failure: report an unusable link and back off for a TTL —
        # neither extreme is right (r2 pinned never-offload for the whole
        # process on one hiccup; an uncached failure would re-pay a
        # seconds-long dead-tunnel probe on EVERY novel batch of the hot
        # verification path during an outage)
        _LINK_FAIL_UNTIL = _time.monotonic() + _LINK_FAIL_TTL_S
        return (1.0, 3600.0)
    return _LINK_PROFILE


def _timed(fn, time_mod) -> float:
    t0 = time_mod.perf_counter()
    fn()
    return time_mod.perf_counter() - t0


# measured throughput constants for the adaptive offload cost model
# (bytes/s of keccak input): the 8-way AVX-512 native batch on one core
# (BENCH r4: 317 MB/s at MPT node sizes; scalar fallback ~80) vs the
# device kernel, slope-timed on a v5e-1 (chained data-dependent batches in
# one dispatch, ground-truth-verified against a numpy u64 emulation —
# r4's 113 MB/s "device" number was a tunnel-RTT measurement artifact,
# not compute):
#   - Pallas (ops/keccak_pallas.py): 44.4M hashes/s at MPT node shapes
#     = ~13.5 GB/s of keccak input — beats the host batch ~34x.
#   - jnp/XLA fallback (ops/keccak_jax.py): 35.4M hashes/s = ~10.7 GB/s
#     on the same chip (used if Mosaic is unavailable).
# With the gate open on compute, routing is decided by the measured LINK:
# a locally attached chip pays; the ~40 MB/s dev tunnel never can, since
# shipping the bytes alone costs more than hashing them on the host —
# see device_offload_pays.
NATIVE_HASH_BPS = 300e6
DEVICE_HASH_BPS_PALLAS = 13.5e9
DEVICE_HASH_BPS_JNP = 10.7e9
DEVICE_HASH_BPS_XLA_CPU = 110e6  # jnp kernel on the host CPU: loses to native


def device_hash_bps() -> float:
    """Device keccak throughput for the cost model: which kernel would
    actually serve the batch on this host (Pallas on real TPUs, the jnp
    program elsewhere — the same dispatch keccak256_chunked_auto uses).

    On a CPU-only jax backend (tests' virtual mesh, PHANT_ALLOW_JAX_CPU)
    the "device" is the host itself running the XLA-CPU keccak, which
    loses to the native AVX-512 batch outright — report it as such so the
    offload gate stays closed there (tests that need the device dispatch
    anyway bypass the gate via PHANT_TPU_FORCE_TRIE)."""
    try:
        import jax

        if jax.default_backend() == "cpu":
            return DEVICE_HASH_BPS_XLA_CPU
        from phant_tpu.ops.keccak_pallas import pallas_available

        if pallas_available():
            return DEVICE_HASH_BPS_PALLAS
    except Exception:
        pass
    return DEVICE_HASH_BPS_JNP


def device_offload_possible() -> bool:
    """Could device_offload_pays() EVER return True under the current
    cost model? False while the device hash term alone exceeds the native
    cost — the single predicate both the gate's short-circuit and the
    engine's finish_native fast path key on (one definition, so they
    cannot diverge if the model is reworked)."""
    return device_hash_bps() > NATIVE_HASH_BPS


def device_offload_pays(nbytes: int) -> bool:
    """Shared offload gate for byte-dense hashing work (witness novel-node
    batches, trie-root plans): ship only if upload + round trip + device
    hash beats hashing the same bytes natively on the host. Callers must
    check the crypto backend BEFORE calling — this probes the device link.
    Every verdict counts into `backend.offload_decisions{route=...}` so the
    gate's behavior is auditable from /metrics."""
    from phant_tpu.utils.trace import metrics

    if not device_offload_possible():
        # no link speed can make the inequality hold; skip the probe
        metrics.count("backend.offload_decisions", route="native")
        return False
    up_bps, rtt = device_link_profile()
    pays = (
        nbytes / up_bps + rtt + nbytes / device_hash_bps() < nbytes / NATIVE_HASH_BPS
    )
    metrics.count("backend.offload_decisions", route="device" if pays else "native")
    return pays


def set_evm_backend(name: str) -> None:
    global _EVM_BACKEND
    if name not in _VALID_EVM:
        raise ValueError(f"evm backend must be one of {_VALID_EVM}, got {name!r}")
    _EVM_BACKEND = name


def evm_backend() -> str:
    return _EVM_BACKEND
