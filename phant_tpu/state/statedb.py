"""In-memory world state with journaled snapshots.

Equivalent surface to the reference StateDB (reference:
src/state/statedb.zig:16-194) — accounts/storage CRUD, per-tx original
values for SSTORE gas, EIP-2929 warm sets, touched-address tracking — but
snapshots are O(1) journal marks with undo-log revert instead of the
reference's full deep clone (its own TODO admits the inefficiency,
reference: src/state/statedb.zig:172-173).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from phant_tpu.types.account import Account
from phant_tpu.types.receipt import Log

Address = bytes  # 20 bytes


class StateDB:
    def __init__(self, accounts: Optional[Dict[Address, Account]] = None):
        self.accounts: Dict[Address, Account] = accounts or {}
        # undo log: list of (tag, payload) entries, newest last
        self._journal: List[Tuple] = []
        # incremental state-root cache: a retained secure trie plus the set
        # of addresses mutated since it was last synced. state_root() then
        # re-leafs only dirty accounts instead of rebuilding the whole trie
        # per block (the reference never computes state roots at all —
        # src/blockchain/blockchain.zig:83-85 — so this has no analog).
        # Journal rollbacks restore values of exactly the addresses the
        # forward mutations already marked dirty, so the set stays a
        # superset of every divergence from the synced trie.
        self._root_trie = None
        self._root_dirty: Set[Address] = set()
        # per-account retained storage tries (same per-path scheme): keyed
        # by the Account OBJECT so delete+recreate (journal-rollback-safe
        # identity) invalidates naturally; dirty slots accumulate in
        # set_storage/revert_to
        self._storage_tries: Dict[Address, Tuple[Account, object]] = {}
        self._storage_dirty: Dict[Address, Set[int]] = {}
        # --- per-transaction scope ---
        self._tx_original: Dict[Tuple[Address, int], int] = {}
        self.accessed_addresses: Set[Address] = set()
        self.accessed_storage_keys: Set[Tuple[Address, int]] = set()
        self.touched: Set[Address] = set()
        self.selfdestructs: Set[Address] = set()
        self.created: Set[Address] = set()
        self.logs: List[Log] = []
        self.refund: int = 0
        # EIP-1153 transient storage (Cancun): per-transaction, journaled
        # for call-scope reverts, discarded wholesale at tx end
        self.transient: Dict[Tuple[Address, int], int] = {}

    # ------------------------------------------------------------------
    # tx lifecycle
    # ------------------------------------------------------------------

    def begin_block(self) -> None:
        """Start a block-scoped undo log: every mutation until commit/rollback
        is journaled, so an invalid block rolls back in O(mutations) instead
        of the O(world-state) deep clone the reference uses per snapshot
        (reference: src/state/statedb.zig:171-182)."""
        self._journal.clear()

    def rollback_block(self) -> None:
        """Undo every mutation since begin_block (invalid blocks must leave
        no trace)."""
        self.revert_to(0)

    def start_tx(self) -> None:
        """Reset per-tx scopes (reference: src/state/statedb.zig:62-69 clones
        the whole db as `original_db`; we record originals lazily instead).
        The journal is NOT cleared — it spans the whole block for
        begin_block/rollback_block."""
        self._tx_original.clear()
        self.accessed_addresses = set()
        self.accessed_storage_keys = set()
        self.touched = set()
        self.selfdestructs = set()
        self.created = set()
        self.logs = []
        self.refund = 0
        self.transient = {}  # EIP-1153: cleared at transaction boundaries

    # ------------------------------------------------------------------
    # snapshots (journal marks)
    # ------------------------------------------------------------------

    def snapshot(self) -> int:
        """O(1) — returns a journal mark (reference deep-clones both maps,
        src/state/statedb.zig:171-182)."""
        return len(self._journal)

    def revert_to(self, mark: int) -> None:
        # every state-restoring branch re-marks the address (and slot) in
        # the incremental-root dirty sets: a rollback AFTER a state_root()
        # call (e.g. a block rejected on state-root mismatch) must not
        # leave the retained trie stuck on the rejected state
        while len(self._journal) > mark:
            tag, *payload = self._journal.pop()
            if tag == "balance":
                addr, old = payload
                self.accounts[addr].balance = old
                self._root_dirty.add(addr)
            elif tag == "nonce":
                addr, old = payload
                self.accounts[addr].nonce = old
                self._root_dirty.add(addr)
            elif tag == "storage":
                addr, slot, old = payload
                acct = self.accounts[addr]
                if old == 0:
                    acct.storage.pop(slot, None)
                else:
                    acct.storage[slot] = old
                self._root_dirty.add(addr)
                self._storage_dirty.setdefault(addr, set()).add(slot)
            elif tag == "code":
                addr, old = payload
                self.accounts[addr].code = old
                self._root_dirty.add(addr)
            elif tag == "create_account":
                (addr,) = payload
                self.accounts.pop(addr, None)
                self._root_dirty.add(addr)
            elif tag == "delete_account":
                addr, acct = payload
                self.accounts[addr] = acct
                self._root_dirty.add(addr)
            elif tag == "warm_addr":
                (addr,) = payload
                self.accessed_addresses.discard(addr)
            elif tag == "warm_slot":
                (key,) = payload
                self.accessed_storage_keys.discard(key)
            elif tag == "touch":
                (addr,) = payload
                self.touched.discard(addr)
            elif tag == "selfdestruct":
                (addr,) = payload
                self.selfdestructs.discard(addr)
            elif tag == "created":
                (addr,) = payload
                self.created.discard(addr)
            elif tag == "log":
                # block-level rollback may replay entries from earlier txs
                # whose per-tx log list start_tx already reset
                if self.logs:
                    self.logs.pop()
            elif tag == "refund":
                (old,) = payload
                self.refund = old
            elif tag == "transient":
                key, old = payload
                if old == 0:
                    self.transient.pop(key, None)
                else:
                    self.transient[key] = old
            else:  # pragma: no cover
                raise AssertionError(f"unknown journal tag {tag}")

    # ------------------------------------------------------------------
    # accounts
    # ------------------------------------------------------------------

    def account_exists(self, addr: Address) -> bool:
        return addr in self.accounts

    def get_account(self, addr: Address) -> Optional[Account]:
        return self.accounts.get(addr)

    def _get_or_create(self, addr: Address) -> Account:
        acct = self.accounts.get(addr)
        if acct is None:
            acct = Account()
            self.accounts[addr] = acct
            self._journal.append(("create_account", addr))
            self._root_dirty.add(addr)
        return acct

    def create_account(self, addr: Address) -> Account:
        return self._get_or_create(addr)

    def mark_created(self, addr: Address) -> None:
        """Track contracts created in this tx (EIP-6780-style bookkeeping and
        EIP-2200 original-value semantics for fresh contracts)."""
        self.created.add(addr)
        self._journal.append(("created", addr))

    def delete_account(self, addr: Address) -> None:
        acct = self.accounts.pop(addr, None)
        if acct is not None:
            self._journal.append(("delete_account", addr, acct))
            self._root_dirty.add(addr)

    def is_empty(self, addr: Address) -> bool:
        acct = self.accounts.get(addr)
        return acct is None or acct.is_empty()

    # ------------------------------------------------------------------
    # balances / nonces / code
    # ------------------------------------------------------------------

    def get_balance(self, addr: Address) -> int:
        acct = self.accounts.get(addr)
        return acct.balance if acct else 0

    def set_balance(self, addr: Address, value: int) -> None:
        acct = self._get_or_create(addr)
        self._journal.append(("balance", addr, acct.balance))
        self._root_dirty.add(addr)
        acct.balance = value

    def add_balance(self, addr: Address, delta: int) -> None:
        self.set_balance(addr, self.get_balance(addr) + delta)

    def sub_balance(self, addr: Address, delta: int) -> None:
        bal = self.get_balance(addr)
        if delta > bal:
            raise ValueError("balance underflow")
        self.set_balance(addr, bal - delta)

    def get_nonce(self, addr: Address) -> int:
        acct = self.accounts.get(addr)
        return acct.nonce if acct else 0

    def set_nonce(self, addr: Address, value: int) -> None:
        acct = self._get_or_create(addr)
        self._journal.append(("nonce", addr, acct.nonce))
        self._root_dirty.add(addr)
        acct.nonce = value

    def increment_nonce(self, addr: Address) -> None:
        self.set_nonce(addr, self.get_nonce(addr) + 1)

    def get_code(self, addr: Address) -> bytes:
        acct = self.accounts.get(addr)
        return acct.code if acct else b""

    def set_code(self, addr: Address, code: bytes) -> None:
        acct = self._get_or_create(addr)
        self._journal.append(("code", addr, acct.code))
        self._root_dirty.add(addr)
        acct.code = code

    # ------------------------------------------------------------------
    # storage
    # ------------------------------------------------------------------

    def get_storage(self, addr: Address, slot: int) -> int:
        acct = self.accounts.get(addr)
        return acct.storage.get(slot, 0) if acct else 0

    def set_storage(self, addr: Address, slot: int, value: int) -> None:
        acct = self._get_or_create(addr)
        current = acct.storage.get(slot, 0)
        key = (addr, slot)
        if key not in self._tx_original:
            self._tx_original[key] = current
        self._journal.append(("storage", addr, slot, current))
        self._root_dirty.add(addr)
        self._storage_dirty.setdefault(addr, set()).add(slot)
        if value == 0:
            acct.storage.pop(slot, None)
        else:
            acct.storage[slot] = value

    def get_original_storage(self, addr: Address, slot: int) -> int:
        """Value at the start of the current tx (EIP-2200; reference keeps a
        whole-map clone for this, src/state/statedb.zig:22-25)."""
        key = (addr, slot)
        if key in self._tx_original:
            return self._tx_original[key]
        return self.get_storage(addr, slot)

    # ------------------------------------------------------------------
    # EIP-1153 transient storage (Cancun; no reference analog — the
    # reference EVM is pinned to Shanghai, src/blockchain/vm.zig:472)
    # ------------------------------------------------------------------

    def get_transient(self, addr: Address, slot: int) -> int:
        return self.transient.get((addr, slot), 0)

    def set_transient(self, addr: Address, slot: int, value: int) -> None:
        key = (addr, slot)
        self._journal.append(("transient", key, self.transient.get(key, 0)))
        if value == 0:
            self.transient.pop(key, None)
        else:
            self.transient[key] = value

    # ------------------------------------------------------------------
    # EIP-2929 warm sets (journaled: reverted scopes re-cool their additions)
    # ------------------------------------------------------------------

    def access_address(self, addr: Address) -> bool:
        """Mark warm; returns True if it was already warm."""
        if addr in self.accessed_addresses:
            return True
        self.accessed_addresses.add(addr)
        self._journal.append(("warm_addr", addr))
        return False

    def access_storage_key(self, addr: Address, slot: int) -> bool:
        key = (addr, slot)
        if key in self.accessed_storage_keys:
            return True
        self.accessed_storage_keys.add(key)
        self._journal.append(("warm_slot", key))
        return False

    # ------------------------------------------------------------------
    # touched / selfdestruct / logs / refunds
    # ------------------------------------------------------------------

    def touch(self, addr: Address) -> None:
        if addr not in self.touched:
            self.touched.add(addr)
            self._journal.append(("touch", addr))

    def mark_selfdestruct(self, addr: Address) -> None:
        if addr not in self.selfdestructs:
            self.selfdestructs.add(addr)
            self._journal.append(("selfdestruct", addr))

    def add_log(self, log: Log) -> None:
        self.logs.append(log)
        self._journal.append(("log",))

    def add_refund(self, delta: int) -> None:
        self._journal.append(("refund", self.refund))
        self.refund += delta
        if self.refund < 0:  # pragma: no cover — guarded by EIP-3529 math
            raise AssertionError("negative refund counter")

    # ------------------------------------------------------------------
    # finalization
    # ------------------------------------------------------------------

    def destroy_touched_empty(self) -> None:
        """EIP-158: remove touched accounts that ended the tx empty
        (reference: src/blockchain/blockchain.zig:334-341)."""
        for addr in list(self.touched):
            acct = self.accounts.get(addr)
            if acct is not None and acct.is_empty():
                self.delete_account(addr)

    def _storage_root_incremental(self, addr: Address, acct: Account) -> bytes:
        """Storage root via a retained per-account trie: only dirty slots
        are re-put/deleted. Account-object identity guards delete+recreate
        (rollback restores the original object, so identity is stable)."""
        from phant_tpu.crypto.keccak import keccak256
        from phant_tpu import rlp
        from phant_tpu.state.root import build_storage_trie

        entry = self._storage_tries.get(addr)
        if entry is None or entry[0] is not acct:
            trie = build_storage_trie(acct.storage)
            self._storage_tries[addr] = (acct, trie)
            self._storage_dirty.pop(addr, None)
            return trie.root_hash()
        trie = entry[1]
        for slot in self._storage_dirty.pop(addr, ()):
            value = acct.storage.get(slot, 0)
            key = keccak256(slot.to_bytes(32, "big"))
            if value == 0:
                trie.delete(key)
            else:
                trie.put(key, rlp.encode(rlp.encode_uint(value)))
        return trie.root_hash()

    def flush_root_trie(self):
        """Apply every dirty account to the retained state trie WITHOUT
        hashing it, and return the trie. `state_root()` is flush + host
        `root_hash()`; the replay engine's deferred-root mode flushes per
        block, builds a HashPlan from the unhashed trie, and hashes K
        consecutive block states on device in ONE vmapped dispatch
        (phant_tpu/replay/lowering.py) — the flush/hash split is what lets
        the hashing leave the per-block critical path."""
        from phant_tpu.crypto.keccak import keccak256
        from phant_tpu.state.root import build_state_trie

        if self._root_trie is None:
            self._root_trie = build_state_trie(self.accounts)
        else:
            for addr in self._root_dirty:
                acct = self.accounts.get(addr)
                key = keccak256(addr)
                if acct is None or (acct.is_empty() and not acct.storage):
                    self._root_trie.delete(key)
                    # drop the retained storage trie too: a deleted account's
                    # (acct, trie) entry would otherwise pin the dead Account
                    # and its whole trie for the StateDB's lifetime
                    self._storage_tries.pop(addr, None)
                    self._storage_dirty.pop(addr, None)
                else:
                    # ONE value-encoding definition across every producer
                    # (phant_tpu/commitment/ account_leaf_value) — the
                    # incremental path must never diverge from the
                    # full-rebuild and stateless write-back paths
                    from phant_tpu.commitment import account_leaf_value

                    leaf = account_leaf_value(
                        acct.nonce,
                        acct.balance,
                        self._storage_root_incremental(addr, acct),
                        acct.code_hash(),
                    )
                    self._root_trie.put(key, leaf)
        self._root_dirty.clear()
        return self._root_trie

    def state_root(self) -> bytes:
        # host recursion on purpose, even on --crypto_backend=tpu: the
        # retained trie re-encodes only dirty paths (per-path enc cache),
        # which beats shipping a full plan rebuild to the device every
        # block; the device state-root path serves FULL recomputes (the
        # stateless witness pipeline and the replay engine's deferred
        # segment roots), not incremental resident updates
        return self.flush_root_trie().root_hash()

    def copy(self) -> "StateDB":
        return StateDB({a: acct.copy() for a, acct in self.accounts.items()})
