"""World-state root computation (secure trie over hashed account keys).

The reference skips state-root verification entirely (TODO-disabled,
reference: src/blockchain/blockchain.zig:83-85); the north star requires it
(BASELINE.json). Account leaf = rlp([nonce, balance, storage_root,
code_hash]); account key = keccak(address); storage key = keccak(slot_be32),
storage leaf = rlp(minimal_be(value)).
"""

from __future__ import annotations

from typing import Mapping

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.mpt.mpt import Trie, trie_root_hash
from phant_tpu.types.account import Account


def storage_root(storage: Mapping[int, int]) -> bytes:
    trie = Trie()
    for slot, value in storage.items():
        if value == 0:
            continue  # zero slots are absent from the trie
        key = keccak256(slot.to_bytes(32, "big"))
        trie.put(key, rlp.encode(rlp.encode_uint(value)))
    return trie_root_hash(trie)


def account_leaf(account: Account) -> bytes:
    return rlp.encode([
        rlp.encode_uint(account.nonce),
        rlp.encode_uint(account.balance),
        storage_root(account.storage),
        account.code_hash(),
    ])


def state_root(accounts: Mapping[bytes, Account]) -> bytes:
    """Root over address -> account, skipping EIP-161-empty accounts."""
    trie = Trie()
    for address, account in accounts.items():
        if account.is_empty() and not account.storage:
            continue
        trie.put(keccak256(address), account_leaf(account))
    return trie_root_hash(trie)
