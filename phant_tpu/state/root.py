"""World-state root computation (secure trie over hashed account keys).

The reference skips state-root verification entirely (TODO-disabled,
reference: src/blockchain/blockchain.zig:83-85); the north star requires it
(BASELINE.json). Account leaf = rlp([nonce, balance, storage_root,
code_hash]); account key = keccak(address); storage key = keccak(slot_be32),
storage leaf = rlp(minimal_be(value)).
"""

from __future__ import annotations

from typing import Mapping

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.mpt.mpt import Trie, trie_root_hash
from phant_tpu.types.account import Account


def build_storage_trie(storage: Mapping[int, int]) -> Trie:
    """slot -> value trie (zero slots are absent). The single source of the
    storage-trie key/leaf encoding — witness generation walks these same
    tries (phant_tpu/spec/runner.py _witness_of_state)."""
    trie = Trie()
    for slot, value in storage.items():
        if value == 0:
            continue
        key = keccak256(slot.to_bytes(32, "big"))
        trie.put(key, rlp.encode(rlp.encode_uint(value)))
    return trie


def storage_root(storage: Mapping[int, int]) -> bytes:
    return trie_root_hash(build_storage_trie(storage))


def account_leaf(account: Account) -> bytes:
    # ONE value-encoding definition across every producer
    # (phant_tpu/commitment/ account_leaf_value)
    from phant_tpu.commitment import account_leaf_value

    return account_leaf_value(
        account.nonce,
        account.balance,
        storage_root(account.storage),
        account.code_hash(),
    )


def build_state_trie(accounts: Mapping[bytes, Account]) -> Trie:
    """address -> account trie, skipping EIP-161-empty accounts."""
    trie = Trie()
    for address, account in accounts.items():
        if account.is_empty() and not account.storage:
            continue
        trie.put(keccak256(address), account_leaf(account))
    return trie


def state_root(accounts: Mapping[bytes, Account]) -> bytes:
    """Root over address -> account, skipping EIP-161-empty accounts."""
    return trie_root_hash(build_state_trie(accounts))
