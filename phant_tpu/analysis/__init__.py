"""phantlint — AST-based JAX/TPU hazard analysis for this codebase.

A small plugin-rule static-analysis framework: `symbols.py` parses the
package and resolves a lightweight module/symbol table + call graph,
`core.py` drives per-rule visitors with file:line findings, a
`# phantlint: disable=RULE` escape hatch, and a checked-in baseline for
grandfathered findings. Shipped rules (phant_tpu/analysis/rules/):

  HOSTSYNC    accidental device->host syncs on the verification hot path
  DTYPE       int-literal promotion hazards in the uint32 lane modules
  JITHYGIENE  jit static/closure mistakes that compile-and-misbehave
  LOCK        lock-guarded state touched without the lock
  METRICNAME  metric names: literal, sanitizable, and in METRIC_HELP

CLI: `scripts/phantlint.py` (wired as `make lint`, runs first in
`scripts/check.sh`). Pure `ast` — never imports the code under analysis,
so the gate lints the full package in ~2s and without jax.
"""

from phant_tpu.analysis.core import (
    AnalysisResult,
    Analyzer,
    Finding,
    Rule,
    load_baseline,
    save_baseline,
)
from phant_tpu.analysis.rules import ALL_RULES, default_rules

__all__ = [
    "ALL_RULES",
    "AnalysisResult",
    "Analyzer",
    "Finding",
    "Rule",
    "default_rules",
    "load_baseline",
    "save_baseline",
]
