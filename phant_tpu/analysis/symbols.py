"""Lightweight module/symbol table + call graph for phantlint.

Parses a package with `ast` (no imports are executed — the analyzer must
run without jax so the commit gate stays cheap) and resolves just enough
structure for the rules:

  * per-module tables of top-level functions, classes (methods + resolved
    bases), and import aliases (collected at EVERY scope — this codebase
    imports heavily inside function bodies to keep jax off cold paths);
  * a best-effort call graph over project-global qualnames
    ("pkg.mod.func", "pkg.mod.Class.method") covering: direct calls of
    local/imported functions, `self.method()` (with base-class walk),
    `super().method()`, constructor calls, `alias.func()` module-attribute
    calls, `var.method()` where `var` was assigned from a known
    constructor in the same function, and stored-attribute calls
    `self.attr.method()` / `var.attr.method()` where `self.attr = C(...)`
    appears in any method of the owning class (single-level attribute
    type tracking — how `Blockchain.run_block`'s `self.signer.…` calls
    resolve into the signer layer, so HOSTSYNC's reachability covers
    signer-side syncs without annotated-helper workarounds);
  * jit detection: `@jax.jit`, `@functools.partial(jax.jit, ...)`
    decorators and `name = jax.jit(f)` / `partial(jax.jit, ...)(f)`
    module-level assignments, with their `static_argnames`.

Deliberately NOT a type checker: calls through attributes assigned from
anything but a resolvable constructor (`self.x = factory()`, reassigned
attrs, deeper chains like `self.a.b.method()`) resolve to nothing and
reachability under-approximates there. Rules are written so
under-approximation can only suppress findings, never invent them; an
attribute assigned different classes in different methods resolves to
ALL of them (conservative union).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple


@dataclass
class FunctionInfo:
    qualname: str  # project-global, e.g. "phant_tpu.stateless.execute_stateless"
    module: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls: Optional[str] = None  # owning class name (module-local), if a method
    jitted: bool = False
    static_argnames: Tuple[str, ...] = ()


@dataclass
class ClassInfo:
    qualname: str
    module: str
    node: ast.ClassDef
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    base_names: Tuple[str, ...] = ()  # unresolved (module-local) base names
    # stored-attribute types: attr name -> dotted constructor names seen in
    # `self.<attr> = Ctor(...)` across ALL methods (raw at parse time);
    # Project.__init__ resolves them into `attr_classes` qualname sets
    attr_ctor_names: Dict[str, Set[str]] = field(default_factory=dict)
    attr_classes: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str  # dotted module name
    path: Path
    tree: ast.Module
    source: str
    lines: List[str]
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)  # local name
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> dotted target
    # module-level names assigned a mutable literal (list/dict/set display)
    mutable_globals: Dict[str, int] = field(default_factory=dict)  # name -> lineno


def _dotted(node: ast.AST) -> Optional[str]:
    """Reconstruct a dotted name from Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST, imports: Dict[str, str]) -> bool:
    """Does this expression denote jax.jit (through any import alias)?"""
    d = _dotted(node)
    if d is None:
        return False
    # resolve the leading alias
    head, _, rest = d.partition(".")
    target = imports.get(head, head)
    full = target + ("." + rest if rest else "")
    return full in ("jax.jit", "jax.jit.jit")


def _is_partial(node: ast.AST, imports: Dict[str, str]) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    head, _, rest = d.partition(".")
    target = imports.get(head, head)
    full = target + ("." + rest if rest else "")
    return full in ("functools.partial", "partial")


def _static_argnames_of(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        out.append(elt.value)
                return tuple(out)
    return ()


def _jit_of_decorators(
    fn: ast.AST, imports: Dict[str, str]
) -> Tuple[bool, Tuple[str, ...]]:
    for dec in getattr(fn, "decorator_list", []):
        if _is_jax_jit(dec, imports):
            return True, ()
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func, imports):
                return True, _static_argnames_of(dec)
            # functools.partial(jax.jit, static_argnames=...)
            if (
                _is_partial(dec.func, imports)
                and dec.args
                and _is_jax_jit(dec.args[0], imports)
            ):
                return True, _static_argnames_of(dec)
    return False, ()


def parse_module(name: str, path: Path) -> Optional[ModuleInfo]:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError):
        return None
    mi = ModuleInfo(
        name=name, path=path, tree=tree, source=source, lines=source.splitlines()
    )
    # imports at every scope
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mi.imports[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
                if alias.asname:
                    mi.imports[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: resolve against this module
                base = name.split(".")
                base = base[: len(base) - node.level]
                prefix = ".".join(base + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                mi.imports[alias.asname or alias.name] = (
                    f"{prefix}.{alias.name}" if prefix else alias.name
                )
    # top-level defs / classes / mutable globals
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            jit, statics = _jit_of_decorators(node, mi.imports)
            mi.functions[node.name] = FunctionInfo(
                qualname=f"{name}.{node.name}",
                module=name,
                node=node,
                jitted=jit,
                static_argnames=statics,
            )
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(
                qualname=f"{name}.{node.name}",
                module=name,
                node=node,
                base_names=tuple(
                    b for b in (_dotted(base) for base in node.bases) if b
                ),
            )
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    jit, statics = _jit_of_decorators(item, mi.imports)
                    ci.methods[item.name] = FunctionInfo(
                        qualname=f"{name}.{node.name}.{item.name}",
                        module=name,
                        node=item,
                        cls=node.name,
                        jitted=jit,
                        static_argnames=statics,
                    )
                    _collect_attr_ctors(ci, item)
            mi.classes[node.name] = ci
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if isinstance(node.value, (ast.List, ast.Dict, ast.Set)):
                    mi.mutable_globals[tgt.id] = node.lineno
                else:
                    _maybe_assigned_jit(mi, tgt.id, node.value)
    return mi


def _collect_attr_ctors(ci: ClassInfo, method: ast.AST) -> None:
    """Record `self.<attr> = <Ctor>(...)` assignments (any method, any
    nesting depth) as raw dotted constructor names; Project.__init__
    resolves them against the project's classes. Assignments from
    non-calls or non-self targets are ignored — only a direct constructor
    call pins a type we can trust."""
    for node in ast.walk(method):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        d = _dotted(node.value.func)
        if d is None:
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                ci.attr_ctor_names.setdefault(tgt.attr, set()).add(d)


def _maybe_assigned_jit(mi: ModuleInfo, name: str, value: ast.AST) -> None:
    """`f = jax.jit(g)` / `f = functools.partial(jax.jit, ...)(g)`: mark g
    (and register f as an alias of a jitted function)."""
    if not isinstance(value, ast.Call):
        return
    inner: Optional[ast.AST] = None
    statics: Tuple[str, ...] = ()
    if _is_jax_jit(value.func, mi.imports) and value.args:
        inner = value.args[0]
        statics = _static_argnames_of(value)
    elif (
        isinstance(value.func, ast.Call)
        and _is_partial(value.func.func, mi.imports)
        and value.func.args
        and _is_jax_jit(value.func.args[0], mi.imports)
        and value.args
    ):
        inner = value.args[0]
        statics = _static_argnames_of(value.func)
    if inner is None:
        return
    d = _dotted(inner)
    if d and d in mi.functions:
        fi = mi.functions[d]
        fi.jitted = True
        fi.static_argnames = statics
        # the wrapper name calls through to the same function
        mi.imports.setdefault(name, fi.qualname)


class Project:
    """All parsed modules plus the resolved call graph."""

    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.modules = modules
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        for mi in modules.values():
            for fi in mi.functions.values():
                self.functions[fi.qualname] = fi
            for ci in mi.classes.values():
                self.classes[ci.qualname] = ci
                for fi in ci.methods.values():
                    self.functions[fi.qualname] = fi
        # resolve stored-attribute constructor names BEFORE the call graph
        # is built (the graph consumes attr_classes)
        for mi in modules.values():
            for ci in mi.classes.values():
                for attr, ctors in ci.attr_ctor_names.items():
                    resolved = {
                        q
                        for q in (
                            self.resolve_name(mi.name, d) for d in ctors
                        )
                        if q is not None and q in self.classes
                    }
                    if resolved:
                        ci.attr_classes[attr] = resolved
        self.call_graph: Dict[str, Set[str]] = {}
        for mi in modules.values():
            for fi in mi.functions.values():
                self.call_graph[fi.qualname] = self._calls_of(mi, fi)
            for ci in mi.classes.values():
                for fi in ci.methods.values():
                    self.call_graph[fi.qualname] = self._calls_of(mi, fi)

    # -- name resolution ----------------------------------------------------

    def resolve_name(self, module: str, dotted: str) -> Optional[str]:
        """Module-local dotted name -> project-global qualname (function or
        class), through import aliases; None for anything external."""
        mi = self.modules.get(module)
        if mi is None:
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            if head in mi.functions:
                return mi.functions[head].qualname
            if head in mi.classes:
                return mi.classes[head].qualname
            target = mi.imports.get(head)
            if target is None:
                return None
            if target in self.functions or target in self.classes:
                return target
            return None
        target = mi.imports.get(head)
        if target is None:
            return None
        cand = f"{target}.{rest}"
        if cand in self.functions or cand in self.classes:
            return cand
        return None

    def resolve_class(self, module: str, dotted: str) -> Optional[ClassInfo]:
        q = self.resolve_name(module, dotted)
        return self.classes.get(q) if q else None

    def method_of(self, ci: ClassInfo, name: str) -> Optional[FunctionInfo]:
        """Method lookup with single-inheritance base walk."""
        seen: Set[str] = set()
        stack = [ci]
        while stack:
            c = stack.pop()
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            if name in c.methods:
                return c.methods[name]
            for b in c.base_names:
                base = self.resolve_class(c.module, b)
                if base is not None:
                    stack.append(base)
        return None

    def attr_classes_of(self, ci: ClassInfo, attr: str) -> List[ClassInfo]:
        """The resolved class(es) a stored attribute may hold, walking base
        classes like method_of. Conservative union: an attribute assigned
        different constructors in different methods returns all of them."""
        seen: Set[str] = set()
        out: List[ClassInfo] = []
        stack = [ci]
        while stack:
            c = stack.pop()
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            for q in c.attr_classes.get(attr, ()):
                target = self.classes.get(q)
                if target is not None and target.qualname not in {
                    o.qualname for o in out
                }:
                    out.append(target)
            for b in c.base_names:
                base = self.resolve_class(c.module, b)
                if base is not None:
                    stack.append(base)
        return out

    # -- call graph ---------------------------------------------------------

    def ctor_typed_locals(self, mi: ModuleInfo, fi: FunctionInfo) -> Dict[str, ClassInfo]:
        """Local vars assigned from known constructors: var -> ClassInfo.
        Shared by the call graph and the concurrency rules (locks.py)."""
        var_classes: Dict[str, ClassInfo] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                d = _dotted(node.value.func)
                if d is not None:
                    ci = self.resolve_class(mi.name, d)
                    if ci is not None:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                var_classes[tgt.id] = ci
        return var_classes

    def callees_of(
        self,
        mi: ModuleInfo,
        owner: Optional[ClassInfo],
        node: ast.Call,
        var_classes: Dict[str, ClassInfo],
    ) -> Set[str]:
        """Resolve ONE call expression to project-global callee qualnames
        (possibly several for conservative attribute unions; empty for
        external calls). The single resolver behind the call graph — the
        concurrency rules (LOCKORDER/LOCKBLOCK) call it per site so their
        interprocedural walks cannot drift from `call_graph`."""
        out: Set[str] = set()
        func = node.func
        # super().m(...)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Call)
            and isinstance(func.value.func, ast.Name)
            and func.value.func.id == "super"
            and owner is not None
        ):
            for b in owner.base_names:
                base = self.resolve_class(mi.name, b)
                if base is not None:
                    m = self.method_of(base, func.attr)
                    if m is not None:
                        out.add(m.qualname)
                        break
            return out
        # self.m(...) / var.m(...)
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            recv = func.value.id
            if recv == "self" and owner is not None:
                m = self.method_of(owner, func.attr)
                if m is not None:
                    out.add(m.qualname)
                    return out
            if recv in var_classes:
                m = self.method_of(var_classes[recv], func.attr)
                if m is not None:
                    out.add(m.qualname)
                    return out
        # self.attr.m(...) / var.attr.m(...): stored-attribute types
        # (`self.signer = TxSigner(...)` -> `self.signer.get_sender()`)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
        ):
            recv = func.value.value.id
            holder: Optional[ClassInfo] = None
            if recv == "self" and owner is not None:
                holder = owner
            elif recv in var_classes:
                holder = var_classes[recv]
            if holder is not None:
                resolved_any = False
                for target in self.attr_classes_of(holder, func.value.attr):
                    m = self.method_of(target, func.attr)
                    if m is not None:
                        out.add(m.qualname)
                        resolved_any = True
                if resolved_any:
                    return out
        d = _dotted(func)
        if d is None:
            return out
        q = self.resolve_name(mi.name, d)
        if q is None:
            return out
        if q in self.functions:
            out.add(q)
        elif q in self.classes:
            ci = self.classes[q]
            out.add(ci.qualname)  # constructor marker
            m = self.method_of(ci, "__init__")
            if m is not None:
                out.add(m.qualname)
        return out

    def _calls_of(self, mi: ModuleInfo, fi: FunctionInfo) -> Set[str]:
        out: Set[str] = set()
        owner = mi.classes.get(fi.cls) if fi.cls else None
        var_classes = self.ctor_typed_locals(mi, fi)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                out |= self.callees_of(mi, owner, node, var_classes)
        return out

    def reachable(self, entries: Sequence[str]) -> Set[str]:
        """Transitive closure over the call graph. A class qualname entry
        includes every method of the class (conservative)."""
        seen: Set[str] = set()
        stack: List[str] = []
        for e in entries:
            if e in self.functions or e in self.classes:
                stack.append(e)
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            if q in self.classes:
                for m in self.classes[q].methods.values():
                    stack.append(m.qualname)
                continue
            for callee in self.call_graph.get(q, ()):
                if callee not in seen:
                    stack.append(callee)
        return seen

    def module_of(self, qualname: str) -> Optional[ModuleInfo]:
        fi = self.functions.get(qualname)
        if fi is not None:
            return self.modules.get(fi.module)
        ci = self.classes.get(qualname)
        if ci is not None:
            return self.modules.get(ci.module)
        return None
