"""phantsan: an Eraser-style lockset race sanitizer for the serving path.

The static rules (LOCK/LOCKORDER/LOCKBLOCK/THREADSHARE) under-approximate
by construction: sharing through containers, callbacks, or dynamically
chosen locks is invisible to a lexical analysis.  phantsan is the dynamic
backstop — the classic lockset algorithm (Savage et al., "Eraser: A
Dynamic Data Race Detector for Multithreaded Programs", TOCS 1997)
adapted to Python attributes:

  * `enable()` replaces `threading.Lock`/`threading.RLock` with proxy
    factories whose acquire/release maintain a thread-local *held set*.
    `threading.Condition()` and `queue.Queue` pick the proxies up
    automatically (they construct their locks through the patched
    names); Condition-over-proxy works because the proxy implements the
    `_release_save`/`_acquire_restore`/`_is_owned` protocol.
  * `register_shared_class(cls)` instruments `cls.__setattr__` and
    `cls.__getattribute__` to run each instance-attribute access through
    the per-field state machine:

        virgin -> exclusive (single thread; no checking — init is free)
               -> shared (second thread reads)
               -> shared-modified (second thread involved + any write)

    From the first second-thread access on, the field's *candidate
    lockset* is intersected with the locks held at each access.  An empty
    lockset in the shared-modified state is a race: no single lock
    protected every access.  The report carries TWO stacks — the previous
    access and the current one — because a race is a pair of accesses,
    and the previous one is usually the half you didn't think about.

Scope and under-approximation: only attribute REBINDING is tracked
(`self.x = v`, `self.x += v`); in-place mutation of a dict/list held in
an attribute looks like a read.  The GIL makes individual accesses
atomic, so what phantsan reports are not torn words but *atomicity
races*: check-then-act and read-modify-write interleavings — exactly the
class the GIL does NOT prevent.

Everything here must work while `threading.Lock` is patched, so the
module's own bookkeeping locks are captured at import time.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# real ctors, captured before enable() can patch them: the sanitizer's own
# infrastructure must never run through its own proxies
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_STACK_LIMIT = 16

# field states
_EXCLUSIVE = "exclusive"
_SHARED = "shared"
_SHARED_MOD = "shared-modified"

_tls = threading.local()


def _held() -> set:
    h = getattr(_tls, "held", None)
    if h is None:
        h = set()
        _tls.held = h
    return h


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


@dataclass
class RaceReport:
    cls_name: str
    attr: str
    first_thread: str
    first_op: str  # "read" | "write"
    first_stack: List[str]
    second_thread: str
    second_op: str
    second_stack: List[str]

    def format(self) -> str:
        lines = [
            f"phantsan: data race on `{self.cls_name}.{self.attr}` — no "
            "single lock protects every access (empty lockset in the "
            "shared-modified state)",
            f"  access 1: {self.first_op} by thread {self.first_thread}",
        ]
        lines += [
            "    " + l for fr in self.first_stack for l in fr.rstrip().splitlines()
        ]
        lines.append(
            f"  access 2: {self.second_op} by thread {self.second_thread}"
        )
        lines += [
            "    " + l for fr in self.second_stack for l in fr.rstrip().splitlines()
        ]
        return "\n".join(lines)


_reports: List[RaceReport] = []
_reports_lock = _REAL_LOCK()


def reports() -> List[RaceReport]:
    with _reports_lock:
        return list(_reports)


def drain_reports() -> List[RaceReport]:
    """Return accumulated reports and clear the buffer.  Test harnesses
    fail the session on a non-empty drain; the deliberately-racy fixture
    drains its own reports so they never leak into the session check."""
    with _reports_lock:
        out = list(_reports)
        del _reports[:]
    return out


def _record(report: RaceReport) -> None:
    with _reports_lock:
        _reports.append(report)


# ---------------------------------------------------------------------------
# lock proxies
# ---------------------------------------------------------------------------


class _LockProxy:
    """Wraps a real lock; acquire/release maintain the thread-local held
    set.  Implements the `_release_save`/`_acquire_restore`/`_is_owned`
    protocol so `threading.Condition(proxy)` waits correctly (Condition
    prefers those when present, and the proxy always presents them,
    falling back to plain acquire/release for non-reentrant inners)."""

    def __init__(self, inner, reentrant: bool):
        self._phantsan_inner = inner
        self._phantsan_reentrant = reentrant
        self._phantsan_count = 0  # recursion depth, mutated lock-in-hand

    # -- core protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._phantsan_inner.acquire(blocking, timeout)
        if got:
            self._phantsan_count += 1
            _held().add(self)
        return got

    def release(self) -> None:
        self._phantsan_inner.release()
        self._phantsan_count -= 1
        if self._phantsan_count <= 0:
            self._phantsan_count = 0
            _held().discard(self)

    def locked(self) -> bool:
        return self._phantsan_inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<phantsan {type(self._phantsan_inner).__name__} proxy>"

    def _at_fork_reinit(self) -> None:
        # os.fork support (concurrent.futures registers this): the child
        # starts with the lock free and no recursion
        self._phantsan_inner._at_fork_reinit()
        self._phantsan_count = 0

    def __getattr__(self, name):
        # anything the proxy doesn't reimplement delegates to the real
        # lock (only fires for names not found on the proxy class)
        return getattr(self._phantsan_inner, name)

    # -- Condition protocol ----------------------------------------------

    def _release_save(self):
        count = self._phantsan_count
        self._phantsan_count = 0
        _held().discard(self)
        inner = self._phantsan_inner
        if hasattr(inner, "_release_save"):
            return (count, inner._release_save())
        inner.release()
        return (count, None)

    def _acquire_restore(self, state) -> None:
        count, inner_state = state
        inner = self._phantsan_inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(inner_state)
        else:
            inner.acquire()
        self._phantsan_count = count
        _held().add(self)

    def _is_owned(self) -> bool:
        inner = self._phantsan_inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain-Lock fallback, mirroring threading.Condition's own
        if inner.acquire(False):
            inner.release()
            return False
        return True


def _proxy_lock():
    return _LockProxy(_REAL_LOCK(), reentrant=False)


def _proxy_rlock():
    return _LockProxy(_REAL_RLOCK(), reentrant=True)


# ---------------------------------------------------------------------------
# enable / disable
# ---------------------------------------------------------------------------

_enabled = False
_state_lock = _REAL_LOCK()  # guards the enable/disable toggle itself


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Patch `threading.Lock`/`threading.RLock` to the proxy factories.
    Must run BEFORE the shared objects under test are constructed: a lock
    created earlier is a plain lock, invisible to the held-set, and every
    access under it looks unprotected (false races)."""
    global _enabled
    with _state_lock:
        if _enabled:
            return
        threading.Lock = _proxy_lock
        threading.RLock = _proxy_rlock
        _enabled = True


def disable() -> None:
    global _enabled
    with _state_lock:
        if not _enabled:
            return
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        _enabled = False


# ---------------------------------------------------------------------------
# attribute tracking
# ---------------------------------------------------------------------------


@dataclass
class _FieldState:
    first_tid: int
    state: str = _EXCLUSIVE
    lockset: Optional[set] = None  # None = universe (not yet shared)
    last_thread: str = ""
    last_op: str = ""
    last_stack: List[str] = field(default_factory=list)
    reported: bool = False


def _capture_stack() -> List[str]:
    """Frame-walk stack capture: traceback.extract_stack touches linecache
    (source file I/O) on every call, which is ruinous at one capture per
    tracked attribute access — this walks sys._getframe and formats
    `File "...", line N, in fn` lines only, no source text."""
    out: List[str] = []
    f = sys._getframe(3)  # skip _capture_stack, _track, and the wrapper
    depth = 0
    while f is not None and depth < _STACK_LIMIT:
        code = f.f_code
        out.append(
            f'  File "{code.co_filename}", line {f.f_lineno}, '
            f"in {code.co_name}\n"
        )
        f = f.f_back
        depth += 1
    out.reverse()
    return out


def _track(obj: Any, name: str, op: str) -> None:
    if not _enabled:
        return
    if getattr(_tls, "in_tracker", False):
        return
    _tls.in_tracker = True
    try:
        try:
            d = object.__getattribute__(obj, "__dict__")
        except AttributeError:
            return  # __slots__ class: nowhere to hang field state
        fields = d.get("_phantsan_fields")
        if fields is None:
            fields = d["_phantsan_fields"] = {}
            d["_phantsan_fields_lock"] = _REAL_LOCK()
        tid = threading.get_ident()
        tname = threading.current_thread().name
        with d["_phantsan_fields_lock"]:
            st = fields.get(name)
            if st is None:
                fields[name] = _FieldState(
                    first_tid=tid,
                    last_thread=tname,
                    last_op=op,
                    last_stack=_capture_stack(),
                )
                return
            if st.state == _EXCLUSIVE and tid == st.first_tid:
                st.last_thread, st.last_op = tname, op
                st.last_stack = _capture_stack()
                return
            # a second thread is involved: lockset checking is live
            held_now = set(_held())
            if st.lockset is None:
                st.lockset = held_now
            else:
                st.lockset &= held_now
            if op == "write" or st.state == _SHARED_MOD:
                st.state = _SHARED_MOD
            else:
                st.state = _SHARED
            if st.state == _SHARED_MOD and not st.lockset and not st.reported:
                st.reported = True
                _record(
                    RaceReport(
                        cls_name=type(obj).__name__,
                        attr=name,
                        first_thread=st.last_thread,
                        first_op=st.last_op,
                        first_stack=st.last_stack,
                        second_thread=tname,
                        second_op=op,
                        second_stack=_capture_stack(),
                    )
                )
            st.last_thread, st.last_op = tname, op
            st.last_stack = _capture_stack()
    finally:
        _tls.in_tracker = False


_registered: Dict[type, Tuple[Callable, Callable]] = {}


def register_shared_class(cls: type) -> type:
    """Instrument `cls` so every instance-attribute access runs the
    lockset state machine.  Reads are tracked only for names already in
    the instance `__dict__` (method lookups and class attributes are
    noise, not shared state); dunders and `_phantsan*` bookkeeping are
    skipped.  Idempotent; usable as a decorator."""
    if cls in _registered:
        return cls
    orig_setattr = cls.__setattr__
    orig_getattribute = cls.__getattribute__

    def san_setattr(self, name, value):
        orig_setattr(self, name, value)
        if not name.startswith("_phantsan") and not name.startswith("__"):
            _track(self, name, "write")

    def san_getattribute(self, name):
        value = orig_getattribute(self, name)
        if not name.startswith("_phantsan") and not name.startswith("__"):
            try:
                in_dict = name in object.__getattribute__(self, "__dict__")
            except AttributeError:
                in_dict = False
            if in_dict:
                _track(self, name, "read")
        return value

    cls.__setattr__ = san_setattr
    cls.__getattribute__ = san_getattribute
    _registered[cls] = (orig_setattr, orig_getattribute)
    return cls


def unregister(cls: type) -> None:
    pair = _registered.pop(cls, None)
    if pair is not None:
        cls.__setattr__, cls.__getattribute__ = pair


def registered_classes() -> List[type]:
    return list(_registered)


def unregister_all() -> None:
    for cls in list(_registered):
        unregister(cls)


def register_default_shared_classes() -> List[type]:
    """Register the serving path's shared singletons and engines — the
    objects every Engine API handler thread, scheduler worker, and obs
    poller touches concurrently.  Imports lazily: callers enable the
    sanitizer first, so the classes' locks are built as proxies."""
    from phant_tpu.obs.busy import BusyAccountant
    from phant_tpu.obs.flight import FlightRecorder
    from phant_tpu.serving.scheduler import VerificationScheduler
    from phant_tpu.utils.trace import Metrics

    targets = [VerificationScheduler, FlightRecorder, BusyAccountant, Metrics]
    for cls in targets:
        register_shared_class(cls)
    return targets


def wanted() -> bool:
    """True when the environment opts into sanitized runs."""
    return os.environ.get("PHANT_SANITIZE") == "1"
