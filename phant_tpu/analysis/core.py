"""phantlint core: findings, suppression, baseline, and the analyzer driver.

One analyzer, plugin rules. Each rule sees the whole parsed `Project`
(phant_tpu/analysis/symbols.py) and yields `Finding`s with file:line
positions. Three layers of triage, in order:

  1. `# phantlint: disable=RULE[,RULE]` comments — on the offending line,
     or on a comment line directly above it — suppress in place. This is
     the escape hatch for INTENTIONAL hazards (a timed host readback, a
     benign lock-free lazy init); the comment carries the reason in prose.
  2. The baseline file (scripts/phantlint_baseline.json) grandfathers
     known findings by fingerprint so the gate can land before every
     legacy finding is fixed. Fingerprints hash (rule, path, enclosing
     scope, message) but NOT the line number — shifting code around does
     not resurrect a baselined finding.
  3. Anything left is a NEW finding and fails the gate (exit 1).

The analyzer never imports the code under analysis — pure `ast`, so the
commit gate lints the full package in ~2s and without jax.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

from phant_tpu.analysis.symbols import ModuleInfo, Project, parse_module

_DISABLE_RE = re.compile(r"#\s*phantlint:\s*disable=([A-Za-z0-9_,\s]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*phantlint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    severity: str = "error"
    context: str = ""  # enclosing qualname (stable across line shifts)

    def fingerprint(self, occurrence: int = 0) -> str:
        """Line-number-independent identity. `occurrence` disambiguates
        IDENTICAL findings in the same scope (e.g. two `int(jnp.sum(tiny))`
        probes in one function): without it, baselining the first would
        silently mask a second one added later. Occurrence 0 omits the
        suffix so existing baselines keep matching."""
        key = f"{self.rule}|{self.path}|{self.context}|{self.message}"
        if occurrence:
            key += f"|#{occurrence}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "context": self.context,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} {self.severity}: {self.message}"
        )


class Rule:
    """Base class for phantlint rules."""

    name: str = "RULE"
    description: str = ""

    def run(self, project: Project) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    # helper for subclasses
    def finding(
        self,
        project: Project,
        module: ModuleInfo,
        node: ast.AST,
        message: str,
        context: str = "",
        severity: str = "error",
    ) -> Finding:
        return Finding(
            rule=self.name,
            path=rel_path(module.path),
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            severity=severity,
            context=context,
        )


def rel_path(path: Path) -> str:
    """Package-root-relative posix path: walk up the __init__.py chain so
    "…/anywhere/phant_tpu/ops/x.py" is always "phant_tpu/ops/x.py" no
    matter where phantlint runs from. Baseline fingerprints embed this
    path, so it must NOT depend on the invocation cwd (a cwd-relative
    path would resurrect every grandfathered finding the first time the
    tool runs from an editor or CI working dir outside the repo root).
    Non-package files fall back to cwd-relative, then absolute."""
    path = path.resolve()
    parts = [path.name]
    d = path.parent
    found_pkg = False
    while (d / "__init__.py").exists():
        found_pkg = True
        parts.insert(0, d.name)
        d = d.parent
    if found_pkg:
        return "/".join(parts)
    try:
        return path.relative_to(Path.cwd().resolve()).as_posix()
    except ValueError:
        return path.as_posix()


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------


def _disabled_lines(module: ModuleInfo) -> Dict[int, Set[str]]:
    """line (1-based) -> set of rule names disabled there. A directive on a
    pure-comment line applies to the next non-comment line as well, so an
    annotation can sit above a long expression."""
    out: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    pending: Set[str] = set()
    for i, text in enumerate(module.lines, start=1):
        m = _DISABLE_FILE_RE.search(text)
        if m:
            file_wide |= {r.strip() for r in m.group(1).split(",") if r.strip()}
        m = _DISABLE_RE.search(text)
        rules = (
            {r.strip() for r in m.group(1).split(",") if r.strip()} if m else set()
        )
        stripped = text.strip()
        if rules:
            out.setdefault(i, set()).update(rules)
            if stripped.startswith("#"):
                pending |= rules  # standalone comment: applies below too
                continue
        if pending and stripped and not stripped.startswith("#"):
            out.setdefault(i, set()).update(pending)
            pending = set()
    if file_wide:
        for i in range(1, len(module.lines) + 1):
            out.setdefault(i, set()).update(file_wide)
    return out


def is_suppressed(finding: Finding, disabled: Dict[int, Set[str]]) -> bool:
    rules = disabled.get(finding.line)
    if not rules:
        return False
    return finding.rule in rules or "all" in rules


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> Set[str]:
    """Set of grandfathered fingerprints; empty for a missing file."""
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {f["fingerprint"] for f in data.get("findings", [])}


def assign_fingerprints(findings: Sequence[Finding]) -> List[str]:
    """Occurrence-disambiguated fingerprint per finding, in input order
    (callers pass findings sorted by path/line so ordinals are stable).
    The ONE shared implementation for both writing and comparing
    baselines — a divergence here would mask or resurrect findings."""
    counts: Dict[str, int] = {}
    out: List[str] = []
    for f in findings:
        base = f.fingerprint()
        n = counts.get(base, 0)
        counts[base] = n + 1
        out.append(f.fingerprint(occurrence=n))
    return out


def save_baseline(path: Path, findings: Sequence[Finding]) -> None:
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    fps = assign_fingerprints(ordered)
    data = {
        "version": 1,
        "findings": [
            {
                "fingerprint": fp,
                "rule": f.rule,
                "path": f.path,
                "message": f.message,
                "context": f.context,
            }
            for f, fp in zip(ordered, fps)
        ],
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


# ---------------------------------------------------------------------------
# analyzer driver
# ---------------------------------------------------------------------------


def discover_modules(paths: Sequence[Path]) -> Dict[str, ModuleInfo]:
    """Parse every .py under `paths`. Module names are derived from the
    package root (the highest ancestor chain of __init__.py dirs), so
    scanning `phant_tpu/` from the repo root yields `phant_tpu.*` names."""
    modules: Dict[str, ModuleInfo] = {}
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    for f in files:
        if "__pycache__" in f.parts:
            continue
        name = _module_name(f)
        mi = parse_module(name, f)
        if mi is not None:
            modules[name] = mi
    return modules


def _module_name(path: Path) -> str:
    path = path.resolve()
    parts = [path.stem] if path.stem != "__init__" else []
    d = path.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        d = d.parent
    return ".".join(parts) if parts else path.stem


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)  # post-suppression
    new: List[Finding] = field(default_factory=list)  # post-baseline
    suppressed: int = 0
    baselined: int = 0
    modules: int = 0


class Analyzer:
    def __init__(
        self,
        paths: Sequence[Path],
        rules: Sequence[Rule],
        baseline: Optional[Path] = None,
    ):
        self.paths = [Path(p) for p in paths]
        self.rules = list(rules)
        self.baseline_path = baseline

    def run(self) -> AnalysisResult:
        modules = discover_modules(self.paths)
        project = Project(modules)
        disabled = {name: _disabled_lines(mi) for name, mi in modules.items()}
        by_path = {rel_path(mi.path): mi.name for mi in modules.values()}
        result = AnalysisResult(modules=len(modules))
        raw: List[Finding] = []
        for rule in self.rules:
            raw.extend(rule.run(project))
        for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
            mod_name = by_path.get(f.path)
            if mod_name is not None and is_suppressed(f, disabled[mod_name]):
                result.suppressed += 1
                continue
            result.findings.append(f)
        base = (
            load_baseline(self.baseline_path)
            if self.baseline_path is not None
            else set()
        )
        for f, fp in zip(result.findings, assign_fingerprints(result.findings)):
            if fp in base:
                result.baselined += 1
            else:
                result.new.append(f)
        return result


def iter_calls(root: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            yield node
