"""Whole-program lock model for the concurrency rules (LOCKORDER/LOCKBLOCK).

Resolves the project's lock *objects* — not names that merely contain
"lock" — into stable lock ids, then walks every function tracking which
locks are lexically held at each acquisition, call, and blocking
operation:

  * class-attribute locks: `self.<attr> = threading.Lock()/RLock()` in
    `__init__` -> id `"<ClassQualname>.<attr>"`.  A
    `threading.Condition(self.<lock>)` built over a known lock ALIASES it
    (scheduler._cond wraps scheduler._lock — with either held, the same
    mutex is held); a bare `threading.Condition()` owns a fresh RLock and
    gets its own id.
  * module-level locks: `NAME = threading.Lock()` at module top level ->
    id `"<module>.<NAME>"`, resolvable through import aliases from other
    modules.

The per-function summaries under-approximate (an unresolvable context
expr holds nothing; an unresolvable call resolves to no callee), which
rules must translate into "may miss, never invents" findings.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from phant_tpu.analysis.symbols import (
    ClassInfo,
    FunctionInfo,
    ModuleInfo,
    Project,
    _dotted,
)

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
}
_CONDITION_CTOR = "threading.Condition"


def resolve_external(mi: ModuleInfo, dotted: str) -> str:
    """Module-local dotted name -> fully-qualified external name through
    the module's import aliases (`threading.Lock` for `Lock` after
    `from threading import Lock`)."""
    head, _, rest = dotted.partition(".")
    target = mi.imports.get(head, head)
    return target + ("." + rest if rest else "")


@dataclass
class LockDecl:
    lock_id: str
    kind: str  # "lock" | "rlock"
    node: ast.AST
    module: str


@dataclass
class FuncLockSummary:
    """What one function does with locks, lexically."""

    qualname: str
    # (acquired lock id, with-item node, lock ids already held at that point)
    acquisitions: List[Tuple[str, ast.AST, FrozenSet[str]]] = field(
        default_factory=list
    )
    # (callee qualname, call node, lock ids held around the call)
    calls: List[Tuple[str, ast.Call, FrozenSet[str]]] = field(default_factory=list)
    # every call node with the held set (for rules with their own matchers)
    call_nodes: List[Tuple[ast.Call, FrozenSet[str]]] = field(default_factory=list)


class LockModel:
    def __init__(self, project: Project):
        self.project = project
        # class qualname -> attr name -> LockDecl
        self.class_locks: Dict[str, Dict[str, LockDecl]] = {}
        # module name -> var name -> LockDecl
        self.module_locks: Dict[str, Dict[str, LockDecl]] = {}
        # module name -> local alias -> kind, for `_REAL_LOCK =
        # threading.Lock` style ctor aliasing (the sanitizer itself must
        # hold the real ctors while threading.Lock is patched, and its
        # locks are no less locks for it)
        self._ctor_aliases: Dict[str, Dict[str, str]] = {}
        for mi in project.modules.values():
            self._collect_ctor_aliases(mi)
            self._collect_module_locks(mi)
            for ci in mi.classes.values():
                self._collect_class_locks(mi, ci)
        self.summaries: Dict[str, FuncLockSummary] = {}
        for mi in project.modules.values():
            for fi in mi.functions.values():
                self.summaries[fi.qualname] = self._summarize(mi, None, fi)
            for ci in mi.classes.values():
                for fi in ci.methods.values():
                    self.summaries[fi.qualname] = self._summarize(mi, ci, fi)

    # -- lock discovery ------------------------------------------------------

    def _collect_ctor_aliases(self, mi: ModuleInfo) -> None:
        table: Dict[str, str] = {}
        for node in mi.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.Name, ast.Attribute))
            ):
                continue
            d = _dotted(node.value)
            if d is None:
                continue
            kind = _LOCK_CTORS.get(resolve_external(mi, d), _LOCK_CTORS.get(d))
            if kind is not None:
                table[node.targets[0].id] = kind
        if table:
            self._ctor_aliases[mi.name] = table

    def _lock_ctor_kind(self, mi: ModuleInfo, call: ast.Call) -> Optional[str]:
        d = _dotted(call.func)
        if d is None:
            return None
        full = resolve_external(mi, d)
        kind = _LOCK_CTORS.get(full, _LOCK_CTORS.get(d))
        if kind is None:
            kind = self._ctor_aliases.get(mi.name, {}).get(d)
        return kind

    def _is_condition_ctor(self, mi: ModuleInfo, call: ast.Call) -> bool:
        d = _dotted(call.func)
        if d is None:
            return False
        return (
            resolve_external(mi, d) == _CONDITION_CTOR or d == _CONDITION_CTOR
        )

    def _collect_module_locks(self, mi: ModuleInfo) -> None:
        table: Dict[str, LockDecl] = {}
        for node in mi.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            kind = self._lock_ctor_kind(mi, node.value)
            name = node.targets[0].id
            if kind is not None:
                table[name] = LockDecl(
                    lock_id=f"{mi.name}.{name}",
                    kind=kind,
                    node=node,
                    module=mi.name,
                )
            elif self._is_condition_ctor(mi, node.value):
                # Condition over a known module lock aliases it; a bare
                # Condition() owns a fresh RLock
                decl = None
                if node.value.args:
                    arg = node.value.args[0]
                    if isinstance(arg, ast.Name):
                        decl = table.get(arg.id)
                if decl is not None:
                    table[name] = LockDecl(
                        lock_id=decl.lock_id,
                        kind=decl.kind,
                        node=node,
                        module=mi.name,
                    )
                else:
                    table[name] = LockDecl(
                        lock_id=f"{mi.name}.{name}",
                        kind="rlock",
                        node=node,
                        module=mi.name,
                    )
        if table:
            self.module_locks[mi.name] = table

    def _collect_class_locks(self, mi: ModuleInfo, ci: ClassInfo) -> None:
        init = ci.methods.get("__init__")
        if init is None:
            return
        table: Dict[str, LockDecl] = {}
        for node in ast.walk(init.node):
            if not (
                isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
            ):
                continue
            tgt = node.targets[0] if len(node.targets) == 1 else None
            if not (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                continue
            kind = self._lock_ctor_kind(mi, node.value)
            if kind is not None:
                table[tgt.attr] = LockDecl(
                    lock_id=f"{ci.qualname}.{tgt.attr}",
                    kind=kind,
                    node=node,
                    module=mi.name,
                )
                continue
            if self._is_condition_ctor(mi, node.value):
                decl = None
                if node.value.args:
                    arg = node.value.args[0]
                    if (
                        isinstance(arg, ast.Attribute)
                        and isinstance(arg.value, ast.Name)
                        and arg.value.id == "self"
                    ):
                        decl = table.get(arg.attr)
                if decl is not None:
                    table[tgt.attr] = LockDecl(
                        lock_id=decl.lock_id,
                        kind=decl.kind,
                        node=node,
                        module=mi.name,
                    )
                else:
                    table[tgt.attr] = LockDecl(
                        lock_id=f"{ci.qualname}.{tgt.attr}",
                        kind="rlock",
                        node=node,
                        module=mi.name,
                    )
        if table:
            self.class_locks[ci.qualname] = table

    def class_lock_decls(self, ci: ClassInfo) -> Dict[str, LockDecl]:
        """Lock attrs of a class including inherited ones (base walk)."""
        out: Dict[str, LockDecl] = {}
        seen: Set[str] = set()
        stack = [ci]
        while stack:
            c = stack.pop()
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            for attr, decl in self.class_locks.get(c.qualname, {}).items():
                out.setdefault(attr, decl)
            for b in c.base_names:
                base = self.project.resolve_class(c.module, b)
                if base is not None:
                    stack.append(base)
        return out

    # -- context-expr resolution ----------------------------------------------

    def resolve_lock_expr(
        self,
        mi: ModuleInfo,
        owner: Optional[ClassInfo],
        expr: ast.AST,
        self_names: FrozenSet[str] = frozenset({"self"}),
    ) -> Optional[LockDecl]:
        """`with <expr>:` -> the LockDecl it holds, or None if it is not a
        resolvable lock object."""
        # bare NAME: module-level lock, local or imported
        if isinstance(expr, ast.Name):
            decl = self.module_locks.get(mi.name, {}).get(expr.id)
            if decl is not None:
                return decl
            target = mi.imports.get(expr.id)
            if target and "." in target:
                mod, _, var = target.rpartition(".")
                return self.module_locks.get(mod, {}).get(var)
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        # self.X / outer.X (self-alias)
        if isinstance(base, ast.Name):
            if base.id in self_names and owner is not None:
                return self.class_lock_decls(owner).get(expr.attr)
            # module_alias.NAME
            mod = mi.imports.get(base.id)
            if mod is not None:
                decl = self.module_locks.get(mod, {}).get(expr.attr)
                if decl is not None:
                    return decl
            # var.X where var is ctor-typed is handled by the caller
            # passing owner=var's class through `resolve_lock_attr`
            return None
        # self.attr.X: stored-attribute class's lock
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id in self_names
            and owner is not None
        ):
            for holder in self.project.attr_classes_of(owner, base.attr):
                decl = self.class_lock_decls(holder).get(expr.attr)
                if decl is not None:
                    return decl
        return None

    # -- per-function summaries ------------------------------------------------

    def _self_aliases(self, ci: Optional[ClassInfo]) -> FrozenSet[str]:
        names = {"self"}
        if ci is not None:
            init = ci.methods.get("__init__")
            if init is not None:
                for node in ast.walk(init.node):
                    if (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                    ):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                names.add(tgt.id)
        return frozenset(names)

    def lock_resolver(
        self, mi: ModuleInfo, owner: Optional[ClassInfo], fi: FunctionInfo
    ):
        """expr -> Optional[LockDecl] with full resolution for one function:
        self/alias attrs, module locks (local or imported), and lock attrs
        of ctor-typed locals.  The same resolution _summarize uses; exposed
        so LOCK's L2 check resolves actual lock objects instead of matching
        "lock" in the context-expr text."""
        self_names = self._self_aliases(owner)
        var_classes = self.project.ctor_typed_locals(mi, fi)

        def lock_of(expr: ast.AST) -> Optional[LockDecl]:
            decl = self.resolve_lock_expr(mi, owner, expr, self_names)
            if decl is not None:
                return decl
            # var.X where var holds a ctor-typed instance
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id in var_classes
            ):
                return self.class_lock_decls(var_classes[expr.value.id]).get(
                    expr.attr
                )
            return None

        return lock_of

    def _summarize(
        self, mi: ModuleInfo, owner: Optional[ClassInfo], fi: FunctionInfo
    ) -> FuncLockSummary:
        summary = FuncLockSummary(qualname=fi.qualname)
        lock_of = self.lock_resolver(mi, owner, fi)
        var_classes = self.project.ctor_typed_locals(mi, fi)

        def visit_expr(expr: ast.AST, held: FrozenSet[str]) -> None:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    summary.call_nodes.append((node, held))
                    for callee in self.project.callees_of(
                        mi, owner, node, var_classes
                    ):
                        summary.calls.append((callee, node, held))

        def visit_stmts(stmts: List[ast.stmt], held: FrozenSet[str]) -> None:
            for stmt in stmts:
                visit_stmt(stmt, held)

        def visit_stmt(stmt: ast.stmt, held: FrozenSet[str]) -> None:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in stmt.items:
                    visit_expr(item.context_expr, held)
                    decl = lock_of(item.context_expr)
                    if decl is not None:
                        summary.acquisitions.append(
                            (decl.lock_id, item.context_expr, frozenset(inner))
                        )
                        inner.add(decl.lock_id)
                visit_stmts(stmt.body, frozenset(inner))
                return
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: its BODY runs later, not under the current
                # locks — walk it with an empty held set (decorators and
                # defaults evaluate here, under the current set)
                for dec in stmt.decorator_list:
                    visit_expr(dec, held)
                for default in list(stmt.args.defaults) + [
                    d for d in stmt.args.kw_defaults if d is not None
                ]:
                    visit_expr(default, held)
                visit_stmts(stmt.body, frozenset())
                return
            if isinstance(stmt, ast.ClassDef):
                visit_stmts(
                    [s for s in stmt.body if isinstance(s, ast.stmt)], held
                )
                return
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    visit_stmt(child, held)
                elif isinstance(child, ast.expr):
                    visit_expr(child, held)
                elif isinstance(child, ast.ExceptHandler):
                    visit_stmts(child.body, held)
                elif isinstance(child, getattr(ast, "match_case", ())):
                    if child.guard is not None:
                        visit_expr(child.guard, held)
                    visit_stmts(child.body, held)

        visit_stmts(fi.node.body, frozenset())
        return summary

    # -- interprocedural closures ---------------------------------------------

    def acquired_closure(self) -> Dict[str, Set[str]]:
        """qualname -> every lock id acquired anywhere in its transitive
        call closure (including its own lexical acquisitions)."""
        direct: Dict[str, Set[str]] = {
            q: {lid for lid, _, _ in s.acquisitions}
            for q, s in self.summaries.items()
        }
        return _transitive(direct, self.project.call_graph)


def lock_model(project: Project) -> LockModel:
    """Per-Project LockModel memo: LOCK/LOCKORDER/LOCKBLOCK/THREADSHARE all
    consume the same model, and building it walks every function."""
    model = getattr(project, "_phantlint_lock_model", None)
    if model is None or model.project is not project:
        model = LockModel(project)
        project._phantlint_lock_model = model
    return model


def _transitive(
    direct: Dict[str, Set[str]], call_graph: Dict[str, Set[str]]
) -> Dict[str, Set[str]]:
    """Fixed point of `out[f] = direct[f] | union(out[g] for g in calls[f])`."""
    out = {q: set(v) for q, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for q, callees in call_graph.items():
            acc = out.setdefault(q, set())
            before = len(acc)
            for g in callees:
                acc |= out.get(g, set())
            if len(acc) != before:
                changed = True
    return out
