"""phantlint rule registry.

Each rule is a `phant_tpu.analysis.core.Rule` subclass; `default_rules()`
instantiates the shipped set with this repo's hot-path entry points and
lane-module scope. Third-party/experimental rules register by appending a
class to `ALL_RULES` (or passing instances straight to `Analyzer`)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from phant_tpu.analysis.core import Rule
from phant_tpu.analysis.rules.dtype import DTypeRule
from phant_tpu.analysis.rules.hostsync import HostSyncRule
from phant_tpu.analysis.rules.jithygiene import JitHygieneRule
from phant_tpu.analysis.rules.jnphostloop import JnpHostLoopRule
from phant_tpu.analysis.rules.lock import LockRule
from phant_tpu.analysis.rules.lockblock import LockBlockRule
from phant_tpu.analysis.rules.lockorder import LockOrderRule
from phant_tpu.analysis.rules.metricname import MetricNameRule
from phant_tpu.analysis.rules.spanname import SpanNameRule
from phant_tpu.analysis.rules.threadshare import ThreadShareRule

ALL_RULES = [
    HostSyncRule,
    DTypeRule,
    JitHygieneRule,
    JnpHostLoopRule,
    LockRule,
    LockOrderRule,
    LockBlockRule,
    ThreadShareRule,
    MetricNameRule,
    SpanNameRule,
]


def default_rules(only: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instances of every shipped rule; `only` filters by rule name."""
    rules: List[Rule] = [cls() for cls in ALL_RULES]
    if only is not None:
        wanted = {n.upper() for n in only}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; "
                f"known: {[r.name for r in rules]}"
            )
        rules = [r for r in rules if r.name in wanted]
    return rules
