"""LOCK: attributes guarded by a threading.Lock read/written outside it.

Two checks, both tuned to this codebase's concurrency shape (Engine API
handler threads over shared `WitnessEngine` / `Metrics` state):

L1 — class-level lock discipline. For every class whose `__init__` creates
`self.<lock> = threading.Lock()/RLock()`, an attribute is *guarded* once
any method touches it inside `with self.<lock>:`. Every other touch of a
guarded attribute must also hold the lock, except:

  * `__init__` itself (construction is single-threaded by contract);
  * methods named `*_locked` — the documented "caller holds the lock"
    convention (`_verify_batch_locked`, `_stats_snapshot_locked`);
  * private methods whose every intra-class call site is lock-held
    (computed to a fixed point) — helpers of the locked region.

  Public methods are always treated as entry points: a public method that
  touches guarded state unlocked is a finding even if today's only caller
  holds the lock, because nothing stops tomorrow's caller.

  `outer = self` aliasing (the nested request-handler-class idiom in
  engine_api/server.py) is resolved: `with outer._lock:` guards
  `outer.attr` exactly like `self`.

L2 — unlocked lazy init of module globals. The `global X; if X is None:
X = …` memo pattern without a lock lets two threads initialize
concurrently: usually double work, occasionally torn state (a probe
result and its failure-backoff deadline disagreeing). Flagged whenever
the writing function also tests the global and the assignment is not
inside a `with <…lock…>:` block.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Set, Tuple

from phant_tpu.analysis.core import Finding, Rule
from phant_tpu.analysis.locks import lock_model
from phant_tpu.analysis.symbols import ClassInfo, ModuleInfo, Project, _dotted

_LOCK_CTORS = ("threading.Lock", "threading.RLock")


@dataclass
class _Access:
    method: str  # name of the (possibly nested) enclosing function
    attr: str
    node: ast.AST
    locked: bool
    is_call: bool  # base.attr(...) method call


class LockRule(Rule):
    name = "LOCK"
    description = "lock-guarded state touched without the lock"

    def run(self, project: Project) -> Iterator[Finding]:
        for mi in project.modules.values():
            for ci in mi.classes.values():
                yield from self._check_class(project, mi, ci)
            yield from self._check_lazy_init(project, mi)

    # -- L1 ------------------------------------------------------------------

    def _lock_attrs(self, mi: ModuleInfo, ci: ClassInfo) -> Set[str]:
        init = ci.methods.get("__init__")
        if init is None:
            return set()
        out: Set[str] = set()
        for node in ast.walk(init.node):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            d = _dotted(node.value.func)
            if d is None:
                continue
            head, _, rest = d.partition(".")
            full = mi.imports.get(head, head) + ("." + rest if rest else "")
            if full not in _LOCK_CTORS and d not in _LOCK_CTORS:
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    out.add(tgt.attr)
        return out

    def _self_aliases(self, ci: ClassInfo) -> Set[str]:
        names = {"self"}
        init = ci.methods.get("__init__")
        if init is not None:
            for node in ast.walk(init.node):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
        return names

    def _collect(
        self,
        method_name: str,
        body: List[ast.stmt],
        bases: Set[str],
        locks: Set[str],
        accesses: List[_Access],
        calls: List[Tuple[str, str, bool]],  # (method, callee, locked)
        locked: bool,
        func_name: Optional[str] = None,
    ) -> None:
        """Recursive walk tracking with-lock context. Nested defs/classes
        are attributed to their own (inner) function name so __init__'s
        exemption does not leak to handler classes defined inside it."""
        current = func_name or method_name
        for stmt in body:
            self._collect_stmt(current, stmt, bases, locks, accesses, calls, locked)

    def _is_lock_ctx(self, item: ast.withitem, bases: Set[str], locks: Set[str]) -> bool:
        e = item.context_expr
        return (
            isinstance(e, ast.Attribute)
            and isinstance(e.value, ast.Name)
            and e.value.id in bases
            and e.attr in locks
        )

    def _collect_stmt(self, current, stmt, bases, locks, accesses, calls, locked):
        if isinstance(stmt, ast.With):
            inner = locked or any(
                self._is_lock_ctx(i, bases, locks) for i in stmt.items
            )
            for i in stmt.items:
                self._collect_expr(current, i.context_expr, bases, locks, accesses, calls, locked)
            for s in stmt.body:
                self._collect_stmt(current, s, bases, locks, accesses, calls, inner)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for s in stmt.body:
                self._collect_stmt(stmt.name, s, bases, locks, accesses, calls, locked)
            return
        if isinstance(stmt, ast.ClassDef):
            for s in stmt.body:
                self._collect_stmt(current, s, bases, locks, accesses, calls, locked)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._collect_stmt(current, child, bases, locks, accesses, calls, locked)
            elif isinstance(child, ast.expr):
                self._collect_expr(current, child, bases, locks, accesses, calls, locked)
            elif isinstance(child, ast.ExceptHandler):
                # except blocks are where races hide (error paths); their
                # bodies are neither stmt nor expr and must not be skipped
                for s in child.body:
                    self._collect_stmt(current, s, bases, locks, accesses, calls, locked)
            elif isinstance(child, getattr(ast, "match_case", ())):
                # match-case bodies are the same kind of non-stmt container
                if child.guard is not None:
                    self._collect_expr(current, child.guard, bases, locks, accesses, calls, locked)
                for s in child.body:
                    self._collect_stmt(current, s, bases, locks, accesses, calls, locked)

    def _collect_expr(self, current, expr, bases, locks, accesses, calls, locked):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in bases
                ):
                    calls.append((current, f.attr, locked))
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in bases
                and node.attr not in locks
            ):
                accesses.append(
                    _Access(
                        method=current,
                        attr=node.attr,
                        node=node,
                        locked=locked,
                        is_call=False,
                    )
                )

    def _check_class(
        self, project: Project, mi: ModuleInfo, ci: ClassInfo
    ) -> Iterator[Finding]:
        locks = self._lock_attrs(mi, ci)
        if not locks:
            return
        bases = self._self_aliases(ci)
        accesses: List[_Access] = []
        calls: List[Tuple[str, str, bool]] = []
        for name, fi in ci.methods.items():
            self._collect(name, fi.node.body, bases, locks, accesses, calls, False)
        method_names = set(ci.methods)
        # nested defs (handler-class idiom): their names are methods, not
        # data attributes, and they participate in the lock fixed point
        for fi in ci.methods.values():
            for n in ast.walk(fi.node):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    method_names.add(n.name)
        nested_methods = {a.method for a in accesses} | {c[0] for c in calls}
        data_accesses = [a for a in accesses if a.attr not in method_names]
        guarded = {a.attr for a in data_accesses if a.locked}
        if not guarded:
            return
        # fixed point: private helpers whose every call site holds the lock
        lock_required: Set[str] = {
            m for m in (method_names | nested_methods) if m.endswith("_locked")
        }
        changed = True
        while changed:
            changed = False
            for m in method_names | nested_methods:
                if m in lock_required or not m.startswith("_") or m == "__init__":
                    continue
                sites = [c for c in calls if c[1] == m]
                if sites and all(
                    locked_ or caller in lock_required
                    for caller, _, locked_ in sites
                ):
                    lock_required.add(m)
                    changed = True
        for a in data_accesses:
            if a.locked or a.attr not in guarded:
                continue
            if a.method == "__init__" or a.method in lock_required:
                continue
            yield self.finding(
                project,
                mi,
                a.node,
                f"`{ci.node.name}.{a.attr}` is guarded by "
                f"`{sorted(locks)[0]}` elsewhere but touched without it in "
                f"{a.method}() — take the lock or move the access into a "
                "*_locked helper",
                context=f"{ci.qualname}.{a.method}",
            )

    # -- L2 ------------------------------------------------------------------

    def _check_lazy_init(self, project: Project, mi: ModuleInfo) -> Iterator[Finding]:
        model = lock_model(project)
        funcs: List[Tuple[Optional[ClassInfo], object]] = [
            (None, fi) for fi in mi.functions.values()
        ]
        for ci in mi.classes.values():
            funcs.extend((ci, fi) for fi in ci.methods.values())
        for owner, fi in funcs:
            if fi.node.name.endswith("_locked"):
                continue  # documented "caller holds the lock" convention
            globals_declared: Set[str] = set()
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Global):
                    globals_declared.update(node.names)
            if not globals_declared:
                continue
            tested = self._tested_globals(fi.node, globals_declared)
            if not tested:
                continue
            lock_of = model.lock_resolver(mi, owner, fi)
            for name, node in self._unlocked_stores(fi.node, tested, lock_of):
                yield self.finding(
                    project,
                    mi,
                    node,
                    f"lazy init of module global `{name}` in "
                    f"{fi.node.name}() is not under a lock — concurrent "
                    "callers race the memo (double init / torn state)",
                    context=fi.qualname,
                )

    @staticmethod
    def _tested_globals(fn: ast.AST, names: Set[str]) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                for n in ast.walk(node.test):
                    if isinstance(n, ast.Name) and n.id in names:
                        out.add(n.id)
        return out

    def _unlocked_stores(self, fn: ast.AST, names: Set[str], lock_of):
        """(name, node) for the FIRST assignment to each of `names` outside
        any with-lock block (one finding per global per function).
        `lock_of` is LockModel.lock_resolver's predicate: a with-item
        counts as a lock only if it resolves to an actual Lock/RLock
        object — a context manager merely NAMED "…lock…" does not."""
        seen: Set[str] = set()

        def walk(stmts, locked):
            for stmt in stmts:
                if isinstance(stmt, ast.With):
                    inner = locked or any(
                        lock_of(i.context_expr) is not None for i in stmt.items
                    )
                    yield from walk(stmt.body, inner)
                    continue
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    continue  # nested scopes declare their own globals
                if not locked and isinstance(
                    stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)
                ):
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for tgt in targets:
                        for n in ast.walk(tgt):
                            if (
                                isinstance(n, ast.Name)
                                and isinstance(n.ctx, ast.Store)
                                and n.id in names
                                and n.id not in seen
                            ):
                                seen.add(n.id)
                                yield n.id, stmt
                for attr in ("body", "orelse", "finalbody"):
                    part = getattr(stmt, attr, None)
                    if isinstance(part, list) and part and isinstance(
                        part[0], ast.stmt
                    ):
                        yield from walk(part, locked)
                for h in getattr(stmt, "handlers", []) or []:
                    yield from walk(h.body, locked)

        yield from walk(fn.body, False)
