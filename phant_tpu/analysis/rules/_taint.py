"""Function-local device-value taint tracking (shared by HOSTSYNC/DTYPE).

A value is "device-tainted" when it (transitively) comes from a call into
jax — a jit-compiled project function, a `jnp.*`/`jax.*` call through any
import alias, or a `jax.jit(f)(...)` inline dispatch — or, optionally,
from a parameter of a jitted function (inside jit every argument is a
tracer). Taint propagates through assignments, arithmetic, subscripts,
attribute access, and method calls on tainted receivers, to a fixed point
over the function body (nested defs included: closures see the enclosing
taint, which is how deferred `resolve()` readbacks are caught).

This is a heuristic, not an escape analysis: parameters of plain host
functions are NOT tainted (the flag belongs at the call site that built
the device value), and unknown calls do not launder taint away only when
the receiver itself is tainted. Under-approximation can suppress a
finding; it cannot invent one.
"""

from __future__ import annotations

import ast
from typing import Optional, Set

from phant_tpu.analysis.symbols import ModuleInfo, Project, _dotted


def resolve_external(mi: ModuleInfo, dotted: str) -> str:
    """Expand the leading alias of a dotted name through the module's
    imports: "jnp.sum" -> "jax.numpy.sum", "np.asarray" -> "numpy.asarray"."""
    head, _, rest = dotted.partition(".")
    target = mi.imports.get(head, head)
    return f"{target}.{rest}" if rest else target


def is_jax_call(project: Project, mi: ModuleInfo, call: ast.Call) -> bool:
    """Does this call produce a device value (jax/jnp/jitted function)?"""
    func = call.func
    # jax.jit(f)(...) inline dispatch
    if isinstance(func, ast.Call):
        d = _dotted(func.func)
        if d is not None and resolve_external(mi, d).startswith("jax."):
            return True
        return False
    d = _dotted(func)
    if d is None:
        return False
    q = project.resolve_name(mi.name, d)
    if q is not None:
        fi = project.functions.get(q)
        if fi is not None and fi.jitted:
            return True
        return False
    full = resolve_external(mi, d)
    return full == "jax" or full.startswith(("jax.", "jax_"))


class Taint:
    """Tainted-local-name computation for one function body."""

    def __init__(
        self,
        project: Project,
        mi: ModuleInfo,
        fn: ast.AST,
        taint_params: bool = False,
    ):
        self.project = project
        self.mi = mi
        self.fn = fn
        self.names: Set[str] = set()
        if taint_params:
            a = fn.args
            for arg in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            ):
                self.names.add(arg.arg)
            if a.vararg:
                self.names.add(a.vararg.arg)
        self._fixed_point()

    def _fixed_point(self) -> None:
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.fn):
                targets = None
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if targets is None or value is None:
                    continue
                if not self.tainted(value):
                    continue
                for tgt in targets:
                    for n in self._target_names(tgt):
                        if n not in self.names:
                            self.names.add(n)
                            changed = True

    @staticmethod
    def _target_names(tgt: ast.AST):
        if isinstance(tgt, ast.Name):
            yield tgt.id
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                yield from Taint._target_names(elt)
        elif isinstance(tgt, ast.Starred):
            yield from Taint._target_names(tgt.value)

    def tainted(self, node: ast.AST) -> bool:
        """Is this expression (possibly) a device value?"""
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Call):
            if is_jax_call(self.project, self.mi, node):
                return True
            # method call on a tainted receiver (x.reshape, x.at[...].set)
            func = node.func
            while isinstance(func, (ast.Attribute, ast.Subscript)):
                func = func.value
            if isinstance(func, ast.Call):
                return self.tainted(func)
            return isinstance(func, ast.Name) and func.id in self.names
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self.tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.tainted(node.left) or any(
                self.tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.tainted(e) for e in node.elts)
        return False


def snippet(node: ast.AST, limit: int = 48) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover — unparse failure on exotic nodes
        return "<expr>"
    return text if len(text) <= limit else text[: limit - 1] + "…"
