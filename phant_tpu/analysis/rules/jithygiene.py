"""JITHYGIENE: jit boundary mistakes that compile-and-misbehave.

jax.jit failures split into loud (tracing errors) and quiet (a cache that
never hits, a closure that captures stale state). This rule catches both
classes statically, on every `@jax.jit` / `functools.partial(jax.jit,…)`
function and every `name = jax.jit(f)`-style module-level wrapping:

  * J1 — `static_argnames` naming a parameter that does not exist: jax
    silently ignores unknown names (the arg traces instead of
    specializing, so every distinct value retraces… or worse, doesn't).
  * J2 — a jitted parameter with a mutable default (list/dict/set): the
    default is unhashable as a static and a shared mutable across traces
    otherwise.
  * J3 — a parameter used where tracing needs a Python value — `range()`,
    a shape argument (`zeros`/`full`/`reshape`/`broadcast_to`/`arange`),
    or an `if`/`while` test — without being in `static_argnames`
    (`.shape`/`.ndim`/`.dtype` attribute reads are static and exempt).
  * J4 — jitted code (or an intra-module helper it calls) reading a
    module-level mutable literal (list/dict/set): the first trace bakes
    the value in; later mutation is silently ignored. Constant tables
    belong in tuples or arrays.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from phant_tpu.analysis.core import Finding, Rule
from phant_tpu.analysis.symbols import (
    FunctionInfo,
    ModuleInfo,
    Project,
    _dotted,
)

_SHAPE_CALLS = {
    "zeros",
    "ones",
    "empty",
    "full",
    "arange",
    "reshape",
    "broadcast_to",
}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class JitHygieneRule(Rule):
    name = "JITHYGIENE"
    description = "jit static/closure hygiene on device entry points"

    def run(self, project: Project) -> Iterator[Finding]:
        for mi in project.modules.values():
            funcs = list(mi.functions.values())
            for ci in mi.classes.values():
                funcs.extend(ci.methods.values())
            jitted = [fi for fi in funcs if fi.jitted]
            for fi in jitted:
                yield from self._check_signature(project, mi, fi)
                yield from self._check_traced_usage(project, mi, fi)
            if jitted:
                yield from self._check_mutable_globals(project, mi, jitted)

    # -- J1 / J2 -------------------------------------------------------------

    def _check_signature(self, project, mi, fi) -> Iterator[Finding]:
        params = set(_param_names(fi.node))
        for name in fi.static_argnames:
            if name not in params:
                yield self.finding(
                    project,
                    mi,
                    fi.node,
                    f"static_argnames={name!r} does not match any parameter "
                    f"of {fi.node.name}() — jax ignores it and the argument "
                    "traces instead of specializing",
                    context=fi.qualname,
                )
        a = fi.node.args
        defaults = list(a.defaults) + [d for d in a.kw_defaults if d is not None]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield self.finding(
                    project,
                    mi,
                    d,
                    f"jitted function {fi.node.name}() has a mutable default "
                    "argument — unhashable as a static, shared across traces "
                    "otherwise",
                    context=fi.qualname,
                )

    # -- J3 ------------------------------------------------------------------

    def _check_traced_usage(self, project, mi, fi) -> Iterator[Finding]:
        traced = set(_param_names(fi.node)) - set(fi.static_argnames)
        if not traced:
            return
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(fi.node):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        def names_in(node: ast.AST) -> Set[str]:
            """Traced params referenced in node, minus static .shape reads."""
            out: Set[str] = set()
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and n.id in traced:
                    p = parents.get(id(n))
                    if (
                        isinstance(p, ast.Attribute)
                        and p.attr in _STATIC_ATTRS
                        and p.value is n
                    ):
                        continue
                    out.add(n.id)
            return out

        reported: Set[str] = set()

        def report(node, names, how):
            for name in sorted(names - reported):
                reported.add(name)
                yield self.finding(
                    project,
                    mi,
                    node,
                    f"traced parameter `{name}` of {fi.node.name}() is used "
                    f"as a Python value ({how}) — add it to static_argnames "
                    "or hoist it out of the jitted function",
                    context=fi.qualname,
                )

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Name) and func.id == "range":
                    yield from report(
                        node, set().union(*(names_in(a) for a in node.args)),
                        "range() bound",
                    )
                else:
                    d = _dotted(func)
                    leaf = d.rsplit(".", 1)[-1] if d else (
                        func.attr if isinstance(func, ast.Attribute) else None
                    )
                    if leaf in _SHAPE_CALLS and node.args:
                        shape_args = node.args[:1]
                        yield from report(
                            node,
                            set().union(*(names_in(a) for a in shape_args)),
                            f"shape argument of {leaf}()",
                        )
            elif isinstance(node, (ast.If, ast.While)):
                yield from report(
                    node.test, names_in(node.test), "if/while test"
                )

    # -- J4 ------------------------------------------------------------------

    def _check_mutable_globals(
        self, project: Project, mi: ModuleInfo, jitted: List[FunctionInfo]
    ) -> Iterator[Finding]:
        # intra-module closure: jitted functions + their callees in-module
        in_module = {
            fi.qualname
            for fi in list(mi.functions.values())
            + [m for c in mi.classes.values() for m in c.methods.values()]
        }
        closure = project.reachable([fi.qualname for fi in jitted]) & in_module
        for qualname in sorted(closure):
            fi = project.functions[qualname]
            local_names = set(_param_names(fi.node))
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    local_names.add(node.id)
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
                    continue
                if node.id in local_names:
                    continue
                lineno = mi.mutable_globals.get(node.id)
                origin = mi.name
                if lineno is None and node.id in mi.imports:
                    target = mi.imports[node.id]
                    omod, _, oname = target.rpartition(".")
                    other = project.modules.get(omod)
                    if other is not None and oname in other.mutable_globals:
                        lineno, origin = other.mutable_globals[oname], omod
                if lineno is None:
                    continue
                yield self.finding(
                    project,
                    mi,
                    node,
                    f"jit-reachable code reads module-level mutable "
                    f"`{node.id}` ({origin}:{lineno}) — the first trace "
                    "bakes it in; use a tuple/array constant",
                    context=qualname,
                )
