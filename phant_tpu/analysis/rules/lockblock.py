"""LOCKBLOCK: blocking operations inside a `with <lock>` region.

A lock on the serving path bounds every other thread's latency by the
longest critical section — a blocking call inside one turns a mutex into
a convoy (and, against the scheduler's own worker, into a deadlock when
the blocked-on work needs the same lock to finish).  Flagged operations:

  * `queue.Queue/SimpleQueue/LifoQueue/PriorityQueue` `.get()/.put()` on
    receivers whose constructor is visible (stored attrs or locals);
  * `Future.result()` — waits for another thread, which may need the lock;
  * `block_until_ready()` / `jax.device_get()` — device sync can be a full
    dispatch+transfer round trip;
  * `time.sleep`;
  * `Thread.join()` (ctor-typed receivers, plus `*thread*`-named attrs —
    `"sep".join(...)` never matches: a string receiver is not thread-named);
  * socket/HTTP sends: `sendall/recv/accept/getresponse`,
    `urllib.request.urlopen`, `subprocess` waits (`run/check_call/
    check_output/communicate`).

`.wait()` is exempt: `Condition.wait` RELEASES the lock while waiting —
that is the one blocking-under-lock shape that is correct by design.
(`Event.wait` under a lock would be a real bug this exemption hides; the
codebase convention is Condition, and phantsan exists to catch the rest.)

Interprocedural: calling a function whose transitive closure contains a
blocking op, while holding a lock, is flagged at the call site naming the
inner operation — the lock-held path to a blocking call is the bug, not
just the lexical nesting.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from phant_tpu.analysis.core import Finding, Rule
from phant_tpu.analysis.locks import LockModel, lock_model, _transitive, resolve_external
from phant_tpu.analysis.symbols import (
    ClassInfo,
    FunctionInfo,
    ModuleInfo,
    Project,
    _dotted,
)

_QUEUE_CTORS = {
    "queue.Queue",
    "queue.SimpleQueue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
}
_THREAD_CTOR = "threading.Thread"
_BLOCKING_EXTERNALS = {
    "time.sleep": "time.sleep()",
    "jax.device_get": "jax.device_get()",
    "urllib.request.urlopen": "urllib.request.urlopen()",
    "subprocess.run": "subprocess.run()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
    "os.system": "os.system()",
}
_BLOCKING_METHODS = {
    "result": "Future.result()",
    "block_until_ready": "block_until_ready() device sync",
    "sendall": "socket sendall()",
    "recv": "socket recv()",
    "accept": "socket accept()",
    "getresponse": "HTTP getresponse()",
    "communicate": "subprocess communicate()",
}
_QUEUE_METHODS = {"get": "queue get()", "put": "queue put()"}


class LockBlockRule(Rule):
    name = "LOCKBLOCK"
    description = "blocking operation while holding a lock"

    def run(self, project: Project) -> Iterator[Finding]:
        model = lock_model(project)
        # per-function direct blocking ops (regardless of local lock state —
        # the caller's held set is what matters interprocedurally)
        direct_ops: Dict[str, Set[str]] = {}
        sites: List[Tuple[str, ModuleInfo, ast.Call, str, frozenset]] = []
        for mi in project.modules.values():
            funcs: List[Tuple[Optional[ClassInfo], FunctionInfo]] = [
                (None, fi) for fi in mi.functions.values()
            ]
            for ci in mi.classes.values():
                funcs.extend((ci, fi) for fi in ci.methods.values())
            for ci, fi in funcs:
                summary = model.summaries[fi.qualname]
                queue_attrs, thread_attrs = self._typed_attrs(project, mi, ci)
                var_queues, var_threads = self._typed_locals(mi, fi)
                ops: Set[str] = set()
                for call, held in summary.call_nodes:
                    desc = self._blocking_desc(
                        mi,
                        call,
                        queue_attrs,
                        thread_attrs,
                        var_queues,
                        var_threads,
                    )
                    if desc is None:
                        continue
                    if held:
                        # guarded at its own site: reported once, directly;
                        # NOT propagated to callers (the callee's author
                        # already made a locking decision there — the one
                        # finding is where the prose waiver belongs, not
                        # every transitive caller of a memoized builder)
                        sites.append((fi.qualname, mi, call, desc, held))
                    else:
                        ops.add(desc)
                direct_ops[fi.qualname] = ops

        # direct findings: the op itself sits under a lock
        direct_nodes = {id(call) for _, _, call, _, _ in sites}
        for qualname, mi, call, desc, held in sites:
            yield self.finding(
                project,
                mi,
                call,
                f"blocking {desc} while holding "
                + ", ".join(f"`{l}`" for l in sorted(held))
                + " — every waiter on the lock now waits on this too; move "
                "the blocking call outside the critical section",
                context=qualname,
            )

        # interprocedural: a lock-held call whose closure blocks. The
        # closure flows only through LOCK-FREE call edges: if g calls h
        # under a lock of its own, that site is g's finding (or g's prose
        # waiver) — g is the decision point, and re-flagging every caller
        # of g would turn one waived one-time-build into a file of noise.
        unlocked_calls: Dict[str, Set[str]] = {
            q: {callee for callee, _, held in s.calls if not held}
            for q, s in model.summaries.items()
        }
        closure = _transitive(direct_ops, unlocked_calls)
        for q, summary in model.summaries.items():
            mi = project.module_of(q)
            if mi is None:
                continue
            reported: Set[int] = set()
            for callee, node, held in summary.calls:
                if not held or id(node) in reported or id(node) in direct_nodes:
                    continue
                inner = closure.get(callee, set())
                if not inner:
                    continue
                reported.add(id(node))
                sample = sorted(inner)[0]
                yield self.finding(
                    project,
                    mi,
                    node,
                    f"call into {callee}() may block ({sample}"
                    + (f" +{len(inner) - 1} more" if len(inner) > 1 else "")
                    + ") while holding "
                    + ", ".join(f"`{l}`" for l in sorted(held)),
                    context=q,
                )

    # ------------------------------------------------------------------

    @staticmethod
    def _typed_attrs(
        project: Project, mi: ModuleInfo, ci: Optional[ClassInfo]
    ) -> Tuple[Set[str], Set[str]]:
        """self-attrs whose recorded ctor is a queue / a Thread."""
        queues: Set[str] = set()
        threads: Set[str] = set()
        if ci is None:
            return queues, threads
        for attr, ctors in ci.attr_ctor_names.items():
            for d in ctors:
                full = resolve_external(mi, d)
                if full in _QUEUE_CTORS:
                    queues.add(attr)
                elif full == _THREAD_CTOR:
                    threads.add(attr)
        return queues, threads

    @staticmethod
    def _typed_locals(
        mi: ModuleInfo, fi: FunctionInfo
    ) -> Tuple[Set[str], Set[str]]:
        queues: Set[str] = set()
        threads: Set[str] = set()
        for node in ast.walk(fi.node):
            if not (
                isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
            ):
                continue
            d = _dotted(node.value.func)
            if d is None:
                continue
            full = resolve_external(mi, d)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if full in _QUEUE_CTORS:
                        queues.add(tgt.id)
                    elif full == _THREAD_CTOR:
                        threads.add(tgt.id)
        return queues, threads

    def _blocking_desc(
        self,
        mi: ModuleInfo,
        call: ast.Call,
        queue_attrs: Set[str],
        thread_attrs: Set[str],
        var_queues: Set[str],
        var_threads: Set[str],
    ) -> Optional[str]:
        func = call.func
        d = _dotted(func)
        if d is not None:
            full = resolve_external(mi, d)
            if full in _BLOCKING_EXTERNALS:
                return _BLOCKING_EXTERNALS[full]
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value

        def recv_in(attrs: Set[str], local_vars: Set[str]) -> bool:
            if isinstance(recv, ast.Name):
                return recv.id in local_vars
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
            ):
                return recv.attr in attrs
            return False

        if attr in _QUEUE_METHODS:
            if recv_in(queue_attrs, var_queues):
                return _QUEUE_METHODS[attr]
            return None
        if attr == "join":
            if recv_in(thread_attrs, var_threads):
                return "Thread.join()"
            rd = _dotted(recv)
            if rd is not None and "thread" in rd.lower():
                return "Thread.join()"
            return None
        if attr in _BLOCKING_METHODS:
            return _BLOCKING_METHODS[attr]
        return None
