"""HOSTSYNC: accidental device->host syncs on the verification hot path.

The north-star loop (batched keccak over witness nodes, post-state roots,
vmapped ecrecover) only sustains its throughput while the device pipeline
stays asynchronous: a stray `.item()`, `int(device_value)` or
`np.asarray(device_value)` inside the hot path forces a blocking
round-trip per call — invisible in review, catastrophic in the profiler
(the exact failure mode MHOT's hash-pipeline analysis warns about).

Scope: every function reachable (phant_tpu/analysis/symbols.py call
graph) from the hot-path entry points — `stateless.execute_stateless`
and `WitnessEngine.verify_batch` by default. Flags:

  * `.item()` calls (always — a scalar pull is a sync no matter the type);
  * `.block_until_ready()` calls (an explicit sync; legitimate ones are
    probes/benchmarks and carry a disable annotation with the reason);
  * `jax.device_get(...)`;
  * `int()` / `bool()` / `float()` / `np.asarray()` / `np.array()` over a
    device-tainted expression (see rules/_taint.py).

Intentional syncs — the timed `keccak.host_readback` phase, the one-shot
link probe — are annotated `# phantlint: disable=HOSTSYNC` with a reason,
which doubles as in-code documentation of where the honest syncs live.
"""

from __future__ import annotations

import ast
from typing import Iterator, Sequence, Tuple

from phant_tpu.analysis.core import Finding, Rule, iter_calls
from phant_tpu.analysis.rules._taint import (
    Taint,
    is_jax_call,
    resolve_external,
    snippet,
)
from phant_tpu.analysis.symbols import Project, _dotted

DEFAULT_ENTRIES: Tuple[str, ...] = (
    "phant_tpu.stateless.execute_stateless",
    "phant_tpu.ops.witness_engine.WitnessEngine.verify_batch",
    # mesh serving (PR 7): the per-device executor loop and the routing/
    # megabatch entries are the serving hot path — a stray sync in a lane
    # stalls one chip's whole pipeline
    "phant_tpu.serving.mesh_exec.MeshExecutorPool.submit",
    "phant_tpu.serving.mesh_exec.MeshExecutorPool._run_executor",
    "phant_tpu.serving.mesh_exec.MeshExecutorPool.run_megabatch",
    # device-resident intern table (PR 8): the whole point of the
    # resident route is that dispatch enqueues with ZERO host sync —
    # a reintroduced readback in the scan/assign/enqueue path puts the
    # tunnel back on the per-batch critical path and silently undoes
    # the architecture (the resolve stage's honest syncs are annotated)
    "phant_tpu.ops.witness_engine.WitnessEngine.begin_batch",
    "phant_tpu.ops.witness_resident.ResidentTable.dispatch",
    # streaming witness ingestion (PR 9): the prefetch stage exists to
    # take work OFF the serving critical path — the engine pre-scan and
    # the scheduler's prefetch worker must never pull a device scalar
    # (a sync there re-serializes the 4th stage against the device and
    # silently turns the overlap win into a stall)
    "phant_tpu.ops.witness_engine.WitnessEngine.prefetch_batch",
    "phant_tpu.serving.scheduler.VerificationScheduler._prefetch_run",
    # batched post-state roots (PR 11): plan lowering (the merge the
    # prefetch stage runs) and the root_many dispatch path exist to
    # enqueue the merged program with ZERO host sync — a reintroduced
    # `.item()`/readback in the level loop puts a blocking round trip
    # back on every coalesced post root (the resolve stage's honest
    # readback is annotated)
    "phant_tpu.ops.root_engine.RootEngine.prefetch_batch",
    "phant_tpu.ops.root_engine.RootEngine.root_many",
    # coalesced sender recovery (PR 14): the sig lane's merge (the row
    # concat + limb encode the prefetch stage runs) and the sig_many
    # dispatch path exist to enqueue the merged ecrecover with ZERO host
    # sync — a reintroduced `.item()`/readback in the merge loop puts a
    # blocking round trip back on every coalesced recovery (the resolve
    # stage's honest sender readback is annotated)
    "phant_tpu.ops.sig_engine.SigEngine.prefetch_batch",
    "phant_tpu.ops.sig_engine.SigEngine.sig_many",
    # critical-path attribution (PR 15): the busy-time integration points
    # in the lane loops — begin_batch's handoff (busy begin) and the
    # resolve worker (busy end) — are pure host arithmetic by design; a
    # reintroduced `.item()`/readback there would put a device sync on
    # EVERY pipelined batch under the banner of observability (the mesh
    # lane loop, _run_executor above, already covers its own busy
    # brackets)
    "phant_tpu.serving.scheduler.VerificationScheduler._pipeline_handoff",
    "phant_tpu.serving.scheduler.VerificationScheduler._resolve_run",
    # pluggable commitment schemes (PR 12): the binary backend's witness
    # pack loop (full-subtree node collection) and proof-path walk feed
    # the serving differential/bench spans and the fixture-translation
    # harness — pure host-bytes work by design; a reintroduced `.item()`
    # or device readback in these walks would put a sync inside the
    # per-block witness generation loop
    "phant_tpu.commitment.binary.BinaryScheme.collect_nodes",
    "phant_tpu.commitment.binary.BinaryScheme.proof_nodes",
    # historical replay (PR 18): segment plan lowering runs on the
    # replay pipeline's prefetch stage — it groups K blocks' root plans
    # into structure-sharing runs and stacks the payload blobs for ONE
    # vmapped device program, all host-side shape work by design; a
    # reintroduced `.item()`/readback there re-serializes segment N+1's
    # prep against segment N's device work (the resolve stage's honest
    # per-root readback lives in resolve_segment_roots, off this list)
    "phant_tpu.replay.lowering.lower_segment_plans",
)

_SCALAR_BUILTINS = ("int", "bool", "float")


class HostSyncRule(Rule):
    name = "HOSTSYNC"
    description = "device->host sync inside the hot verification path"

    def __init__(self, entries: Sequence[str] = DEFAULT_ENTRIES):
        self.entries = tuple(entries)

    def run(self, project: Project) -> Iterator[Finding]:
        for qualname in sorted(project.reachable(self.entries)):
            fi = project.functions.get(qualname)
            if fi is None:
                continue
            mi = project.modules.get(fi.module)
            if mi is None:
                continue
            taint = Taint(project, mi, fi.node, taint_params=fi.jitted)
            for call in iter_calls(fi.node):
                func = call.func
                if isinstance(func, ast.Attribute):
                    if func.attr == "item" and not call.args:
                        yield self.finding(
                            project,
                            mi,
                            call,
                            f"`{snippet(call)}` forces a device->host sync "
                            "(.item()) on the hot path",
                            context=qualname,
                        )
                        continue
                    if func.attr == "block_until_ready":
                        yield self.finding(
                            project,
                            mi,
                            call,
                            f"`{snippet(call)}` blocks on device completion "
                            "on the hot path",
                            context=qualname,
                        )
                        continue
                d = _dotted(func)
                if d is not None:
                    full = resolve_external(mi, d)
                    if full == "jax.device_get":
                        yield self.finding(
                            project,
                            mi,
                            call,
                            f"`{snippet(call)}` copies a device value to "
                            "host on the hot path",
                            context=qualname,
                        )
                        continue
                    if full in ("numpy.asarray", "numpy.array") and any(
                        taint.tainted(a) for a in call.args
                    ):
                        yield self.finding(
                            project,
                            mi,
                            call,
                            f"`{snippet(call)}` materializes a device value "
                            "on host (blocking readback) on the hot path",
                            context=qualname,
                        )
                        continue
                if (
                    isinstance(func, ast.Name)
                    and func.id in _SCALAR_BUILTINS
                    and func.id not in mi.imports
                    and func.id not in mi.functions
                    and any(taint.tainted(a) for a in call.args)
                ):
                    yield self.finding(
                        project,
                        mi,
                        call,
                        f"`{snippet(call)}` pulls a device scalar to host "
                        f"({func.id}() is a blocking sync) on the hot path",
                        context=qualname,
                    )
