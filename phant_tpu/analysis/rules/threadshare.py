"""THREADSHARE: thread-shared classes that own no lock.

Escape analysis extending LOCK from "classes that HAVE a lock use it
consistently" to "classes that SHOULD have one do": a class instance is
*thread-shared* once it is reachable from more than one thread —

  * `threading.Thread(target=self.m)` inside a class: the instance runs a
    worker, so every attribute is visible to (at least) the spawning
    thread and the worker;
  * `threading.Thread(target=f, args=(obj, ...))` with a ctor-typed obj:
    the object crosses into the thread;
  * `NAME = Ctor(...)` at module level: a published singleton — every
    importing thread shares the one instance (obs.flight.flight,
    utils.trace.metrics).

A shared class with post-`__init__` attribute mutation and no
`threading.Lock/RLock` attr (own or inherited) is flagged.  Waivers:

  * `# phantlint: immutable` on the class-def line or the line directly
    above — the author asserts all post-init state is read-only or
    benignly monotonic (phantsan validates the claim at runtime);
  * the ordinary `# phantlint: disable=THREADSHARE` suppression.

Under-approximation: sharing through containers, callbacks, or factory
returns is invisible here — phantsan (analysis/sanitizer.py) is the
dynamic backstop for those.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Set

from phant_tpu.analysis.core import Finding, Rule
from phant_tpu.analysis.locks import LockModel, lock_model, resolve_external
from phant_tpu.analysis.symbols import ClassInfo, ModuleInfo, Project, _dotted

_THREAD_CTOR = "threading.Thread"
_IMMUTABLE_RE = re.compile(r"#\s*phantlint:\s*immutable\b")


class ThreadShareRule(Rule):
    name = "THREADSHARE"
    description = "thread-shared class without a lock"

    def run(self, project: Project) -> Iterator[Finding]:
        model = lock_model(project)
        shared: dict = {}  # class qualname -> reason string (first wins)

        def mark(ci: Optional[ClassInfo], reason: str) -> None:
            if ci is not None:
                shared.setdefault(ci.qualname, reason)

        for mi in project.modules.values():
            self._scan_module(project, mi, mark)

        for qualname in sorted(shared):
            ci = project.classes.get(qualname)
            if ci is None:
                continue
            mi = project.modules.get(ci.module)
            if mi is None:
                continue
            if model.class_lock_decls(ci):
                continue
            if self._is_waived(mi, ci):
                continue
            mutated = self._post_init_mutation(ci)
            if mutated is None:
                continue
            yield self.finding(
                project,
                mi,
                ci.node,
                f"`{ci.node.name}` is thread-shared ({shared[qualname]}) "
                f"but owns no lock, and `{mutated}` is mutated after "
                "__init__ — add a threading.Lock around the mutable state, "
                "or waive with `# phantlint: immutable` if every post-init "
                "access is read-only",
                context=ci.qualname,
            )

    # ------------------------------------------------------------------

    def _scan_module(self, project: Project, mi: ModuleInfo, mark) -> None:
        # module-level publications: NAME = Ctor(...) of a project class
        for node in mi.tree.body:
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and any(isinstance(t, ast.Name) for t in node.targets)
            ):
                d = _dotted(node.value.func)
                if d is not None:
                    mark(
                        project.resolve_class(mi.name, d),
                        "published as a module-level singleton",
                    )
        # Thread(...) escapes, anywhere in the module
        for owner_name, fn in self._functions(mi):
            owner = mi.classes.get(owner_name) if owner_name else None
            var_classes = None
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d is None or resolve_external(mi, d) != _THREAD_CTOR:
                    continue
                if var_classes is None:
                    var_classes = self._ctor_vars(project, mi, fn)
                for kw in node.keywords:
                    if kw.arg == "target":
                        self._mark_target(
                            project, mi, owner, var_classes, kw.value, mark
                        )
                    elif kw.arg == "args" and isinstance(
                        kw.value, (ast.Tuple, ast.List)
                    ):
                        for elt in kw.value.elts:
                            if (
                                isinstance(elt, ast.Name)
                                and elt.id in var_classes
                            ):
                                mark(
                                    var_classes[elt.id],
                                    "passed into threading.Thread(args=…)",
                                )

    @staticmethod
    def _functions(mi: ModuleInfo):
        for fi in mi.functions.values():
            yield None, fi.node
        for cname, ci in mi.classes.items():
            for fi in ci.methods.values():
                yield cname, fi.node

    @staticmethod
    def _ctor_vars(project: Project, mi: ModuleInfo, fn: ast.AST):
        out = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                d = _dotted(node.value.func)
                if d is not None:
                    ci = project.resolve_class(mi.name, d)
                    if ci is not None:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                out[tgt.id] = ci
        return out

    def _mark_target(
        self, project, mi, owner, var_classes, target: ast.AST, mark
    ) -> None:
        # target=self.m -> the owning instance escapes to the worker
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
        ):
            if target.value.id == "self" and owner is not None:
                mark(owner, "runs a threading.Thread worker (target=self.…)")
            elif target.value.id in var_classes:
                mark(
                    var_classes[target.value.id],
                    "bound method handed to threading.Thread(target=…)",
                )

    @staticmethod
    def _post_init_mutation(ci: ClassInfo) -> Optional[str]:
        """First self-attribute stored outside __init__, or None."""
        for name in sorted(ci.methods):
            if name == "__init__":
                continue
            fi = ci.methods[name]
            for node in ast.walk(fi.node):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        return tgt.attr
        return None

    @staticmethod
    def _is_waived(mi: ModuleInfo, ci: ClassInfo) -> bool:
        line = getattr(ci.node, "lineno", 1)
        for i in (line - 1, line):  # the line above, then the def line
            if 1 <= i <= len(mi.lines) and _IMMUTABLE_RE.search(
                mi.lines[i - 1]
            ):
                return True
        return False
