"""JNPHOSTLOOP: `jnp.*` calls inside host-side Python loops.

A `jnp.*` call is one device dispatch. Inside a jitted function that is
free — the Python loop unrolls at trace time into a single compiled
program. Inside a plain `for`/`while` loop on the HOST it is a
per-element device dispatch: every iteration pays the dispatch round
trip (and usually runs a tiny kernel), the exact antipattern the batched
/ vmapped hot loops exist to avoid. ROADMAP open item (c) asked for this
rule once a refactor could plausibly reintroduce the pattern — the
pipelined witness execution split (pack/dispatch/resolve across threads,
PR 5) is that refactor: moving device calls between stages is precisely
where a stray per-iteration `jnp.asarray` would creep in.

Scope: functions that are neither jitted themselves nor reachable from a
jitted function (reachable callees run traced, where host loops unroll
at trace time). Calls are resolved to the `jax.numpy` namespace through
any import alias (`import jax.numpy as jnp`, `from jax import numpy`,
dotted `jax.numpy.foo`). Nested function definitions are separate scopes
and are skipped (the symbol table does not track them — suppressing, not
inventing, findings). The usual `# phantlint: disable=JNPHOSTLOOP`
escape hatch applies to intentional per-iteration dispatches (e.g. a
deliberately serialized device probe loop).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from phant_tpu.analysis.core import Finding, Rule
from phant_tpu.analysis.symbols import ModuleInfo, Project, _dotted

_OWN_SCOPE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def _jnp_target(mi: ModuleInfo, call: ast.Call) -> str:
    """The dotted callee when it resolves into jax.numpy, else ''."""
    d = _dotted(call.func)
    if d is None:
        return ""
    head, _, rest = d.partition(".")
    target = mi.imports.get(head, head)
    full = target + ("." + rest if rest else "")
    if full == "jax.numpy" or full.startswith("jax.numpy."):
        return d
    return ""


def _loop_calls(fn: ast.AST) -> Iterator[tuple]:
    """(loop_kind, Call) for every call that executes PER ITERATION of a
    For/While in `fn`, excluding nested function/class scopes. A for
    loop's iterable expression and a loop's `else` clause run exactly
    once — they inherit the surrounding context, never the loop's — while
    a while loop's test re-evaluates every iteration and counts."""

    def walk(node: ast.AST, in_loop: str) -> Iterator[tuple]:
        if isinstance(node, _OWN_SCOPE):
            return  # separate scope: analyzed (or not) on its own
        if in_loop and isinstance(node, ast.Call):
            yield in_loop, node
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield from walk(node.iter, in_loop)  # evaluated once
            yield from walk(node.target, in_loop)
            for stmt in node.body:
                yield from walk(stmt, "for")
            for stmt in node.orelse:
                yield from walk(stmt, in_loop)  # runs once, after the loop
            return
        if isinstance(node, ast.While):
            yield from walk(node.test, "while")  # re-evaluated per pass
            for stmt in node.body:
                yield from walk(stmt, "while")
            for stmt in node.orelse:
                yield from walk(stmt, in_loop)
            return
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            # the most idiomatic form of the antipattern:
            # `[jnp.asarray(n) for n in nodes]` is one dispatch per
            # element. The FIRST generator's iterable evaluates once;
            # everything else — element expr, conditions, inner iters —
            # runs per iteration.
            gens = node.generators
            yield from walk(gens[0].iter, in_loop)
            for gen in gens:
                yield from walk(gen.target, "comprehension")
                for cond in gen.ifs:
                    yield from walk(cond, "comprehension")
            for gen in gens[1:]:
                yield from walk(gen.iter, "comprehension")
            if isinstance(node, ast.DictComp):
                yield from walk(node.key, "comprehension")
                yield from walk(node.value, "comprehension")
            else:
                yield from walk(node.elt, "comprehension")
            return
        for child in ast.iter_child_nodes(node):
            yield from walk(child, in_loop)

    for child in ast.iter_child_nodes(fn):
        yield from walk(child, "")


class JnpHostLoopRule(Rule):
    name = "JNPHOSTLOOP"
    description = "jnp calls inside host-side loops (per-element dispatch)"

    def run(self, project: Project) -> Iterator[Finding]:
        # traced scope: jitted functions plus everything they call — their
        # loops unroll at trace time, one compiled program, no dispatch
        jitted = [q for q, fi in project.functions.items() if fi.jitted]
        traced: Set[str] = project.reachable(jitted)
        for mi in project.modules.values():
            funcs = list(mi.functions.values())
            for ci in mi.classes.values():
                funcs.extend(ci.methods.values())
            for fi in funcs:
                if fi.jitted or fi.qualname in traced:
                    continue
                for loop_kind, call in _loop_calls(fi.node):
                    target = _jnp_target(mi, call)
                    if not target:
                        continue
                    yield self.finding(
                        project,
                        mi,
                        call,
                        f"`{target}(...)` inside a host-side {loop_kind} "
                        "loop — one device dispatch per iteration; "
                        "batch/vmap the operation or hoist it out of the "
                        "loop",
                        context=fi.qualname,
                    )
