"""METRICNAME: one static gate for the metric-name/help catalog.

Absorbs the name/help checks of the old runtime `scripts/metrics_lint.py`
into the analyzer (the script is now a thin shim over this rule), so the
exposition checker and the static checker cannot drift apart:

  * M1 — a registry call (`metrics.count/gauge_set/gauge_add/observe/
    observe_hist/phase`) whose metric name is not a string literal:
    dynamic names are a cardinality hazard and invisible to this gate
    (annotate the few legitimate sites, e.g. names drawn from an adjacent
    literal table).
  * M2 — a literal name that is not `[a-z0-9_.]+`: the Prometheus
    sanitizer (`trace.prometheus_name`) would mangle it lossily.
  * M3 — a literal name with no entry in `trace.METRIC_HELP`: every
    exported family documents itself or the gate is red.
  * M4 — catalog rot: a `METRIC_HELP` key that appears nowhere in the
    package as a string literal is a dead catalog entry.

The catalog is read from the module that defines `METRIC_HELP` (the
metrics registry module, phant_tpu/utils/trace.py in this repo) — found
by scanning, so fixture packages in tests can carry their own.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set, Tuple

from phant_tpu.analysis.core import Finding, Rule, iter_calls
from phant_tpu.analysis.rules._taint import snippet
from phant_tpu.analysis.symbols import ModuleInfo, Project, _dotted

_NAME_RE = re.compile(r"^[a-z0-9_.]+$")
_METHODS = ("count", "gauge_set", "gauge_add", "observe", "observe_hist", "phase")


class MetricNameRule(Rule):
    name = "METRICNAME"
    description = "metric names: literal, sanitizable, and in METRIC_HELP"

    def run(self, project: Project) -> Iterator[Finding]:
        catalog = self._find_catalog(project)
        if catalog is None:
            return
        cat_module, help_node, keys = catalog
        used: Set[str] = set()
        for mi in project.modules.values():
            in_catalog = mi.name == cat_module.name
            for node in ast.walk(mi.tree):
                if (
                    isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and not self._inside(help_node, node, in_catalog)
                ):
                    used.add(node.value)
            if in_catalog:
                continue  # the registry implementation passes names through
            yield from self._check_sites(project, mi, cat_module.name, keys)
        for key, lineno in sorted(keys.items()):
            if key not in used:
                yield Finding(
                    rule=self.name,
                    path=self._rel(cat_module),
                    line=lineno,
                    col=1,
                    message=(
                        f"METRIC_HELP entry {key!r} is never emitted anywhere "
                        "in the package — dead catalog entry (or the emit "
                        "site builds the name dynamically: make it literal)"
                    ),
                    context=f"{cat_module.name}.METRIC_HELP",
                )

    @staticmethod
    def _rel(mi: ModuleInfo) -> str:
        from phant_tpu.analysis.core import rel_path

        return rel_path(mi.path)

    @staticmethod
    def _inside(help_node: ast.AST, node: ast.AST, same_module: bool) -> bool:
        if not same_module:
            return False
        return (
            getattr(node, "lineno", 0) >= help_node.lineno
            and getattr(node, "end_lineno", 0) <= (help_node.end_lineno or 0)
        )

    def _find_catalog(
        self, project: Project
    ) -> Optional[Tuple[ModuleInfo, ast.AST, Dict[str, int]]]:
        for mi in project.modules.values():
            for node in mi.tree.body:
                target = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                else:
                    continue
                if (
                    isinstance(target, ast.Name)
                    and target.id == "METRIC_HELP"
                    and isinstance(value, ast.Dict)
                ):
                    keys = {
                        k.value: k.lineno
                        for k in value.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    }
                    return mi, node, keys
        return None

    def _check_sites(
        self, project: Project, mi: ModuleInfo, cat_module: str, keys: Dict[str, int]
    ) -> Iterator[Finding]:
        for call in iter_calls(mi.tree):
            name_arg = self._metric_name_arg(mi, call, cat_module)
            if name_arg is None:
                continue
            if not (
                isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
            ):
                yield self.finding(
                    project,
                    mi,
                    call,
                    f"`{snippet(call)}` uses a non-literal metric name — "
                    "dynamic names defeat the static catalog gate and risk "
                    "unbounded cardinality",
                    context=mi.name,
                )
                continue
            name = name_arg.value
            if not _NAME_RE.match(name):
                yield self.finding(
                    project,
                    mi,
                    call,
                    f"metric name {name!r} is not [a-z0-9_.]+ — the "
                    "Prometheus family sanitization would be lossy",
                    context=mi.name,
                )
            if name not in keys:
                yield self.finding(
                    project,
                    mi,
                    call,
                    f"metric name {name!r} has no METRIC_HELP entry — add "
                    "its help string to the registry catalog",
                    context=mi.name,
                )

    def _metric_name_arg(
        self, mi: ModuleInfo, call: ast.Call, cat_module: str
    ) -> Optional[ast.AST]:
        """The metric-name argument of a registry call — positional OR
        `name=` keyword (a keyword-only dynamic name must not slip past
        M1) — else None for non-registry calls. A registry call whose
        name cannot be located at all (e.g. `metrics.count(**kw)`) yields
        the call node itself, which is non-literal and so flags as M1."""
        func = call.func
        is_registry = False
        if isinstance(func, ast.Attribute) and func.attr in _METHODS:
            d = _dotted(func.value)
            if d is not None:
                head, _, rest = d.partition(".")
                full = mi.imports.get(head, head) + ("." + rest if rest else "")
                is_registry = full == f"{cat_module}.metrics" or d == "metrics"
        elif isinstance(func, ast.Name):
            is_registry = mi.imports.get(func.id) == f"{cat_module}.phase"
        if not is_registry:
            return None
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == "name":
                return kw.value
        return call
