"""LOCKORDER: lock-acquisition order cycles across the whole program.

Builds the global lock-acquisition graph over the call graph: an edge
A -> B means some function acquires lock B (a `with` on a resolved lock
object, locks.py) while already holding lock A — either lexically nested
in one function, or because a function called with A held transitively
acquires B.  A cycle in that graph is a potential deadlock: two threads
entering the cycle from different edges can each hold the lock the other
needs.  Also flags the degenerate one-lock case — re-acquiring a
non-reentrant `threading.Lock` lexically inside its own `with` block —
which deadlocks a single thread with itself.

Interprocedural edges deliberately skip the A -> A case: the static lock
id conflates instances (`a._lock` and `b._lock` of the same class share
one id), so "holds `C._lock`, calls a method that takes `C._lock`" is
routinely two different instances.  The lexical same-expression case has
no such excuse and is reported.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from phant_tpu.analysis.core import Finding, Rule, rel_path
from phant_tpu.analysis.locks import LockModel, lock_model
from phant_tpu.analysis.symbols import Project

# witness for one graph edge: (holder, acquired) proven at a site
_Edge = Tuple[str, str]


class LockOrderRule(Rule):
    name = "LOCKORDER"
    description = "lock-acquisition order cycles (potential deadlocks)"

    def run(self, project: Project) -> Iterator[Finding]:
        model = lock_model(project)
        closure = model.acquired_closure()
        lock_kinds = self._lock_kinds(model)
        edges: Dict[_Edge, Tuple[str, ast.AST, str]] = {}  # -> (qualname, node, path)

        def add_edge(a: str, b: str, qualname: str, node: ast.AST) -> None:
            mi = project.module_of(qualname)
            if mi is None:
                return
            path = rel_path(mi.path)
            prev = edges.get((a, b))
            key = (path, getattr(node, "lineno", 0))
            if prev is None or key < (prev[2], getattr(prev[1], "lineno", 0)):
                edges[(a, b)] = (qualname, node, path)

        for q, summary in model.summaries.items():
            for lock_id, node, held in summary.acquisitions:
                for h in held:
                    if h != lock_id:
                        add_edge(h, lock_id, q, node)
                if lock_id in held and lock_kinds.get(lock_id) == "lock":
                    mi = project.module_of(q)
                    if mi is not None:
                        yield self.finding(
                            project,
                            mi,
                            node,
                            f"re-acquiring non-reentrant lock `{lock_id}` "
                            "inside its own `with` block — this deadlocks "
                            "the acquiring thread with itself",
                            context=q,
                        )
            for callee, node, held in summary.calls:
                if not held:
                    continue
                for inner in closure.get(callee, ()):
                    for h in held:
                        if h != inner:
                            add_edge(h, inner, q, node)

        yield from self._cycle_findings(project, edges)

    # ------------------------------------------------------------------

    @staticmethod
    def _lock_kinds(model: LockModel) -> Dict[str, str]:
        kinds: Dict[str, str] = {}
        for table in list(model.class_locks.values()) + list(
            model.module_locks.values()
        ):
            for decl in table.values():
                kinds.setdefault(decl.lock_id, decl.kind)
        return kinds

    def _cycle_findings(
        self, project: Project, edges: Dict[_Edge, Tuple[str, ast.AST, str]]
    ) -> Iterator[Finding]:
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            cyc_edges = sorted(
                (a, b) for (a, b) in edges if a in scc and b in scc
            )
            witnesses = []
            for a, b in cyc_edges:
                qualname, node, path = edges[(a, b)]
                witnesses.append(
                    f"`{a}` held while acquiring `{b}` in {qualname}() "
                    f"({path}:{getattr(node, 'lineno', '?')})"
                )
            first_q, first_node, _ = edges[cyc_edges[0]]
            mi = project.module_of(first_q)
            if mi is None:
                continue
            yield self.finding(
                project,
                mi,
                first_node,
                "lock-order cycle among "
                + ", ".join(f"`{l}`" for l in sorted(scc))
                + " — potential cross-thread deadlock: "
                + "; ".join(witnesses),
                context=first_q,
            )


def _sccs(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan's strongly connected components, iteratively."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recursed = False
            succs = sorted(adj.get(node, ()))
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recursed = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recursed:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)
    return out
