"""DTYPE: promotion/overflow hazards in the uint32 lane-math modules.

The device kernels do all 64-bit work as uint32 lane pairs; a Python int
literal slipped into that math without an explicit cast either overflows
int32 at trace time or silently promotes a lane to a wider dtype, which
breaks bit-exactness against the CPU backends (differential tests catch
it late; this rule catches it at commit time). Scope defaults to the
lane-math modules named by the framework: ops/keccak_jax.py,
ops/secp256k1_jax.py, ops/witness_jax.py.

Checks, applied inside "lane functions" (jit entry points plus their
intra-scope transitive callees, whose parameters are tracers):

  * D1 — a bare int literal that does not fit int32 (|v| >= 2**31) mixed
    into tainted lane math (binop operand, `.set(...)` on a tainted
    `.at[]` chain, or argument beside a tainted one) without a direct
    `jnp.uint32(...)`-style cast;
  * D3 — true division `/` touching a tainted value (floats have no place
    in lane math; `//` is what integer code means).

Plus module-wide (host packers included, since their arrays feed the
device layout):

  * D2 — array constructors (`zeros`/`ones`/`empty`/`full`/`arange`/
    `fromiter`/`frombuffer`/`array` on numpy or jax.numpy) without an
    explicit dtype: default dtypes (float64 / platform int) are exactly
    the drift this rule exists to stop.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Sequence, Set, Tuple

from phant_tpu.analysis.core import Finding, Rule, iter_calls
from phant_tpu.analysis.rules._taint import Taint, resolve_external, snippet
from phant_tpu.analysis.symbols import FunctionInfo, ModuleInfo, Project, _dotted

DEFAULT_MODULES: Tuple[str, ...] = (
    "phant_tpu.ops.keccak_jax",
    "phant_tpu.ops.secp256k1_jax",
    "phant_tpu.ops.witness_jax",
)

_INT32_MAX = 2**31 - 1

_DTYPE_NAMES = {
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "int8",
    "int16",
    "int32",
    "int64",
    "float32",
    "float64",
    "bfloat16",
}

#: constructor -> index of the positional dtype slot (None = keyword only)
_CREATORS: Dict[str, Optional[int]] = {
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "fromiter": 1,
    "frombuffer": 1,
    "array": 1,
    "arange": None,
}


def _is_cast_call(mi: ModuleInfo, call: ast.Call) -> bool:
    """jnp.uint32(x) / np.int64(x) / jnp.asarray(x, dtype=...) / x.astype."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "astype":
        return True
    d = _dotted(func)
    if d is None:
        return False
    full = resolve_external(mi, d)
    if full.startswith(("numpy.", "jax.numpy.")):
        leaf = full.rsplit(".", 1)[1]
        if leaf in _DTYPE_NAMES:
            return True
        if leaf in ("asarray", "array") and any(
            kw.arg == "dtype" for kw in call.keywords
        ):
            return True
    return False


def _dtype_expr(mi: ModuleInfo, node: ast.AST) -> bool:
    """Does this expression denote a dtype (np.uint32, "…", bool, int)?"""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, ast.Name) and node.id in ("bool", "int", "float"):
        return True
    d = _dotted(node)
    if d is None:
        return False
    full = resolve_external(mi, d)
    return (
        full.startswith(("numpy.", "jax.numpy."))
        and full.rsplit(".", 1)[1] in _DTYPE_NAMES
    )


class DTypeRule(Rule):
    name = "DTYPE"
    description = "implicit dtype promotion in uint32 lane-math modules"

    def __init__(self, modules: Sequence[str] = DEFAULT_MODULES):
        self.scope = tuple(modules)

    def run(self, project: Project) -> Iterator[Finding]:
        scoped = [project.modules[m] for m in self.scope if m in project.modules]
        lane = self._lane_functions(project, scoped)
        for mi in scoped:
            # D2 covers the whole module (host packers + module constants)
            yield from self._check_creators(project, mi)
            funcs = list(mi.functions.values())
            for ci in mi.classes.values():
                funcs.extend(ci.methods.values())
            for fi in funcs:
                yield from self._check_function(project, mi, fi, lane)

    def _lane_functions(self, project: Project, scoped) -> Set[str]:
        """jitted functions in scope + their transitive callees in scope."""
        entries = []
        in_scope = set()
        for mi in scoped:
            for fi in mi.functions.values():
                in_scope.add(fi.qualname)
                if fi.jitted:
                    entries.append(fi.qualname)
            for ci in mi.classes.values():
                for fi in ci.methods.values():
                    in_scope.add(fi.qualname)
                    if fi.jitted:
                        entries.append(fi.qualname)
        return project.reachable(entries) & in_scope

    def _check_function(
        self,
        project: Project,
        mi: ModuleInfo,
        fi: FunctionInfo,
        lane: Set[str],
    ) -> Iterator[Finding]:
        is_lane = fi.qualname in lane
        taint = Taint(project, mi, fi.node, taint_params=is_lane)
        casted: Set[int] = set()  # id() of literal nodes under a cast
        for call in iter_calls(fi.node):
            if _is_cast_call(mi, call):
                for a in call.args:
                    casted.add(id(a))
        if is_lane:
            yield from self._check_lane(project, mi, fi, taint, casted)

    def _check_lane(self, project, mi, fi, taint, casted) -> Iterator[Finding]:
        def big_literal(node: ast.AST) -> bool:
            return (
                isinstance(node, ast.Constant)
                and isinstance(node.value, int)
                and not isinstance(node.value, bool)
                and abs(node.value) > _INT32_MAX
                and id(node) not in casted
            )

        for node in ast.walk(fi.node):
            if isinstance(node, ast.BinOp):
                if isinstance(node.op, ast.Div) and (
                    taint.tainted(node.left) or taint.tainted(node.right)
                ):
                    yield self.finding(
                        project,
                        mi,
                        node,
                        f"true division `{snippet(node)}` promotes lane math "
                        "to float — use // or an explicit cast",
                        context=fi.qualname,
                    )
                for lit, other in (
                    (node.left, node.right),
                    (node.right, node.left),
                ):
                    if big_literal(lit) and taint.tainted(other):
                        yield self.finding(
                            project,
                            mi,
                            lit,
                            f"int literal {getattr(lit, 'value', '?'):#x} "
                            "does not fit int32; mixing it into lane math "
                            "without jnp.uint32(...) promotes or overflows",
                            context=fi.qualname,
                        )
            elif isinstance(node, ast.Call):
                func = node.func
                recv_tainted = False
                if isinstance(func, ast.Attribute):
                    recv_tainted = taint.tainted(func.value)
                any_tainted = recv_tainted or any(
                    taint.tainted(a) for a in node.args
                )
                if not any_tainted:
                    continue
                for a in node.args:
                    if big_literal(a):
                        yield self.finding(
                            project,
                            mi,
                            a,
                            f"int literal {a.value:#x} does not fit int32; "
                            f"passing it uncast into `{snippet(node)}` "
                            "promotes or overflows the lane dtype",
                            context=fi.qualname,
                        )

    def _check_creators(self, project, mi) -> Iterator[Finding]:
        for call in iter_calls(mi.tree):
            d = _dotted(call.func)
            if d is None:
                continue
            full = resolve_external(mi, d)
            if not full.startswith(("numpy.", "jax.numpy.")):
                continue
            leaf = full.rsplit(".", 1)[1]
            if leaf not in _CREATORS:
                continue
            if any(kw.arg == "dtype" for kw in call.keywords):
                continue
            slot = _CREATORS[leaf]
            if slot is not None and len(call.args) > slot:
                continue
            if leaf == "arange" and any(
                _dtype_expr(mi, a) for a in call.args
            ):
                continue
            yield self.finding(
                project,
                mi,
                call,
                f"`{snippet(call)}` creates an array without an explicit "
                "dtype in a lane-math module (default dtype drifts by "
                "platform)",
                context=mi.name,
            )
