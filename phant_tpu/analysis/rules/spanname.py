"""SPANNAME: the METRICNAME gate for the TRACE vocabulary.

Span names (`span("verify_block", ...)`, utils/trace.py) and flight-event
kinds (`flight.record("sched.admit", ...)`, phant_tpu/obs/flight.py) are
exactly as dashboard-visible as metric families — a misspelled or
undocumented name silently forks the trace vocabulary. This rule holds
them to the METRICNAME discipline against the `SPAN_HELP` catalog (the
module that defines it — utils/trace.py in this repo; fixture packages in
tests carry their own):

  * S1 — a `span(...)` / `flight.record(...)` call whose name/kind is not
    a string literal: dynamic names are invisible to this gate (annotate
    the rare legitimate site).
  * S2 — a literal name that is not `[a-z0-9_.]+` (keeps span names
    joinable with the dotted metric namespace).
  * S3 — a literal name with no `SPAN_HELP` entry: every span/event kind
    documents itself or the gate is red.
  * S4 — catalog rot: a `SPAN_HELP` key that appears nowhere in the
    package as a string literal is a dead catalog entry.

Call-site resolution mirrors METRICNAME: `span` resolved through imports
to the catalog module's `span`, and `.record(...)` on a name resolving to
the obs flight singleton (`<...>.flight.flight` or a bare `flight`).
Internal pass-through calls inside the catalog module and `self.record`
inside the recorder implementation are not registry calls.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set, Tuple

from phant_tpu.analysis.core import Finding, Rule, iter_calls
from phant_tpu.analysis.rules._taint import snippet
from phant_tpu.analysis.symbols import ModuleInfo, Project, _dotted

_NAME_RE = re.compile(r"^[a-z0-9_.]+$")


class SpanNameRule(Rule):
    name = "SPANNAME"
    description = "span/flight-event names: literal, sanitizable, and in SPAN_HELP"

    def run(self, project: Project) -> Iterator[Finding]:
        catalog = self._find_catalog(project)
        if catalog is None:
            return
        cat_module, help_node, keys = catalog
        used: Set[str] = set()
        for mi in project.modules.values():
            in_catalog = mi.name == cat_module.name
            for node in ast.walk(mi.tree):
                if (
                    isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and not self._inside(help_node, node, in_catalog)
                ):
                    used.add(node.value)
            if in_catalog:
                continue  # the tracer implementation passes names through
            yield from self._check_sites(project, mi, cat_module.name, keys)
        for key, lineno in sorted(keys.items()):
            if key not in used:
                yield Finding(
                    rule=self.name,
                    path=self._rel(cat_module),
                    line=lineno,
                    col=1,
                    message=(
                        f"SPAN_HELP entry {key!r} is never emitted anywhere "
                        "in the package — dead catalog entry (or the emit "
                        "site builds the name dynamically: make it literal)"
                    ),
                    context=f"{cat_module.name}.SPAN_HELP",
                )

    @staticmethod
    def _rel(mi: ModuleInfo) -> str:
        from phant_tpu.analysis.core import rel_path

        return rel_path(mi.path)

    @staticmethod
    def _inside(help_node: ast.AST, node: ast.AST, same_module: bool) -> bool:
        if not same_module:
            return False
        return (
            getattr(node, "lineno", 0) >= help_node.lineno
            and getattr(node, "end_lineno", 0) <= (help_node.end_lineno or 0)
        )

    def _find_catalog(
        self, project: Project
    ) -> Optional[Tuple[ModuleInfo, ast.AST, Dict[str, int]]]:
        for mi in project.modules.values():
            for node in mi.tree.body:
                target = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                else:
                    continue
                if (
                    isinstance(target, ast.Name)
                    and target.id == "SPAN_HELP"
                    and isinstance(value, ast.Dict)
                ):
                    keys = {
                        k.value: k.lineno
                        for k in value.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    }
                    return mi, node, keys
        return None

    def _check_sites(
        self, project: Project, mi: ModuleInfo, cat_module: str, keys: Dict[str, int]
    ) -> Iterator[Finding]:
        for call in iter_calls(mi.tree):
            name_arg = self._span_name_arg(mi, call, cat_module)
            if name_arg is None:
                continue
            if not (
                isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
            ):
                yield self.finding(
                    project,
                    mi,
                    call,
                    f"`{snippet(call)}` uses a non-literal span/event name — "
                    "dynamic names defeat the static trace-vocabulary gate",
                    context=mi.name,
                )
                continue
            name = name_arg.value
            if not _NAME_RE.match(name):
                yield self.finding(
                    project,
                    mi,
                    call,
                    f"span/event name {name!r} is not [a-z0-9_.]+ — keep the "
                    "trace vocabulary joinable with the metric namespace",
                    context=mi.name,
                )
            if name not in keys:
                yield self.finding(
                    project,
                    mi,
                    call,
                    f"span/event name {name!r} has no SPAN_HELP entry — add "
                    "its help string to the trace-vocabulary catalog",
                    context=mi.name,
                )

    def _span_name_arg(
        self, mi: ModuleInfo, call: ast.Call, cat_module: str
    ) -> Optional[ast.AST]:
        """The name argument of a span()/flight.record() call — positional
        OR `name=`/`kind=` keyword — else None for non-registry calls. A
        registry call with no locatable name yields the call node itself,
        which is non-literal and so flags as S1."""
        func = call.func
        is_registry = False
        keyword = "name"
        if isinstance(func, ast.Name):
            is_registry = mi.imports.get(func.id) == f"{cat_module}.span"
        elif isinstance(func, ast.Attribute):
            d = _dotted(func.value)
            if d is not None:
                head, _, rest = d.partition(".")
                full = mi.imports.get(head, head) + ("." + rest if rest else "")
                if func.attr == "span":
                    # trace.span(...) attribute form
                    is_registry = full == cat_module or d == "trace"
                elif func.attr == "record":
                    # the obs flight singleton: `flight.record(...)` via
                    # `from ...obs.flight import flight` (or a bare name)
                    keyword = "kind"
                    is_registry = full.endswith(".flight.flight") or d == "flight"
        if not is_registry:
            return None
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == keyword:
                return kw.value
        return call
