"""Engine API: JSON-RPC DTOs, payload decoding, and method handlers.

Equivalent surface to the reference engine_api layer (reference:
src/engine_api/engine_api.zig:22-85 and
src/engine_api/execution_payload.zig:12-213): the hex-string JSON
intermediate (`payload_from_json` ≈ AllPossibleExecutionParams
.to_execution_payload, engine_api.zig:38-77), `ExecutionPayload.to_block`
(execution_payload.zig:125-166), `new_payload_v2_handler`
(execution_payload.zig:175-182), `get_client_version_v1_handler`
(execution_payload.zig:206-213), and the forkchoice / payload-status /
blobs DTOs (execution_payload.zig:12-100). The HTTP server lives in
`phant_tpu.engine_api.server` (reference: httpz at main.zig:143-149).

Deviation from the reference: `to_block` keys the tx/withdrawal tries by
canonical `rlp(index)` (mainnet rule) rather than the reference's 32-byte
big-endian index keys (execution_payload.zig:128-139) — the reference only
ever compares these roots against values it computed the same way, so its
quirk is unobservable there, while real payloads need the canonical rule.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from phant_tpu.mpt.mpt import ordered_trie_root
from phant_tpu.types.block import Block, BlockHeader, EMPTY_UNCLE_HASH
from phant_tpu.types.transaction import Transaction, decode_tx
from phant_tpu.types.withdrawal import Withdrawal
from phant_tpu.utils.hexutils import (
    bytes_to_hex,
    hex_to_address,
    hex_to_bytes,
    hex_to_hash,
    hex_to_int,
    int_to_hex,
)
from phant_tpu.version import RELEASE, revision

CLIENT_CODE = "PH"  # (reference: execution_payload.zig:189)
CLIENT_NAME = "phant-tpu"


class EngineAPIError(Exception):
    pass


# ---------------------------------------------------------------------------
# DTOs (reference: execution_payload.zig:12-100)


@dataclass
class PayloadAttributes:
    timestamp: int
    random: bytes
    suggested_fee_recipient: bytes
    withdrawals: Tuple[Withdrawal, ...]
    beacon_root: Optional[bytes] = None


@dataclass
class PayloadStatusV1:
    status: str
    witness: bytes = b""
    latest_valid_hash: Optional[bytes] = None
    validation_error: Optional[str] = None

    def to_json(self) -> dict:
        return {
            "status": self.status,
            "latestValidHash": (
                bytes_to_hex(self.latest_valid_hash)
                if self.latest_valid_hash is not None
                else None
            ),
            "validationError": self.validation_error,
        }


@dataclass
class StatelessPayloadStatusV1:
    status: str
    state_root: bytes
    receipt_root: bytes
    validator_error: Optional[str] = None

    def to_json(self) -> dict:
        return {
            "status": self.status,
            "stateRoot": bytes_to_hex(self.state_root),
            "receiptsRoot": bytes_to_hex(self.receipt_root),
            "validationError": self.validator_error,
        }


@dataclass
class BlobAndProofV1:
    blob: bytes
    proof: bytes


@dataclass
class BlobsBundleV1:
    commitments: Tuple[bytes, ...] = ()
    proofs: Tuple[bytes, ...] = ()
    blobs: Tuple[bytes, ...] = ()


@dataclass
class TransitionConfigurationV1:
    terminal_total_difficulty: str
    terminal_block_hash: bytes
    terminal_block_number: int


class PayloadVersion:
    """(reference: execution_payload.zig:59-63)"""

    V1 = 1
    V2 = 2
    V3 = 3


@dataclass
class PayloadID:
    """8-byte payload id whose first byte is the version
    (reference: execution_payload.zig:65-88)."""

    inner: bytes = b"\x00" * 8

    def version(self) -> int:
        return self.inner[0]

    def string(self) -> str:
        return self.inner.hex()

    def is_version(self, versions: Sequence[int]) -> bool:
        return self.version() in versions


@dataclass
class ForkchoiceStateV1:
    head_block_hash: bytes
    safe_block_hash: bytes
    finalized_block_hash: bytes


@dataclass
class ForkChoiceResponse:
    payload_status: PayloadStatusV1
    payload_id: Optional[PayloadID] = None


@dataclass
class ExecutionPayloadBody:
    transaction_data: Tuple[bytes, ...]
    withdrawals: Tuple[Withdrawal, ...]


@dataclass
class ClientVersionV1:
    """(reference: execution_payload.zig:191-205)"""

    code: str
    name: str
    version: str
    commit: str

    def string(self) -> str:
        return f"{self.code}-{self.name}-{self.version}-{self.commit}"

    def to_json(self) -> dict:
        return {
            "code": self.code,
            "name": self.name,
            "version": self.version,
            "commit": self.commit,
        }


@dataclass
class ExecutionPayloadEnvelope:
    execution_payload: "ExecutionPayload"
    block_value: bytes
    blobs_bundle: BlobsBundleV1
    requests: Tuple[bytes, ...] = ()
    override: bool = False
    witness: bytes = b""


# ---------------------------------------------------------------------------
# ExecutionPayload (reference: execution_payload.zig:102-173)


@dataclass
class ExecutionPayload:
    parent_hash: bytes
    fee_recipient: bytes
    state_root: bytes
    receipts_root: bytes
    logs_bloom: bytes
    prev_randao: bytes
    block_number: int
    gas_limit: int
    gas_used: int
    timestamp: int
    extra_data: bytes
    base_fee_per_gas: int
    block_hash: bytes
    transactions: Tuple[Transaction, ...] = ()
    withdrawals: Optional[Tuple[Withdrawal, ...]] = None
    blob_gas_used: Optional[int] = None
    excess_blob_gas: Optional[int] = None
    # V3 (Cancun): passed beside the payload in newPayloadV3, but part of
    # the header (and thus of blockHash)
    parent_beacon_block_root: Optional[bytes] = None
    # V4 (Prague): derived from the executionRequests side channel, part
    # of the header (and thus of blockHash)
    requests_hash: Optional[bytes] = None

    def to_block(self) -> Block:
        """Build a Block, deriving tx/withdrawal MPT roots for the header
        (reference: execution_payload.zig:125-166) — the stateless hot path
        that the TPU backend batches."""
        txs_root = ordered_trie_root([tx.encode() for tx in self.transactions])
        wd_root = (
            ordered_trie_root([w.encode() for w in self.withdrawals])
            if self.withdrawals is not None
            else None
        )
        header = BlockHeader(
            parent_hash=self.parent_hash,
            uncle_hash=EMPTY_UNCLE_HASH,
            fee_recipient=self.fee_recipient,
            state_root=self.state_root,
            transactions_root=txs_root,
            receipts_root=self.receipts_root,
            logs_bloom=self.logs_bloom,
            difficulty=0,
            block_number=self.block_number,
            gas_limit=self.gas_limit,
            gas_used=self.gas_used,
            timestamp=self.timestamp,
            extra_data=self.extra_data,
            mix_hash=self.prev_randao,
            nonce=b"\x00" * 8,
            base_fee_per_gas=self.base_fee_per_gas,
            withdrawals_root=wd_root,
            blob_gas_used=self.blob_gas_used,
            excess_blob_gas=self.excess_blob_gas,
            parent_beacon_block_root=self.parent_beacon_block_root,
            requests_hash=self.requests_hash,
        )
        return Block(
            header=header,
            transactions=tuple(self.transactions),
            uncles=(),
            withdrawals=self.withdrawals,
        )


def payload_from_json(params: dict) -> ExecutionPayload:
    """Decode the hex-string JSON form of an execution payload
    (reference: AllPossibleExecutionParams.to_execution_payload,
    engine_api.zig:38-77; withdrawal support extends the reference, which
    drops the field)."""
    txs = tuple(decode_tx(hex_to_bytes(t)) for t in params.get("transactions", []))
    withdrawals: Optional[Tuple[Withdrawal, ...]] = None
    if "withdrawals" in params and params["withdrawals"] is not None:
        withdrawals = tuple(
            Withdrawal(
                index=hex_to_int(w["index"]),
                validator_index=hex_to_int(w["validatorIndex"]),
                address=hex_to_address(w["address"]),
                amount=hex_to_int(w["amount"]),
            )
            for w in params["withdrawals"]
        )
    return ExecutionPayload(
        parent_hash=hex_to_hash(params["parentHash"]),
        fee_recipient=hex_to_address(params["feeRecipient"]),
        state_root=hex_to_hash(params["stateRoot"]),
        receipts_root=hex_to_hash(params["receiptsRoot"]),
        logs_bloom=hex_to_bytes(params["logsBloom"]),
        prev_randao=hex_to_hash(params["prevRandao"]),
        block_number=hex_to_int(params["blockNumber"]),
        gas_limit=hex_to_int(params["gasLimit"]),
        gas_used=hex_to_int(params["gasUsed"]),
        timestamp=hex_to_int(params["timestamp"]),
        extra_data=hex_to_bytes(params.get("extraData", "0x")),
        base_fee_per_gas=hex_to_int(params["baseFeePerGas"]),
        block_hash=hex_to_hash(params["blockHash"]),
        transactions=txs,
        withdrawals=withdrawals,
        blob_gas_used=(
            hex_to_int(params["blobGasUsed"]) if "blobGasUsed" in params else None
        ),
        excess_blob_gas=(
            hex_to_int(params["excessBlobGas"]) if "excessBlobGas" in params else None
        ),
    )


# ---------------------------------------------------------------------------
# Handlers


def new_payload_v3_handler(
    blockchain,
    payload: ExecutionPayload,
    expected_blob_versioned_hashes,
    parent_beacon_block_root: bytes,
) -> PayloadStatusV1:
    """`engine_newPayloadV3` (Cancun; beyond the reference, whose method
    list stops at listing it, main.zig:24-54): folds the side-channel
    parentBeaconBlockRoot into the header, checks the CL's expected blob
    versioned hashes against the concatenated tx blob hashes, then runs
    the common validation path."""
    from dataclasses import replace as drep

    from phant_tpu.types.transaction import BlobTx

    if payload.blob_gas_used is None or payload.excess_blob_gas is None:
        # required V3 payload fields — a payload without them must not
        # silently execute under pre-Cancun rules
        raise ValueError(
            "engine_newPayloadV3 payload requires blobGasUsed and "
            "excessBlobGas"
        )
    payload = drep(payload, parent_beacon_block_root=parent_beacon_block_root)
    got_hashes = [
        h
        for tx in payload.transactions
        if isinstance(tx, BlobTx)
        for h in tx.blob_versioned_hashes
    ]
    if list(expected_blob_versioned_hashes) != got_hashes:
        return PayloadStatusV1(
            status="INVALID",
            validation_error="blob versioned hashes mismatch",
        )
    return new_payload_v2_handler(blockchain, payload)


def new_payload_v4_handler(
    blockchain,
    payload: ExecutionPayload,
    expected_blob_versioned_hashes,
    parent_beacon_block_root: bytes,
    execution_requests,
) -> PayloadStatusV1:
    """`engine_newPayloadV4` (Prague): validates the executionRequests
    side channel per EIP-7685's engine rules (strictly type-ascending,
    no empty request data), folds its hash into the header, then runs the
    V3 path.  run_block independently recomputes the requests from
    execution (deposit logs + 7002/7251 system calls) and rejects the
    block on mismatch."""
    from dataclasses import replace as drep

    from phant_tpu.blockchain.requests import compute_requests_hash

    items = []
    prev_type = -1
    for raw in execution_requests:
        item = hex_to_bytes(raw)
        if len(item) < 2:
            return PayloadStatusV1(
                status="INVALID",
                validation_error="executionRequests item without data",
            )
        if item[0] <= prev_type:
            return PayloadStatusV1(
                status="INVALID",
                validation_error="executionRequests not strictly type-ascending",
            )
        prev_type = item[0]
        items.append(item)
    payload = drep(payload, requests_hash=compute_requests_hash(items))
    return new_payload_v3_handler(
        blockchain, payload, expected_blob_versioned_hashes, parent_beacon_block_root
    )


def new_payload_v2_handler(blockchain, payload: ExecutionPayload) -> PayloadStatusV1:
    """(reference: execution_payload.zig:175-182, which returns void; the
    JSON-RPC layer here reports VALID/INVALID per the Engine API spec,
    including the blockHash == keccak(rlp(header)) check the reference
    skips). An INVALID payload must leave no trace, so partial execution
    rolls back (same contract as the spec runner)."""
    from phant_tpu.blockchain.chain import BlockError

    block = payload.to_block()
    computed_hash = block.header.hash()
    if computed_hash != payload.block_hash:
        return PayloadStatusV1(
            status="INVALID",
            validation_error=(
                f"blockHash mismatch: payload {payload.block_hash.hex()}, "
                f"computed {computed_hash.hex()}"
            ),
        )
    try:
        # run_block journals + rolls back internally on failure; the tx /
        # withdrawal roots were derived by to_block one call earlier, so
        # skip re-deriving them
        blockchain.run_block(block, check_body_roots=False)
    except BlockError as e:
        return PayloadStatusV1(status="INVALID", validation_error=str(e))
    return PayloadStatusV1(status="VALID", latest_valid_hash=computed_hash)


def execute_stateless_payload_v1_handler(
    blockchain, payload: ExecutionPayload, witness_json: dict
) -> StatelessPayloadStatusV1:
    """`engine_executeStatelessPayloadV1`: execute the payload against ONLY
    its witness — linked multiproof verification (the TPU-batched flagship
    kernel when `--crypto_backend=tpu`), lazy witness-backed state, full
    block execution, and post-state-root recompute over the partial trie
    (phant_tpu/stateless.py). The reference lists this method but never
    implements it (reference: src/main.zig:24-54 vs main.zig:58-70).

    witness_json: {"headers": ["0x<parent header rlp>", ...],
    "state": ["0x<node rlp>", ...], "codes": ["0x<bytecode>", ...],
    "preStateRoot": "0x.." (optional — defaults to the parent header's
    stateRoot)} — the geth-style stateless witness shape. When a parent
    header is shipped in the witness, the payload executes against IT, not
    against the node's resident head: a stateless call must be able to
    verify a non-head block.
    """
    from phant_tpu import rlp
    from phant_tpu.blockchain.chain import BlockError
    from phant_tpu.stateless import StatelessError, execute_stateless

    zero = b"\x00" * 32
    block = payload.to_block()
    computed_hash = block.header.hash()
    if computed_hash != payload.block_hash:
        return StatelessPayloadStatusV1(
            status="INVALID",
            state_root=zero,
            receipt_root=zero,
            validator_error=(
                f"blockHash mismatch: payload {payload.block_hash.hex()}, "
                f"computed {computed_hash.hex()}"
            ),
        )
    try:
        headers = witness_json.get("headers") or []
        ancestors = []
        if headers:
            try:
                ancestors = [
                    BlockHeader.from_rlp_list(rlp.decode(hex_to_bytes(h)))
                    for h in headers
                ]
            except (rlp.DecodeError, ValueError, KeyError, IndexError) as e:
                # a malformed witness is an INVALID payload status, not a
                # JSON-RPC protocol error — callers branch on result.status
                return StatelessPayloadStatusV1(
                    status="INVALID",
                    state_root=zero,
                    receipt_root=zero,
                    validator_error=f"witness header does not decode: {e}",
                )
            parent = ancestors[0]
            if parent.hash() != block.header.parent_hash:
                return StatelessPayloadStatusV1(
                    status="INVALID",
                    state_root=zero,
                    receipt_root=zero,
                    validator_error="witness parent header does not match payload parentHash",
                )
            # authenticate the whole ancestor chain: header i+1 must be the
            # parent of header i, anchoring every hash to the verified
            # parent — an unlinked header could inject a forged BLOCKHASH
            # (reference behavior being mirrored: the Frontier 256-ancestor
            # ring, src/blockchain/forks/frontier.zig:29-58)
            for i in range(len(ancestors) - 1):
                if ancestors[i].parent_hash != ancestors[i + 1].hash():
                    return StatelessPayloadStatusV1(
                        status="INVALID",
                        state_root=zero,
                        receipt_root=zero,
                        validator_error=(
                            f"witness header {i + 1} does not chain to header {i}"
                        ),
                    )
        else:
            parent = blockchain.parent_header
        try:
            if "preStateRoot" in witness_json:
                pre_root = hex_to_hash(witness_json["preStateRoot"])
            else:
                pre_root = parent.state_root
            nodes = [hex_to_bytes(n) for n in witness_json.get("state", [])]
            codes = [hex_to_bytes(c) for c in witness_json.get("codes", [])]
        except (ValueError, TypeError, AttributeError) as e:
            # same contract as malformed headers: a bad witness is an
            # INVALID payload status, not a JSON-RPC protocol error
            # (AttributeError: non-string JSON entries hit str methods)
            return StatelessPayloadStatusV1(
                status="INVALID",
                state_root=zero,
                receipt_root=zero,
                validator_error=f"witness does not decode: {e}",
            )
        # fork selection mirrors the node's own (fork_for over the chain
        # config), but the instance binds to the STATELESS state: the node's
        # resident fork may be bound to its resident StateDB (PragueFork
        # writes EIP-2935 slots), and a stateless run must not touch
        # resident state. Frontier-family forks are preloaded with the
        # authenticated ancestor hashes (BLOCKHASH at depth <= 256 serves
        # witness headers; deeper reads return zero — the EVM enforces the
        # window). Prague-family forks read/write history through the
        # witnessed EIP-2935 contract storage instead, so the history write
        # lands in the recomputed post root exactly as in full execution.
        from phant_tpu.blockchain.fork import FrontierFork, fork_for

        config = getattr(blockchain, "config", None)

        def fork_factory(state):
            if config is not None:
                fork = fork_for(
                    config, state, block.header.block_number, block.header.timestamp
                )
            else:
                fork = FrontierFork()
            if isinstance(fork, FrontierFork):
                for h in ancestors[:256]:
                    fork.update_parent_block_hash(h.block_number, h.hash())
            return fork

        _result, post_root = execute_stateless(
            blockchain.chain_id,
            parent,
            block,
            pre_root,
            nodes,
            codes,
            fork_factory=fork_factory,
        )
    except (StatelessError, BlockError) as e:
        return StatelessPayloadStatusV1(
            status="INVALID",
            state_root=zero,
            receipt_root=zero,
            validator_error=str(e),
        )
    return StatelessPayloadStatusV1(
        status="VALID",
        state_root=post_root,
        receipt_root=block.header.receipts_root,
    )


def get_client_version_v1_handler() -> ClientVersionV1:
    """(reference: execution_payload.zig:206-213)"""
    return ClientVersionV1(
        code=CLIENT_CODE, name=CLIENT_NAME, version=RELEASE, commit=revision()
    )


# The full supported-method list (reference: main.zig:24-54). The starred
# methods have real handlers — the reference implements two (main.zig:58-70);
# executeStatelessPayloadV1 is implemented beyond it. The rest return a
# JSON-RPC error (reference replies HTTP 500, main.zig:72).
SUPPORTED_METHODS = (
    "engine_forkchoiceUpdatedV1",
    "engine_forkchoiceUpdatedV2",
    "engine_forkchoiceUpdatedV3",
    "engine_forkchoiceUpdatedWithWitnessV1",
    "engine_forkchoiceUpdatedWithWitnessV2",
    "engine_forkchoiceUpdatedWithWitnessV3",
    "engine_exchangeTransitionConfigurationV1",
    "engine_getPayloadV1",
    "engine_getPayloadV2",
    "engine_getPayloadV3",
    "engine_getPayloadV4",
    "engine_getBlobsV1",
    "engine_newPayloadV1",
    "engine_newPayloadV2",  # * implemented
    "engine_newPayloadV3",  # * implemented (Cancun; beyond reference)
    "engine_newPayloadV4",  # * implemented (Prague; beyond reference)
    "engine_newPayloadWithWitnessV1",
    "engine_newPayloadWithWitnessV2",
    "engine_newPayloadWithWitnessV3",
    "engine_newPayloadWithWitnessV4",
    "engine_executeStatelessPayloadV1",  # * implemented (beyond reference)
    "engine_executeStatelessPayloadV2",
    "engine_executeStatelessPayloadV3",
    "engine_executeStatelessPayloadV4",
    "engine_getPayloadBodiesByHashV1",
    "engine_getPayloadBodiesByHashV2",
    "engine_getPayloadBodiesByRangeV1",
    "engine_getPayloadBodiesByRangeV2",
    "engine_getClientVersionV1",  # * implemented
    "phant_witnessEngineStats",  # * implemented (framework observability)
)


#: JSON-RPC error for a versioned newPayload whose timestamp falls outside
#: the method's fork window (Engine API spec "Unsupported fork" rule)
UNSUPPORTED_FORK_CODE = -38005


def _unsupported_fork(blockchain, timestamp: int, version: int) -> bool:
    """Engine API fork-timestamp rule: newPayloadV3 serves exactly the
    Cancun window, V4 exactly Prague — a payload timestamp outside the
    method's window must return -38005 rather than execute under the
    wrong rules. Only a chain config can place the fork boundaries;
    config-less fixture chains skip the check (their tests drive any
    version against any payload)."""
    config = getattr(blockchain, "config", None)
    if config is None:
        return False
    cancun = getattr(config, "cancunTime", None)
    prague = getattr(config, "pragueTime", None)
    osaka = getattr(config, "osakaTime", None)
    if version == 3:
        if cancun is None or timestamp < cancun:
            return True
        return prague is not None and timestamp >= prague
    if version == 4:
        if prague is None or timestamp < prague:
            return True
        return osaka is not None and timestamp >= osaka
    return False


def handle_request(blockchain, request: dict) -> Tuple[int, dict]:
    """Dispatch one JSON-RPC request; returns (http_status, response_body)
    (reference: engineAPIHandler, main.zig:56-74)."""
    from phant_tpu.serving import SchedulerError
    from phant_tpu.utils.trace import metrics

    req_id = request.get("id")
    method = request.get("method", "")
    base = {"jsonrpc": "2.0", "id": req_id}
    # bound counter cardinality: untrusted method strings share one bucket,
    # known methods label one shared family (one help string, one dashboard
    # query over `method`)
    if method in SUPPORTED_METHODS:
        metrics.count("engine_api.requests", method=method)
    else:
        metrics.count("engine_api.unknown_method")
    try:
        if method == "engine_newPayloadV2":
            with metrics.phase("engine_api.decode_payload"):
                payload = payload_from_json(request["params"][0])
            with metrics.phase("engine_api.new_payload"):
                status = new_payload_v2_handler(blockchain, payload)
            return 200, {**base, "result": status.to_json()}
        if method == "engine_newPayloadV3":
            with metrics.phase("engine_api.decode_payload"):
                payload = payload_from_json(request["params"][0])
                expected_hashes = [
                    hex_to_hash(h) for h in request["params"][1]
                ]
                beacon_root = hex_to_hash(request["params"][2])
            if _unsupported_fork(blockchain, payload.timestamp, version=3):
                return 200, {
                    **base,
                    "error": {
                        "code": UNSUPPORTED_FORK_CODE,
                        "message": "Unsupported fork",
                    },
                }
            with metrics.phase("engine_api.new_payload"):
                status = new_payload_v3_handler(
                    blockchain, payload, expected_hashes, beacon_root
                )
            return 200, {**base, "result": status.to_json()}
        if method == "engine_newPayloadV4":
            with metrics.phase("engine_api.decode_payload"):
                payload = payload_from_json(request["params"][0])
                expected_hashes = [
                    hex_to_hash(h) for h in request["params"][1]
                ]
                beacon_root = hex_to_hash(request["params"][2])
                execution_requests = request["params"][3]
            if _unsupported_fork(blockchain, payload.timestamp, version=4):
                return 200, {
                    **base,
                    "error": {
                        "code": UNSUPPORTED_FORK_CODE,
                        "message": "Unsupported fork",
                    },
                }
            with metrics.phase("engine_api.new_payload"):
                status = new_payload_v4_handler(
                    blockchain,
                    payload,
                    expected_hashes,
                    beacon_root,
                    execution_requests,
                )
            return 200, {**base, "result": status.to_json()}
        if method == "engine_executeStatelessPayloadV1":
            with metrics.phase("engine_api.decode_payload"):
                payload = payload_from_json(request["params"][0])
                witness_json = request["params"][1]
            with metrics.phase("engine_api.execute_stateless"):
                sstatus = execute_stateless_payload_v1_handler(
                    blockchain, payload, witness_json
                )
            return 200, {**base, "result": sstatus.to_json()}
        if method == "engine_getClientVersionV1":
            ver = get_client_version_v1_handler()
            return 200, {**base, "result": [ver.to_json()]}
        if method == "phant_witnessEngineStats":
            # framework observability (no reference analog): the memoized
            # witness engine's cache effectiveness for the serving path
            from phant_tpu.stateless import shared_witness_engine

            return 200, {
                **base,
                "result": shared_witness_engine().stats_snapshot(),
            }
    except SchedulerError:
        # scheduler overload/deadline/down is a transport-level condition,
        # not bad params — the HTTP layer maps it to its distinct JSON-RPC
        # code and a 503 (engine_api/server.py)
        raise
    except Exception as e:  # malformed params etc.
        return 200, {**base, "error": {"code": -32602, "message": str(e)}}
    # unimplemented-but-known vs unknown (reference: res.status=500 main.zig:72)
    if method in SUPPORTED_METHODS:
        return 500, {
            **base,
            "error": {"code": -32601, "message": f"{method} not implemented"},
        }
    return 200, {**base, "error": {"code": -32601, "message": "method not found"}}
