"""Threaded HTTP JSON-RPC server for the Engine API.

Equivalent surface to the reference's httpz server wiring (reference:
src/main.zig:143-149: POST / routed to engineAPIHandler with the
*Blockchain as per-request context). Uses the stdlib ThreadingHTTPServer.

Request execution goes through the continuous-batching scheduler
(phant_tpu/serving/) instead of the old global execution lock:

* state-mutating methods (`engine_newPayload*`, `engine_forkchoiceUpdated*`)
  run as SERIAL jobs on the scheduler's single executor thread — mutation
  stays exclusive (the reference is effectively serial there too) without
  a mutex held across the whole request;
* `engine_executeStatelessPayloadV1` runs CONCURRENTLY on the handler
  threads (stateless execution shares nothing), and its witness
  verification coalesces with other in-flight requests into one
  engine/device `verify_batch` dispatch via the scheduler's batch
  assembler (stateless.verify_witness_nodes) — with `--sched-mesh N`
  those dispatches fan out over N device-pinned executors
  (serving/mesh_exec.py), and `/healthz` carries the per-device lane
  state under `scheduler.mesh` (any dead lane turns the probe 503
  exactly like a dead executor: routed batches would never complete);
* scheduler rejections map to distinct JSON-RPC errors: queue full /
  tenant quota / evicted -32050, deadline expired -32051, executor down
  -32052 — all HTTP 503, counted under `sched.rejected{reason=,tenant=}`;
* multi-tenant QoS (phant_tpu/serving/qos.py): `X-Phant-Tenant` names the
  per-client admission lane (quota + weighted fair dequeue) and
  `X-Phant-Priority: head` marks head-of-chain work that preempts
  backfill — state-mutating methods are always head class;
* slow-loris tolerance: every accepted connection carries a socket
  read/write deadline (PHANT_HTTP_TIMEOUT_S, default 30s) so a client
  that stalls mid-headers, mid-body, or mid-read frees the handler
  thread; the stall is counted in `engine_api.client_disconnects`.

Observability surface: `GET /metrics` serves the process metrics registry
as Prometheus text exposition (histogram families additionally carry
derived bucket-interpolated p50/p99 gauges), `GET /healthz` a JSON
liveness probe that includes the scheduler state (queue depth, executor
liveness, per-lane `device_busy_pct`) and turns 503 when the executor has
died; `GET /debug/flight` serves the obs flight recorder's ring (recent
spans / errors / scheduler transitions) live, `GET /debug/slow` the
SLO-exemplar ring (obs/critpath.py — full span trees of requests that
blew `--slo-budget-ms`), `GET /debug/timeline?window=S` the unified
tail-sampled timeline as Perfetto-loadable Chrome-trace JSON
(obs/timeline.py — requests, lane batches, device busy windows on one
time axis), `POST /debug/profile?seconds=T` grabs an
on-demand, single-flight-guarded `jax_profile` capture into
`--profile-dir` (obs/profiler.py), and the first `/healthz` flip to 503
auto-dumps the flight ring to `build/flight/` (phant_tpu/obs/). Every POST runs inside its own trace
context — the `trace_id` rides the scheduler jobs and span records the
request creates, and is echoed back in the `X-Phant-Trace` response
header — and is counted, latency-histogrammed, and gauge-tracked in
flight (phant_tpu/utils/trace.py). `serve_metrics()` runs the same GET
endpoints standalone for `--metrics-port` deployments where the Engine API
port is CL-only."""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from phant_tpu.engine_api import handle_request
from phant_tpu.obs import critpath, flight, profiler, timeline
from phant_tpu.obs.flight import refresh_from_env as _refresh_flight_ring
from phant_tpu.serving import (
    PRIORITY_BACKFILL,
    PRIORITY_HEAD,
    SchedulerConfig,
    SchedulerError,
    VerificationScheduler,
    active_scheduler,
    current_priority,
    current_tenant,
    install,
    sanitize_tenant,
    tenant_context,
    uninstall,
)
from phant_tpu.utils.trace import (
    REQUEST_SECONDS_BUCKETS,
    current_trace_id,
    metrics,
    trace_context,
)

log = logging.getLogger("phant_tpu.engine_api")

_START_MONOTONIC = time.monotonic()

#: methods that mutate Blockchain state and therefore run as serial jobs
#: on the scheduler's executor (everything else is read-only or stateless
#: and runs concurrently on the handler threads)
_SERIAL_METHOD_PREFIXES = ("engine_newPayload", "engine_forkchoiceUpdated")


def _http_timeout() -> float:
    """Socket read/write deadline per accepted connection
    (PHANT_HTTP_TIMEOUT_S, default 30; <=0 disables). A client that sends
    headers and then stalls — the slow-loris shape scripts/loadgen.py
    deliberately produces — must not pin a handler thread forever: the
    deadline frees the thread and the stall is counted in
    `engine_api.client_disconnects`. Read per connection so tests and the
    load harness can tighten it without rebinding the server."""
    return float(os.environ.get("PHANT_HTTP_TIMEOUT_S", "30"))


class _StatelessGate:
    """Bounded concurrency for `engine_executeStatelessPayloadV1`.

    The scheduler bounds QUEUED witness verifications, but the rest of a
    stateless execution (witness decode, EVM re-execution, root check)
    runs on the handler thread — and ThreadingHTTPServer spawns one per
    connection, so under open-loop overload the box accumulates hundreds
    of half-done executions that thrash each other into multi-second p99s
    while every one of them eventually "succeeds" (loadgen measured
    exactly this before the gate existed). Graceful degradation means
    refusing work the box cannot finish promptly: at most `limit`
    stateless executions run at once; a request that cannot get a slot
    within its class's patience sheds with the standard overload code
    (-32050, `sched.rejected{reason=saturated, tenant=...}`).

    Patience is the priority lever: backfill waits ~PHANT_HTTP_GATE_PATIENCE_S
    (default 0.5s — overload must shed fast, not stack), head-of-chain
    (`X-Phant-Priority: head`) waits 8x that before giving up. The serial
    mutation lane never passes through this gate at all (shed order:
    backfill first, never mutations)."""

    def __init__(self, limit: int, patience_s: float):
        self._sem = threading.Semaphore(limit) if limit > 0 else None
        self.limit = limit
        self.patience_s = patience_s

    def acquire(self, head: bool) -> bool:
        if self._sem is None:
            return True
        patience = self.patience_s * (8.0 if head else 1.0)
        return self._sem.acquire(timeout=patience)

    def release(self) -> None:
        if self._sem is not None:
            self._sem.release()


def _default_gate() -> _StatelessGate:
    limit = int(
        os.environ.get(
            "PHANT_HTTP_MAX_CONCURRENT", str(max(8, 4 * (os.cpu_count() or 2)))
        )
    )
    patience = float(os.environ.get("PHANT_HTTP_GATE_PATIENCE_S", "0.5"))
    return _StatelessGate(limit, patience)


#: the scheduler instance whose death already triggered a healthz-503 dump
#: (flip detection is per SCHEDULER, not per process: a later server's own
#: first 503 must still dump, and healthy scrapes clear the latch)
_healthz_dumped_for = None
_healthz_lock = threading.Lock()


def _healthz_payload() -> tuple:
    """(http_status, payload): liveness plus scheduler state. A dead
    scheduler executor means the node can no longer execute payloads, so
    the probe reports 503 — orchestrators must restart, not route — and
    the FIRST flip to 503 dumps the flight ring (the postmortem the
    restart would otherwise destroy)."""
    from phant_tpu.version import RELEASE, revision

    global _healthz_dumped_for
    from phant_tpu.commitment import active_scheme

    payload = {
        "status": "ok",
        "version": RELEASE,
        "revision": revision(),
        "uptime_s": round(time.monotonic() - _START_MONOTONIC, 1),
        # how state is committed on this node (--commitment): a CL pairing
        # with the wrong scheme sees every payload rejected on its state
        # root, so the probe names the scheme explicitly
        "commitment": active_scheme().name,
    }
    status = 200
    sched = active_scheduler()
    if sched is not None:
        st = sched.state()
        payload["scheduler"] = st
        if not st["executor_alive"]:
            payload["status"] = "unhealthy"
            status = 503
    # every debug-ring capacity in one place (the --flight-ring /
    # --timeline-* config surfaces echo back what actually took effect)
    payload["debug_rings"] = {
        "flight": flight.ring_capacity(),
        "slow": critpath.slow.capacity,
        "timeline": timeline.capacity(),
    }
    with _healthz_lock:
        if status == 503:
            flipped = sched is not _healthz_dumped_for
            _healthz_dumped_for = sched
        else:
            flipped = False
            _healthz_dumped_for = None
    if flipped:
        flight.dump("healthz_503")
    return status, payload


class _HTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a real listen backlog. The stdlib default
    (request_queue_size=5) turns overload into multi-second connect waits
    in the KERNEL accept queue — an invisible, unshed, unmeasured queue in
    front of all the admission control this package builds. A deep backlog
    moves the excess onto handler threads where the stateless gate and the
    scheduler shed it with explicit -32050s within their patience window."""

    request_queue_size = 256


class _ObservableHandler(BaseHTTPRequestHandler):
    """Shared GET surface + disconnect-tolerant reply plumbing."""

    protocol_version = "HTTP/1.1"

    def setup(self) -> None:
        # socket read/write deadline BEFORE any rfile read: a stalled
        # client (slow-loris headers, never-arriving body, wedged reader)
        # raises TimeoutError out of the blocked call instead of pinning
        # this handler thread for the life of the process. The stdlib's
        # handle_one_request already closes the connection on that
        # TimeoutError; the do_POST body read counts it first.
        t = _http_timeout()
        self.timeout = t if t > 0 else None
        super().setup()

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            # re-integrate the device-busy windows to NOW before
            # rendering: the gauges otherwise move only on batch
            # transitions, and a metrics-only scraper would read an idle
            # lane frozen at its last mid-traffic value forever
            sched = active_scheduler()
            if sched is not None:
                sched.refresh_busy_gauges()
            self._reply_raw(
                200,
                metrics.prometheus_text().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/healthz":
            status, payload = _healthz_payload()
            self._reply(status, payload)
        elif path == "/debug/flight":
            # the live flight ring: what a postmortem dump would contain,
            # readable from a still-running server (default=str: span attrs
            # are caller-provided and may not all be JSON-native)
            self._reply_raw(
                200,
                json.dumps(flight.snapshot(), default=str).encode(),
                "application/json",
            )
        elif path == "/debug/timeline":
            # the unified timeline (obs/timeline.py): the last `window`
            # seconds of kept requests, lane batches, device busy
            # windows, and profiler captures as Perfetto-loadable
            # Chrome-trace JSON — curl it straight into ui.perfetto.dev
            query = self.path.partition("?")[2]
            params = dict(
                p.split("=", 1) for p in query.split("&") if "=" in p
            )
            try:
                window = float(params.get("window", "60"))
            except ValueError:
                window = float("nan")
            if not math.isfinite(window) or window <= 0:
                self._reply(
                    400,
                    {"error": "window must be a positive number of seconds"},
                )
            else:
                self._reply_raw(
                    200,
                    json.dumps(timeline.export(window), default=str).encode(),
                    "application/json",
                )
        elif path == "/debug/slow":
            # SLO-busting exemplars (obs/critpath.py): full span trees +
            # critical-path breakdowns of every request that blew
            # --slo-budget-ms (or a per-phase env budget) — the metric
            # says THAT it was slow, this ring says WHY
            self._reply_raw(
                200,
                json.dumps(
                    {
                        "capacity": critpath.slow.capacity,
                        "budget_ms": critpath.budget_ms(),
                        "records": critpath.slow.records(),
                    },
                    default=str,
                ).encode(),
                "application/json",
            )
        else:
            self._reply(404, {"error": "not found"})

    def do_POST(self) -> None:  # noqa: N802 (stdlib API)
        # the standalone metrics server accepts only the debug POSTs; the
        # Engine API handler overrides do_POST and routes /debug/* here
        self._do_debug_post()

    def _do_debug_post(self) -> None:
        """POST /debug/profile?seconds=T — on-demand profiler capture
        (obs/profiler.py): single-flight (503 on overlap), hard-capped
        window, artifacts on disk before the 200 lands."""
        # drain any request body FIRST: these are keep-alive (HTTP/1.1)
        # connections, and unread body bytes would desync the next
        # request on the same socket into a garbage request line
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length:
            try:
                self.rfile.read(length)
            except TimeoutError:
                metrics.count("engine_api.client_disconnects")
                self.close_connection = True
                return
        path, _, query = self.path.partition("?")
        if path != "/debug/profile":
            self._reply(404, {"error": "not found"})
            return
        params = dict(
            p.split("=", 1) for p in query.split("&") if "=" in p
        )
        try:
            seconds = float(params.get("seconds", "5"))
        except ValueError:
            seconds = float("nan")
        try:
            out = profiler.capture(seconds)
        except ValueError as e:
            self._reply(400, {"error": str(e)})
        except profiler.ProfileBusy as e:
            # one trace per process: overlap is operator error, shed it
            self._reply(503, {"error": str(e)})
        except profiler.ProfileError as e:
            self._reply(500, {"error": str(e)})
        else:
            self._reply(200, out)

    def _reply(self, status: int, payload: dict) -> None:
        self._reply_raw(status, json.dumps(payload).encode(), "application/json")

    def _reply_raw(self, status: int, raw: bytes, content_type: str) -> None:
        # a client that hangs up mid-response (CL restart, curl ^C) raises
        # here and would otherwise kill the handler thread silently — count
        # it and keep serving
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(raw)))
            tid = current_trace_id()
            if tid is not None:
                # the request's identity, joinable against span records,
                # flight events, and the batch that served it
                self.send_header("X-Phant-Trace", tid)
            self.end_headers()
            self.wfile.write(raw)
        except (BrokenPipeError, ConnectionResetError, TimeoutError) as e:
            # TimeoutError: a client that stopped READING (full TCP buffer)
            # is the write-side slow-loris; the socket deadline frees the
            # thread and the disconnect counter covers both directions
            metrics.count("engine_api.client_disconnects")
            log.debug("client disconnected mid-reply: %r", e)
            # stop the keep-alive loop: reading the dead socket again would
            # raise out of handle_one_request and traceback to stderr
            self.close_connection = True

    def log_message(self, fmt, *args):  # route to logging, not stderr
        log.debug(fmt, *args)


class EngineAPIServer:
    """HTTP server bound to a Blockchain (reference: main.zig:143-149).

    Owns a `VerificationScheduler` (phant_tpu/serving/): construction
    installs it as the process's active scheduler (so
    stateless.verify_witness_nodes and `/healthz` see it) and shutdown
    drains + uninstalls it. Pass `scheduler=` to share one across
    servers — then the CALLER owns its lifecycle (shutdown here only
    undoes this server's install, never drains a shared scheduler out
    from under its other users) — or `sched_config=` to size the
    queue/batch policy (the `--sched-*` CLI flags,
    phant_tpu/__main__.py)."""

    def __init__(
        self,
        blockchain,
        host: str = "127.0.0.1",
        port: int = 8551,
        scheduler: VerificationScheduler = None,
        sched_config: SchedulerConfig = None,
    ):
        self.blockchain = blockchain
        # re-resolve the obs layers' memoized configs NOW: the CLI writes
        # --slo-budget-ms / --profile-dir / --timeline-* / --flight-ring
        # into the env before constructing the server, and tests
        # monkeypatch the same keys (obs/critpath.py documents why the
        # config is not re-read per request/event)
        critpath.refresh_from_env()
        timeline.refresh_from_env()
        _refresh_flight_ring()
        self._owns_scheduler = scheduler is None
        if scheduler is None:
            scheduler = VerificationScheduler(config=sched_config)
        self.scheduler = scheduler
        # graceful-degradation valve for stateless execution (env-sized at
        # construction: PHANT_HTTP_MAX_CONCURRENT / PHANT_HTTP_GATE_PATIENCE_S)
        self._gate = _default_gate()
        outer = self

        class Handler(_ObservableHandler):
            def do_POST(self) -> None:  # noqa: N802 (stdlib API)
                if self.path.split("?", 1)[0].startswith("/debug/"):
                    # debug surface (profiler capture): not a JSON-RPC
                    # request — skip the Engine API accounting so the
                    # front-door latency histogram measures only traffic
                    return self._do_debug_post()
                t0 = time.perf_counter()
                # Lock-discipline audit (phantlint LOCK, PR 2): the
                # counter / in-flight gauge / latency-histogram updates
                # here deliberately run on the handler thread with no
                # exclusion — the registry has its own internal lock
                # (trace.Metrics._lock), and serializing observability
                # writes would serialize the very concurrency the
                # in-flight gauge measures. phantlint's LOCK rule scopes
                # to the lock-owning object's own attributes, so it
                # (correctly) reports nothing here — this comment, not a
                # disable annotation, is the audit record.
                metrics.gauge_add("engine_api.inflight", 1)
                try:
                    # one trace context per request: the trace_id rides
                    # every span this thread opens and every scheduler job
                    # it submits, and comes back in X-Phant-Trace. The
                    # tenant context (QoS lane + priority class,
                    # serving/qos.py) rides the same thread-local channel:
                    # X-Phant-Tenant names the admission lane (sanitized —
                    # the header is attacker-controlled) and
                    # X-Phant-Priority: head marks head-of-chain work
                    # (state-mutating methods are always head class via
                    # the serial lane, so the header only matters for
                    # executeStateless).
                    tenant = sanitize_tenant(
                        self.headers.get("X-Phant-Tenant")
                    )
                    priority = (
                        PRIORITY_HEAD
                        if self.headers.get("X-Phant-Priority", "").lower()
                        == "head"
                        else PRIORITY_BACKFILL
                    )
                    with trace_context(), tenant_context(tenant, priority):
                        self._handle_post()
                finally:
                    metrics.gauge_add("engine_api.inflight", -1)
                    # the front-door latency histogram rides THE shared
                    # bucket table (trace.REQUEST_SECONDS_BUCKETS): buckets
                    # freeze at first observation, so a second call site
                    # with its own tuple would silently split the family —
                    # and the derived p50/p99 gauges (prometheus_text)
                    # need the overload tail the shared table carries
                    metrics.observe_hist(
                        "engine_api.request_seconds",
                        time.perf_counter() - t0,
                        buckets=REQUEST_SECONDS_BUCKETS,
                    )

            def _handle_post(self) -> None:
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = self.rfile.read(length)
                except TimeoutError:
                    # slow-loris: headers arrived, the promised body never
                    # did — the socket deadline freed this thread. Count
                    # it with the other client disconnects and drop the
                    # connection (a reply would race the dead read state).
                    metrics.count("engine_api.client_disconnects")
                    log.debug("client stalled mid-body; connection dropped")
                    self.close_connection = True
                    return
                try:
                    request = json.loads(body)
                except json.JSONDecodeError:
                    metrics.count("engine_api.request_errors")
                    self._reply(400, {"error": {"code": -32700, "message": "parse error"}})
                    return
                if not isinstance(request, dict):
                    # batch requests and non-object bodies are not supported
                    metrics.count("engine_api.request_errors")
                    self._reply(
                        400,
                        {
                            "jsonrpc": "2.0",
                            "id": None,
                            "error": {"code": -32600, "message": "invalid request"},
                        },
                    )
                    return
                method = request.get("method", "")
                try:
                    if isinstance(method, str) and method.startswith(
                        _SERIAL_METHOD_PREFIXES
                    ):
                        # state-mutating: exclusive execution on the
                        # scheduler's single executor thread (the global
                        # lock's replacement — admission-ordered, drained
                        # on shutdown, fails fast on executor death)
                        status, response = outer.scheduler.submit_serial(
                            lambda: handle_request(outer.blockchain, request)
                        ).result()
                    elif isinstance(method, str) and method.startswith(
                        "engine_executeStateless"
                    ):
                        # concurrently on THIS handler thread, but behind
                        # the bounded-concurrency gate: under overload the
                        # box must shed backfill fast (head-of-chain gets
                        # 8x the patience) instead of thrashing hundreds
                        # of half-done EVM re-executions
                        tenant = current_tenant()
                        if not outer._gate.acquire(
                            current_priority() == PRIORITY_HEAD
                        ):
                            metrics.count(
                                "sched.rejected",
                                reason="saturated",
                                tenant=tenant,
                            )
                            flight.record(
                                "sched.shed",
                                reason="saturated",
                                lane="stateless",
                                tenant=tenant,
                            )
                            metrics.count("engine_api.request_errors")
                            self._reply(
                                503,
                                {
                                    "jsonrpc": "2.0",
                                    "id": request.get("id"),
                                    "error": {
                                        "code": -32050,
                                        "message": "node saturated: "
                                        "stateless execution shed",
                                    },
                                },
                            )
                            return
                        try:
                            status, response = handle_request(
                                outer.blockchain, request
                            )
                        finally:
                            outer._gate.release()
                    else:
                        # read-only: run concurrently on THIS handler
                        # thread; any witness verification inside
                        # coalesces via the scheduler's batch assembler
                        status, response = handle_request(
                            outer.blockchain, request
                        )
                except SchedulerError as e:
                    # overload / deadline / executor-down: distinct
                    # JSON-RPC codes (-32050/-32051/-32052) over HTTP 503
                    metrics.count("engine_api.request_errors")
                    self._reply(
                        e.http_status,
                        {
                            "jsonrpc": "2.0",
                            "id": request.get("id"),
                            "error": {"code": e.code, "message": str(e)},
                        },
                    )
                    return
                if status >= 400 or "error" in response:
                    metrics.count("engine_api.request_errors")
                self._reply(status, response)

        try:
            self._server = _HTTPServer((host, port), Handler)
        except BaseException:
            # a bind failure must not leak the executor thread this
            # constructor just spawned (nobody else holds a reference)
            if self._owns_scheduler:
                scheduler.shutdown(drain=False)
            raise
        # install only after the socket bound: a bind failure must not
        # leak a process-globally installed scheduler
        install(scheduler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def serve_forever(self) -> None:
        log.info("Engine API listening on :%d", self.port)
        self._server.serve_forever()

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        """Graceful: stop accepting connections, then drain the scheduler
        (queued serial/witness jobs complete so in-flight handlers get
        real answers), then release the socket and the scheduler slot.
        A caller-provided (shared) scheduler is NOT drained — only this
        server's install is undone; its lifecycle belongs to the caller."""
        self._server.shutdown()
        try:
            if self._owns_scheduler:
                self.scheduler.shutdown(drain=True)
        finally:
            uninstall(self.scheduler)
            self._server.server_close()


class MetricsServer:
    """Standalone `/metrics` + `/healthz` server (`--metrics-port`): the
    Engine API port is a localhost CL-trust interface, while scrapers may
    live elsewhere — a separate bind keeps the two audiences separable."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9465):
        self._server = _HTTPServer((host, port), _ObservableHandler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def serve_metrics(host: str = "127.0.0.1", port: int = 9465) -> MetricsServer:
    """Start the standalone metrics server in a daemon thread."""
    srv = MetricsServer(host, port)
    srv.serve_in_background()
    log.info("metrics listening on %s:%d", host, srv.port)
    return srv
