"""Threaded HTTP JSON-RPC server for the Engine API.

Equivalent surface to the reference's httpz server wiring (reference:
src/main.zig:143-149: POST / routed to engineAPIHandler with the
*Blockchain as per-request context). Uses the stdlib ThreadingHTTPServer —
the handler holds a lock around block execution because `Blockchain`
mutates shared state (the reference is effectively serial there too).

Observability surface: `GET /metrics` serves the process metrics registry
as Prometheus text exposition, `GET /healthz` a JSON liveness probe;
every POST is counted, latency-histogrammed, and gauge-tracked in flight
(phant_tpu/utils/trace.py). `serve_metrics()` runs the same two GET
endpoints standalone for `--metrics-port` deployments where the Engine API
port is CL-only."""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from phant_tpu.engine_api import handle_request
from phant_tpu.utils.trace import metrics

log = logging.getLogger("phant_tpu.engine_api")

_START_MONOTONIC = time.monotonic()


def _healthz_payload() -> dict:
    from phant_tpu.version import RELEASE, revision

    return {
        "status": "ok",
        "version": RELEASE,
        "revision": revision(),
        "uptime_s": round(time.monotonic() - _START_MONOTONIC, 1),
    }


class _ObservableHandler(BaseHTTPRequestHandler):
    """Shared GET surface + disconnect-tolerant reply plumbing."""

    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._reply_raw(
                200,
                metrics.prometheus_text().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/healthz":
            self._reply(200, _healthz_payload())
        else:
            self._reply(404, {"error": "not found"})

    def _reply(self, status: int, payload: dict) -> None:
        self._reply_raw(status, json.dumps(payload).encode(), "application/json")

    def _reply_raw(self, status: int, raw: bytes, content_type: str) -> None:
        # a client that hangs up mid-response (CL restart, curl ^C) raises
        # here and would otherwise kill the handler thread silently — count
        # it and keep serving
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)
        except (BrokenPipeError, ConnectionResetError) as e:
            metrics.count("engine_api.client_disconnects")
            log.debug("client disconnected mid-reply: %r", e)
            # stop the keep-alive loop: reading the dead socket again would
            # raise out of handle_one_request and traceback to stderr
            self.close_connection = True

    def log_message(self, fmt, *args):  # route to logging, not stderr
        log.debug(fmt, *args)


class EngineAPIServer:
    """HTTP server bound to a Blockchain (reference: main.zig:143-149)."""

    def __init__(self, blockchain, host: str = "127.0.0.1", port: int = 8551):
        self.blockchain = blockchain
        self._lock = threading.Lock()
        outer = self

        class Handler(_ObservableHandler):
            def do_POST(self) -> None:  # noqa: N802 (stdlib API)
                t0 = time.perf_counter()
                # Lock-discipline audit (phantlint LOCK, PR 2): the
                # counter / in-flight gauge / latency-histogram updates
                # here run OUTSIDE outer._lock on purpose — the registry
                # has its own internal lock (trace.Metrics._lock), and
                # holding the request lock across observability writes
                # would serialize the very concurrency the in-flight gauge
                # measures. phantlint's LOCK rule scopes to the lock-owning
                # object's own attributes, so it (correctly) reports
                # nothing here — this comment, not a disable annotation,
                # is the audit record.
                metrics.gauge_add("engine_api.inflight", 1)
                try:
                    self._handle_post()
                finally:
                    metrics.gauge_add("engine_api.inflight", -1)
                    metrics.observe_hist(
                        "engine_api.request_seconds", time.perf_counter() - t0
                    )

            def _handle_post(self) -> None:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    request = json.loads(body)
                except json.JSONDecodeError:
                    metrics.count("engine_api.request_errors")
                    self._reply(400, {"error": {"code": -32700, "message": "parse error"}})
                    return
                if not isinstance(request, dict):
                    # batch requests and non-object bodies are not supported
                    metrics.count("engine_api.request_errors")
                    self._reply(
                        400,
                        {
                            "jsonrpc": "2.0",
                            "id": None,
                            "error": {"code": -32600, "message": "invalid request"},
                        },
                    )
                    return
                with outer._lock:
                    status, response = handle_request(outer.blockchain, request)
                if status >= 400 or "error" in response:
                    metrics.count("engine_api.request_errors")
                self._reply(status, response)

        self._server = ThreadingHTTPServer((host, port), Handler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def serve_forever(self) -> None:
        log.info("Engine API listening on :%d", self.port)
        self._server.serve_forever()

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class MetricsServer:
    """Standalone `/metrics` + `/healthz` server (`--metrics-port`): the
    Engine API port is a localhost CL-trust interface, while scrapers may
    live elsewhere — a separate bind keeps the two audiences separable."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9465):
        self._server = ThreadingHTTPServer((host, port), _ObservableHandler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def serve_metrics(host: str = "127.0.0.1", port: int = 9465) -> MetricsServer:
    """Start the standalone metrics server in a daemon thread."""
    srv = MetricsServer(host, port)
    srv.serve_in_background()
    log.info("metrics listening on %s:%d", host, srv.port)
    return srv
