"""Threaded HTTP JSON-RPC server for the Engine API.

Equivalent surface to the reference's httpz server wiring (reference:
src/main.zig:143-149: POST / routed to engineAPIHandler with the
*Blockchain as per-request context). Uses the stdlib ThreadingHTTPServer —
the handler holds a lock around block execution because `Blockchain`
mutates shared state (the reference is effectively serial there too).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from phant_tpu.engine_api import handle_request

log = logging.getLogger("phant_tpu.engine_api")


class EngineAPIServer:
    """HTTP server bound to a Blockchain (reference: main.zig:143-149)."""

    def __init__(self, blockchain, host: str = "127.0.0.1", port: int = 8551):
        self.blockchain = blockchain
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self) -> None:  # noqa: N802 (stdlib API)
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    request = json.loads(body)
                except json.JSONDecodeError:
                    self._reply(400, {"error": {"code": -32700, "message": "parse error"}})
                    return
                if not isinstance(request, dict):
                    # batch requests and non-object bodies are not supported
                    self._reply(
                        400,
                        {
                            "jsonrpc": "2.0",
                            "id": None,
                            "error": {"code": -32600, "message": "invalid request"},
                        },
                    )
                    return
                with outer._lock:
                    status, response = handle_request(outer.blockchain, request)
                self._reply(status, response)

            def _reply(self, status: int, payload: dict) -> None:
                raw = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def log_message(self, fmt, *args):  # route to logging, not stderr
                log.debug(fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def serve_forever(self) -> None:
        log.info("Engine API listening on :%d", self.port)
        self._server.serve_forever()

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
