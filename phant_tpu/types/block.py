"""Block header and block types with fork-aware RLP.

Equivalent surface to the reference (reference: src/types/block.zig:15-135):
`BlockHeader` carries the post-merge field set plus optional post-Shanghai /
post-Cancun / post-Prague fields; header RLP truncates trailing optional
fields by era so pre-fork hashes stay correct (reference: block.zig:51-69).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256
from phant_tpu.types.transaction import (
    Transaction,
    decode_tx_from_block_item,
    encode_tx_for_block,
)
from phant_tpu.types.withdrawal import Withdrawal

EMPTY_UNCLE_HASH = keccak256(rlp.encode([]))  # keccak(rlp([]))


@dataclass(frozen=True)
class BlockHeader:
    parent_hash: bytes = b"\x00" * 32
    uncle_hash: bytes = EMPTY_UNCLE_HASH
    fee_recipient: bytes = b"\x00" * 20  # a.k.a. coinbase / miner
    state_root: bytes = b"\x00" * 32
    transactions_root: bytes = b"\x00" * 32
    receipts_root: bytes = b"\x00" * 32
    logs_bloom: bytes = b"\x00" * 256
    block_number: int = 0
    gas_limit: int = 0
    gas_used: int = 0
    timestamp: int = 0
    extra_data: bytes = b""
    mix_hash: bytes = b"\x00" * 32
    nonce: bytes = b"\x00" * 8
    base_fee_per_gas: Optional[int] = None  # EIP-1559 (London)
    withdrawals_root: Optional[bytes] = None  # EIP-4895 (Shanghai)
    blob_gas_used: Optional[int] = None  # EIP-4844 (Cancun)
    excess_blob_gas: Optional[int] = None  # EIP-4844 (Cancun)
    parent_beacon_block_root: Optional[bytes] = None  # EIP-4788 (Cancun)
    requests_hash: Optional[bytes] = None  # EIP-7685 (Prague)

    # Headers carry one 32-byte slot that is the PoW mixHash pre-merge and
    # prevRandao post-merge; `prev_randao` below aliases mix_hash.
    difficulty: int = 0

    @property
    def prev_randao(self) -> bytes:
        return self.mix_hash

    def fields(self) -> list:
        """Fork-aware field list: trailing optional fields are included only
        once present (reference: src/types/block.zig:51-69)."""
        out = [
            self.parent_hash,
            self.uncle_hash,
            self.fee_recipient,
            self.state_root,
            self.transactions_root,
            self.receipts_root,
            self.logs_bloom,
            rlp.encode_uint(self.difficulty),
            rlp.encode_uint(self.block_number),
            rlp.encode_uint(self.gas_limit),
            rlp.encode_uint(self.gas_used),
            rlp.encode_uint(self.timestamp),
            self.extra_data,
            self.mix_hash,
            self.nonce,
        ]
        optionals = [
            None if self.base_fee_per_gas is None else rlp.encode_uint(self.base_fee_per_gas),
            self.withdrawals_root,
            None if self.blob_gas_used is None else rlp.encode_uint(self.blob_gas_used),
            None if self.excess_blob_gas is None else rlp.encode_uint(self.excess_blob_gas),
            self.parent_beacon_block_root,
            self.requests_hash,
        ]
        for opt in optionals:
            if opt is None:
                break
            out.append(opt)
        return out

    def encode(self) -> bytes:
        return rlp.encode(self.fields())

    def hash(self) -> bytes:
        """Canonical header hash = keccak(rlp(header))
        (reference: src/common/rlp.zig:14-22 via blockchain.zig:135)."""
        return keccak256(self.encode())

    @classmethod
    def from_rlp_list(cls, items: list) -> "BlockHeader":
        if len(items) < 15:
            raise rlp.DecodeError(f"header wants >=15 fields, got {len(items)}")
        kwargs = dict(
            parent_hash=bytes(items[0]),
            uncle_hash=bytes(items[1]),
            fee_recipient=bytes(items[2]),
            state_root=bytes(items[3]),
            transactions_root=bytes(items[4]),
            receipts_root=bytes(items[5]),
            logs_bloom=bytes(items[6]),
            difficulty=rlp.decode_uint(items[7]),
            block_number=rlp.decode_uint(items[8]),
            gas_limit=rlp.decode_uint(items[9]),
            gas_used=rlp.decode_uint(items[10]),
            timestamp=rlp.decode_uint(items[11]),
            extra_data=bytes(items[12]),
            mix_hash=bytes(items[13]),
            nonce=bytes(items[14]),
        )
        if len(items) > 15:
            kwargs["base_fee_per_gas"] = rlp.decode_uint(items[15])
        if len(items) > 16:
            kwargs["withdrawals_root"] = bytes(items[16])
        if len(items) > 17:
            kwargs["blob_gas_used"] = rlp.decode_uint(items[17])
        if len(items) > 18:
            kwargs["excess_blob_gas"] = rlp.decode_uint(items[18])
        if len(items) > 19:
            kwargs["parent_beacon_block_root"] = bytes(items[19])
        if len(items) > 20:
            kwargs["requests_hash"] = bytes(items[20])
        return cls(**kwargs)


@dataclass(frozen=True)
class Block:
    header: BlockHeader
    transactions: Tuple[Transaction, ...] = ()
    uncles: Tuple[BlockHeader, ...] = ()
    withdrawals: Optional[Tuple[Withdrawal, ...]] = None

    def fields(self) -> list:
        out = [
            self.header.fields(),
            [encode_tx_for_block(tx) for tx in self.transactions],
            [u.fields() for u in self.uncles],
        ]
        if self.withdrawals is not None:
            out.append([w.fields() for w in self.withdrawals])
        return out

    def encode(self) -> bytes:
        return rlp.encode(self.fields())

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        """RLP block decode (reference: src/types/block.zig:78-82)."""
        items = rlp.decode(data)
        if not isinstance(items, list) or len(items) < 3:
            raise rlp.DecodeError("block wants [header, txs, uncles, withdrawals?]")
        header = BlockHeader.from_rlp_list(items[0])
        txs = tuple(decode_tx_from_block_item(t) for t in items[1])
        uncles = tuple(BlockHeader.from_rlp_list(u) for u in items[2])
        withdrawals = None
        if len(items) > 3:
            withdrawals = tuple(Withdrawal.from_rlp_list(w) for w in items[3])
        return cls(header=header, transactions=txs, uncles=uncles, withdrawals=withdrawals)
