"""Account state types (reference: src/state/types.zig:7-50)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from phant_tpu.crypto.keccak import keccak256, EMPTY_KECCAK
from phant_tpu.mpt.mpt import EMPTY_TRIE_ROOT  # canonical definition

EMPTY_CODE_HASH = EMPTY_KECCAK


@dataclass
class Account:
    """One account's mutable state: nonce, balance, code, storage."""

    nonce: int = 0
    balance: int = 0
    code: bytes = b""
    storage: Dict[int, int] = field(default_factory=dict)

    def code_hash(self) -> bytes:
        return keccak256(self.code) if self.code else EMPTY_CODE_HASH

    def is_empty(self) -> bool:
        """EIP-161 empty: no code, zero nonce, zero balance."""
        return not self.code and self.nonce == 0 and self.balance == 0

    def copy(self) -> "Account":
        return Account(
            nonce=self.nonce,
            balance=self.balance,
            code=self.code,
            storage=dict(self.storage),
        )
