"""Ethereum transaction types: legacy, EIP-2930 access-list, EIP-1559
fee-market, EIP-4844 blob (Cancun).

Equivalent surface to the reference's tagged union (reference:
src/types/transaction.zig:10-273) plus the type-3 blob transaction the
reference lacks (its chainspec stops at Shanghai): EIP-2718 typed envelope
decode/encode, per-type keccak tx hash, and uniform getters. Implemented
as dataclasses with a small dispatch table instead of a tagged union.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256

AccessListEntry = Tuple[bytes, Tuple[bytes, ...]]  # (address20, (storage_key32, ...))

TX_TYPE_LEGACY = 0x00
TX_TYPE_ACCESS_LIST = 0x01
TX_TYPE_FEE_MARKET = 0x02
TX_TYPE_BLOB = 0x03
TX_TYPE_SET_CODE = 0x04

# EIP-4844 blob constants (consensus-critical); GAS_PER_BLOB's single
# source of truth is the gas schedule (phant_tpu/evm/gas.py)
from phant_tpu.evm.gas import GAS_PER_BLOB  # noqa: E402

VERSIONED_HASH_VERSION_KZG = 0x01


def _encode_access_list(access_list: Sequence[AccessListEntry]) -> list:
    return [[addr, [k for k in keys]] for addr, keys in access_list]


def _decode_access_list(item) -> Tuple[AccessListEntry, ...]:
    out = []
    for entry in item:
        addr, keys = entry
        out.append((bytes(addr), tuple(bytes(k) for k in keys)))
    return tuple(out)


@dataclass(frozen=True)
class LegacyTx:
    """Pre-EIP-2718 transaction (reference: src/types/transaction.zig:144-202)."""

    nonce: int
    gas_price: int
    gas_limit: int
    to: Optional[bytes]  # None => contract creation
    value: int
    data: bytes
    v: int
    r: int
    s: int

    tx_type: int = field(default=TX_TYPE_LEGACY, init=False, repr=False)

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.nonce),
            rlp.encode_uint(self.gas_price),
            rlp.encode_uint(self.gas_limit),
            self.to if self.to is not None else b"",
            rlp.encode_uint(self.value),
            self.data,
            rlp.encode_uint(self.v),
            rlp.encode_uint(self.r),
            rlp.encode_uint(self.s),
        ]

    def encode(self) -> bytes:
        return rlp.encode(self.fields())

    def hash(self) -> bytes:
        return keccak256(self.encode())

    # EIP-155: chain id recoverable from v (reference: transaction.zig:195-202)
    def chain_id(self) -> Optional[int]:
        if self.v in (27, 28):
            return None
        return (self.v - 35) // 2

    @classmethod
    def from_rlp_list(cls, items: list) -> "LegacyTx":
        if len(items) != 9:
            raise rlp.DecodeError(f"legacy tx wants 9 fields, got {len(items)}")
        to = bytes(items[3])
        return cls(
            nonce=rlp.decode_uint(items[0]),
            gas_price=rlp.decode_uint(items[1]),
            gas_limit=rlp.decode_uint(items[2]),
            to=to if to else None,
            value=rlp.decode_uint(items[4]),
            data=bytes(items[5]),
            v=rlp.decode_uint(items[6]),
            r=rlp.decode_uint(items[7]),
            s=rlp.decode_uint(items[8]),
        )


@dataclass(frozen=True)
class AccessListTx:
    """EIP-2930 typed tx 0x01 (reference: src/types/transaction.zig:204-236)."""

    chain_id_val: int
    nonce: int
    gas_price: int
    gas_limit: int
    to: Optional[bytes]
    value: int
    data: bytes
    access_list: Tuple[AccessListEntry, ...]
    y_parity: int
    r: int
    s: int

    tx_type: int = field(default=TX_TYPE_ACCESS_LIST, init=False, repr=False)

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.chain_id_val),
            rlp.encode_uint(self.nonce),
            rlp.encode_uint(self.gas_price),
            rlp.encode_uint(self.gas_limit),
            self.to if self.to is not None else b"",
            rlp.encode_uint(self.value),
            self.data,
            _encode_access_list(self.access_list),
            rlp.encode_uint(self.y_parity),
            rlp.encode_uint(self.r),
            rlp.encode_uint(self.s),
        ]

    def encode(self) -> bytes:
        return bytes([TX_TYPE_ACCESS_LIST]) + rlp.encode(self.fields())

    def hash(self) -> bytes:
        return keccak256(self.encode())

    def chain_id(self) -> Optional[int]:
        return self.chain_id_val

    @classmethod
    def from_rlp_list(cls, items: list) -> "AccessListTx":
        if len(items) != 11:
            raise rlp.DecodeError(f"2930 tx wants 11 fields, got {len(items)}")
        to = bytes(items[4])
        return cls(
            chain_id_val=rlp.decode_uint(items[0]),
            nonce=rlp.decode_uint(items[1]),
            gas_price=rlp.decode_uint(items[2]),
            gas_limit=rlp.decode_uint(items[3]),
            to=to if to else None,
            value=rlp.decode_uint(items[5]),
            data=bytes(items[6]),
            access_list=_decode_access_list(items[7]),
            y_parity=rlp.decode_uint(items[8]),
            r=rlp.decode_uint(items[9]),
            s=rlp.decode_uint(items[10]),
        )


@dataclass(frozen=True)
class FeeMarketTx:
    """EIP-1559 typed tx 0x02 (reference: src/types/transaction.zig:238-273)."""

    chain_id_val: int
    nonce: int
    max_priority_fee_per_gas: int
    max_fee_per_gas: int
    gas_limit: int
    to: Optional[bytes]
    value: int
    data: bytes
    access_list: Tuple[AccessListEntry, ...]
    y_parity: int
    r: int
    s: int

    tx_type: int = field(default=TX_TYPE_FEE_MARKET, init=False, repr=False)

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.chain_id_val),
            rlp.encode_uint(self.nonce),
            rlp.encode_uint(self.max_priority_fee_per_gas),
            rlp.encode_uint(self.max_fee_per_gas),
            rlp.encode_uint(self.gas_limit),
            self.to if self.to is not None else b"",
            rlp.encode_uint(self.value),
            self.data,
            _encode_access_list(self.access_list),
            rlp.encode_uint(self.y_parity),
            rlp.encode_uint(self.r),
            rlp.encode_uint(self.s),
        ]

    def encode(self) -> bytes:
        return bytes([TX_TYPE_FEE_MARKET]) + rlp.encode(self.fields())

    def hash(self) -> bytes:
        return keccak256(self.encode())

    def chain_id(self) -> Optional[int]:
        return self.chain_id_val

    @classmethod
    def from_rlp_list(cls, items: list) -> "FeeMarketTx":
        if len(items) != 12:
            raise rlp.DecodeError(f"1559 tx wants 12 fields, got {len(items)}")
        to = bytes(items[5])
        return cls(
            chain_id_val=rlp.decode_uint(items[0]),
            nonce=rlp.decode_uint(items[1]),
            max_priority_fee_per_gas=rlp.decode_uint(items[2]),
            max_fee_per_gas=rlp.decode_uint(items[3]),
            gas_limit=rlp.decode_uint(items[4]),
            to=to if to else None,
            value=rlp.decode_uint(items[6]),
            data=bytes(items[7]),
            access_list=_decode_access_list(items[8]),
            y_parity=rlp.decode_uint(items[9]),
            r=rlp.decode_uint(items[10]),
            s=rlp.decode_uint(items[11]),
        )


@dataclass(frozen=True)
class BlobTx:
    """EIP-4844 typed tx 0x03 (Cancun; beyond the reference's Shanghai
    ceiling, src/types/transaction.zig stops at type 0x02). This is the
    *payload* form that appears in blocks and Engine API payloads — the
    network wrapper (blobs + KZG commitments + proofs) never enters the
    execution layer."""

    chain_id_val: int
    nonce: int
    max_priority_fee_per_gas: int
    max_fee_per_gas: int
    gas_limit: int
    to: Optional[bytes]  # MUST be a 20-byte address (no blob creates)
    value: int
    data: bytes
    access_list: Tuple[AccessListEntry, ...]
    max_fee_per_blob_gas: int
    blob_versioned_hashes: Tuple[bytes, ...]
    y_parity: int
    r: int
    s: int

    tx_type: int = field(default=TX_TYPE_BLOB, init=False, repr=False)

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.chain_id_val),
            rlp.encode_uint(self.nonce),
            rlp.encode_uint(self.max_priority_fee_per_gas),
            rlp.encode_uint(self.max_fee_per_gas),
            rlp.encode_uint(self.gas_limit),
            self.to if self.to is not None else b"",
            rlp.encode_uint(self.value),
            self.data,
            _encode_access_list(self.access_list),
            rlp.encode_uint(self.max_fee_per_blob_gas),
            [h for h in self.blob_versioned_hashes],
            rlp.encode_uint(self.y_parity),
            rlp.encode_uint(self.r),
            rlp.encode_uint(self.s),
        ]

    def encode(self) -> bytes:
        return bytes([TX_TYPE_BLOB]) + rlp.encode(self.fields())

    def hash(self) -> bytes:
        return keccak256(self.encode())

    def chain_id(self) -> Optional[int]:
        return self.chain_id_val

    def blob_gas(self) -> int:
        return GAS_PER_BLOB * len(self.blob_versioned_hashes)

    @classmethod
    def from_rlp_list(cls, items: list) -> "BlobTx":
        if len(items) != 14:
            raise rlp.DecodeError(f"4844 tx wants 14 fields, got {len(items)}")
        to = bytes(items[5])
        if len(to) != 20:
            raise rlp.DecodeError("blob tx `to` must be a 20-byte address")
        return cls(
            chain_id_val=rlp.decode_uint(items[0]),
            nonce=rlp.decode_uint(items[1]),
            max_priority_fee_per_gas=rlp.decode_uint(items[2]),
            max_fee_per_gas=rlp.decode_uint(items[3]),
            gas_limit=rlp.decode_uint(items[4]),
            to=to,
            value=rlp.decode_uint(items[6]),
            data=bytes(items[7]),
            access_list=_decode_access_list(items[8]),
            max_fee_per_blob_gas=rlp.decode_uint(items[9]),
            blob_versioned_hashes=tuple(bytes(h) for h in items[10]),
            y_parity=rlp.decode_uint(items[11]),
            r=rlp.decode_uint(items[12]),
            s=rlp.decode_uint(items[13]),
        )


@dataclass(frozen=True)
class Authorization:
    """One EIP-7702 authorization tuple: authority (recovered from the
    signature over keccak(0x05 || rlp([chain_id, address, nonce]))) asks
    to set its code to the delegation designator 0xef0100 || address."""

    chain_id: int
    address: bytes  # 20-byte delegate (zero address clears the delegation)
    nonce: int
    y_parity: int
    r: int
    s: int

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.chain_id),
            self.address,
            rlp.encode_uint(self.nonce),
            rlp.encode_uint(self.y_parity),
            rlp.encode_uint(self.r),
            rlp.encode_uint(self.s),
        ]

    @classmethod
    def from_rlp_list(cls, items: list) -> "Authorization":
        if not isinstance(items, list) or len(items) != 6:
            raise rlp.DecodeError("authorization wants 6 fields")
        address = bytes(items[1])
        if len(address) != 20:
            raise rlp.DecodeError("authorization address must be 20 bytes")
        return cls(
            chain_id=rlp.decode_uint(items[0]),
            address=address,
            nonce=rlp.decode_uint(items[2]),
            y_parity=rlp.decode_uint(items[3]),
            r=rlp.decode_uint(items[4]),
            s=rlp.decode_uint(items[5]),
        )


@dataclass(frozen=True)
class SetCodeTx:
    """EIP-7702 typed tx 0x04 (Prague; beyond the reference's Shanghai
    ceiling, src/types/transaction.zig stops at type 0x02): an EIP-1559
    tx carrying a non-empty authorization list that installs delegation
    designators on the signing authorities' accounts."""

    chain_id_val: int
    nonce: int
    max_priority_fee_per_gas: int
    max_fee_per_gas: int
    gas_limit: int
    to: Optional[bytes]  # MUST be a 20-byte address (no set-code creates)
    value: int
    data: bytes
    access_list: Tuple[AccessListEntry, ...]
    authorization_list: Tuple[Authorization, ...]
    y_parity: int
    r: int
    s: int

    tx_type: int = field(default=TX_TYPE_SET_CODE, init=False, repr=False)

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.chain_id_val),
            rlp.encode_uint(self.nonce),
            rlp.encode_uint(self.max_priority_fee_per_gas),
            rlp.encode_uint(self.max_fee_per_gas),
            rlp.encode_uint(self.gas_limit),
            self.to if self.to is not None else b"",
            rlp.encode_uint(self.value),
            self.data,
            _encode_access_list(self.access_list),
            [a.fields() for a in self.authorization_list],
            rlp.encode_uint(self.y_parity),
            rlp.encode_uint(self.r),
            rlp.encode_uint(self.s),
        ]

    def encode(self) -> bytes:
        return bytes([TX_TYPE_SET_CODE]) + rlp.encode(self.fields())

    def hash(self) -> bytes:
        return keccak256(self.encode())

    def chain_id(self) -> Optional[int]:
        return self.chain_id_val

    @classmethod
    def from_rlp_list(cls, items: list) -> "SetCodeTx":
        if len(items) != 13:
            raise rlp.DecodeError(f"7702 tx wants 13 fields, got {len(items)}")
        to = bytes(items[5])
        if len(to) != 20:
            raise rlp.DecodeError("set-code tx `to` must be a 20-byte address")
        if not isinstance(items[9], list) or not items[9]:
            raise rlp.DecodeError("set-code tx needs a non-empty auth list")
        return cls(
            chain_id_val=rlp.decode_uint(items[0]),
            nonce=rlp.decode_uint(items[1]),
            max_priority_fee_per_gas=rlp.decode_uint(items[2]),
            max_fee_per_gas=rlp.decode_uint(items[3]),
            gas_limit=rlp.decode_uint(items[4]),
            to=to,
            value=rlp.decode_uint(items[6]),
            data=bytes(items[7]),
            access_list=_decode_access_list(items[8]),
            authorization_list=tuple(
                Authorization.from_rlp_list(a) for a in items[9]
            ),
            y_parity=rlp.decode_uint(items[10]),
            r=rlp.decode_uint(items[11]),
            s=rlp.decode_uint(items[12]),
        )


Transaction = Union[LegacyTx, AccessListTx, FeeMarketTx, BlobTx, SetCodeTx]


def decode_tx(data: bytes) -> Transaction:
    """EIP-2718 envelope decode (reference: src/types/transaction.zig:28-44)."""
    if not data:
        raise rlp.DecodeError("empty transaction bytes")
    first = data[0]
    if first > 0x7F:  # RLP list prefix => legacy tx
        items = rlp.decode(data)
        if not isinstance(items, list):
            raise rlp.DecodeError("legacy tx must be an RLP list")
        return LegacyTx.from_rlp_list(items)
    if first == TX_TYPE_ACCESS_LIST:
        items = rlp.decode(data[1:])
        if not isinstance(items, list):
            raise rlp.DecodeError("typed tx payload must be an RLP list")
        return AccessListTx.from_rlp_list(items)
    if first == TX_TYPE_FEE_MARKET:
        items = rlp.decode(data[1:])
        if not isinstance(items, list):
            raise rlp.DecodeError("typed tx payload must be an RLP list")
        return FeeMarketTx.from_rlp_list(items)
    if first == TX_TYPE_BLOB:
        items = rlp.decode(data[1:])
        if not isinstance(items, list):
            raise rlp.DecodeError("typed tx payload must be an RLP list")
        return BlobTx.from_rlp_list(items)
    if first == TX_TYPE_SET_CODE:
        items = rlp.decode(data[1:])
        if not isinstance(items, list):
            raise rlp.DecodeError("typed tx payload must be an RLP list")
        return SetCodeTx.from_rlp_list(items)
    raise rlp.DecodeError(f"unsupported tx type 0x{first:02x}")


def decode_tx_from_block_item(item) -> Transaction:
    """Decode a tx embedded in a block-body RLP list: legacy txs appear as
    nested lists, typed txs as opaque byte strings (reference:
    src/types/transaction.zig:65-77)."""
    if isinstance(item, list):
        return LegacyTx.from_rlp_list(item)
    return decode_tx(bytes(item))


def encode_tx_for_block(tx: Transaction):
    """Inverse of decode_tx_from_block_item: legacy txs embed as RLP lists,
    typed txs as byte strings."""
    if isinstance(tx, LegacyTx):
        return tx.fields()
    return tx.encode()


# --- uniform getters (reference: src/types/transaction.zig:87-141) ---


def effective_gas_price(tx: Transaction, base_fee: int) -> int:
    """EIP-1559 effective price; legacy/2930 are flat gas_price
    (reference: src/blockchain/blockchain.zig:276-287)."""
    if isinstance(tx, (FeeMarketTx, BlobTx, SetCodeTx)):
        priority = min(tx.max_priority_fee_per_gas, tx.max_fee_per_gas - base_fee)
        return priority + base_fee
    return tx.gas_price


def max_fee_per_gas(tx: Transaction) -> int:
    if isinstance(tx, (FeeMarketTx, BlobTx, SetCodeTx)):
        return tx.max_fee_per_gas
    return tx.gas_price


def blob_gas_of(tx: Transaction) -> int:
    return tx.blob_gas() if isinstance(tx, BlobTx) else 0


def access_list_of(tx: Transaction) -> Tuple[AccessListEntry, ...]:
    if isinstance(tx, LegacyTx):
        return ()
    return tx.access_list


def authorization_list_of(tx: Transaction) -> Tuple["Authorization", ...]:
    return tx.authorization_list if isinstance(tx, SetCodeTx) else ()
