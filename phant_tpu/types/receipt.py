"""Receipts, logs, and the 2048-bit logs bloom.

Equivalent surface to the reference (reference: src/types/receipt.zig:13-70):
receipt RLP {status, cumulative_gas_used, bloom, logs} with EIP-2718 type
prefix for typed txs, and the yellow-paper M3:2048 bloom — 3 bit positions
taken from the first three 16-bit big-endian words of keccak256(entry).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

from phant_tpu import rlp
from phant_tpu.crypto.keccak import keccak256

BLOOM_BYTES = 256


@dataclass(frozen=True)
class Log:
    address: bytes  # 20 bytes
    topics: Tuple[bytes, ...]  # each 32 bytes
    data: bytes

    def fields(self) -> list:
        return [self.address, [t for t in self.topics], self.data]


def _bloom_add(bloom: bytearray, entry: bytes) -> None:
    h = keccak256(entry)
    for i in (0, 2, 4):
        bit = ((h[i] << 8) | h[i + 1]) & 0x7FF  # low 11 bits => 0..2047
        byte_index = BLOOM_BYTES - 1 - bit // 8
        bloom[byte_index] |= 1 << (bit % 8)


def logs_bloom(logs: Sequence[Log]) -> bytes:
    """Bloom over all log addresses and topics
    (reference: src/types/receipt.zig:50-63)."""
    bloom = bytearray(BLOOM_BYTES)
    for log in logs:
        _bloom_add(bloom, log.address)
        for topic in log.topics:
            _bloom_add(bloom, topic)
    return bytes(bloom)


@dataclass(frozen=True)
class Receipt:
    tx_type: int
    succeeded: bool
    cumulative_gas_used: int
    logs: Tuple[Log, ...]
    bloom: bytes = field(default=b"")

    def __post_init__(self):
        if not self.bloom:
            object.__setattr__(self, "bloom", logs_bloom(self.logs))

    def fields(self) -> list:
        return [
            b"\x01" if self.succeeded else b"",
            rlp.encode_uint(self.cumulative_gas_used),
            self.bloom,
            [log.fields() for log in self.logs],
        ]

    def encode(self) -> bytes:
        """EIP-2718: typed receipts get the tx-type prefix byte."""
        payload = rlp.encode(self.fields())
        if self.tx_type == 0:
            return payload
        return bytes([self.tx_type]) + payload
