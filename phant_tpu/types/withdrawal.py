"""EIP-4895 withdrawal (reference: src/types/withdrawal.zig:7-21)."""

from __future__ import annotations

from dataclasses import dataclass

from phant_tpu import rlp

GWEI = 10**9


@dataclass(frozen=True)
class Withdrawal:
    index: int
    validator_index: int
    address: bytes  # 20 bytes
    amount: int  # in gwei; credited as amount * 10**9 wei

    def fields(self) -> list:
        return [
            rlp.encode_uint(self.index),
            rlp.encode_uint(self.validator_index),
            self.address,
            rlp.encode_uint(self.amount),
        ]

    def encode(self) -> bytes:
        return rlp.encode(self.fields())

    @classmethod
    def from_rlp_list(cls, items: list) -> "Withdrawal":
        if len(items) != 4:
            raise rlp.DecodeError(f"withdrawal wants 4 fields, got {len(items)}")
        return cls(
            index=rlp.decode_uint(items[0]),
            validator_index=rlp.decode_uint(items[1]),
            address=bytes(items[2]),
            amount=rlp.decode_uint(items[3]),
        )
