"""Fork-varying behavior: BLOCKHASH history.

Equivalent surface to the reference's vtable (reference:
src/blockchain/fork.zig:7-29): Frontier keeps an in-memory ring of the last
256 ancestor hashes (reference: src/blockchain/forks/frontier.zig:12-58);
Prague writes them into the EIP-2935 system contract's storage ring
(reference: src/blockchain/forks/prague.zig:8-57).
"""

from __future__ import annotations

from typing import Dict, Optional

from phant_tpu.state.statedb import StateDB

HISTORY_STORAGE_ADDRESS = bytes.fromhex("0000f90827f1c53a10cb7a02335b175320002935")
HISTORY_SERVE_WINDOW = 8191

# The EIP-2935 system contract's deployed runtime bytecode (from the EIP's
# deployment transaction): get path returns the ring-buffer slot for a
# requested ancestor within the 8191-block serve window; set path (caller ==
# 0xff..fe system address) writes block.number-1's hash. The reference
# deploys real code too (reference: src/blockchain/forks/prague.zig:54-57).
HISTORY_CONTRACT_CODE = bytes.fromhex(
    "3373fffffffffffffffffffffffffffffffffffffffe14604657602036036042"
    "575f35600143038111604257611fff81430311604257611fff9006545f5260205f"
    "f35b5f5ffd5b5f35611fff60014303065500"
)


class Fork:
    """BLOCKHASH provider interface (reference: fork.zig:9-13), extended
    with a block-start hook for fork-scoped system updates (EIP-4788
    beacon roots under Cancun; the reference has no Cancun fork)."""

    def update_parent_block_hash(self, number: int, block_hash: bytes) -> None:
        raise NotImplementedError

    def get_block_hash(self, number: int) -> bytes:
        raise NotImplementedError

    def on_block_start(self, header) -> None:
        """System-contract updates at the start of block processing."""


class FrontierFork(Fork):
    """Ring buffer of the last 256 ancestor hashes
    (reference: frontier.zig:29-58)."""

    def __init__(self):
        self._hashes: Dict[int, bytes] = {}

    def update_parent_block_hash(self, number: int, block_hash: bytes) -> None:
        self._hashes[number] = block_hash
        self._hashes.pop(number - 256, None)

    def get_block_hash(self, number: int) -> bytes:
        return self._hashes.get(number, b"\x00" * 32)


def fork_for(config, state: StateDB, block_number: int, timestamp: int) -> "Fork":
    """Pick the fork implementation from the chain config's activation
    schedule — the wiring the reference leaves as a TODO (reference:
    src/engine_api/engine_api.zig:125 "pick the fork based on chain
    config + block number + timestamp")."""
    name = config.fork_at(block_number, timestamp)
    if name in ("prague", "osaka"):
        return PragueFork(state)
    if name == "cancun":
        return CancunFork(state)
    return FrontierFork()


class PragueFork(Fork):
    """EIP-2935: ancestor hashes in the history system contract
    (reference: prague.zig:26-52; deployContract prague.zig:54-57).
    Prague retains Cancun's EIP-4788 beacon-root update — on_block_start
    writes the same twin ring slots (the reference's prague.zig covers
    only the BLOCKHASH experiment)."""

    def __init__(self, state: StateDB):
        self.state = state
        self.deploy_contract()
        if not state.get_code(BEACON_ROOTS_ADDRESS):
            state.create_account(BEACON_ROOTS_ADDRESS)
            state.set_nonce(BEACON_ROOTS_ADDRESS, 1)
            state.set_code(BEACON_ROOTS_ADDRESS, BEACON_ROOTS_CODE)

    def on_block_start(self, header) -> None:
        _write_beacon_root(self.state, header)

    def deploy_contract(self) -> None:
        if not self.state.get_code(HISTORY_STORAGE_ADDRESS):
            self.state.create_account(HISTORY_STORAGE_ADDRESS)
            self.state.set_nonce(HISTORY_STORAGE_ADDRESS, 1)
            self.state.set_code(HISTORY_STORAGE_ADDRESS, HISTORY_CONTRACT_CODE)

    def update_parent_block_hash(self, number: int, block_hash: bytes) -> None:
        slot = number % HISTORY_SERVE_WINDOW
        self.state.create_account(HISTORY_STORAGE_ADDRESS)
        # journaled write so block-level rollback undoes it
        self.state.set_storage(
            HISTORY_STORAGE_ADDRESS, slot, int.from_bytes(block_hash, "big")
        )

    def get_block_hash(self, number: int) -> bytes:
        value = self.state.get_storage(HISTORY_STORAGE_ADDRESS, number % HISTORY_SERVE_WINDOW)
        return value.to_bytes(32, "big")


# --- Cancun (no reference analog: its fork set stops at Shanghai/Prague
# BLOCKHASH experiments, src/blockchain/forks/) ------------------------------

BEACON_ROOTS_ADDRESS = bytes.fromhex("000f3df6d732807ef1319fb7b8bb8522d0beac02")
BEACON_ROOTS_BUFFER = 8191

# EIP-4788 deployed runtime bytecode (from the EIP's deployment tx): caller
# == 0xff..fe writes (timestamp, root) into the twin ring buffers; anyone
# else calls with a 32-byte timestamp and gets the matching root or reverts.
BEACON_ROOTS_CODE = bytes.fromhex(
    "3373fffffffffffffffffffffffffffffffffffffffe14604d57602036146024"
    "575f5ffd5b5f35801560495762001fff810690815414603c575f5ffd5b62001f"
    "ff01545f5260205ff35b5f5ffd5b62001fff42064281555f359062001fff0155"
    "00"
)


def _write_beacon_root(state: StateDB, header) -> None:
    """The EIP-4788 system call's storage effect:
    storage[ts % 8191] = ts, storage[ts % 8191 + 8191] = root."""
    root = getattr(header, "parent_beacon_block_root", None)
    if root is None:
        return
    ts = header.timestamp
    slot = ts % BEACON_ROOTS_BUFFER
    state.set_storage(BEACON_ROOTS_ADDRESS, slot, ts)
    state.set_storage(
        BEACON_ROOTS_ADDRESS,
        slot + BEACON_ROOTS_BUFFER,
        int.from_bytes(root, "big"),
    )


class CancunFork(FrontierFork):
    """Cancun: Frontier-style BLOCKHASH ring (EIP-2935 activates later, in
    Prague) plus the EIP-4788 parent-beacon-root system update at block
    start."""

    def __init__(self, state: StateDB):
        super().__init__()
        self.state = state
        if not state.get_code(BEACON_ROOTS_ADDRESS):
            state.create_account(BEACON_ROOTS_ADDRESS)
            state.set_nonce(BEACON_ROOTS_ADDRESS, 1)
            state.set_code(BEACON_ROOTS_ADDRESS, BEACON_ROOTS_CODE)

    def on_block_start(self, header) -> None:
        _write_beacon_root(self.state, header)
