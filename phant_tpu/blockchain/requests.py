"""EIP-7685 execution-layer requests (Prague): deposits (EIP-6110),
withdrawal requests (EIP-7002), consolidations (EIP-7251).

The reference predates the requests fork surface entirely (its Prague
experiment is only the EIP-2935 BLOCKHASH ring,
src/blockchain/forks/prague.zig) — this module is fork-mandated
framework-beyond-reference scope, mirrored on the execution-specs
semantics:

- deposits are PARSED out of the deposit contract's DepositEvent logs
  emitted during normal tx execution (no system call);
- withdrawal + consolidation requests are DEQUEUED by end-of-block system
  calls to their predeploy contracts (caller = the 0xff..fe system
  address, 30M gas, no fee, no block-gas accounting); the contracts'
  runtime code ships with the chain state (genesis/fixture pre-state),
  not with this client;
- the block commits to them via header.requests_hash =
  sha256(concat(sha256(type || data) for each NON-EMPTY request list)).
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

from phant_tpu.crypto.keccak import keccak256

SYSTEM_ADDRESS = bytes.fromhex("fffffffffffffffffffffffffffffffffffffffe")
SYSTEM_CALL_GAS = 30_000_000

# mainnet beacon-chain deposit contract (EIP-6110); spec-test chains use
# the same address for their mock deposit contracts
DEPOSIT_CONTRACT_ADDRESS = bytes.fromhex("00000000219ab540356cbb839cbe05303d7705fa")
DEPOSIT_EVENT_SIGNATURE_HASH = keccak256(b"DepositEvent(bytes,bytes,bytes,bytes,bytes)")

# EIP-7002 / EIP-7251 predeploys
WITHDRAWAL_REQUEST_ADDRESS = bytes.fromhex("00000961ef480eb55e80d19ad83579a64c007002")
CONSOLIDATION_REQUEST_ADDRESS = bytes.fromhex("0000bbddc7ce488642fb579f8b00f3a590007251")

DEPOSIT_REQUEST_TYPE = b"\x00"
WITHDRAWAL_REQUEST_TYPE = b"\x01"
CONSOLIDATION_REQUEST_TYPE = b"\x02"


class RequestsError(ValueError):
    """Malformed request surface => the block is invalid."""


def parse_deposit_event_data(data: bytes) -> bytes:
    """DepositEvent(bytes,bytes,bytes,bytes,bytes) ABI data -> the 192-byte
    deposit request (pubkey48 || withdrawal_credentials32 || amount8 ||
    signature96 || index8).  The layout is rigidly validated (EIP-6110:
    anything off-shape invalidates the block, it cannot be skipped)."""
    if len(data) != 576:
        raise RequestsError(f"deposit event data length {len(data)} != 576")

    def word(i: int) -> int:
        return int.from_bytes(data[32 * i : 32 * (i + 1)], "big")

    # head: offsets of the five dynamic fields
    if (word(0), word(1), word(2), word(3), word(4)) != (160, 256, 320, 384, 512):
        raise RequestsError("deposit event field offsets malformed")
    # length prefix of each tail section
    if word(5) != 48:  # pubkey
        raise RequestsError("deposit pubkey length != 48")
    if data[256:288] != (32).to_bytes(32, "big"):
        raise RequestsError("deposit withdrawal_credentials length != 32")
    if data[320:352] != (8).to_bytes(32, "big"):
        raise RequestsError("deposit amount length != 8")
    if data[384:416] != (96).to_bytes(32, "big"):
        raise RequestsError("deposit signature length != 96")
    if data[512:544] != (8).to_bytes(32, "big"):
        raise RequestsError("deposit index length != 8")
    pubkey = data[192:240]
    withdrawal_credentials = data[288:320]
    amount = data[352:360]
    signature = data[416:512]
    index = data[544:552]
    return pubkey + withdrawal_credentials + amount + signature + index


def extract_deposit_requests(
    receipts: Sequence, deposit_address: bytes = DEPOSIT_CONTRACT_ADDRESS
) -> bytes:
    """Concatenated deposit requests from the block's receipts, in log
    order (EIP-6110).  `deposit_address` is per-network (the chainspec's
    depositContractAddress — Sepolia's differs from mainnet's)."""
    out = []
    for receipt in receipts:
        for log in receipt.logs:
            if (
                log.address == deposit_address
                and len(log.topics) >= 1
                and log.topics[0] == DEPOSIT_EVENT_SIGNATURE_HASH
            ):
                out.append(parse_deposit_event_data(log.data))
    return b"".join(out)


def compute_requests_hash(requests: List[bytes]) -> bytes:
    """EIP-7685: sha256 over the sha256 of each request item (each item =
    type byte || data; empty-data items must already be excluded)."""
    m = hashlib.sha256()
    for req in requests:
        m.update(hashlib.sha256(req).digest())
    return m.digest()
