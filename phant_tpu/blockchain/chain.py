"""Block-level validation and execution (the core hot loop).

Equivalent surface to the reference Blockchain (reference:
src/blockchain/blockchain.zig:44-377): header validation (gas-limit bounds,
EIP-1559 base-fee recurrence, PoS fields, parent hash), the per-tx loop
(sender recovery -> intrinsic gas -> warm-set prefill -> EVM execution ->
refunds -> coinbase credit -> EIP-158 cleanup), withdrawals, and the
post-execution root checks. Goes beyond the reference by actually verifying
state root and logs bloom (TODO-disabled there,
reference: blockchain.zig:83-88).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

from phant_tpu import rlp
from phant_tpu.crypto.secp256k1 import SignatureError
from phant_tpu.evm import gas as G
from phant_tpu.evm.interpreter import Evm
from phant_tpu.evm.message import Environment, Message
from phant_tpu.evm.precompiles import precompile_addresses
from phant_tpu.blockchain.fork import Fork, FrontierFork
from phant_tpu.signer.signer import TxSigner
from phant_tpu.state.statedb import StateDB
from phant_tpu.types.block import Block, BlockHeader
from phant_tpu.types.receipt import Receipt, logs_bloom
from phant_tpu.types.transaction import (
    BlobTx,
    FeeMarketTx,
    SetCodeTx,
    Transaction,
    VERSIONED_HASH_VERSION_KZG,
    access_list_of,
    authorization_list_of,
    blob_gas_of,
    effective_gas_price,
    max_fee_per_gas,
)
from phant_tpu.types.withdrawal import GWEI
from phant_tpu.mpt.mpt import ordered_trie_root

ELASTICITY_MULTIPLIER = 2  # reference: params.zig:36
BASE_FEE_MAX_CHANGE_DENOMINATOR = 8  # reference: params.zig:37
GAS_LIMIT_ADJUSTMENT_FACTOR = 1024  # reference: blockchain.zig:140-145
GAS_LIMIT_MINIMUM = 5000


class BlockError(Exception):
    """Consensus-invalid block (maps to fixture expectException)."""


@dataclass
class BlockExecutionResult:
    """(reference: blockchain.zig:147-153)"""

    gas_used: int
    receipts: List[Receipt]
    logs_bloom: bytes
    requests_hash: Optional[bytes] = None  # EIP-7685 (Prague blocks only)


class Blockchain:
    """Holds chain config + parent header and runs blocks
    (reference: blockchain.zig:44-96)."""

    def __init__(
        self,
        chain_id: int,
        state: StateDB,
        parent_header: BlockHeader,
        fork: Optional[Fork] = None,
        verify_state_root: bool = True,
        config=None,
    ):
        self.chain_id = chain_id
        self.state = state
        self.parent_header = parent_header
        self.fork = fork if fork is not None else FrontierFork()
        self.signer = TxSigner(chain_id)
        self.verify_state_root = verify_state_root
        # chain config (fork-activation schedule); the stateless handler
        # uses it to pick the fork for witness-backed execution
        self.config = config
        # a config naming a known public network arms the KZG dev-setup
        # guard: 0x0A must refuse the forgeable dev tau there (crypto/kzg
        # set_public_network; config-less fixture chains stay unguarded)
        if config is not None:
            from phant_tpu.config import PUBLIC_CHAIN_IDS

            if getattr(config, "chainId", None) in PUBLIC_CHAIN_IDS:
                from phant_tpu.crypto import kzg

                kzg.set_public_network(
                    getattr(config, "ChainName", None) or str(config.chainId)
                )

    # ------------------------------------------------------------------

    def run_block(
        self,
        block: Block,
        check_body_roots: bool = True,
        senders: Optional[List[Optional[bytes]]] = None,
    ) -> BlockExecutionResult:
        """Validate + execute + verify roots (reference: blockchain.zig:61-96).

        An invalid block leaves no trace: execution is journaled and rolled
        back on any failure. `check_body_roots=False` skips re-deriving the
        tx/withdrawal roots — used by the Engine API path, whose `to_block`
        derived exactly those roots from the same tx/withdrawal tuples one
        call earlier (the blockHash check covers header integrity there).
        `senders` optionally supplies prefetched sender addresses (None
        entries = invalid signature) — the run_blocks pipeline's window
        prefetch, or the serving sig lane's merged cross-request
        ecrecover (stateless.dispatch_sender_recovery ->
        ops/sig_engine.py), both join the block here."""
        self.validate_block_header(block.header)
        if block.uncles:
            raise BlockError("post-merge blocks must have no uncles")

        self.state.begin_block()
        try:
            return self._execute_block(block, check_body_roots, senders)
        except BaseException:
            self.state.rollback_block()
            raise

    def run_blocks(
        self, blocks: List[Block], check_body_roots: bool = True
    ) -> List[BlockExecutionResult]:
        """Sequential block import with pipelined sender recovery: on
        `--crypto_backend=tpu`, whole windows of upcoming blocks' signatures
        are dispatched to the device ecrecover kernel while earlier blocks
        execute on the CPU — the device computes under the EVM's feet and
        per-dispatch latency is amortized over hundreds of txs. The
        reference's import loop is strictly serial per tx
        (reference: src/blockchain/blockchain.zig:61-96, :241); the batching
        axis across blocks is this framework's north-star addition.

        When a scheduler sig lane is installed (stateless.
        sender_lane_available), the SAME window pipeline engages even
        without a device: each window's rows go through
        `dispatch_sender_recovery`, so the rows are built once per WINDOW
        and the fused recovery runs on the scheduler's executor threads
        under the EVM's feet. Before the r18 fix this path fell through
        to the plain loop and paid a per-block signing-hash + recovery on
        the critical path with the lane sitting idle."""
        from phant_tpu.backend import crypto_backend, jax_device_ok
        from phant_tpu.stateless import (
            dispatch_sender_recovery,
            sender_lane_available,
        )

        results = []
        lane = sender_lane_available()
        if not lane and not (crypto_backend() == "tpu" and jax_device_ok()):
            for block in blocks:
                results.append(self.run_block(block, check_body_roots))
            return results

        window = int(os.environ.get("PHANT_TPU_PREFETCH_SIGS", "2048"))
        # split blocks into windows of >= `window` signatures; dispatch each
        # window's recovery in ONE fused device call, two windows in flight
        spans: List[Tuple[int, int]] = []  # [start_block, end_block)
        start, count = 0, 0
        for i, b in enumerate(blocks):
            count += len(b.transactions)
            if count >= window:
                spans.append((start, i + 1))
                start, count = i + 1, 0
        if start < len(blocks):
            spans.append((start, len(blocks)))

        def dispatch(span):
            s, e = span
            txs = [tx for b in blocks[s:e] for tx in b.transactions]
            if lane:
                # route the whole window through the sig lane: rows are
                # built once here and recovery runs on the scheduler's
                # executor threads; a shed/crashed lane degrades inside
                # the returned resolve (dispatch_sender_recovery), and a
                # lane that went away between windows falls through to
                # the direct dispatch below
                handle = dispatch_sender_recovery(self.chain_id, txs)
                if handle is not None:
                    return handle
            try:
                return self.signer.recover_senders_async(txs)
            except Exception as exc:  # staging onto a dead device can raise
                # synchronously; defer to resolve() so the CPU fallback
                # covers dispatch-time failures too
                def failed(e=exc):
                    raise e

                return failed

        def resolve(span, handle):
            """Materialize a window's senders; a device failure mid-replay
            (tunnel drop, OOM, preemption) degrades to the CPU batch for
            the window instead of sinking the import — the reference has
            no device to lose (its crypto is always in-process,
            src/crypto/ecdsa.zig); fault tolerance here is the cost of the
            offload. The fallback pins THIS call to the CPU path instead of
            flipping the process-global backend (which would race the
            threaded Engine API server)."""
            try:
                return handle()
            except Exception:
                import logging

                logging.getLogger("phant.chain").warning(
                    "device sender-recovery failed for blocks %s-%s; "
                    "recovering on CPU",
                    span[0],
                    span[1] - 1,
                    exc_info=True,
                )
                txs = [tx for b in blocks[span[0] : span[1]] for tx in b.transactions]
                return self.signer.recover_senders_async(txs, force_cpu=True)()

        pending: List = []
        next_span = 0
        for k in range(min(2, len(spans))):
            pending.append(dispatch(spans[k]))
            next_span += 1

        for si, (s, e) in enumerate(spans):
            senders_flat = resolve(spans[si], pending.pop(0))
            if next_span < len(spans):  # keep the device one window ahead
                pending.append(dispatch(spans[next_span]))
                next_span += 1
            pos = 0
            for block in blocks[s:e]:
                n = len(block.transactions)
                results.append(
                    self.run_block(
                        block, check_body_roots, senders=senders_flat[pos : pos + n]
                    )
                )
                pos += n
        return results

    def _execute_block(
        self,
        block: Block,
        check_body_roots: bool,
        senders: Optional[List[Optional[bytes]]] = None,
    ) -> BlockExecutionResult:
        # record parent hash for BLOCKHASH (reference: blockchain.zig:71)
        self.fork.update_parent_block_hash(
            self.parent_header.block_number, self.parent_header.hash()
        )
        # fork-scoped system updates (EIP-4788 beacon root under Cancun);
        # journaled, so an invalid block rolls them back with everything else
        self.fork.on_block_start(block.header)

        result = self.apply_body(block, senders)

        header = block.header
        if result.gas_used != header.gas_used:
            raise BlockError(
                f"gas_used mismatch: computed {result.gas_used}, header {header.gas_used}"
            )
        if check_body_roots:
            tx_root = ordered_trie_root([tx.encode() for tx in block.transactions])
            if tx_root != header.transactions_root:
                raise BlockError("transactions root mismatch")
            if block.withdrawals is not None:
                wd_root = ordered_trie_root([w.encode() for w in block.withdrawals])
                if wd_root != header.withdrawals_root:
                    raise BlockError("withdrawals root mismatch")
        receipts_root = ordered_trie_root([r.encode() for r in result.receipts])
        if receipts_root != header.receipts_root:
            raise BlockError("receipts root mismatch")
        if result.logs_bloom != header.logs_bloom:
            raise BlockError("logs bloom mismatch")
        if result.requests_hash is not None:
            # EIP-7685: a Prague block must commit to its requests
            if header.requests_hash is None:
                raise BlockError("prague header missing requests_hash")
            if result.requests_hash != header.requests_hash:
                raise BlockError(
                    f"requests hash mismatch: computed "
                    f"{result.requests_hash.hex()}, header "
                    f"{header.requests_hash.hex()}"
                )
        elif header.requests_hash is not None:
            raise BlockError("requests_hash before prague")
        if self.verify_state_root:
            # beyond reference (TODO-disabled at blockchain.zig:83-85)
            computed = self.state.state_root()
            if computed != header.state_root:
                raise BlockError(
                    f"state root mismatch: {computed.hex()} != {header.state_root.hex()}"
                )

        self.parent_header = block.header
        return result

    # ------------------------------------------------------------------

    def cancun_active(self, header: BlockHeader) -> bool:
        """Cancun dispatch: the chain config's schedule when present, else
        the header's own blob-gas fields (fixtures and synthetic chains are
        self-describing). The reference pins EVMC_SHANGHAI with a TODO
        (src/blockchain/vm.zig:472); this is that TODO done.

        The header-trusting fallback is for CONFIG-LESS chains only —
        trusted inputs by construction (fixtures, synthetic benches).
        Every network entry point (the Engine API server, __main__)
        constructs its Blockchain with a config, so untrusted payload
        bytes never pick their own fork here."""
        if self.config is not None:
            name = self.config.fork_at(header.block_number, header.timestamp)
            return name in ("cancun", "prague", "osaka")
        return header.excess_blob_gas is not None

    def prague_active(self, header: BlockHeader) -> bool:
        """Prague dispatch (EIP-7702 set-code txs, EIP-7623 calldata
        floor, EIP-7691 blob schedule, EIP-2935 ring). Config-less chains
        (fixtures/synthetic) follow the fork instance they were built
        with — the same rule blob_schedule uses, so a CancunFork chain
        can never half-activate Prague."""
        from phant_tpu.blockchain.fork import PragueFork

        if self.config is not None:
            name = self.config.fork_at(header.block_number, header.timestamp)
            return name in ("prague", "osaka")
        return isinstance(self.fork, PragueFork)

    def blob_schedule(self, header: BlockHeader) -> tuple:
        """(max_blob_gas, target_blob_gas, fee_update_fraction) for this
        block — EIP-7691 raised all three at Prague. Config-less chains
        (fixtures, synthetic benches) derive the schedule from the fork
        instance they were constructed with."""
        from phant_tpu.blockchain.fork import PragueFork

        if self.config is not None:
            name = self.config.fork_at(header.block_number, header.timestamp)
        elif isinstance(self.fork, PragueFork):
            name = "prague"
        else:
            name = "cancun"
        return G.blob_schedule(name)

    def validate_block_header(self, header: BlockHeader) -> None:
        """(reference: blockchain.zig:100-138; the blob-gas rules are
        EIP-4844, beyond the reference's Shanghai ceiling)"""
        parent = self.parent_header
        if self.cancun_active(header):
            if header.blob_gas_used is None or header.excess_blob_gas is None:
                raise BlockError("cancun header missing blob gas fields")
            max_blob_gas, target_blob_gas, _frac = self.blob_schedule(header)
            if header.blob_gas_used > max_blob_gas:
                raise BlockError("blob gas used above block maximum")
            if header.blob_gas_used % G.GAS_PER_BLOB != 0:
                raise BlockError("blob gas used not a blob multiple")
            expected_excess = G.calc_excess_blob_gas(
                parent.excess_blob_gas or 0,
                parent.blob_gas_used or 0,
                target=target_blob_gas,
            )
            if header.excess_blob_gas != expected_excess:
                raise BlockError(
                    f"excess blob gas mismatch: header {header.excess_blob_gas}, "
                    f"expected {expected_excess}"
                )
        elif header.blob_gas_used is not None or header.excess_blob_gas is not None:
            raise BlockError("blob gas fields before cancun")
        if header.base_fee_per_gas is None:
            raise BlockError("missing base fee (pre-London unsupported)")
        expected_base_fee = calculate_base_fee(
            parent.gas_limit, parent.gas_used,
            parent.base_fee_per_gas if parent.base_fee_per_gas is not None else 0,
        )
        if header.base_fee_per_gas != expected_base_fee:
            raise BlockError(
                f"base fee mismatch: header {header.base_fee_per_gas}, expected {expected_base_fee}"
            )
        if header.gas_used > header.gas_limit:
            raise BlockError("gas_used above gas_limit")
        check_gas_limit(header.gas_limit, parent.gas_limit)
        if header.timestamp <= parent.timestamp:
            raise BlockError("timestamp not after parent")
        if header.block_number != parent.block_number + 1:
            raise BlockError("block number not parent+1")
        if len(header.extra_data) > 32:
            raise BlockError("extra data too long")
        # PoS fields (reference: blockchain.zig:124-129)
        if header.difficulty != 0:
            raise BlockError("difficulty must be 0 post-merge")
        if header.nonce != b"\x00" * 8:
            raise BlockError("nonce must be zero post-merge")
        from phant_tpu.types.block import EMPTY_UNCLE_HASH

        if header.uncle_hash != EMPTY_UNCLE_HASH:
            raise BlockError("uncle hash must be empty-list hash")
        if header.parent_hash != parent.hash():
            raise BlockError("parent hash mismatch")

    # ------------------------------------------------------------------

    def apply_body(
        self, block: Block, senders: Optional[List[Optional[bytes]]] = None
    ) -> BlockExecutionResult:
        """(reference: blockchain.zig:155-205)"""
        header = block.header
        gas_available = header.gas_limit
        receipts: List[Receipt] = []
        cumulative_gas = 0
        all_logs = []

        # recover every sender up front — one fused batch (native, or device
        # when the tpu backend and batch size warrant it; reference recovers
        # per-tx, blockchain.zig:241). Prefetched senders arrive from two
        # producers: run_blocks (device recovery windows ahead of the
        # replay) and the serving sig lane (one merged ecrecover across
        # concurrent requests, dispatched at decode time — ops/
        # sig_engine.py). The None-entry error message below must stay
        # byte-identical to get_senders_batch's SignatureError text: the
        # lane's invalid-signature attribution contract rides on it.
        if senders is None:
            try:
                senders = self.signer.get_senders_batch(list(block.transactions))
            except SignatureError as e:
                raise BlockError(f"invalid signature: {e}") from e
        else:
            if len(senders) != len(block.transactions):
                raise BlockError("prefetched sender count mismatch")
            bad = [i for i, a in enumerate(senders) if a is None]
            if bad:
                raise BlockError(
                    f"invalid signature: unrecoverable signature at tx index {bad[0]}"
                )

        # block-constant fork context computed ONCE (the schedule scan and
        # the fake_exponential blob fee are per-header facts; the tx loop
        # is the replay hot path)
        cancun = self.cancun_active(header)
        if cancun:
            max_blob_gas, _target, fee_fraction = self.blob_schedule(header)
            bbf = G.blob_base_fee(header.excess_blob_gas or 0, fee_fraction)
        else:
            max_blob_gas, bbf = 0, 0
        blob_gas_used = 0
        for tx, sender in zip(block.transactions, senders):
            self.check_transaction(
                tx, header, gas_available, sender, cancun=cancun, blob_base_fee=bbf
            )
            blob_gas_used += blob_gas_of(tx)
            if cancun and blob_gas_used > max_blob_gas:
                raise BlockError("block blob gas above maximum")
            gas_used, tx_logs, succeeded = self.process_transaction(
                tx, sender, header, cancun=cancun, blob_base_fee=bbf
            )
            gas_available -= gas_used
            cumulative_gas += gas_used
            receipts.append(
                Receipt(
                    tx_type=tx.tx_type,
                    succeeded=succeeded,
                    cumulative_gas_used=cumulative_gas,
                    logs=tuple(tx_logs),
                )
            )
            all_logs.extend(tx_logs)

        if cancun and blob_gas_used != (header.blob_gas_used or 0):
            raise BlockError(
                f"blob gas used mismatch: computed {blob_gas_used}, "
                f"header {header.blob_gas_used}"
            )

        # withdrawals (reference: blockchain.zig:193-196)
        if block.withdrawals:
            for wd in block.withdrawals:
                self.state.add_balance(wd.address, wd.amount * GWEI)
                acct = self.state.get_account(wd.address)
                if acct is not None and acct.is_empty():
                    self.state.delete_account(wd.address)

        # EIP-7685 requests surface (Prague): deposits parsed from this
        # block's receipts, withdrawal/consolidation requests dequeued by
        # end-of-block system calls (phant_tpu/blockchain/requests.py)
        requests_hash = None
        if self.prague_active(header):
            requests_hash = self._collect_requests(receipts, header)

        return BlockExecutionResult(
            gas_used=cumulative_gas,
            receipts=receipts,
            logs_bloom=logs_bloom(all_logs),
            requests_hash=requests_hash,
        )

    def _collect_requests(self, receipts, header: BlockHeader) -> bytes:
        from phant_tpu.blockchain import requests as req
        from phant_tpu.utils.hexutils import hex_to_address

        deposit_addr = req.DEPOSIT_CONTRACT_ADDRESS
        if self.config is not None and getattr(
            self.config, "depositContractAddress", None
        ):
            deposit_addr = hex_to_address(self.config.depositContractAddress)
        try:
            deposits = req.extract_deposit_requests(receipts, deposit_addr)
        except req.RequestsError as e:
            raise BlockError(str(e)) from e
        withdrawals = self._system_call(req.WITHDRAWAL_REQUEST_ADDRESS, header)
        consolidations = self._system_call(
            req.CONSOLIDATION_REQUEST_ADDRESS, header
        )
        items = []
        if deposits:
            items.append(req.DEPOSIT_REQUEST_TYPE + deposits)
        if withdrawals:
            items.append(req.WITHDRAWAL_REQUEST_TYPE + withdrawals)
        if consolidations:
            items.append(req.CONSOLIDATION_REQUEST_TYPE + consolidations)
        return req.compute_requests_hash(items)

    def _system_call(self, target: bytes, header: BlockHeader) -> bytes:
        """EIP-7002/7251 end-of-block system call: caller = the system
        address, 30M gas, feeless, outside block-gas accounting; the
        output bytes ARE the request data.  A missing predeploy or a
        failing call invalidates the block (the requests cannot be
        proven absent)."""
        from phant_tpu.blockchain import requests as req
        from phant_tpu.evm.interpreter import Evm
        from phant_tpu.evm.message import REVISION_PRAGUE, Environment, Message

        state = self.state
        if not state.get_code(target):
            raise BlockError(f"missing system contract 0x{target.hex()}")
        state.start_tx()  # fresh warm sets / refund / logs for the call
        env = Environment(
            state=state,
            origin=req.SYSTEM_ADDRESS,
            coinbase=header.fee_recipient,
            block_number=header.block_number,
            gas_limit=header.gas_limit,
            gas_price=0,
            timestamp=header.timestamp,
            prev_randao=header.prev_randao,
            base_fee=header.base_fee_per_gas or 0,
            chain_id=self.chain_id,
            block_hash_fn=self.fork.get_block_hash,
            revision=REVISION_PRAGUE,
        )
        evm = Evm(env)
        result = evm.execute_message(
            Message(
                caller=req.SYSTEM_ADDRESS,
                target=target,
                value=0,
                data=b"",
                gas=req.SYSTEM_CALL_GAS,
            )
        )
        if not result.success:
            raise BlockError(
                f"system call to 0x{target.hex()} failed: {result.error}"
            )
        return result.output

    # ------------------------------------------------------------------

    def check_transaction(
        self,
        tx: Transaction,
        header: BlockHeader,
        gas_available: int,
        sender: bytes,
        cancun: Optional[bool] = None,
        blob_base_fee: Optional[int] = None,
    ) -> None:
        """(reference: blockchain.zig:237-260 + validateTransaction :345-353;
        sender recovery itself happens batched in apply_body). `cancun` /
        `blob_base_fee` are block constants apply_body precomputes; direct
        callers may omit them."""
        if cancun is None:
            cancun = self.cancun_active(header)
        if tx.gas_limit > gas_available:
            raise BlockError("tx gas limit exceeds available block gas")
        base_fee = header.base_fee_per_gas or 0
        if isinstance(tx, (FeeMarketTx, BlobTx, SetCodeTx)):
            if tx.max_fee_per_gas < tx.max_priority_fee_per_gas:
                raise BlockError("max fee below priority fee")
            if tx.max_fee_per_gas < base_fee:
                raise BlockError("max fee below base fee")
        else:
            if tx.gas_price < base_fee:
                raise BlockError("gas price below base fee")

        if isinstance(tx, SetCodeTx):
            # EIP-7702 validity (no reference analog — type 4 postdates it)
            if not self.prague_active(header):
                raise BlockError("set-code tx before prague")
            if tx.to is None:
                raise BlockError("set-code tx cannot create")
            if not tx.authorization_list:
                raise BlockError("set-code tx without authorizations")

        blob_fee = 0
        if isinstance(tx, BlobTx):
            # EIP-4844 validity (no reference analog — type 3 postdates it)
            if not cancun:
                raise BlockError("blob tx before cancun")
            if tx.to is None:
                raise BlockError("blob tx cannot create")
            if not tx.blob_versioned_hashes:
                raise BlockError("blob tx without blobs")
            for h in tx.blob_versioned_hashes:
                if len(h) != 32 or h[0] != VERSIONED_HASH_VERSION_KZG:
                    raise BlockError("bad blob versioned hash version")
            if blob_base_fee is None:
                blob_base_fee = G.blob_base_fee(
                    header.excess_blob_gas or 0, self.blob_schedule(header)[2]
                )
            if tx.max_fee_per_blob_gas < blob_base_fee:
                raise BlockError("max blob fee below blob base fee")
            blob_fee = tx.blob_gas() * tx.max_fee_per_blob_gas

        # intrinsic validity (reference: validateTransaction blockchain.zig:345-353)
        is_create = tx.to is None
        if is_create and len(tx.data) > G.MAX_INITCODE_SIZE:
            raise BlockError("initcode exceeds EIP-3860 limit")
        intrinsic = G.intrinsic_gas(
            tx.data,
            is_create,
            access_list_of(tx),
            len(tx.data) if is_create else 0,
            n_authorizations=len(authorization_list_of(tx)),
        )
        if intrinsic > tx.gas_limit:
            raise BlockError("intrinsic gas exceeds limit")
        if self.prague_active(header) and G.calldata_floor_gas(tx.data) > tx.gas_limit:
            raise BlockError("gas limit below EIP-7623 calldata floor")

        sender_acct = self.state.get_account(sender)
        nonce = sender_acct.nonce if sender_acct else 0
        if nonce != tx.nonce:
            raise BlockError(f"nonce mismatch: tx {tx.nonce}, account {nonce}")
        if sender_acct is not None and sender_acct.code:
            # EIP-3607, as amended by EIP-7702 — but the designator
            # exemption exists only once Prague is live; pre-Prague every
            # code-bearing sender is rejected (consensus: other clients
            # reject such blocks too)
            if not (
                self.prague_active(header)
                and G.is_delegation_designator(sender_acct.code)
            ):
                raise BlockError("sender is not EOA (EIP-3607)")
        max_cost = tx.gas_limit * max_fee_per_gas(tx) + tx.value + blob_fee
        balance = sender_acct.balance if sender_acct else 0
        if balance < max_cost:
            raise BlockError("insufficient sender balance for gas + value")

    # ------------------------------------------------------------------

    def _apply_authorizations(self, tx: Transaction, state) -> int:
        """EIP-7702 per-tuple processing; returns the gas-refund credit.

        For each authorization: screen chain id (0 or ours) and nonce
        ceiling, recover the authority from its signature over
        keccak(0x05 ‖ rlp([chain_id, address, nonce])), warm the authority,
        and — if its code is empty or already a delegation and its nonce
        matches — install 0xef0100‖address (or clear it for the zero
        address) and bump the authority nonce. Existing authorities earn
        the PER_EMPTY_ACCOUNT_COST − PER_AUTH_BASE_COST refund. Any
        screening failure skips the TUPLE, never the tx."""
        from phant_tpu.signer.signer import recover_authority

        refund = 0
        for auth in authorization_list_of(tx):
            if auth.chain_id not in (0, self.chain_id):
                continue
            if auth.nonce >= 2**64 - 1:
                continue
            authority = recover_authority(auth)
            if authority is None:
                continue
            # the authority is warmed even when a later check skips the
            # tuple (EIP-7702: added to accessed_addresses regardless)
            state.access_address(authority)
            acct = state.get_account(authority)
            code = acct.code if acct else b""
            if code and not G.is_delegation_designator(code):
                continue  # a real contract cannot be delegated
            nonce = acct.nonce if acct else 0
            if nonce != auth.nonce:
                continue
            # refund keys on trie PRESENCE (EELS `account_exists`), not
            # non-emptiness: an existing-but-empty authority still refunds
            if acct is not None:
                refund += G.PER_EMPTY_ACCOUNT_COST - G.PER_AUTH_BASE_COST
            if auth.address == b"\x00" * 20:
                state.set_code(authority, b"")  # clear the delegation
            else:
                state.set_code(authority, G.DELEGATION_PREFIX + auth.address)
            state.increment_nonce(authority)
            state.touch(authority)
        return refund

    def process_transaction(
        self,
        tx: Transaction,
        sender: bytes,
        header: BlockHeader,
        cancun: Optional[bool] = None,
        blob_base_fee: Optional[int] = None,
    ) -> Tuple[int, list, bool]:
        """(reference: blockchain.zig:262-343). `cancun` / `blob_base_fee`
        are block constants apply_body precomputes; direct callers may omit
        them."""
        state = self.state
        state.start_tx()
        base_fee = header.base_fee_per_gas or 0
        gas_price = effective_gas_price(tx, base_fee)
        priority_fee = gas_price - base_fee
        if cancun is None:
            cancun = self.cancun_active(header)
        if blob_base_fee is None:
            blob_base_fee = (
                G.blob_base_fee(
                    header.excess_blob_gas or 0, self.blob_schedule(header)[2]
                )
                if cancun
                else 0
            )
        blob_fee_rate = blob_base_fee

        from phant_tpu.evm.message import (
            REVISION_CANCUN,
            REVISION_PRAGUE,
            REVISION_SHANGHAI,
        )

        if self.prague_active(header):
            revision = REVISION_PRAGUE
        elif cancun:
            revision = REVISION_CANCUN
        else:
            revision = REVISION_SHANGHAI
        env = Environment(
            state=state,
            origin=sender,
            coinbase=header.fee_recipient,
            block_number=header.block_number,
            gas_limit=header.gas_limit,
            gas_price=gas_price,
            timestamp=header.timestamp,
            prev_randao=header.prev_randao,
            base_fee=base_fee,
            chain_id=self.chain_id,
            block_hash_fn=self.fork.get_block_hash,
            revision=revision,
            blob_hashes=(
                tx.blob_versioned_hashes if isinstance(tx, BlobTx) else ()
            ),
            blob_base_fee=blob_fee_rate,
        )

        # buy gas, bump nonce (reference: blockchain.zig:266-301); the blob
        # fee (EIP-4844) is burned up front at the BLOCK's blob base fee and
        # never refunded — it is not execution gas
        state.sub_balance(sender, tx.gas_limit * gas_price)
        if isinstance(tx, BlobTx):
            state.sub_balance(sender, tx.blob_gas() * blob_fee_rate)
        state.increment_nonce(sender)

        # EIP-2929 warm-set prefill incl. EIP-3651 warm coinbase
        # (reference: blockchain.zig:293-301, params.zig:19-29)
        state.access_address(sender)
        state.access_address(header.fee_recipient)
        for addr in precompile_addresses(revision):
            state.access_address(addr)
        if tx.to is not None:
            state.access_address(tx.to)
        for addr, keys in access_list_of(tx):
            state.access_address(addr)
            for key in keys:
                state.access_storage_key(addr, int.from_bytes(key, "big"))

        intrinsic = G.intrinsic_gas(
            tx.data, tx.to is None, access_list_of(tx),
            len(tx.data) if tx.to is None else 0,
            n_authorizations=len(authorization_list_of(tx)),
        )
        exec_gas = tx.gas_limit - intrinsic

        # EIP-7702 authorization processing: after the sender nonce bump,
        # before execution. Tuple-level failures skip the tuple (the tx
        # stays valid); auth refunds survive a reverted execution because
        # the delegations themselves do (they are tx-level state, not part
        # of the message frame's journal scope).
        auth_refund = self._apply_authorizations(tx, state)

        if revision >= REVISION_PRAGUE and tx.to is not None:
            # EIP-7702: a delegated destination's delegate is warmed for
            # free at the tx top level (nested CALLs pay for it at the
            # calling instruction instead). After auth processing — this
            # very tx may have just installed the delegation on tx.to.
            to_code = state.get_code(tx.to)
            if G.is_delegation_designator(to_code):
                state.access_address(G.delegation_target(to_code))

        evm = Evm(env)
        msg = Message(
            caller=sender,
            target=tx.to,
            value=tx.value,
            data=tx.data,
            gas=exec_gas,
        )
        result = evm.execute_message(msg)

        # refunds (reference: blockchain.zig:312-331; EIP-3529 quotient 5).
        # EIP-7702 auth refunds apply even when execution reverted — the
        # delegations they correspond to were still installed
        gas_used = tx.gas_limit - result.gas_left
        counter = (state.refund if result.success else 0) + auth_refund
        refund = min(counter, gas_used // G.REFUND_QUOTIENT)
        gas_used -= refund
        if revision >= REVISION_PRAGUE:
            # EIP-7623: calldata-heavy txs pay at least the floor price
            # (applied after refunds; check_transaction already rejected
            # gas limits below the floor)
            gas_used = max(gas_used, G.calldata_floor_gas(tx.data))
        state.add_balance(sender, (tx.gas_limit - gas_used) * gas_price)

        # coinbase priority fee (reference: blockchain.zig:325-331)
        state.touch(header.fee_recipient)
        if priority_fee * gas_used:
            state.add_balance(header.fee_recipient, priority_fee * gas_used)

        # selfdestructs delete accounts wholesale
        for addr in state.selfdestructs:
            state.delete_account(addr)

        # EIP-158 (reference: blockchain.zig:334-341 via statedb)
        state.destroy_touched_empty()

        logs = list(state.logs) if result.success else []
        return gas_used, logs, result.success


# ---------------------------------------------------------------------------


def calculate_base_fee(parent_gas_limit: int, parent_gas_used: int, parent_base_fee: int) -> int:
    """EIP-1559 recurrence (reference: blockchain.zig:107-123)."""
    parent_gas_target = parent_gas_limit // ELASTICITY_MULTIPLIER
    if parent_gas_used == parent_gas_target:
        return parent_base_fee
    if parent_gas_used > parent_gas_target:
        gas_used_delta = parent_gas_used - parent_gas_target
        delta = max(
            parent_base_fee * gas_used_delta // parent_gas_target // BASE_FEE_MAX_CHANGE_DENOMINATOR,
            1,
        )
        return parent_base_fee + delta
    gas_used_delta = parent_gas_target - parent_gas_used
    delta = (
        parent_base_fee * gas_used_delta // parent_gas_target // BASE_FEE_MAX_CHANGE_DENOMINATOR
    )
    return parent_base_fee - delta


def check_gas_limit(gas_limit: int, parent_gas_limit: int) -> None:
    """(reference: blockchain.zig:140-145)"""
    max_delta = parent_gas_limit // GAS_LIMIT_ADJUSTMENT_FACTOR
    if gas_limit >= parent_gas_limit + max_delta:
        raise BlockError("gas limit increased too much")
    if gas_limit <= parent_gas_limit - max_delta:
        raise BlockError("gas limit decreased too much")
    if gas_limit < GAS_LIMIT_MINIMUM:
        raise BlockError("gas limit below minimum")
